"""bcfl_tpu — TPU-native framework for communication-efficient asynchronous
peer-to-peer federated LLM fine-tuning with a blockchain-style weight ledger.

A ground-up JAX/XLA/Pallas redesign of the capabilities of the reference repo
``Sreebhargavibalijaa/Building-Communication-Efficient-Asynchronous-Peer-to-Peer-
Federated-LLMs-with-Blockchain`` (see ``SURVEY.md``):

- every federated client is a slot on a ``clients`` mesh axis — one TPU chip
  (or a vmapped stack of clients per chip); all clients train one round inside
  a single compiled XLA program,
- server-mode FedAvg lowers to a masked ``jax.lax.psum`` over ICI
  (reference: Flower ``FedAvg`` strategy, ``src/Servercase/server_IID_IMDB.py:205-218``),
- serverless P2P gossip lowers to ``jax.lax.ppermute`` along a ring
  (reference: hand-rolled averaging loop,
  ``src/Serverlesscase/serverless_NonIID_IMDB.py:284-297``),
- the anomaly-node filters (PageRank / DBSCAN / modified-Z / communities) and
  the hash-chained weight ledger run on the TPU-VM host and gate which clients
  contribute to each aggregation round (reference: offline notebook analysis,
  ``All_graphs_IMDB_dataset.ipynb``),
- async mode is host-scheduled with staleness-weighted aggregation; the
  sync/async information-passing-time model of the reference notebooks is
  implemented for real in :mod:`bcfl_tpu.topology`.

Nothing is copied from the reference; it is Python/torch/Flower, this is
JAX-first. Reference citations in docstrings are for behavioural parity only.
"""

__version__ = "0.1.0"

from bcfl_tpu.config import FedConfig  # noqa: F401
