"""`bcfl-tpu lint` — AST-based static analysis of the repo's concurrency,
determinism, and telemetry contracts (ANALYSIS.md).

- :mod:`bcfl_tpu.analysis.core` — the framework: :class:`Finding`,
  :class:`Checker` + registry, the ``# lint: disable=<id> — <why>``
  suppression convention, the committed baseline, and the
  :func:`lint_main` CLI (``bcfl-tpu lint``).
- :mod:`bcfl_tpu.analysis.concurrency` — **guarded-by** (registered
  shared fields only touched under their declared lock) and
  **lock-order** (the static acquisition graph is cycle-free).
- :mod:`bcfl_tpu.analysis.determinism` — **determinism** (seeded-draw
  modules: no wall clock, no module-level RNG, no unsorted dict/set
  iteration).
- :mod:`bcfl_tpu.analysis.telemetry_schema` — **telemetry-schema**
  (every literal emit names an EVENT_TYPES entry with its required
  fields).
- :mod:`bcfl_tpu.analysis.wire_static` — **socket-deadline** and
  **no-frame-concat** (the AST successors of the two grep guards that
  used to live in tests/test_wire_chaos.py).

stdlib-only: no jax, no third-party imports.
"""

from bcfl_tpu.analysis import (  # noqa: F401 — populate the registry
    concurrency,
    determinism,
    telemetry_schema,
    wire_static,
)
from bcfl_tpu.analysis.core import (  # noqa: F401
    CHECKERS,
    DEFAULT_BASELINE,
    JSON_VERSION,
    PACKAGE_DIR,
    Checker,
    Finding,
    Source,
    baseline_json,
    checker_ids,
    lint_main,
    load_baseline,
    run_lint,
)
from bcfl_tpu.analysis.determinism import SEEDED_SCOPE  # noqa: F401
from bcfl_tpu.analysis.wire_static import iter_socket_sites  # noqa: F401
