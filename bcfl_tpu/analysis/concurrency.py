"""Concurrency checkers: guarded-by field access and lock acquisition
order (ANALYSIS.md).

The dist runtime is genuinely threaded — per-destination sender workers,
per-connection serve threads, a leader intake thread, watchdog Timers, a
SIGTERM handler — and the repo's own comments document which lock guards
which shared field (``transport._bump``: "a plain += is a racy
read-add-store"). These two checkers turn those comments into enforced
declarations:

- **guarded-by** — a field registered with a trailing ``# guarded-by:
  <lock>`` comment on its ``__init__`` assignment must only be accessed
  inside a ``with self.<lock>`` block (or from a method annotated
  ``# guarded-by: <lock>`` on its ``def`` line, meaning callers hold the
  lock). The ``(writes)`` qualifier restricts enforcement to mutations —
  the honest contract for counters whose reads are GIL-atomic snapshot
  reads (reports) while their ``+=`` is the read-add-store race.
- **lock-order** — the static graph "lock B acquired while lock A held"
  (direct ``with`` nesting, same-class method calls resolved
  transitively, plus the known telemetry seam: every ``telemetry.emit``
  takes the EventWriter's internal lock). Any cycle is the deadlock the
  pipelined sender + intake thread made possible; a plain ``Lock``
  re-acquired while already held is reported too (only RLock/Condition
  are reentrant).

Static limits (documented, deliberate): accesses through another object
(``self.rep.quarantine_drops`` guarded by a lock the *runtime* owns) and
locks passed across classes are not resolved — the registry covers fields
whose lock lives on the same object, which is every lock site the dist
runtime has today.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from bcfl_tpu.analysis.core import Checker, Finding, Source, register

_GUARD_RE = re.compile(
    r"#\s*guarded-by:\s*([A-Za-z_]\w*)\s*(?P<writes>\(writes\))?")

#: constructors whose result is treated as a lock attribute
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
#: reentrant lock constructors (self-nesting is legal)
_REENTRANT = {"RLock", "Condition"}

#: calls that are known to acquire a lock the AST cannot see locally:
#: every telemetry emit/flush goes through EventWriter's internal RLock
#: (bcfl_tpu/telemetry/events.py) — the one cross-module seam that
#: matters, because emit sites sit inside detector/report critical
#: sections
_TELEMETRY_LOCK = "EventWriter._lock"
_TELEMETRY_FUNCS = {"emit", "emit_sampled", "flush"}
_TELEMETRY_BASES = {"telemetry", "_telemetry"}


def _lock_ctor_name(node: ast.AST) -> Optional[str]:
    """'RLock' for ``threading.RLock()`` / ``RLock()`` calls, else None."""
    if not isinstance(node, ast.Call):
        return None
    fn = node.func
    name = None
    if isinstance(fn, ast.Attribute):
        name = fn.attr
    elif isinstance(fn, ast.Name):
        name = fn.id
    return name if name in _LOCK_CTORS else None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _telemetry_acquire(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute) and fn.attr in _TELEMETRY_FUNCS:
        base = fn.value
        if isinstance(base, ast.Name) and base.id in _TELEMETRY_BASES:
            return True
        if isinstance(base, ast.Attribute) and base.attr in _TELEMETRY_BASES:
            return True
    return False


@dataclasses.dataclass
class _ClassInfo:
    name: str
    locks: Dict[str, str]              # lock attr -> ctor name
    guarded: Dict[str, Tuple[str, bool]]  # field -> (lock attr, writes_only)
    methods: Dict[str, ast.FunctionDef]
    annotations: Dict[str, Set[str]]   # method -> locks held by contract


def _scan_class(src: Source, cls: ast.ClassDef) -> _ClassInfo:
    locks: Dict[str, str] = {}
    guarded: Dict[str, Tuple[str, bool]] = {}
    methods: Dict[str, ast.FunctionDef] = {}
    annotations: Dict[str, Set[str]] = {}
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        methods[item.name] = item
        held = set()
        m = _GUARD_RE.search(src.line_text(item.lineno))
        if m and src.comment_on(item.lineno, "guarded-by:"):
            held.add(m.group(1))
        annotations[item.name] = held
        for node in ast.walk(item):
            # lock attrs + guarded-field registrations, wherever assigned
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                value = node.value
                for t in targets:
                    attr = _self_attr(t)
                    if attr is None:
                        continue
                    ctor = _lock_ctor_name(value) if value is not None \
                        else None
                    if ctor is not None:
                        locks[attr] = ctor
                        continue
                    gm = _GUARD_RE.search(src.line_text(node.lineno))
                    if gm and src.comment_on(node.lineno, "guarded-by:"):
                        guarded[attr] = (gm.group(1),
                                         gm.group("writes") is not None)
    return _ClassInfo(cls.name, locks, guarded, methods, annotations)


def _is_write(node: ast.Attribute, parents: Dict[ast.AST, ast.AST]) -> bool:
    """Store/Del context, or the base of a subscript that is itself being
    stored/deleted (``self.d[k] = v`` / ``self.d[k] += 1``)."""
    if isinstance(node.ctx, (ast.Store, ast.Del)):
        return True
    parent = parents.get(node)
    if (isinstance(parent, ast.Subscript) and parent.value is node
            and isinstance(parent.ctx, (ast.Store, ast.Del))):
        return True
    return False


def _walk_with_locks(fn: ast.AST, lock_attrs: Set[str], held: Tuple[str, ...],
                     visit) -> None:
    """DFS that tracks which of the class's locks are held via ``with
    self.<lock>`` nesting; ``visit(node, held)`` fires on every node."""
    visit(fn, held)
    if isinstance(fn, (ast.With, ast.AsyncWith)):
        acquired = list(held)
        for item in fn.items:
            attr = _self_attr(item.context_expr)
            if attr is not None and attr in lock_attrs:
                acquired.append(attr)
            visit(item.context_expr, held)
        inner = tuple(acquired)
        for stmt in fn.body:
            _walk_with_locks(stmt, lock_attrs, inner, visit)
        return
    for child in ast.iter_child_nodes(fn):
        _walk_with_locks(child, lock_attrs, held, visit)


@register
class GuardedByChecker(Checker):
    id = "guarded-by"
    contract = ("registered shared fields are only accessed under their "
                "declared lock (# guarded-by: <lock> annotations)")

    def check(self, src: Source) -> Iterable[Finding]:
        out: List[Finding] = []
        if src.tree is None:
            return out
        classes = [n for n in ast.walk(src.tree)
                   if isinstance(n, ast.ClassDef)]
        scanned = {cls.name: (_scan_class(src, cls), cls)
                   for cls in classes}

        def merged_locks(name: str, seen: Tuple[str, ...]) -> Dict[str, str]:
            # a subclass guards state with the base's lock (e.g. the phi
            # detector reuses FailureDetector._lock) — resolve lock attrs
            # through same-file bases so those registrations still verify
            info, cls = scanned[name]
            locks = dict(info.locks)
            for b in cls.bases:
                base = b.id if isinstance(b, ast.Name) else None
                if base in scanned and base not in seen:
                    for k, v in merged_locks(base, seen + (name,)).items():
                        locks.setdefault(k, v)
            return locks

        for cls in classes:
            info, _ = scanned[cls.name]
            if not info.guarded:
                continue
            info = dataclasses.replace(
                info, locks=merged_locks(cls.name, ()))
            # fail-loudly on a registration naming a lock that is not a
            # lock attribute of this class (typo'd annotations must not
            # silently un-guard a field)
            for field, (lock, _w) in sorted(info.guarded.items()):
                if lock not in info.locks:
                    out.append(self.finding(
                        src, cls,
                        f"{info.name}.{field} is declared guarded-by "
                        f"{lock!r}, but {info.name} has no lock attribute "
                        f"of that name"))
            for mname, fn in info.methods.items():
                if mname == "__init__":
                    continue  # construction happens-before publication
                parents: Dict[ast.AST, ast.AST] = {}
                for p in ast.walk(fn):
                    for ch in ast.iter_child_nodes(p):
                        parents[ch] = p
                base_held = tuple(info.annotations.get(mname, ()))

                def visit(node, held, _fn_name=mname):
                    attr = _self_attr(node) if isinstance(
                        node, ast.Attribute) else None
                    if attr is None or attr not in info.guarded:
                        return
                    lock, writes_only = info.guarded[attr]
                    if lock not in info.locks:
                        return  # already reported above
                    write = _is_write(node, parents)
                    if writes_only and not write:
                        return
                    if lock in held:
                        return
                    out.append(self.finding(
                        src, node,
                        f"{info.name}.{attr} is guarded by self.{lock} "
                        f"but is {'written' if write else 'read'} in "
                        f"{_fn_name}() outside `with self.{lock}` "
                        f"(annotate the method `# guarded-by: {lock}` if "
                        f"every caller holds it)"))

                _walk_with_locks(fn, set(info.locks), base_held, visit)
        return out


@register
class LockOrderChecker(Checker):
    id = "lock-order"
    contract = ("the static lock acquisition graph (lock B taken while "
                "lock A held) is cycle-free; non-reentrant locks are "
                "never self-nested")

    def __init__(self):
        # edge (held, acquired) -> one example "file:line (context)"
        self.edges: Dict[Tuple[str, str], str] = {}
        self.lock_ctors: Dict[str, str] = {_TELEMETRY_LOCK: "RLock"}
        self._example_src: Dict[Tuple[str, str], Tuple[str, int]] = {}

    # ----------------------------------------------------------- per file

    def check(self, src: Source) -> Iterable[Finding]:
        if src.tree is None:
            return ()
        # module-level locks (e.g. native/build.py `_lock`)
        mod_locks: Dict[str, str] = {}
        mod_name = (src.rel or src.path).rsplit("/", 1)[-1].rsplit(".", 1)[0]
        for node in src.tree.body:
            if isinstance(node, ast.Assign):
                ctor = _lock_ctor_name(node.value)
                if ctor:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            lid = f"{mod_name}.{t.id}"
                            mod_locks[t.id] = lid
                            self.lock_ctors[lid] = ctor
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            self._check_class(src, cls, mod_locks)
        # module-level functions using module locks
        for fn in src.tree.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_edges(src, fn, mod_locks, {}, {}, ())
        return ()

    def _check_class(self, src: Source, cls: ast.ClassDef,
                     mod_locks: Dict[str, str]) -> None:
        info = _scan_class(src, cls)
        for attr, ctor in info.locks.items():
            self.lock_ctors[f"{info.name}.{attr}"] = ctor
        # pass 1: per-method direct acquisitions (for call propagation)
        direct: Dict[str, Set[str]] = {}
        for mname, fn in info.methods.items():
            acq: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        a = _self_attr(item.context_expr)
                        if a in info.locks:
                            acq.add(f"{info.name}.{a}")
                if isinstance(node, ast.Call) and _telemetry_acquire(node):
                    acq.add(_TELEMETRY_LOCK)
            direct[mname] = acq
        # pass 2: transitive closure over same-class self.method() calls
        calls: Dict[str, Set[str]] = {m: set() for m in info.methods}
        for mname, fn in info.methods.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    a = _self_attr(node.func)
                    if a in info.methods:
                        calls[mname].add(a)
        effective = {m: set(s) for m, s in direct.items()}
        changed = True
        while changed:
            changed = False
            for m in info.methods:
                for callee in calls[m]:
                    new = effective[callee] - effective[m]
                    if new:
                        effective[m] |= new
                        changed = True
        # pass 3: edges — annotation locks and with-nesting both count as
        # "held"; anything acquired below adds an edge
        for mname, fn in info.methods.items():
            base = tuple(f"{info.name}.{a}"
                         for a in info.annotations.get(mname, ())
                         if a in info.locks)
            self._collect_edges(src, fn, mod_locks, info.locks,
                                {m: effective[m] for m in info.methods},
                                base, class_name=info.name)

    def _collect_edges(self, src: Source, fn, mod_locks: Dict[str, str],
                       class_locks: Dict[str, str],
                       method_acquires: Dict[str, Set[str]],
                       base_held: Tuple[str, ...],
                       class_name: str = "") -> None:
        def lock_id_of(expr) -> Optional[str]:
            a = _self_attr(expr)
            if a is not None and a in class_locks:
                return f"{class_name}.{a}"
            if isinstance(expr, ast.Name) and expr.id in mod_locks:
                return mod_locks[expr.id]
            return None

        def add_edge(held: Tuple[str, ...], acquired: str, node) -> None:
            for h in held:
                key = (h, acquired)
                if key not in self.edges:
                    self.edges[key] = f"{src.path}:{node.lineno}"
                    self._example_src[key] = (src.path, node.lineno)

        def walk(node, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = list(held)
                for item in node.items:
                    lid = lock_id_of(item.context_expr)
                    if lid is not None:
                        add_edge(tuple(inner), lid, item.context_expr)
                        inner.append(lid)
                for stmt in node.body:
                    walk(stmt, tuple(inner))
                return
            if isinstance(node, ast.Call):
                if _telemetry_acquire(node) and held:
                    add_edge(held, _TELEMETRY_LOCK, node)
                a = _self_attr(node.func)
                if a is not None and a in method_acquires and held:
                    for lid in method_acquires[a]:
                        add_edge(held, lid, node)
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        walk(fn, base_held)

    # ----------------------------------------------------------- finalize

    def finalize(self) -> Iterable[Finding]:
        out: List[Finding] = []
        # self-nesting of a non-reentrant lock is an immediate deadlock
        for (a, b), where in sorted(self.edges.items()):
            if a == b and self.lock_ctors.get(a) not in _REENTRANT:
                path, line = self._example_src[(a, b)]
                out.append(Finding(
                    checker=self.id, file=path, line=line,
                    message=f"non-reentrant lock {a} acquired while "
                            f"already held (plain Lock deadlocks on "
                            f"re-entry; use RLock or restructure)"))
        # cycle detection over the directed edge set (self-loops excluded
        # — handled above; RLock self-loops are legal re-entry)
        adj: Dict[str, Set[str]] = {}
        for (a, b) in self.edges:
            if a != b:
                adj.setdefault(a, set()).add(b)
        for cycle in _find_cycles(adj):
            # anchor the report at the edge closing the cycle
            key = (cycle[-1], cycle[0])
            path, line = self._example_src.get(
                key, self._example_src[(cycle[0], cycle[1])]
                if (cycle[0], cycle[1]) in self._example_src
                else next(iter(self._example_src.values())))
            order = " -> ".join(cycle + [cycle[0]])
            out.append(Finding(
                checker=self.id, file=path, line=line,
                message=f"lock-order cycle: {order} (two threads taking "
                        f"these locks in opposite orders deadlock; pick "
                        f"one global order)"))
        return out


def _find_cycles(adj: Dict[str, Set[str]]) -> List[List[str]]:
    """Elementary cycles via Tarjan SCCs: one representative cycle per
    strongly connected component with more than one node."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    counter = [0]
    sccs: List[List[str]] = []
    nodes = sorted(set(adj) | {b for bs in adj.values() for b in bs})

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(adj.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            if len(comp) > 1:
                sccs.append(sorted(comp))

    for v in nodes:
        if v not in index:
            strongconnect(v)
    return sccs
