"""`bcfl-tpu lint` — AST-based static analysis of the repo's own contracts
(ANALYSIS.md).

The repo's core claims — bit-identical seeded chaos draws, bit-for-bit
crash/resume, ledger digests stable across the wire, zero invariant
violations under byzantine + wire chaos — are *contracts*. Until this
package they were enforced only at runtime (tests, invariant queries over
event streams) plus two substring-grep "static guard" tests. Meanwhile the
runtime grew genuinely concurrent (per-destination sender workers, a
leader intake thread, a dozen-plus lock sites) and the telemetry surface
grew to ~50 emit sites across ten files — exactly where silent races and
nondeterminism creep in. This framework rejects contract violations at
lint time, before they become a flaky loopback test.

Design constraints (all load-bearing):

- **stdlib only** (``ast``, ``tokenize``, ``argparse``, ``json``): the
  analysis package itself imports no jax and no third-party modules —
  checkers must run anywhere the source does. (Importing it still
  executes ``bcfl_tpu/__init__``, whose config chain pulls the ML stack —
  the same cost the ``trace`` subcommand pays; the constraint here is
  that the CHECKERS never depend on it.)
- **Checkers are registered declaratively** (:func:`register`): each owns
  one checker id, one contract, and produces :class:`Finding` rows with a
  stable ``file:line`` anchor. Adding a checker is subclassing
  :class:`Checker` + the decorator (ANALYSIS.md "Adding a checker").
- **Suppressions are explicit and justified**: ``# lint:
  disable=<checker-id> — <justification>`` on the offending line (or a
  standalone comment line directly above it). A suppression WITHOUT a
  justification does not suppress — it is itself a finding — so every
  grandfathered site carries its reason in the source.
- **A committed baseline** (``baseline.json`` next to this module) can
  grandfather findings during adoption; ``--no-baseline`` ignores it. The
  baseline is keyed on (checker, package-relative file, message) — line
  numbers churn, messages are the stable identity.
- **Exit code is the contract**: ``bcfl-tpu lint`` exits nonzero on any
  finding that is neither suppressed nor baselined, which is what makes
  the repo-wide run in tests/test_analysis.py (and the chaos_smoke lint
  leg) a standing guard.

Scope rule: files inside the ``bcfl_tpu`` package are checked under each
checker's package scoping (e.g. socket-deadline only under ``dist/``,
determinism only in the seeded-draw modules); files OUTSIDE the package
are treated as fully in scope for every checker — that is the fixture /
one-off-script workflow.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: schema version of the ``--json`` output (tests pin the key sets)
JSON_VERSION = 1

#: the bcfl_tpu package root (scope anchor for package-relative paths)
PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the committed grandfather file (empty == every contract enforced live)
DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.json")

#: checker id reserved for the framework's own suppression hygiene
SUPPRESSION_ID = "suppression"

_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Za-z0-9_,\-]+)"
    r"(?:\s*(?:—|–|--|-|:)?\s*(?P<why>\S.*))?$")


@dataclasses.dataclass
class Finding:
    """One checker hit, anchored to ``file:line``.

    ``suppressed`` / ``baselined`` are verdicts the runner stamps after
    matching suppression comments and the baseline file; a finding fails
    the run only when both are False."""

    checker: str
    file: str       # path as scanned (absolute)
    line: int
    message: str
    suppressed: bool = False
    justification: Optional[str] = None
    baselined: bool = False

    @property
    def failing(self) -> bool:
        return not (self.suppressed or self.baselined)

    def rel_file(self) -> str:
        """Package-relative posix path when under bcfl_tpu/ (the stable
        baseline key), else the basename."""
        ap = os.path.abspath(self.file)
        if ap.startswith(PACKAGE_DIR + os.sep):
            rel = os.path.relpath(ap, os.path.dirname(PACKAGE_DIR))
            return rel.replace(os.sep, "/")
        return os.path.basename(ap)

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.checker}] {self.message}"

    def to_json(self) -> Dict:
        return {
            "checker": self.checker,
            "file": self.rel_file(),
            "line": self.line,
            "message": self.message,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }


@dataclasses.dataclass
class _Suppression:
    line: int           # the line of code the suppression covers
    ids: Set[str]
    justification: Optional[str]
    comment_line: int   # where the comment itself sits
    used: bool = False


class Source:
    """One parsed file: text, lines, AST, parsed suppressions."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        with open(self.path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.text, filename=self.path)
        except SyntaxError as e:
            self.parse_error = e
        # package scoping: None when the file is outside bcfl_tpu/ —
        # checkers then treat it as fully in scope (fixtures, scripts)
        self.rel: Optional[str] = None
        if self.path.startswith(PACKAGE_DIR + os.sep):
            self.rel = os.path.relpath(
                self.path, PACKAGE_DIR).replace(os.sep, "/")
        self._comment_cache: Optional[List[Tuple[int, int, str]]] = None
        self.suppressions: List[_Suppression] = self._parse_suppressions()

    # ------------------------------------------------------- suppressions

    def _comments(self) -> List[Tuple[int, int, str]]:
        """[(line, col, text)] of every comment token (tokenize-accurate:
        a '#' inside a string literal is never a comment). Tokenized once
        and cached — comment_on is called per def line / call site."""
        if self._comment_cache is not None:
            return self._comment_cache
        out: List[Tuple[int, int, str]] = []
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out.append((tok.start[0], tok.start[1], tok.string))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # fall back to nothing: an unparseable file already surfaces
            # as a parse-error finding
            pass
        self._comment_cache = out
        return out

    def _parse_suppressions(self) -> List[_Suppression]:
        out = []
        for line, col, text in self._comments():
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            ids = {i.strip() for i in m.group(1).split(",") if i.strip()}
            why = m.group("why")
            # a standalone comment line covers the next line carrying
            # code; a trailing comment covers its own line
            standalone = self.lines[line - 1][:col].strip() == ""
            target = line
            if standalone:
                target = line + 1
                while (target <= len(self.lines)
                       and (not self.lines[target - 1].strip()
                            or self.lines[target - 1].lstrip()
                            .startswith("#"))):
                    target += 1
            out.append(_Suppression(line=target, ids=ids,
                                    justification=why, comment_line=line))
        return out

    def suppression_for(self, checker_id: str,
                        line: int) -> Optional[_Suppression]:
        for s in self.suppressions:
            if s.line == line and (checker_id in s.ids or "all" in s.ids):
                return s
        return None

    # ------------------------------------------------------------ helpers

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1]
        return ""

    def comment_on(self, line: int, needle: str) -> bool:
        """Does line ``line`` carry a comment containing ``needle``?
        (Comment-accurate — a match inside a string does not count.)"""
        for ln, _col, text in self._comments():
            if ln == line and needle in text:
                return True
        return False


class Checker:
    """Base class. Subclasses set ``id`` + ``contract`` and implement
    :meth:`check` (per file); cross-file checkers accumulate state in
    ``check`` and yield the rest from :meth:`finalize`. Checker instances
    are constructed fresh per lint run — state never leaks between runs."""

    id: str = ""
    contract: str = ""

    def check(self, src: Source) -> Iterable[Finding]:
        return ()

    def finalize(self) -> Iterable[Finding]:
        return ()

    def finding(self, src: Source, node, message: str) -> Finding:
        line = node if isinstance(node, int) else getattr(node, "lineno", 1)
        return Finding(checker=self.id, file=src.path, line=line,
                       message=message)


#: checker id -> class (populated by the @register decorators at import)
CHECKERS: Dict[str, type] = {}


def register(cls: type) -> type:
    if not cls.id:
        raise ValueError(f"checker {cls.__name__} has no id")
    if cls.id in CHECKERS:
        raise ValueError(f"duplicate checker id {cls.id!r}")
    CHECKERS[cls.id] = cls
    return cls


def checker_ids() -> List[str]:
    return sorted(CHECKERS)


# ---------------------------------------------------------------- baseline


def load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    """(checker, package-relative file, message) triples the repo has
    grandfathered. A missing file is an empty baseline; a PRESENT but
    unreadable one (merge-conflict garbage, schema drift) fails loudly —
    silently treating it as empty would un-grandfather everything with a
    wall of confusing findings instead of one clear error."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return set()
    except json.JSONDecodeError as e:
        raise ValueError(
            f"baseline {path} is not valid JSON: {e}") from None
    try:
        return {(row["checker"], row["file"], row["message"])
                for row in data.get("findings", ())}
    except (TypeError, KeyError, AttributeError) as e:
        raise ValueError(
            f"baseline {path} is unreadable (each findings row needs "
            f"checker/file/message): {e!r}") from None


def baseline_json(findings: Sequence[Finding]) -> str:
    """Serialize ``findings`` in the committed baseline format (what
    ``--write-baseline`` emits) — sorted, line-number free."""
    rows = sorted({(f.checker, f.rel_file(), f.message) for f in findings})
    return json.dumps(
        {"version": JSON_VERSION,
         "findings": [{"checker": c, "file": fl, "message": m}
                      for c, fl, m in rows]},
        indent=2) + "\n"


# ------------------------------------------------------------------ runner


def iter_py_files(paths: Sequence[str]) -> List[str]:
    out = []
    for p in paths:
        ap = os.path.abspath(p)
        if os.path.isdir(ap):
            for dirpath, dirnames, files in os.walk(ap):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__")
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(files) if f.endswith(".py"))
        elif ap.endswith(".py"):
            out.append(ap)
    # dedup, stable order
    seen: Set[str] = set()
    uniq = []
    for f in out:
        if f not in seen:
            seen.add(f)
            uniq.append(f)
    return uniq


def run_lint(paths: Sequence[str],
             checker_ids_filter: Optional[Sequence[str]] = None,
             use_baseline: bool = True,
             baseline_path: str = DEFAULT_BASELINE) -> List[Finding]:
    """Run the (selected) checkers over every ``.py`` under ``paths`` and
    return ALL findings — suppressed and baselined ones included, with
    their verdicts stamped. Callers decide the exit code via
    :attr:`Finding.failing`."""
    # the checker modules self-register on import; import here so `import
    # bcfl_tpu.analysis.core` alone stays side-effect-light
    from bcfl_tpu.analysis import (  # noqa: F401
        concurrency,
        determinism,
        telemetry_schema,
        wire_static,
    )

    ids = list(checker_ids_filter) if checker_ids_filter else checker_ids()
    unknown = [i for i in ids if i not in CHECKERS]
    if unknown:
        raise ValueError(
            f"unknown checker id(s) {unknown}; known: {checker_ids()}")
    checkers = [CHECKERS[i]() for i in ids]

    files = iter_py_files(paths)
    if not files:
        # a typo'd path (or the wrong cwd) must not make the standing
        # guard pass vacuously while checking zero files
        raise ValueError(
            f"no .py files found under {list(paths)!r} — nothing to lint")

    findings: List[Finding] = []
    sources: Dict[str, Source] = {}
    for path in files:
        src = Source(path)
        sources[path] = src
        if src.parse_error is not None:
            findings.append(Finding(
                checker="parse-error", file=src.path,
                line=src.parse_error.lineno or 1,
                message=f"file does not parse: {src.parse_error.msg}"))
            continue
        for c in checkers:
            findings.extend(c.check(src))
    for c in checkers:
        findings.extend(c.finalize())

    # --- suppression pass: justified suppressions mark findings; a
    # suppression without a justification is itself a finding and
    # suppresses nothing (the convention REQUIRES the why)
    for f in findings:
        src = sources.get(f.file)
        if src is None:
            continue
        sup = src.suppression_for(f.checker, f.line)
        if sup is not None and sup.justification:
            f.suppressed = True
            f.justification = sup.justification
            sup.used = True
        elif sup is not None:
            sup.used = True  # matched, but invalid — reported below
    for src in sources.values():
        for sup in src.suppressions:
            if not sup.justification:
                findings.append(Finding(
                    checker=SUPPRESSION_ID, file=src.path,
                    line=sup.comment_line,
                    message="suppression without a justification: write "
                            "'# lint: disable=<id> — <why>' (the why is "
                            "mandatory; this suppression was ignored)"))

    # --- baseline pass
    if use_baseline:
        grandfathered = load_baseline(baseline_path)
        for f in findings:
            if (f.checker, f.rel_file(), f.message) in grandfathered:
                f.baselined = True

    findings.sort(key=lambda f: (f.file, f.line, f.checker, f.message))
    return findings


# --------------------------------------------------------------------- CLI


def lint_main(argv: Optional[Sequence[str]] = None) -> int:
    """``bcfl-tpu lint [PATHS] [--checker ID] [--json] [--no-baseline]
    [--list-checkers] [--write-baseline]`` — exit 0 iff no unsuppressed,
    unbaselined finding exists."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="bcfl-tpu lint",
        description="AST-based static analysis of the repo's concurrency, "
                    "determinism, and telemetry contracts (ANALYSIS.md)")
    ap.add_argument("paths", nargs="*", default=None, metavar="PATH",
                    help="files or directories to lint (default: the "
                         "installed bcfl_tpu package)")
    ap.add_argument("--checker", action="append", default=None,
                    metavar="ID",
                    help="run only this checker (repeatable; default all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout (schema "
                         "version %d)" % JSON_VERSION)
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the committed baseline: every finding "
                         "counts")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: the committed "
                         "bcfl_tpu/analysis/baseline.json)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="print the current unsuppressed findings in "
                         "baseline format (adoption helper) and exit 0")
    ap.add_argument("--list-checkers", action="store_true",
                    help="list checker ids and the contract each enforces")
    args = ap.parse_args(argv)

    from bcfl_tpu.analysis import (  # noqa: F401 — populate the registry
        concurrency,
        determinism,
        telemetry_schema,
        wire_static,
    )

    if args.list_checkers:
        for cid in checker_ids():
            print(f"{cid:18s} {CHECKERS[cid].contract}")
        return 0

    paths = args.paths or [PACKAGE_DIR]
    try:
        findings = run_lint(paths, checker_ids_filter=args.checker,
                            use_baseline=not args.no_baseline,
                            baseline_path=args.baseline)
    except ValueError as e:
        # unknown --checker id, empty path set, unreadable baseline:
        # usage errors, exit 2 — never a silent pass or a raw traceback
        ap.error(str(e))
    failing = [f for f in findings if f.failing]

    if args.write_baseline:
        # every unsuppressed finding, INCLUDING currently-baselined ones:
        # regenerating the baseline must be a superset operation, or
        # redirecting the output over baseline.json would silently drop
        # every already-grandfathered entry
        print(baseline_json([f for f in findings if not f.suppressed]),
              end="")
        return 0

    if args.as_json:
        print(json.dumps({
            "version": JSON_VERSION,
            "checkers": (sorted(args.checker) if args.checker
                         else checker_ids()),
            "findings": [f.to_json() for f in findings],
            "counts": {
                "total": len(findings),
                "suppressed": sum(f.suppressed for f in findings),
                "baselined": sum(f.baselined for f in findings),
                "failing": len(failing),
            },
        }, indent=2))
    else:
        for f in failing:
            print(f.render())
        n_sup = sum(f.suppressed for f in findings)
        n_base = sum(f.baselined for f in findings)
        print(f"bcfl-tpu lint: {len(failing)} finding(s) "
              f"({n_sup} suppressed, {n_base} baselined)")
    return 1 if failing else 0
