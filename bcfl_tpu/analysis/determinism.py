"""Determinism checker for the seeded-draw modules (ANALYSIS.md).

The chaos/robustness stack's central contract is "a fault's fate is a
pure function of its coordinates" (RUNTIME.md §4: a message's fault fate
= f(round-that-produced-it), never worker timing): every chaos draw, every
byzantine behavior, every codec stochastic-rounding uniform comes from an
explicitly seeded stream keyed by (seed, lane, round, ids). Three bug
classes silently break that — and survive every single-process test:

- **wall-clock reads** (``time.time`` / ``time.monotonic``) feeding a
  decision: two runs of the same schedule diverge by host speed,
- **module-level RNG** (stdlib ``random``, ``np.random.<draw>``, or an
  UNSEEDED ``np.random.default_rng()``): a global stream any import can
  perturb, unlike the ``default_rng((seed, lane, ...))`` keyed streams,
- **unsorted dict/set iteration** whose order reaches a seeded draw or a
  digest: CPython insertion order is deterministic per process, but two
  *hosts* constructing the container differently draw RNG in different
  leaf order — a cross-host nondeterminism bug in the lineage records.

Scope (:data:`SEEDED_SCOPE`): the modules whose outputs the determinism
proofs pin. Files outside the bcfl_tpu package are fully in scope (the
fixture workflow). Telemetry/deadline wall-clock uses inside scope are
annotated with the standard suppression
(``# lint: disable=determinism — <why>``).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from bcfl_tpu.analysis.core import Checker, Finding, Source, register

#: package-relative file -> None (whole module in scope) or a tuple of
#: class/function names (only code enclosed by one of those names is in
#: scope). These are the modules whose seeded draws the repo's
#: determinism contracts pin (ROBUSTNESS.md, RUNTIME.md §4):
SEEDED_SCOPE: Dict[str, Optional[Tuple[str, ...]]] = {
    # the chaos schedule itself: every lane's draws
    "faults/plan.py": None,
    # adversarial payload mutations (bit-identical per coordinates)
    "dist/byzantine.py": None,
    # codec stochastic rounding / chunk grids (bit-identical encode pins)
    "compression/codecs.py": None,
    # the codec's Pallas kernels: they consume the precomputed stochastic-
    # rounding uniforms as an input operand (never draw RNG themselves) and
    # their outputs sit under the same bit-identical encode pins — so the
    # whole module is held to the no-wall-clock / no-global-RNG /
    # no-unsorted-iteration contract
    "ops/pallas_codec.py": None,
    # the kernel harness: impl resolution decides WHICH kernel encodes a
    # payload — the decision must be a pure function of (registry, impl,
    # backend), never of host timing or iteration order
    "ops/registry.py": None,
    # robust merge: vote order feeds krum selection + lineage records
    "dist/robust.py": None,
    # evidence aggregation order feeds the committed reputation rows
    "reputation/dist.py": None,
    # the wire + limp chaos lanes' draw seams (the rest of transport.py
    # is wall-clock country: deadlines, backoff, detector probes — the
    # phi estimator MEASURES the live run and is excluded by design)
    "dist/transport.py": ("WireChaos", "LimpChaos"),
    # votes_by_peer construction: peer iteration order reaches the
    # lineage record and the krum-selected-peer translation
    "dist/runtime.py": ("_apply_robust_merge",),
    # gossip's pure seams: the seeded neighbor draw (topology replay),
    # the canonical-order commutative merge, and the state digest — the
    # GossipPeerRuntime class around them is wall-clock country
    # (hello cadence, drain windows, arrival latencies)
    "dist/gossip.py": ("sample_neighbors", "hedge_neighbors",
                       "probe_targets", "merge_states", "state_digest",
                       "_walk_sorted"),
}

_WALLCLOCK = {"time", "monotonic", "time_ns", "monotonic_ns",
              "perf_counter", "perf_counter_ns"}
_NP_NAMES = {"np", "numpy"}
#: iterable-producing wrappers we look through when flagging iteration
_TRANSPARENT = {"enumerate", "list", "tuple", "reversed"}


def _dotted(node: ast.AST) -> Optional[List[str]]:
    """['np', 'random', 'default_rng'] for nested attributes, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _scope_names(src: Source) -> Optional[Tuple[str, ...]]:
    """None = whole file in scope; () = out of scope; else the name
    filter."""
    if src.rel is None:
        return None  # outside the package: fixtures are fully in scope
    if src.rel in SEEDED_SCOPE:
        return SEEDED_SCOPE[src.rel]
    return ()


@register
class DeterminismChecker(Checker):
    id = "determinism"
    contract = ("seeded-draw modules use no wall clock, no module-level "
                "RNG, and no unsorted dict/set iteration (fault fate = "
                "f(coordinates), RUNTIME.md §4)")

    def check(self, src: Source) -> Iterable[Finding]:
        if src.tree is None:
            return ()
        names = _scope_names(src)
        if names == ():
            return ()
        out: List[Finding] = []

        def in_scope_walk(node, enclosed: bool) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                enclosed = enclosed or (names is None
                                        or node.name in names)
                for child in ast.iter_child_nodes(node):
                    in_scope_walk(child, enclosed)
                return
            if enclosed or names is None:
                self._check_node(src, node, out)
            for child in ast.iter_child_nodes(node):
                in_scope_walk(child, enclosed)

        in_scope_walk(src.tree, names is None)
        return out

    # ------------------------------------------------------------- rules

    def _check_node(self, src: Source, node: ast.AST,
                    out: List[Finding]) -> None:
        if isinstance(node, ast.Call):
            self._check_call(src, node, out)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._check_iter(src, node.iter, out)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            for gen in node.generators:
                self._check_iter(src, gen.iter, out)

    def _check_call(self, src: Source, call: ast.Call,
                    out: List[Finding]) -> None:
        dotted = _dotted(call.func)
        if dotted is None:
            return
        if len(dotted) == 2 and dotted[0] == "time" \
                and dotted[1] in _WALLCLOCK:
            out.append(self.finding(
                src, call,
                f"wall-clock read time.{dotted[1]}() in a seeded-draw "
                f"module: a fault's fate must be a pure function of its "
                f"coordinates, never of host timing (suppress with a "
                f"justification for telemetry/deadline uses)"))
            return
        if dotted[0] == "random" and len(dotted) >= 2:
            out.append(self.finding(
                src, call,
                f"stdlib random.{dotted[1]}() uses the process-global RNG "
                f"stream: draw from np.random.default_rng((seed, lane, "
                f"...)) keyed by the fault coordinates instead"))
            return
        if (len(dotted) >= 3 and dotted[0] in _NP_NAMES
                and dotted[1] == "random"):
            if dotted[2] == "default_rng":
                if not call.args and not call.keywords:
                    out.append(self.finding(
                        src, call,
                        "np.random.default_rng() without a seed draws "
                        "from OS entropy: key it by the fault "
                        "coordinates, e.g. default_rng((seed, lane, "
                        "round))"))
                return
            out.append(self.finding(
                src, call,
                f"np.random.{dotted[2]}() uses the module-level global "
                f"RNG: draw from np.random.default_rng((seed, lane, ...)) "
                f"keyed by the fault coordinates instead"))

    def _check_iter(self, src: Source, it: ast.AST,
                    out: List[Finding]) -> None:
        # look through enumerate/list/tuple/reversed wrappers
        inner = it
        while (isinstance(inner, ast.Call)
               and isinstance(inner.func, ast.Name)
               and inner.func.id in _TRANSPARENT and inner.args):
            inner = inner.args[0]
        if (isinstance(inner, ast.Call)
                and isinstance(inner.func, ast.Attribute)
                and inner.func.attr in ("items", "keys", "values")
                and not inner.args):
            what = f".{inner.func.attr}()"
        elif (isinstance(inner, ast.Call)
              and isinstance(inner.func, ast.Name)
              and inner.func.id in ("set", "frozenset")):
            what = "a set"
        elif isinstance(inner, (ast.Set, ast.SetComp)):
            what = "a set"
        else:
            return
        out.append(self.finding(
            src, it,
            f"iteration over {what} without sorted() in a seeded-draw "
            f"module: dict/set order differs across hosts and feeds the "
            f"draw/digest order — wrap in sorted(...)"))
