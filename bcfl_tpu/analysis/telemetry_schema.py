"""Telemetry-schema checker: every literal ``telemetry.emit(<type>, ...)``
names a catalogued event and carries its required fields (ANALYSIS.md).

The event writer validates at runtime — but deliberately NEVER raises: an
unknown type or a missing required field is a counted-and-dropped bad
event (telemetry must not take down the run it observes). The flip side
is that an emit-site typo is invisible until an invariant query finds
nothing to read — the exact failure mode a run-crashing validator would
have caught in the first unit test. This checker closes that gap
statically: the catalogue (:data:`EVENT_TYPES` in
``bcfl_tpu/telemetry/events.py``) is the single source of truth, checked
here at lint time and in the writer at run time, so the two cannot drift.

What is checked, and when:

- the first argument of ``emit``/``emit_sampled`` when it is a string
  literal (dynamic event names are skipped — the runtime counter is the
  only guard there),
- required-field presence when the keyword set is statically complete:
  explicit keywords plus ``**{...}`` dict literals with constant string
  keys count; any other ``**`` expansion makes the field set unknowable
  and skips the field check (the type check still applies).

Receivers matter: only calls through a ``telemetry``/``_telemetry``
binding (module convention across the repo) or a bare imported
``emit``/``emit_sampled`` are checked — ``self.emit(...)`` inside the
writer and ``w.emit(...)`` on explicit writer objects are not emit-seam
call sites.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from bcfl_tpu.analysis.core import Checker, Finding, Source, register
from bcfl_tpu.telemetry.events import EVENT_TYPES

_FUNCS = {"emit": 1, "emit_sampled": 2}  # name -> index of first field arg
_BASES = {"telemetry", "_telemetry"}


def _emit_call(call: ast.Call) -> Optional[str]:
    """'emit'/'emit_sampled' when ``call`` is an emit-seam call site."""
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id in _FUNCS:
        return fn.id
    if isinstance(fn, ast.Attribute) and fn.attr in _FUNCS:
        base = fn.value
        if isinstance(base, ast.Name) and base.id in _BASES:
            return fn.attr
        if isinstance(base, ast.Attribute) and base.attr in _BASES:
            return fn.attr
    return None


def _static_fields(call: ast.Call) -> Optional[Set[str]]:
    """The statically-known keyword field set, or None when a ``**``
    expansion makes it unknowable."""
    fields: Set[str] = set()
    for kw in call.keywords:
        if kw.arg is not None:
            fields.add(kw.arg)
            continue
        # **expr: a dict literal with constant string keys is still
        # statically complete (the `**{"from": ...}` idiom for reserved
        # words); anything else is not
        if isinstance(kw.value, ast.Dict) and all(
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                for k in kw.value.keys):
            fields.update(k.value for k in kw.value.keys)
            continue
        return None
    return fields


@register
class TelemetrySchemaChecker(Checker):
    id = "telemetry-schema"
    contract = ("every literal telemetry.emit(<type>) names an "
                "EVENT_TYPES entry and passes its required fields when "
                "statically visible")

    def check(self, src: Source) -> Iterable[Finding]:
        if src.tree is None:
            return ()
        # the catalogue module itself is the definition site, not a call
        # site population worth checking against itself
        if src.rel == "telemetry/events.py":
            return ()
        out: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _emit_call(node)
            if fname is None:
                continue
            first = _FUNCS[fname]
            if len(node.args) <= first - 1:
                continue
            ev = node.args[0]
            if not (isinstance(ev, ast.Constant)
                    and isinstance(ev.value, str)):
                continue  # dynamic event name: runtime counter's job
            name = ev.value
            if name not in EVENT_TYPES:
                out.append(self.finding(
                    src, node,
                    f"unknown telemetry event type {name!r}: not in "
                    f"EVENT_TYPES (bcfl_tpu/telemetry/events.py) — at "
                    f"runtime this emit is silently counted and DROPPED"))
                continue
            fields = _static_fields(node)
            if fields is None:
                continue  # ** expansion: field set not statically visible
            missing = [k for k in EVENT_TYPES[name] if k not in fields]
            if missing:
                out.append(self.finding(
                    src, node,
                    f"telemetry.emit({name!r}) is missing required "
                    f"field(s) {missing} (EVENT_TYPES) — at runtime this "
                    f"emit is silently counted and DROPPED"))
        return out
