"""Wire-layer static checkers: socket deadlines and frame concatenation
(ANALYSIS.md).

These are the AST re-implementations of the two grep guards that used to
live in ``tests/test_wire_chaos.py`` — same contracts, real resolution:

- **socket-deadline** (RUNTIME.md §7 "nothing can wedge"): every socket
  ``recv`` / ``recv_into`` / ``accept`` / ``connect`` /
  ``create_connection`` call site under ``bcfl_tpu/dist`` must carry a
  visible deadline. The grep version accepted the word "timeout" anywhere
  within a ±3-line text window — a comment three lines away could
  "cover" an unrelated call. This version resolves the actual call: a
  ``timeout``/``timeout_s``/``deadline`` keyword (or a positional
  argument whose expression mentions one), a ``settimeout``/``_budget``
  call in the enclosing function (the streaming reader's budget idiom),
  or an explicit ``# deadline: ...`` pointer on the statement (or the
  line directly above it). It also covers ``recv_into`` — which the
  substring patterns never matched.
- **no-frame-concat** (RUNTIME.md §3, the r11 zero-copy send path): no
  production code may build a full frame payload as one ``bytes`` —
  ``pack_frame`` (the in-memory reference) is only callable from
  ``dist/wire.py`` itself, and nothing under ``bcfl_tpu/dist`` may
  ``b"".join`` a payload. A regression here silently doubles peak
  serialization memory per send (a model-sized copy), exactly what the
  streaming writer (``wire.write_frame``) exists to avoid.

Package scoping: socket-deadline applies under ``dist/``; no-frame-concat
applies package-wide for ``pack_frame`` and under ``dist/`` for
``b"".join``, with ``dist/wire.py`` (the reference implementation) exempt
from both. Files outside the package are fully in scope.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Tuple

from bcfl_tpu.analysis.core import Checker, Finding, Source, register

#: method names that are deadline-bearing socket operations
SOCKET_METHODS = ("accept", "recv", "recv_into", "connect")
#: function names that open a connection (socket.create_connection)
SOCKET_FUNCS = ("create_connection",)

_TIMEOUT_KWARGS = {"timeout", "timeout_s", "deadline", "deadline_s"}
_BUDGET_CALLS = {"settimeout", "_budget"}


def _socket_site(call: ast.Call) -> Optional[str]:
    """The matched operation name when ``call`` is a socket-op call site
    (e.g. 'recv' for ``sock.recv(...)``, 'create_connection' for
    ``socket.create_connection(...)``), else None."""
    fn = call.func
    if isinstance(fn, ast.Attribute):
        if fn.attr in SOCKET_METHODS:
            return fn.attr
        if fn.attr in SOCKET_FUNCS:
            return fn.attr
    if isinstance(fn, ast.Name) and fn.id in SOCKET_FUNCS:
        return fn.id
    return None


def iter_socket_sites(tree: ast.AST) -> List[Tuple[ast.Call, str, Optional[ast.AST]]]:
    """Every socket-op call site in ``tree`` as ``(call, op, enclosing
    function)`` — shared by the checker and the grep-parity test in
    tests/test_analysis.py, so the two cannot drift."""
    parents: Dict[ast.AST, ast.AST] = {}
    for p in ast.walk(tree):
        for ch in ast.iter_child_nodes(p):
            parents[ch] = p
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            op = _socket_site(node)
            if op is None:
                continue
            fn = node
            while fn is not None and not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = parents.get(fn)
            out.append((node, op, fn))
    return out


def _has_deadline_evidence(src: Source, call: ast.Call,
                           enclosing: Optional[ast.AST]) -> bool:
    # (1) an explicit timeout/deadline keyword on the call itself
    for kw in call.keywords:
        if kw.arg in _TIMEOUT_KWARGS:
            return True
    # (2) a positional argument whose expression names a timeout/deadline
    # (e.g. read_frame(conn, self.io_timeout_s))
    for arg in call.args:
        text = ast.unparse(arg)
        if "timeout" in text or "deadline" in text:
            return True
    # (3) the enclosing function budgets the socket: a settimeout(...) or
    # _budget() call anywhere in it (the streaming reader's idiom — the
    # per-chunk recv runs under the budget set just above it)
    if enclosing is not None:
        for node in ast.walk(enclosing):
            if isinstance(node, ast.Call):
                fn = node.func
                name = (fn.attr if isinstance(fn, ast.Attribute)
                        else fn.id if isinstance(fn, ast.Name) else None)
                if name in _BUDGET_CALLS:
                    return True
    # (4) an explicit '# deadline: ...' pointer on the statement's span or
    # the line directly above it (comment-accurate, not substring-in-code)
    start = call.lineno
    end = getattr(call, "end_lineno", call.lineno) or call.lineno
    for line in range(start - 1, end + 1):
        if src.comment_on(line, "deadline:"):
            return True
    return False


@register
class SocketDeadlineChecker(Checker):
    id = "socket-deadline"
    contract = ("every socket recv/recv_into/accept/connect/"
                "create_connection under dist/ carries a visible deadline "
                "(kwarg, enclosing settimeout/_budget, or '# deadline:' "
                "pointer)")

    def check(self, src: Source) -> Iterable[Finding]:
        if src.tree is None:
            return ()
        if src.rel is not None and not src.rel.startswith("dist/"):
            return ()  # package scope: the dist wire layer only
        out: List[Finding] = []
        for call, op, enclosing in iter_socket_sites(src.tree):
            if _has_deadline_evidence(src, call, enclosing):
                continue
            out.append(self.finding(
                src, call,
                f"socket call site .{op}(...) without a visible deadline "
                f"(add a timeout kwarg, a settimeout in the enclosing "
                f"function, or a '# deadline: ...' pointer to where it "
                f"is enforced) — a new call site without one wedges a "
                f"peer in CI, not here"))
        return out


@register
class NoFrameConcatChecker(Checker):
    id = "no-frame-concat"
    contract = ("no pack_frame call outside dist/wire.py; no b\"\".join "
                "under dist/ — full-frame payloads must stream "
                "(wire.write_frame), never concatenate")

    def check(self, src: Source) -> Iterable[Finding]:
        if src.tree is None:
            return ()
        if src.rel == "dist/wire.py":
            return ()  # the in-memory reference implementation lives here
        in_dist = src.rel is None or src.rel.startswith("dist/")
        out: List[Finding] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            name = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if name == "pack_frame":
                out.append(self.finding(
                    src, node,
                    "pack_frame() call outside dist/wire.py: the "
                    "in-memory reference materializes the whole payload "
                    "— production sends must stream via wire.write_frame"))
            elif (in_dist and name == "join"
                  and isinstance(fn, ast.Attribute)
                  and isinstance(fn.value, ast.Constant)
                  and fn.value.value == b""):
                out.append(self.finding(
                    src, node,
                    'b"".join(...) under dist/: a full-frame payload '
                    "concatenation allocates a model-sized copy per send "
                    "— stream via wire.write_frame instead"))
        return out
