from bcfl_tpu.checkpoint.checkpoint import (  # noqa: F401
    ROUND_STATUSES,
    apply_storage_fault,
    classify_round,
    restore_checkpoint,
    restore_latest,
    save_checkpoint,
    scrub,
)
