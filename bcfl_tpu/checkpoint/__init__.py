from bcfl_tpu.checkpoint.checkpoint import save_checkpoint, restore_latest  # noqa: F401
