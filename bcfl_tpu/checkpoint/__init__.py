from bcfl_tpu.checkpoint.checkpoint import (  # noqa: F401
    restore_checkpoint,
    restore_latest,
    save_checkpoint,
)
