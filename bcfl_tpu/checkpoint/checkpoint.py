"""Crash-safe checkpoint/resume via Orbax.

The reference only saves (``global_model.save_pretrained(...)`` every round,
``serverless_NonIID_IMDB.py:305`` — doubling as its model-size probe) and has
no load/resume path at all (SURVEY.md §5). Here a checkpoint is
``(round, param state, ledger json, rng seed)`` and :func:`restore_latest`
actually resumes a run mid-training. The state tree is deliberately open:
the engine also threads the compression error-feedback residual
(COMPRESSION.md) and the peer-lifecycle reputation arrays
(``rep_trust``/``rep_state``/``rep_timer`` + counters, ROBUSTNESS.md §6)
through it, so a resumed run re-enters with every trust score and
quarantine timer exactly where the crash left them — the bit-identical
crash/resume contract covers the lifecycle trajectory, not just the
params.

Crash safety (ROBUSTNESS.md):

- **Atomic commit.** The state tree is written to a dot-prefixed staging
  directory, then renamed into ``round_XXXXXX`` — the single commit point —
  and only then is the integrity metadata (a SHA-256 params digest via
  :func:`bcfl_tpu.ledger.ledger.params_digest`, plus the sidecar ledger
  JSON) fsynced into place. A crash at any instant leaves no ``round_``
  entry at all (staging names are invisible to the scan), a complete tree
  pending metadata (restored, unverified — exactly like a legacy
  checkpoint), or a complete verified one; it can never leave a truncated
  directory that :func:`restore_latest`'s newest-first scan would pick up,
  and never a valid tree paired with a MISMATCHING digest (on re-save the
  stale meta is deleted before the old tree is touched), so the digest
  check can only ever reject genuine corruption.
- **Verified restore.** ``restore_latest`` walks checkpoints newest-first,
  re-derives each candidate's params digest and compares it to the
  committed metadata; a checkpoint that fails to load (truncated by a
  pre-atomic writer, half-deleted, ...) or whose digest mismatches (silent
  bit corruption) is skipped with a warning and the next older valid one
  is restored — the engine resumes from the last GOOD state instead of
  crashing on a partial one.
- **Legacy tolerance.** Checkpoints written before the metadata sidecar
  existed restore as before (no digest to verify, separate
  ``ledger_XXXXXX.json`` file honored).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from bcfl_tpu.telemetry import events as _telemetry

logger = logging.getLogger(__name__)

# staging prefix: never matches the `round_` scan, so an interrupted save is
# invisible to restore_latest until the atomic rename commits it
_STAGING = ".staging."
_META_SUFFIX = ".meta.json"


def _to_host(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _state_digest(state) -> str:
    """Hex SHA-256 over the state tree (leaf names + dtypes + shapes + raw
    bytes) — the ledger's canonical params digest reused as checkpoint
    integrity evidence. Computed on the host copy, so the digest of a
    restored tree reproduces it bit-for-bit."""
    from bcfl_tpu.ledger.ledger import params_digest

    return params_digest(state).hex()


def _fsync_dir(path: str) -> None:
    """Durably record directory-entry changes (renames) — without this the
    atomic rename can itself be lost by a power cut."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platform without directory fds; best effort
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _meta_path(directory: str, round_idx: int) -> str:
    return os.path.join(directory, f"round_{round_idx:06d}{_META_SUFFIX}")


def save_checkpoint(directory: str, round_idx: int, state: Dict[str, Any],
                    ledger_json: Optional[str] = None) -> str:
    """Atomically write ``state`` (a pytree of arrays) for ``round_idx``;
    returns the committed path.

    Commit protocol: stage the orbax tree under a scan-invisible name,
    rename it to ``round_XXXXXX`` (the one atomic commit point), then fsync
    the metadata sidecar (digest + ledger json) into place. Ordering
    invariant: a valid tree may transiently lack metadata (restored
    unverified, like a legacy checkpoint) but is NEVER paired with a
    mismatching digest — on re-save of an existing round the stale meta is
    deleted before the old tree is disturbed, so the digest check rejects
    only genuine corruption."""
    _t0 = time.perf_counter()
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    name = f"round_{round_idx:06d}"
    final = os.path.join(directory, name)
    staging = os.path.join(directory, _STAGING + name)
    if os.path.isdir(staging):  # leftover from an interrupted save
        shutil.rmtree(staging)

    host = _to_host(state)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(staging, host, force=True)

    meta_path = _meta_path(directory, round_idx)
    if os.path.isdir(final):
        # re-save of the same round: retire the old meta FIRST (the old
        # tree degrades to unverified, never digest-mismatched), then the
        # old tree (a crash here falls back to the previous round — the
        # writer was mid-overwrite, so that is the newest consistent state)
        if os.path.exists(meta_path):
            os.unlink(meta_path)
            _fsync_dir(directory)
        shutil.rmtree(final)
    os.replace(staging, final)  # commit point
    _fsync_dir(directory)

    meta = {"round": int(round_idx), "digest": _state_digest(host),
            "ledger": ledger_json}
    meta_staging = os.path.join(directory, _STAGING + name + _META_SUFFIX)
    with open(meta_staging, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(meta_staging, meta_path)
    _fsync_dir(directory)
    # one typed event per committed checkpoint (a no-op without an
    # installed writer): crash/rejoin analysis over the merged timeline
    # needs to know which versions were durable when
    _telemetry.emit("ckpt.save", step=int(round_idx), dir=directory,
                    wall_s=time.perf_counter() - _t0)
    return final


def _read_meta(directory: str, round_idx: int) -> Optional[Dict[str, Any]]:
    path = _meta_path(directory, round_idx)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("checkpoint meta %s unreadable (%s); treating "
                       "checkpoint as legacy/unverified", path, e)
        return None


def restore_checkpoint(directory: str, round_idx: int
                       ) -> Optional[Tuple[Dict[str, Any], Optional[str]]]:
    """``(state, ledger_json)`` of ONE specific committed checkpoint, or
    None if it is absent/unrestorable. Unlike :func:`restore_latest` this
    does not fall back to an older round — it is the forensic read the
    proof harnesses use to compare a specific durable state against what
    a resumed process reports having restored (bit-identical-restore
    gates in scripts/dist_byzantine.py)."""
    directory = os.path.abspath(directory)
    path = os.path.join(directory, f"round_{int(round_idx):06d}")
    if not os.path.isdir(path):
        return None
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            state = ckptr.restore(path)
    except Exception as e:  # truncated/partial tree
        logger.warning("checkpoint %s failed to restore (%s)", path, e)
        return None
    meta = _read_meta(directory, int(round_idx))
    if meta is not None and meta.get("digest"):
        if _state_digest(state) != meta["digest"]:
            # the same integrity bar as restore_latest: ground truth that
            # fails its own committed digest is not ground truth — a
            # bit-identity gate comparing against it would fail (or pass)
            # for the wrong reason
            logger.warning("checkpoint %s params digest mismatch", path)
            return None
    return state, (meta.get("ledger") if meta is not None else None)


def restore_latest(directory: str) -> Optional[Tuple[int, Dict[str, Any], Optional[str]]]:
    """(round, state, ledger_json) of the newest VALID checkpoint, or None.

    Walks checkpoints newest-first; a candidate that fails to restore or
    whose params digest mismatches its committed metadata is skipped (with
    a warning) in favor of the next older one — a half-written or corrupted
    newest checkpoint degrades the resume point by one interval instead of
    killing the run."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    rounds = sorted(
        int(d.split("_")[1]) for d in os.listdir(directory)
        if d.startswith("round_") and d.split("_")[1].isdigit()
        and os.path.isdir(os.path.join(directory, d))
    )
    for r in reversed(rounds):
        path = os.path.join(directory, f"round_{r:06d}")
        try:
            with ocp.PyTreeCheckpointer() as ckptr:
                state = ckptr.restore(path)
        except Exception as e:  # truncated/partial tree: try the next older
            logger.warning("checkpoint %s failed to restore (%s); falling "
                           "back to the previous checkpoint", path, e)
            continue
        meta = _read_meta(directory, r)
        if meta is not None and meta.get("digest"):
            if _state_digest(state) != meta["digest"]:
                logger.warning(
                    "checkpoint %s params digest mismatch (bit corruption "
                    "or foreign overwrite); falling back to the previous "
                    "checkpoint", path)
                continue
        ledger_json = meta.get("ledger") if meta is not None else None
        if ledger_json is None:
            # pre-metadata layout: ledger in its own sidecar file
            legacy = os.path.join(directory, f"ledger_{r:06d}.json")
            if os.path.exists(legacy):
                with open(legacy) as f:
                    ledger_json = f.read()
        _telemetry.emit("ckpt.restore", step=int(r), dir=directory)
        return r, state, ledger_json
    return None
