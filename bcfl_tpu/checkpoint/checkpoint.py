"""Checkpoint/resume via Orbax.

The reference only saves (``global_model.save_pretrained(...)`` every round,
``serverless_NonIID_IMDB.py:305`` — doubling as its model-size probe) and has
no load/resume path at all (SURVEY.md §5). Here a checkpoint is
``(round, param state, ledger json, rng seed)`` and :func:`restore_latest`
actually resumes a run mid-training.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp


def _to_host(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def save_checkpoint(directory: str, round_idx: int, state: Dict[str, Any],
                    ledger_json: Optional[str] = None) -> str:
    """Write ``state`` (a pytree of arrays) for ``round_idx``; returns path."""
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"round_{round_idx:06d}")
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, _to_host(state), force=True)
    if ledger_json is not None:
        with open(os.path.join(directory, f"ledger_{round_idx:06d}.json"), "w") as f:
            f.write(ledger_json)
    return path


def restore_latest(directory: str) -> Optional[Tuple[int, Dict[str, Any], Optional[str]]]:
    """(round, state, ledger_json) of the newest checkpoint, or None."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    rounds = sorted(
        int(d.split("_")[1]) for d in os.listdir(directory)
        if d.startswith("round_") and d.split("_")[1].isdigit()
    )
    if not rounds:
        return None
    r = rounds[-1]
    with ocp.PyTreeCheckpointer() as ckptr:
        state = ckptr.restore(os.path.join(directory, f"round_{r:06d}"))
    ledger_path = os.path.join(directory, f"ledger_{r:06d}.json")
    ledger_json = None
    if os.path.exists(ledger_path):
        with open(ledger_path) as f:
            ledger_json = f.read()
    return r, state, ledger_json
