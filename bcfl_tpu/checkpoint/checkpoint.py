"""Crash-safe checkpoint/resume via Orbax.

The reference only saves (``global_model.save_pretrained(...)`` every round,
``serverless_NonIID_IMDB.py:305`` — doubling as its model-size probe) and has
no load/resume path at all (SURVEY.md §5). Here a checkpoint is
``(round, param state, ledger json, rng seed)`` and :func:`restore_latest`
actually resumes a run mid-training. The state tree is deliberately open:
the engine also threads the compression error-feedback residual
(COMPRESSION.md) and the peer-lifecycle reputation arrays
(``rep_trust``/``rep_state``/``rep_timer`` + counters, ROBUSTNESS.md §6)
through it, so a resumed run re-enters with every trust score and
quarantine timer exactly where the crash left them — the bit-identical
crash/resume contract covers the lifecycle trajectory, not just the
params.

Crash safety (ROBUSTNESS.md):

- **Atomic commit.** The state tree is written to a dot-prefixed staging
  directory, then renamed into ``round_XXXXXX`` — the single commit point —
  and only then is the integrity metadata (a SHA-256 params digest via
  :func:`bcfl_tpu.ledger.ledger.params_digest`, plus the sidecar ledger
  JSON) fsynced into place. A crash at any instant leaves no ``round_``
  entry at all (staging names are invisible to the scan), a complete tree
  pending metadata (restored, unverified — exactly like a legacy
  checkpoint), or a complete verified one; it can never leave a truncated
  directory that :func:`restore_latest`'s newest-first scan would pick up,
  and never a valid tree paired with a MISMATCHING digest (on re-save the
  stale meta is deleted before the old tree is touched), so the digest
  check can only ever reject genuine corruption.
- **Verified restore.** ``restore_latest`` walks checkpoints newest-first,
  re-derives each candidate's params digest and compares it to the
  committed metadata; a checkpoint that fails to load (truncated by a
  pre-atomic writer, half-deleted, ...) or whose digest mismatches (silent
  bit corruption) is skipped with a warning and the next older valid one
  is restored — the engine resumes from the last GOOD state instead of
  crashing on a partial one.
- **Legacy tolerance.** Checkpoints written before the metadata sidecar
  existed restore as before (no digest to verify, separate
  ``ledger_XXXXXX.json`` file honored).
- **One classification API.** :func:`classify_round` is the single reader
  underneath :func:`restore_latest`, :func:`restore_checkpoint` and
  :func:`scrub` — every caller sees the same damage taxonomy
  (:data:`ROUND_STATUSES`), so the forensic view and the resume view can
  never drift apart again (they did once: PR 10's ad-hoc
  ``restore_checkpoint`` returned a different shape).
- **Retention.** ``save_checkpoint(..., keep_last=K)`` garbage-collects
  rounds beyond the newest K strictly AFTER the new round's commit+fsync,
  so a crash mid-GC can only ever leave EXTRA old checkpoints, never zero
  valid ones.
- **Chaos seam.** :func:`apply_storage_fault` is the storage fault lane's
  injection point (FaultPlan ``storage_*``, ROBUSTNESS.md §10): it damages
  committed durable state in one of :data:`~bcfl_tpu.faults.plan.STORAGE_CLASSES`
  deterministic ways. :func:`scrub` is the matching audit a peer runs
  before trusting its own disk.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from bcfl_tpu.telemetry import events as _telemetry

logger = logging.getLogger(__name__)

# staging prefix: never matches the `round_` scan, so an interrupted save is
# invisible to restore_latest until the atomic rename commits it
_STAGING = ".staging."
_META_SUFFIX = ".meta.json"

# the damage taxonomy classify_round reports (ROBUSTNESS.md §10):
#   ok              — restored, params digest verified, ledger chain verifies
#   unverified      — restored, pre-metadata legacy layout (nothing to verify)
#   unrestorable    — the tree itself fails to load (torn/truncated/bit rot
#                     caught by the store)
#   digest_mismatch — the tree loads but its params digest does not match
#                     the committed metadata (silent payload bit rot)
#   meta_corrupt    — a metadata sidecar EXISTS but is unreadable (the
#                     atomic protocol never leaves this; it is damage, not
#                     a legacy checkpoint)
#   ledger_corrupt  — tree + digest fine but the embedded ledger chain no
#                     longer verifies link-by-link (chain tampering)
#   deleted         — the round dir is gone but its metadata survived (the
#                     evidence trail outright deletion leaves behind)
#   missing         — neither dir nor metadata (never committed, or rolled
#                     back — rollback is locally INDISTINGUISHABLE from
#                     "never got that far"; only the chain high-water guard
#                     catches it)
ROUND_STATUSES = ("ok", "unverified", "unrestorable", "digest_mismatch",
                  "meta_corrupt", "ledger_corrupt", "deleted", "missing")

# statuses a resume may trust
_USABLE = ("ok", "unverified")


def _to_host(tree):
    return jax.tree.map(lambda x: np.asarray(x), tree)


def _state_digest(state) -> str:
    """Hex SHA-256 over the state tree (leaf names + dtypes + shapes + raw
    bytes) — the ledger's canonical params digest reused as checkpoint
    integrity evidence. Computed on the host copy, so the digest of a
    restored tree reproduces it bit-for-bit."""
    from bcfl_tpu.ledger.ledger import params_digest

    return params_digest(state).hex()


def _fsync_dir(path: str) -> None:
    """Durably record directory-entry changes (renames) — without this the
    atomic rename can itself be lost by a power cut."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platform without directory fds; best effort
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _meta_path(directory: str, round_idx: int) -> str:
    return os.path.join(directory, f"round_{round_idx:06d}{_META_SUFFIX}")


def save_checkpoint(directory: str, round_idx: int, state: Dict[str, Any],
                    ledger_json: Optional[str] = None,
                    keep_last: int = 0) -> str:
    """Atomically write ``state`` (a pytree of arrays) for ``round_idx``;
    returns the committed path.

    Commit protocol: stage the orbax tree under a scan-invisible name,
    rename it to ``round_XXXXXX`` (the one atomic commit point), then fsync
    the metadata sidecar (digest + ledger json) into place. Ordering
    invariant: a valid tree may transiently lack metadata (restored
    unverified, like a legacy checkpoint) but is NEVER paired with a
    mismatching digest — on re-save of an existing round the stale meta is
    deleted before the old tree is disturbed, so the digest check rejects
    only genuine corruption.

    ``keep_last > 0`` bounds the directory: after the NEW round is fully
    committed and fsynced, rounds beyond the newest ``keep_last`` are
    garbage-collected (dir + metadata + legacy ledger sidecar). The
    ordering means a crash at any point during GC leaves extra OLD
    checkpoints behind, never fewer than ``keep_last`` valid ones — the
    retention knob can not create the zero-valid-checkpoint state the
    atomic commit exists to prevent."""
    _t0 = time.perf_counter()
    directory = os.path.abspath(directory)
    os.makedirs(directory, exist_ok=True)
    name = f"round_{round_idx:06d}"
    final = os.path.join(directory, name)
    staging = os.path.join(directory, _STAGING + name)
    if os.path.isdir(staging):  # leftover from an interrupted save
        shutil.rmtree(staging)

    host = _to_host(state)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(staging, host, force=True)

    meta_path = _meta_path(directory, round_idx)
    if os.path.isdir(final):
        # re-save of the same round: retire the old meta FIRST (the old
        # tree degrades to unverified, never digest-mismatched), then the
        # old tree (a crash here falls back to the previous round — the
        # writer was mid-overwrite, so that is the newest consistent state)
        if os.path.exists(meta_path):
            os.unlink(meta_path)
            _fsync_dir(directory)
        shutil.rmtree(final)
    os.replace(staging, final)  # commit point
    _fsync_dir(directory)

    meta = {"round": int(round_idx), "digest": _state_digest(host),
            "ledger": ledger_json}
    meta_staging = os.path.join(directory, _STAGING + name + _META_SUFFIX)
    with open(meta_staging, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(meta_staging, meta_path)
    _fsync_dir(directory)
    removed = []
    if keep_last and keep_last > 0:
        committed = _list_rounds(directory)
        for r in committed[:-keep_last] if len(committed) > keep_last else []:
            _remove_round(directory, r, keep_meta=False)
            removed.append(r)
        if removed:
            _fsync_dir(directory)
    # one typed event per committed checkpoint (a no-op without an
    # installed writer): crash/rejoin analysis over the merged timeline
    # needs to know which versions were durable when. chain_len (rows in
    # the committed ledger) is what the no_rollback_readmission invariant
    # compares across process incarnations.
    chain_len = None
    if ledger_json:
        try:
            chain_len = len(json.loads(ledger_json))
        except (ValueError, TypeError):
            pass
    _telemetry.emit("ckpt.save", step=int(round_idx), dir=directory,
                    wall_s=time.perf_counter() - _t0, chain_len=chain_len,
                    gc=len(removed))
    return final


def _list_rounds(directory: str) -> list:
    """Committed round indices (dirs only), ascending."""
    if not os.path.isdir(directory):
        return []
    return sorted(
        int(d.split("_")[1]) for d in os.listdir(directory)
        if d.startswith("round_") and d.split("_")[1].isdigit()
        and os.path.isdir(os.path.join(directory, d))
    )


def _meta_rounds(directory: str) -> list:
    """Round indices with a metadata sidecar present, ascending."""
    if not os.path.isdir(directory):
        return []
    out = []
    for f in os.listdir(directory):
        if not (f.startswith("round_") and f.endswith(_META_SUFFIX)):
            continue
        stem = f[:-len(_META_SUFFIX)].split("_")[1]
        if stem.isdigit():
            out.append(int(stem))
    return sorted(out)


def _remove_round(directory: str, round_idx: int, keep_meta: bool) -> None:
    """Remove one committed round (tree + legacy ledger sidecar; metadata
    too unless ``keep_meta``). No fsync — callers batch it."""
    name = f"round_{round_idx:06d}"
    path = os.path.join(directory, name)
    if os.path.isdir(path):
        shutil.rmtree(path)
    if not keep_meta and os.path.exists(_meta_path(directory, round_idx)):
        os.unlink(_meta_path(directory, round_idx))
    legacy = os.path.join(directory, f"ledger_{round_idx:06d}.json")
    if os.path.exists(legacy):
        os.unlink(legacy)


def _read_meta(directory: str, round_idx: int) -> Optional[Dict[str, Any]]:
    path = _meta_path(directory, round_idx)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        logger.warning("checkpoint meta %s unreadable (%s); treating "
                       "checkpoint as legacy/unverified", path, e)
        return None


def classify_round(directory: str, round_idx: int
                   ) -> Tuple[str, Optional[Dict[str, Any]], Optional[str]]:
    """``(status, state, ledger_json)`` for ONE round — the single reader
    behind :func:`restore_latest`, :func:`restore_checkpoint` and
    :func:`scrub`. ``status`` is one of :data:`ROUND_STATUSES`; ``state``
    and ``ledger_json`` are non-None only for the usable statuses
    (``ok``/``unverified``)."""
    directory = os.path.abspath(directory)
    round_idx = int(round_idx)
    path = os.path.join(directory, f"round_{round_idx:06d}")
    meta_path = _meta_path(directory, round_idx)
    if not os.path.isdir(path):
        return (("deleted" if os.path.exists(meta_path) else "missing"),
                None, None)
    meta = None
    if os.path.exists(meta_path):
        meta = _read_meta(directory, round_idx)
        if meta is None:
            # present-but-unreadable: the atomic protocol (staged write +
            # fsync + rename) never leaves this state, so it is damage —
            # NOT the legacy no-sidecar layout the unverified path covers
            return "meta_corrupt", None, None
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            state = ckptr.restore(path)
    except Exception as e:  # truncated/partial tree
        logger.warning("checkpoint %s failed to restore (%s)", path, e)
        return "unrestorable", None, None
    if meta is not None and meta.get("digest"):
        if _state_digest(state) != meta["digest"]:
            logger.warning("checkpoint %s params digest mismatch (bit "
                           "corruption or foreign overwrite)", path)
            return "digest_mismatch", None, None
    ledger_json = meta.get("ledger") if meta is not None else None
    if ledger_json is None:
        # pre-metadata layout: ledger in its own sidecar file
        legacy = os.path.join(directory, f"ledger_{round_idx:06d}.json")
        if os.path.exists(legacy):
            with open(legacy) as f:
                ledger_json = f.read()
    if ledger_json:
        # the chain is durable state too: a checkpoint whose embedded
        # ledger no longer verifies link-by-link must not be resumed from
        # (a peer re-announcing a tampered chain would poison every
        # reconcile it participates in)
        from bcfl_tpu.ledger.ledger import Ledger

        try:
            if Ledger.from_json(ledger_json).verify_chain() != -1:
                logger.warning("checkpoint %s ledger chain fails "
                               "verification", path)
                return "ledger_corrupt", None, None
        except (ValueError, KeyError, TypeError) as e:
            logger.warning("checkpoint %s ledger json unreadable (%s)",
                           path, e)
            return "ledger_corrupt", None, None
    return ("ok" if meta is not None else "unverified"), state, ledger_json


def restore_checkpoint(directory: str, round_idx: int
                       ) -> Optional[Tuple[int, Dict[str, Any], Optional[str]]]:
    """``(round, state, ledger_json)`` of ONE specific committed checkpoint
    — the same shape :func:`restore_latest` returns — or None if it is
    absent or damaged. Unlike ``restore_latest`` this does not fall back to
    an older round: it is the forensic read the proof harnesses use to
    compare a specific durable state against what a resumed process reports
    having restored (bit-identical-restore gates in
    scripts/dist_byzantine.py)."""
    status, state, ledger_json = classify_round(directory, round_idx)
    if status not in _USABLE:
        logger.warning("checkpoint %s/round_%06d not restorable: %s",
                       directory, int(round_idx), status)
        return None
    return int(round_idx), state, ledger_json


def restore_latest(directory: str) -> Optional[Tuple[int, Dict[str, Any], Optional[str]]]:
    """(round, state, ledger_json) of the newest VALID checkpoint, or None.

    Walks checkpoints newest-first via :func:`classify_round`; a candidate
    that fails to restore, whose params digest mismatches its committed
    metadata, or whose embedded ledger chain fails verification is skipped
    (with a warning) in favor of the next older one — a half-written or
    corrupted newest checkpoint degrades the resume point by one interval
    instead of killing the run."""
    directory = os.path.abspath(directory)
    if not os.path.isdir(directory):
        return None
    for r in reversed(_list_rounds(directory)):
        status, state, ledger_json = classify_round(directory, r)
        if status in _USABLE:
            _telemetry.emit("ckpt.restore", step=int(r), dir=directory)
            return r, state, ledger_json
        logger.warning("checkpoint %s/round_%06d %s; falling back to the "
                       "previous checkpoint", directory, r, status)
    return None


def scrub(directory: str) -> Dict[str, Any]:
    """Audit EVERY round of a peer's durable state before trusting it —
    the startup half of the storage fault lane (ROBUSTNESS.md §10).

    Returns::

        {"empty":         no committed rounds, no metadata, no staging,
         "rounds":        ((round, status), ...) ascending, the union of
                          dir-listed and metadata-listed rounds,
         "newest_intact": newest usable round index or None,
         "damaged":       ((round, status), ...) for non-usable statuses,
         "torn":          (staging entry names, ...) — interrupted commits
                          left on disk}

    and emits one ``scrub`` telemetry event summarising the verdict
    (``clean`` / ``damaged`` / ``empty``). Note what scrub can NOT see:
    a clean rollback (newest rounds removed dir+meta) classifies as
    ``missing``/absent — locally indistinguishable from "never got that
    far". That detection belongs to the chain high-water guard in the
    dist runtime, which is why ``no_rollback_readmission`` is an
    invariant over the merged timeline rather than a scrub status."""
    directory = os.path.abspath(directory)
    torn = tuple(sorted(
        d for d in (os.listdir(directory) if os.path.isdir(directory) else ())
        if d.startswith(_STAGING)))
    rounds = sorted(set(_list_rounds(directory)) | set(_meta_rounds(directory)))
    statuses = tuple((r, classify_round(directory, r)[0]) for r in rounds)
    damaged = tuple((r, s) for r, s in statuses if s not in _USABLE)
    usable = [r for r, s in statuses if s in _USABLE]
    report = {
        "empty": not statuses and not torn,
        "rounds": statuses,
        "newest_intact": max(usable) if usable else None,
        "damaged": damaged,
        "torn": torn,
    }
    verdict = ("empty" if report["empty"]
               else "damaged" if (damaged or torn) else "clean")
    _telemetry.emit("scrub", status=verdict, dir=directory,
                    newest_intact=report["newest_intact"],
                    damaged=len(damaged), torn=len(torn))
    return report


_HEX = "0123456789abcdef"


def _rot_hex(ch: str) -> str:
    """A DIFFERENT hex digit, deterministically (bit rot that always
    changes the value)."""
    return _HEX[(_HEX.index(ch.lower()) + 1) % 16]


def _tree_files(path: str) -> list:
    """Every file under a committed round dir, largest first (name-ordered
    within a size tie) — the deterministic target order the flip/truncate
    damage classes index into."""
    out = []
    for root, _dirs, files in os.walk(path):
        for fn in files:
            p = os.path.join(root, fn)
            out.append((-os.path.getsize(p), os.path.relpath(p, path), p))
    return [p for _sz, _rel, p in sorted(out)]


def apply_storage_fault(directory: str, action: Dict[str, Any]
                        ) -> Optional[Dict[str, Any]]:
    """Damage committed durable state per one FaultPlan storage draw
    (``FaultPlan.storage_action``) — the injection half of the storage
    fault lane. ``action`` is ``{"cls", "frac", "delete_last"}``; the
    damage targets the NEWEST committed round (plus older ones for
    delete/rollback). Returns a record of what was done (for the ``chaos``
    telemetry event) or None when there was nothing to damage — the lane
    models media failure of state that EXISTS, never a failure to write.

    Class semantics (see STORAGE_CLASSES in bcfl_tpu.faults.plan):
    ``delete`` removes round dirs but LEAVES the metadata sidecars — the
    evidence trail real deletion tends to leave; ``rollback`` removes the
    newest round dir AND metadata cleanly, leaving an older intact
    snapshot as the apparent newest — locally undetectable by design."""
    directory = os.path.abspath(directory)
    rounds = _list_rounds(directory)
    if not rounds:
        return None
    cls = action["cls"]
    frac = float(action.get("frac", 0.0))
    newest = rounds[-1]
    name = f"round_{newest:06d}"
    path = os.path.join(directory, name)
    meta_path = _meta_path(directory, newest)
    record: Dict[str, Any] = {"cls": cls, "round": int(newest)}

    if cls == "torn":
        # re-create the interrupted-commit state: tree back under a
        # scan-invisible staging name, no committed dir, no metadata
        staging = os.path.join(directory, _STAGING + name)
        if os.path.isdir(staging):
            shutil.rmtree(staging)
        if os.path.exists(meta_path):
            os.unlink(meta_path)
        os.replace(path, staging)
    elif cls in ("payload_flip", "truncate"):
        files = [f for f in _tree_files(path) if os.path.getsize(f) > 0]
        if not files:
            return None
        target = files[0]
        size = os.path.getsize(target)
        offset = min(int(frac * size), size - 1)
        record["file"] = os.path.relpath(target, directory)
        record["offset"] = offset
        if cls == "payload_flip":
            with open(target, "r+b") as f:
                f.seek(offset)
                b = f.read(1)
                f.seek(offset)
                f.write(bytes([b[0] ^ 0xFF]))
        else:
            with open(target, "r+b") as f:
                f.truncate(offset)
    elif cls == "meta_flip":
        # target the newest round that HAS a sidecar — the newest dir may
        # transiently lack one (kill landed inside the commit window)
        metas = _meta_rounds(directory)
        if not metas:
            return None
        record["round"] = int(metas[-1])
        meta_path = _meta_path(directory, metas[-1])
        # rot one hex digit of the committed params digest: the smallest
        # metadata bit flip that is GUARANTEED detectable (a flip landing
        # in json whitespace would be a silent no-op the soak's
        # every-class-fired gate could not count)
        with open(meta_path, "rb") as f:
            raw = bytearray(f.read())
        tag = b'"digest": "'
        idx = raw.find(tag)
        if idx < 0:
            return None
        pos = idx + len(tag) + min(int(frac * 64), 63)
        raw[pos] = ord(_rot_hex(chr(raw[pos])))
        record["offset"] = pos
        with open(meta_path, "wb") as f:
            f.write(raw)
    elif cls == "ledger":
        metas = _meta_rounds(directory)
        if not metas:
            return None
        newest = metas[-1]
        record["round"] = int(newest)
        meta_path = _meta_path(directory, newest)
        meta = _read_meta(directory, newest)
        if not meta or not meta.get("ledger"):
            return None
        try:
            rows = json.loads(meta["ledger"])
        except (ValueError, TypeError):
            return None
        if not rows:
            return None
        row = rows[min(int(frac * len(rows)), len(rows) - 1)]
        row["head"] = _rot_hex(row["head"][0]) + row["head"][1:]
        meta["ledger"] = json.dumps(rows)
        record["row"] = min(int(frac * len(rows)), len(rows) - 1)
        with open(meta_path, "w") as f:
            json.dump(meta, f)
    elif cls == "delete":
        k = max(1, int(action.get("delete_last", 1)))
        victims = rounds[-k:]
        for r in victims:
            # keep_meta: deletion leaves the sidecars — the evidence scrub
            # classifies as "deleted" (vs rollback, which sweeps both)
            p = os.path.join(directory, f"round_{r:06d}")
            if os.path.isdir(p):
                shutil.rmtree(p)
        record["rounds"] = [int(r) for r in victims]
    elif cls == "rollback":
        _remove_round(directory, newest, keep_meta=False)
        record["now_newest"] = int(rounds[-2]) if len(rounds) > 1 else None
    else:
        raise ValueError(f"unknown storage damage class {cls!r}")
    _fsync_dir(directory)
    return record
