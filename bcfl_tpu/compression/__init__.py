"""Communication compression for update exchange (COMPRESSION.md).

In-graph, jit-compatible codecs for client update deltas — int8 per-chunk
quantization with stochastic rounding, top-k sparsification, and their
composition — with error-feedback residuals carried in the engine round
state, payload fingerprinting for the ledger, and bytes-on-wire accounting.
"""

from bcfl_tpu.compression.codecs import (
    KERNEL_IMPLS,
    KINDS,
    CompressionConfig,
    codec_key,
    corrupt_payload,
    decode_tree,
    encode_tree,
    payload_nbytes,
    roundtrip,
    wire_format,
    zero_residual,
)

__all__ = [
    "KERNEL_IMPLS",
    "KINDS",
    "CompressionConfig",
    "codec_key",
    "corrupt_payload",
    "decode_tree",
    "encode_tree",
    "payload_nbytes",
    "roundtrip",
    "wire_format",
    "zero_residual",
]
