"""In-graph communication codecs for client update deltas.

The paper's title promises *communication-efficient* P2P federated LLMs, but
until this module every round program exchanged full-precision update trees.
Here the quantity that crosses the simulated wire is a compressed encoding of
each client's **delta** (post-train params minus the round's reference
params, a quantity both endpoints can reconstruct against), with the
compression error carried forward in an **error-feedback residual** so it
never accumulates (Seide et al. 2014; Karimireddy et al. 2019 — the SNIPPETS
top-k/error-feedback exemplars implement the same scheme host-side; here it
is jit-compatible global-array math compiled INTO the GSPMD round programs).

Codecs (``CompressionConfig.kind``):

- ``int8`` — linear int8 quantization with per-chunk float32 scales
  (``chunk`` elements share one ``max|x|/127`` scale) and optional
  **stochastic rounding** (``floor(x/s + u)``, ``u ~ U[0,1)`` — unbiased, so
  quantization noise averages out across clients/rounds instead of biasing
  the aggregate). ~4x smaller than float32.
- ``topk`` — per-leaf magnitude top-k sparsification: keep the
  ``ceil(topk_frac * N)`` largest-|x| coordinates as (value, index) pairs.
  The dropped mass goes into the error-feedback residual and is transmitted
  in a later round once it grows large enough to make the cut.
- ``int8+topk`` — top-k first, then int8-quantize the surviving values:
  roughly ``(1 + 4) * k`` bytes per leaf vs ``4 * N`` raw.

All codec math is shape-static (chunk counts and k are Python ints derived
from leaf shapes at trace time), so a codec compiles into the round program
once and never retraces across rounds. Payload trees keep a leading global
client dim ``[C, ...]`` on every part, which makes them directly
fingerprintable by :func:`bcfl_tpu.ledger.fingerprint.client_fingerprint`
(the ledger chains digests of the COMPRESSED payload — auth covers what was
actually transmitted) and transport-corruptible by the fault plan
(:func:`corrupt_payload` perturbs the float parts; integer parts stay, so a
scheduled corruption is never silently widened into undefined int casts).

Bytes-on-wire accounting (:func:`payload_nbytes`) is host-side arithmetic
over leaf shapes — no device transfer — and feeds the per-round
``RoundRecord.bytes_on_wire`` metrics and the topology comms model.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

# importing pallas_codec registers the codec kernel ops (int8_quantize,
# topk_select, int8_dequant, topk_scatter) with the kernel harness
from bcfl_tpu.ops import pallas_codec  # noqa: F401
from bcfl_tpu.ops import registry

Tree = Any

KINDS = ("none", "int8", "topk", "int8+topk")

#: kernel impl selection for the codec hot loop (PERF.md "Custom kernels"):
#: "auto" = Pallas on TPU / XLA elsewhere, or force either. Every impl
#: produces byte-identical payloads (the registry's declared parity for
#: the codec ops), so this NEVER appears in :func:`wire_format`.
KERNEL_IMPLS = registry.IMPLS

# fold_in tag separating the codec's stochastic-rounding stream from the
# training dropout stream derived from the same per-round key
_CODEC_LANE = 0x51F7


@dataclasses.dataclass(frozen=True)
class CompressionConfig:
    """Build-time static codec parameters. Frozen/hashable so it lives inside
    :class:`bcfl_tpu.config.FedConfig` and keys the compiled-program cache
    (`fed.client_step._PROGRAM_CACHE`) — two configs that differ in any field
    get distinct round programs, never a silent cross-codec program reuse."""

    kind: str = "none"  # none | int8 | topk | int8+topk
    # int8: elements per quantization chunk (one f32 scale per chunk)
    chunk: int = 256
    # topk: fraction of each leaf's coordinates kept (>= 1 element per leaf)
    topk_frac: float = 0.05
    # unbiased stochastic rounding for int8 (deterministic per (round, seed))
    stochastic: bool = True
    # carry the per-client compression error into the next round's encode
    error_feedback: bool = True
    # codec kernel impl: "auto" (Pallas on TPU, XLA elsewhere), "xla", or
    # "pallas" (interpret mode off-TPU). Payload bytes are identical under
    # every value — deliberately NOT part of wire_format(), so a resume
    # may switch impls freely
    kernel_impl: str = "auto"

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown compression kind {self.kind!r} "
                             f"(one of {KINDS})")
        if self.chunk < 1:
            raise ValueError(f"chunk must be >= 1, got {self.chunk}")
        if not 0.0 < self.topk_frac <= 1.0:
            raise ValueError(
                f"topk_frac must be in (0, 1], got {self.topk_frac}")
        if self.kernel_impl not in KERNEL_IMPLS:
            raise ValueError(
                f"unknown kernel_impl {self.kernel_impl!r} "
                f"(one of {KERNEL_IMPLS})")

    @property
    def enabled(self) -> bool:
        return self.kind != "none"


# --------------------------------------------------------------------- leaves


def _path_name(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "name", p)))
                    for p in path)


def _leaf_k(comp: CompressionConfig, n: int) -> int:
    return max(1, int(math.ceil(comp.topk_frac * n)))


def _int8_parts(y: jnp.ndarray, chunk: int, key,
                stochastic: bool) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[C, N] f32 -> (q int8 [C, M, chunk], scale f32 [C, M])."""
    C, N = y.shape
    pad = (-N) % chunk
    if pad:
        y = jnp.pad(y, ((0, 0), (0, pad)))
    M = (N + pad) // chunk
    y = y.reshape(C, M, chunk)
    scale = jnp.max(jnp.abs(y), axis=-1) / 127.0  # [C, M]
    z = y / jnp.maximum(scale, 1e-30)[..., None]
    if stochastic:
        # floor(z + u) is unbiased: E[q] = z for u ~ U[0, 1)
        z = jnp.floor(z + jax.random.uniform(key, z.shape))
    else:
        z = jnp.round(z)
    q = jnp.clip(z, -127.0, 127.0).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _topk_parts(y: jnp.ndarray, k: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[C, N] f32 -> (val f32 [C, k], idx int32 [C, k]) by |value|."""
    _, idx = jax.lax.top_k(jnp.abs(y), k)
    val = jnp.take_along_axis(y, idx, axis=1)
    return val, idx.astype(jnp.int32)


def _encode_leaf(comp: CompressionConfig, y: jnp.ndarray, key) -> dict:
    """[C, N] f32 -> payload part dict (all parts lead with C)."""
    n = y.shape[1]
    if comp.kind == "int8":
        q, s = _int8_parts(y, comp.chunk, key, comp.stochastic)
        return {"q": q, "s": s}
    if comp.kind == "topk":
        val, idx = _topk_parts(y, _leaf_k(comp, n))
        return {"v": val, "i": idx}
    if comp.kind == "int8+topk":
        k = _leaf_k(comp, n)
        val, idx = _topk_parts(y, k)
        q, s = _int8_parts(val, min(comp.chunk, k), key, comp.stochastic)
        return {"q": q, "s": s, "i": idx}
    raise ValueError(f"unknown compression kind {comp.kind!r}")


def _decode_leaf(comp: CompressionConfig, part: dict, n: int) -> jnp.ndarray:
    """payload part -> [C, N] f32. Decode selection goes through the same
    kernel registry (``int8_dequant`` / ``topk_scatter`` are registered
    XLA-only, so any ``kernel_impl`` degrades to the reference — "reject
    nothing")."""
    if comp.kind == "int8":
        return _run_op("int8_dequant", comp.kernel_impl,
                       part["q"], part["s"], n=n)
    if comp.kind == "topk":
        return _run_op("topk_scatter", comp.kernel_impl,
                       part["v"], part["i"], n=n)
    if comp.kind == "int8+topk":
        k = part["i"].shape[1]
        val = _run_op("int8_dequant", comp.kernel_impl,
                      part["q"], part["s"], n=k)
        return _run_op("topk_scatter", comp.kernel_impl,
                       val, part["i"], n=n)
    raise ValueError(f"unknown compression kind {comp.kind!r}")


# ---------------------------------------------------------------------- trees


def codec_key(stacked_keys) -> jax.Array:
    """Derive the codec's stochastic-rounding key from a round's stacked
    per-client training keys ([C] typed keys): one fold_in off client 0's
    key, on a lane the training stream never uses — deterministic per round,
    identical on the per-round and fused paths (both receive the same
    per-round key rows)."""
    return jax.random.fold_in(stacked_keys[0], _CODEC_LANE)


def encode_tree_unfused(comp: CompressionConfig, delta: Tree, key) -> dict:
    """Per-leaf reference encoder: one generic quantize/top-k lowering per
    leaf. Kept as the bit-identity oracle for the fused path below
    (tests/test_compression.py pins fused == unfused); the production
    entrypoint is :func:`encode_tree`."""
    flat = jax.tree_util.tree_flatten_with_path(delta)[0]
    if not flat:
        raise ValueError("cannot encode an empty tree")
    out = {}
    for i, (path, x) in enumerate(flat):
        C = x.shape[0]
        y = x.reshape(C, -1).astype(jnp.float32)
        out[_path_name(path)] = _encode_leaf(
            comp, y, jax.random.fold_in(key, i))
    return out


def _run_op(name: str, impl: str, *args, **kwargs):
    """Resolve a codec kernel op through the harness and run it. A Pallas
    impl that declines the shape (``NotImplementedError`` — e.g. a top-k
    row wider than the single-block VMEM budget) degrades to the XLA
    reference for that group: the declared parity is bit-identical, so the
    fallback is invisible on the wire."""
    fn, resolved = registry.resolve(name, impl)
    if resolved == "pallas":
        try:
            return fn(*args, **kwargs)
        except NotImplementedError:
            return registry.get_op(name).xla(*args, **kwargs)
    return fn(*args, **kwargs)


def _int8_parts_batched(ys, keys, chunk: int, stochastic: bool,
                        impl: str = "xla"):
    """Fused int8 quantize over several [C, N_i] leaves sharing one chunk
    size: each leaf is padded to its chunk grid exactly as
    :func:`_int8_parts` would, the grids are CONCATENATED along the chunk
    axis, and the scale/divide/round/clip/cast pipeline runs ONCE over the
    union — per-chunk groupings (and the per-leaf stochastic-rounding
    uniforms, drawn under each leaf's own fold_in key) are unchanged, so
    the split-back parts are bit-identical to the per-leaf encode.

    The quantize pipeline itself runs through the kernel registry
    (``int8_quantize``: XLA reference or the fused-VMEM-pass Pallas kernel
    of :mod:`bcfl_tpu.ops.pallas_codec`, selected by ``impl``). The
    stochastic-rounding uniforms are ALWAYS drawn here, outside the
    kernel, under each leaf's own key — the kernel receives them as an
    operand, so impl selection never touches the draw stream.

    Returns [(q, scale)] in input order."""
    grids, Ms = [], []
    for y in ys:
        C, N = y.shape
        pad = (-N) % chunk
        if pad:
            y = jnp.pad(y, ((0, 0), (0, pad)))
        M = (N + pad) // chunk
        grids.append(y.reshape(C, M, chunk))
        Ms.append(M)
    g = jnp.concatenate(grids, axis=1)  # [C, sum(M), chunk]
    u = None
    if stochastic:
        # per-leaf uniforms under each leaf's own key (the identity with
        # the unfused path), concatenated along the same chunk axis
        u = jnp.concatenate(
            [jax.random.uniform(k, grid.shape)
             for k, grid in zip(keys, grids)], axis=1)
    q, scale = _run_op("int8_quantize", impl, g, u, stochastic=stochastic)
    out, off = [], 0
    for M in Ms:
        out.append((q[:, off:off + M], scale[:, off:off + M]
                    .astype(jnp.float32)))
        off += M
    return out


def _topk_parts_batched(ys, k: int, impl: str = "xla"):
    """Fused top-k over several [C, N] leaves of ONE flattened width:
    stacked to [L*C, N], a single magnitude-select sorts every row — the
    selection is row-independent, so each leaf's (val, idx) rows are
    bit-identical to its standalone call. The select runs through the
    kernel registry (``topk_select``: ``lax.top_k`` reference or the
    row-blocked Pallas kernel, which reproduces lax.top_k's tie-breaking
    exactly). Returns [(val, idx)] in input order."""
    L = len(ys)
    C, N = ys[0].shape
    stacked = jnp.concatenate(ys, axis=0)  # [L*C, N]
    val, idx = _run_op("topk_select", impl, stacked, k=k)
    return [(val[i * C:(i + 1) * C], idx[i * C:(i + 1) * C])
            for i in range(L)]


def encode_tree(comp: CompressionConfig, delta: Tree, key) -> dict:
    """Stacked [C, ...] f32 delta tree -> payload dict keyed by leaf path.

    The payload is a plain pytree (dict of dicts of arrays), so it flows
    through jit/scan, shards on the client axis, fingerprints via
    ``client_fingerprint``, and device_gets like any other tree.

    FUSED dispatch (the comms hot path): instead of lowering one generic
    quantize / top-k per leaf, leaves are grouped — every leaf joins ONE
    concatenated int8 chunk-grid quantize, and leaves sharing a flattened
    width share ONE stacked ``lax.top_k`` (a transformer's N identical
    layers collapse to one call per distinct shape). The math is arranged
    so every per-leaf part is BIT-IDENTICAL to the per-leaf reference
    encode (:func:`encode_tree_unfused` — chunk groupings, per-leaf
    stochastic-rounding keys, and top-k row independence are all
    preserved), so ledger digests, wire frames, and checkpointed
    error-feedback state are unchanged. All shapes stay trace-time static:
    zero per-round retraces, pinned in tests/test_compression.py."""
    flat = jax.tree_util.tree_flatten_with_path(delta)[0]
    if not flat:
        raise ValueError("cannot encode an empty tree")
    paths, ys, keys = [], [], []
    for i, (path, x) in enumerate(flat):
        C = x.shape[0]
        paths.append(_path_name(path))
        ys.append(x.reshape(C, -1).astype(jnp.float32))
        keys.append(jax.random.fold_in(key, i))
    out: dict = {}
    if comp.kind in ("topk", "int8+topk"):
        # group by flattened width (same n => same k => stackable rows)
        by_n: dict = {}
        for i, y in enumerate(ys):
            by_n.setdefault(y.shape[1], []).append(i)
        vals = [None] * len(ys)
        idxs = [None] * len(ys)
        # sorted: group processing order must be a function of the leaf
        # WIDTHS, not of flatten insertion order — results land by leaf
        # index either way, but the trace/draw order stays host-invariant
        for n, group in sorted(by_n.items()):
            parts = _topk_parts_batched([ys[i] for i in group],
                                        _leaf_k(comp, n),
                                        impl=comp.kernel_impl)
            for i, (v, ix) in zip(group, parts):
                vals[i], idxs[i] = v, ix
        if comp.kind == "topk":
            for p, v, ix in zip(paths, vals, idxs):
                out[p] = {"v": v, "i": ix}
            return out
        # int8+topk: quantize the surviving values, fused per chunk size
        # min(chunk, k) — leaves sharing a width share a k, hence a grid
        by_ck: dict = {}
        for i, v in enumerate(vals):
            by_ck.setdefault(min(comp.chunk, v.shape[1]), []).append(i)
        for ck, group in sorted(by_ck.items()):  # same order contract
            parts = _int8_parts_batched(
                [vals[i] for i in group], [keys[i] for i in group],
                ck, comp.stochastic, impl=comp.kernel_impl)
            for i, (q, s) in zip(group, parts):
                out[paths[i]] = {"q": q, "s": s, "i": idxs[i]}
        return out
    if comp.kind == "int8":
        parts = _int8_parts_batched(ys, keys, comp.chunk, comp.stochastic,
                                    impl=comp.kernel_impl)
        for p, (q, s) in zip(paths, parts):
            out[p] = {"q": q, "s": s}
        return out
    raise ValueError(f"unknown compression kind {comp.kind!r}")


def decode_tree(comp: CompressionConfig, payload: dict, like: Tree) -> Tree:
    """payload -> stacked f32 delta tree shaped like ``like`` ([C, ...])."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for path, x in flat:
        part = payload[_path_name(path)]
        C = x.shape[0]
        n = 1
        for d in x.shape[1:]:
            n *= d
        leaves.append(_decode_leaf(comp, part, n).reshape(x.shape))
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(like), leaves)


def roundtrip(comp: CompressionConfig, delta: Tree, resid: Tree,
              key) -> Tuple[dict, Tree, Tree]:
    """One wire exchange with error feedback: compensate the delta with the
    carried residual, encode, decode, and return what each side sees.

    Returns ``(payload, decoded, resid')`` — ``payload`` is what crosses the
    wire (and what the ledger fingerprints), ``decoded`` [C, ...] f32 is the
    receiver's reconstruction, ``resid' = (delta + resid) - decoded`` is the
    sender-side error the NEXT round's encode re-injects (zeros when
    ``error_feedback`` is off, so the carried state keeps one stable shape
    across both settings)."""
    if comp.error_feedback:
        comp_in = jax.tree.map(
            lambda d, r: d.astype(jnp.float32) + r, delta, resid)
    else:
        comp_in = jax.tree.map(lambda d: d.astype(jnp.float32), delta)
    payload = encode_tree(comp, comp_in, key)
    decoded = decode_tree(comp, payload, comp_in)
    if comp.error_feedback:
        resid = jax.tree.map(jnp.subtract, comp_in, decoded)
    else:
        resid = jax.tree.map(jnp.zeros_like, resid)
    return payload, decoded, resid


def zero_residual(trainable: Tree, num_clients: int) -> Tree:
    """Fresh [C, ...] f32 error-feedback state for an (unstacked) trainable
    template."""
    return jax.tree.map(
        lambda x: jnp.zeros((num_clients,) + x.shape, jnp.float32), trainable)


def corrupt_payload(payload: dict, scales: jnp.ndarray) -> dict:
    """Transport corruption of a compressed payload: add the per-client
    scale to every FLOAT part (quantization scales / top-k values). Integer
    parts (int8 codes, indices) are left alone — adding 1e6 through an int
    cast would be an undefined-overflow no-op rather than the fault plan's
    'exact float perturbation, never silent' contract. Every codec has at
    least one float part per leaf, so a scheduled corruption always lands
    (and always moves the payload fingerprint)."""
    return jax.tree.map(
        lambda x: x + scales.reshape(
            (-1,) + (1,) * (x.ndim - 1)).astype(x.dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, payload)


def wire_format(comp: Optional["CompressionConfig"]) -> str:
    """Canonical identity string of the bytes this codec puts on the wire.

    Recorded in checkpoints (like the resolved PRNG impl name) so resume can
    REFUSE a codec change: a compressed run resumed under a different codec
    would silently re-inject the checkpointed error-feedback residual into
    the wrong encode (shapes match, semantics don't), and resuming
    uncompressed would silently drop the residual entirely.

    Only the fields the kind actually CONSUMES are part of the identity —
    a pure-topk run resumed with a different int8 chunk size has an
    unchanged encode, and refusing it would block a legitimate resume.
    ``kernel_impl`` is deliberately EXCLUDED: every impl's payload is
    byte-identical (the registry's bit-identical parity contract for the
    codec ops), so resuming a TPU run on CPU — or forcing the Pallas
    kernels mid-run — is always legitimate."""
    if comp is None or not comp.enabled:
        return "none"
    parts = [comp.kind]
    if comp.kind in ("int8", "int8+topk"):
        parts.append(f"chunk={comp.chunk}")
        parts.append(f"stochastic={int(comp.stochastic)}")
    if comp.kind in ("topk", "int8+topk"):
        parts.append(f"topk={comp.topk_frac}")
    parts.append(f"ef={int(comp.error_feedback)}")
    return ":".join(parts)


# ----------------------------------------------------------------- accounting


def payload_nbytes(comp: Optional[CompressionConfig], template: Tree) -> int:
    """Bytes ONE client ships per round for this codec, from leaf shapes
    alone (no device transfer). ``template`` is the unstacked trainable tree
    (or anything with its shapes/dtypes). ``None``/``kind='none'`` = the raw
    full-precision tree."""
    total = 0
    for leaf in jax.tree.leaves(template):
        n = int(leaf.size) if hasattr(leaf, "size") else 1
        if comp is None or not comp.enabled:
            total += n * jnp.dtype(leaf.dtype).itemsize
        elif comp.kind == "int8":
            m = -(-n // comp.chunk)  # ceil
            total += m * comp.chunk * 1 + m * 4
        elif comp.kind == "topk":
            total += _leaf_k(comp, n) * (4 + 4)
        elif comp.kind == "int8+topk":
            k = _leaf_k(comp, n)
            ck = min(comp.chunk, k)
            m = -(-k // ck)
            total += m * ck * 1 + m * 4 + k * 4
    return total
