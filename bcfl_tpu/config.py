"""Single configuration surface for the whole framework.

The reference has no config system: its configuration space is 11 near-copy
scripts whose deltas are module-level constants (``CHECKPOINT``,
``NUM_CLIENTS``, ``NUM_ROUNDS``, ``DEVICE``, dataset + column names, partition
arithmetic) — see SURVEY.md §2.1 for the per-file matrix. Here that space is
one frozen dataclass; the 11 scripts become presets in
:mod:`bcfl_tpu.entrypoints.presets`.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from bcfl_tpu.compression import CompressionConfig
from bcfl_tpu.faults import FaultPlan
from bcfl_tpu.reputation import ReputationConfig


@dataclasses.dataclass(frozen=True)
class PartitionConfig:
    """How each client selects its local train/test subset.

    ``iid``: every client draws ``iid_samples`` random examples
    (reference: ``random.sample(range(len(ds)), 100)``,
    ``src/Serverlesscase/serverless_IID_IMDB.py:60-65``), optionally a fresh
    resample each round (``resample_each_round``, reference behaviour at
    ``serverless_IID_IMDB.py:258``).

    ``contiguous`` (Non-IID): client ``k`` takes the index slice
    ``[stride*k, stride*k + train_span)`` for train and either the trailing
    slice ``[stride*k + train_span, stride*(k+1))`` (``test_mode='trailing'``,
    reference ``serverless_NonIID_IMDB.py:59-60`` — the 300k/240 schedule) or a
    fixed shared slice ``[0, test_span)`` (``test_mode='fixed'``, reference
    ``Serverless_NonIID_Medical_transcriptions.py:55-56`` — the 500i/400
    schedule).
    """

    kind: str = "iid"  # "iid" | "contiguous"
    iid_samples: int = 100
    iid_test_samples: Optional[int] = None  # default: same as iid_samples
    resample_each_round: bool = False
    stride: int = 300
    train_span: int = 240
    test_span: int = 60
    test_mode: str = "trailing"  # "trailing" | "fixed"

    def __post_init__(self):
        if self.kind not in ("iid", "contiguous"):
            raise ValueError(f"unknown partition kind: {self.kind!r}")
        if self.test_mode not in ("trailing", "fixed"):
            raise ValueError(f"unknown test_mode: {self.test_mode!r}")
        if self.kind == "contiguous":
            if self.train_span > self.stride:
                raise ValueError(
                    f"train_span {self.train_span} > stride {self.stride}: "
                    "client slices would overlap"
                )
            if self.test_mode == "trailing" and self.train_span + self.test_span > self.stride:
                raise ValueError(
                    f"train_span+test_span {self.train_span + self.test_span} > "
                    f"stride {self.stride}: trailing test slice would overlap the "
                    "next client's train slice"
                )


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """P2P network model + anomaly gating (reference: notebook-only, cells 0-12
    of ``All_graphs_IMDB_dataset.ipynb``; here it is wired into training)."""

    anomaly_filter: Optional[str] = None  # None|"pagerank"|"dbscan"|"zscore"|"community"
    # bandwidth matrix source: "reference" = the notebook's fixed 10-node graph,
    # "random" = sampled in [bw_low, bw_high] mbps like the notebook's values.
    bandwidth: str = "reference"
    bw_low: float = 88.0
    bw_high: float = 496.0
    # gossip mixing coefficient for ring gossip (serverless mode)
    gossip_alpha: float = 0.5
    gossip_steps: int = 1  # ring-gossip rounds per federated round


@dataclasses.dataclass(frozen=True)
class LedgerConfig:
    """Hash-chained weight ledger (the real implementation of the reference's
    'BC-FL' — described only in ``README.md:10`` and MT notebook cells 26-28)."""

    enabled: bool = False
    use_native: bool = True  # C++ SHA-256 core if built, hashlib otherwise
    # ledger-entry payload size (bytes) for communication accounting: the
    # reference models the blockchain payload as 0.043 GB vs the 0.4036 GB
    # full model (MT notebook cell 27 vs 23)
    entry_payload_bytes: int = 46_170_898  # 0.043 GiB-class default


@dataclasses.dataclass(frozen=True)
class DistConfig:
    """Multi-host async P2P runtime knobs (``FedConfig.runtime='dist'``,
    RUNTIME.md). Each peer is a real OS process owning a slice of the
    clients; update exchange is length-prefixed TCP over loopback/DCN
    carrying the configured codec's wire format plus ledger fingerprint
    digests; aggregation is FedBuff-style buffered async with MEASURED
    (arrival-order) staleness. All timeouts are hard deadlines — a hung
    peer fails the run instead of wedging it (the harness reaps it)."""

    peers: int = 2
    host: str = "127.0.0.1"
    # first listen port; peer p listens on base_port + p. 0 = the spawner
    # picks free ports and passes them down (scripts/dist_async.py, CLI)
    base_port: int = 0
    # merge target at a component leader, in DISTINCT sending peers (each
    # update carries its sender's whole client slice; several updates from
    # one sender count once toward the target and collapse into one vote
    # under a robust aggregator — the "f of k" arithmetic is over peers).
    # 0 = 1: merge on every arrival — the pure-async setting, and the one
    # that makes the measured staleness distribution non-degenerate. Must
    # be <= peers.
    buffer: int = 0
    # leader-side cap on waiting for the buffer to fill: merge whatever
    # arrived once this many seconds pass since the first buffered update
    # (a departed peer must not stall every future merge)
    buffer_timeout_s: float = 20.0
    # peer-process watchdog: no observable progress (no message, no local
    # round) for this long -> the peer exits nonzero instead of wedging
    idle_timeout_s: float = 120.0
    # hard wall deadline for one peer process; the in-process watchdog
    # enforces it even if the supervisor died
    peer_deadline_s: float = 600.0
    # checkpoint every N adopted/produced global versions (0 = off); the
    # crash/rejoin path restores from the newest one
    checkpoint_every_versions: int = 1
    # checkpoint retention: keep only the newest K committed rounds on
    # disk (0 = keep everything). Removal of older rounds is ordered
    # strictly AFTER the new round's commit+fsync, so a crash mid-GC can
    # only ever leave EXTRA rounds, never fewer than K usable ones.
    checkpoint_keep_last: int = 0
    # --- self-healing transport policy (RUNTIME.md "Delivery contract") ---
    # every logical send retries failed attempts with exponential backoff
    # (base * 2^k, capped at retry_max_s, deterministically jittered) up to
    # send_retries RE-tries, all under the per-destination send_deadline_s
    # wall budget — at-least-once delivery, made safe by the receiver's
    # per-sender (from, msg_id) dedup window
    send_retries: int = 4
    retry_base_s: float = 0.05
    retry_max_s: float = 2.0
    send_deadline_s: float = 20.0
    # circuit-breaker failure detector: consecutive send-attempt failures
    # move a peer REACHABLE -> SUSPECT (suspect_after) -> DOWN
    # (down_after); any success snaps it back to REACHABLE. While DOWN the
    # circuit is open — sends are skipped except one probe per
    # probe_interval_s, so a recovered peer is re-detected without paying
    # a connect timeout on every message
    suspect_after: int = 2
    down_after: int = 6
    probe_interval_s: float = 2.0
    # --- failure-detection mode (RUNTIME.md "Timing contract") ---
    # "phi" (default) = adaptive phi-accrual-style estimator: per-peer
    # inbound-interval EWMA + variance feed a CONTINUOUS suspicion level
    # phi (monotone in silence, snapped back by any liveness evidence);
    # suspect/down become thresholds on phi and send deadlines adapt per
    # destination from measured RTT/throughput (floor/ceiling clamped
    # below). "fixed" = the consecutive-counter detector above with the
    # static send_deadline_s — bit-compatible with pre-gray-failure
    # replays (the knob the existing dist_chaos legs pin).
    detector: str = "phi"
    # phi thresholds: suspicion grows by 1 per consecutive failed send
    # attempt plus the peer's silence beyond its adaptive expected window
    # (so the defaults grade like suspect_after=2 / down_after=6 under
    # pure failures, while pure silence also accrues — the gray-failure
    # signal the fixed counter is blind to)
    phi_suspect: float = 2.0
    phi_down: float = 6.0
    # clamp on the adaptive expected-silence window (EWMA mean + 3 sigma
    # of inbound intervals): the floor keeps a chatty link from making
    # sub-second silences suspicious, the ceiling bounds how long an
    # unheard-from peer can stay unsuspected
    phi_window_floor_s: float = 5.0
    phi_window_ceil_s: float = 120.0
    # clamp on the adaptive per-destination send deadline (measured RTT
    # headroom + frame_bytes / measured throughput). floor bounds how
    # aggressive a fast link's deadline may get; ceiling bounds how long
    # a limping link can hold a send. detector="fixed" ignores both and
    # uses send_deadline_s verbatim.
    deadline_floor_s: float = 2.0
    deadline_ceil_s: float = 120.0
    # assumed link throughput (bytes/s) before any measurement exists:
    # the size-proportional term of the adaptive deadline divides by this
    # until real throughput samples arrive, so a first-contact 32 MB
    # frame gets a budget that scales with its size instead of starving
    # under a latency-tuned constant (the PR 8 large-frame starvation
    # note)
    min_bandwidth_bps: float = 1_048_576.0
    # gossip hedging: when a sampled neighbor's phi crosses this
    # threshold at dispatch time, the peer re-draws a seeded replacement
    # neighbor (detector="phi" only; the draw is replayable — see
    # bcfl_tpu.dist.gossip.HEDGE_LANE)
    gossip_hedge_phi: float = 2.0
    # receiver-side per-sender dedup window (message ids); ids at or below
    # (newest seen - window) are treated as duplicates and dropped
    dedup_window: int = 1024
    # bounded inbox: a flooding (or chaos-duplicated) peer cannot grow a
    # leader's queue without bound — overflow REFUSES the newest frame
    # (no ack, dedup id un-recorded, counted in transport stats
    # `inbox_overflow`), so the sender's retry can still deliver it once
    # the inbox drains — at-least-once survives a full inbox
    inbox_max: int = 1024
    # partial-report cadence: peers rewrite their report_peer*.json every
    # N local rounds (and on every adopted/produced version, at startup,
    # and on SIGTERM) with status="running" — a SIGKILLed or stalled peer
    # leaves a current partial report instead of nothing. 0 disables the
    # periodic rewrites (startup/terminal writes remain).
    report_every_rounds: int = 5
    # quorum degradation: the FedBuff leader's buffer target counts only
    # component peers the detector does NOT hold DOWN (merges recorded as
    # degraded while any are), and below this reachable fraction of the
    # component the leader refuses to advance the global at all (the idle
    # watchdog bounds that wait)
    quorum_frac: float = 0.5
    # --- comms/compute overlap (RUNTIME.md §4, PERF.md) ---
    # pipeline=True (default) overlaps communication with computation:
    # update sends and global broadcasts go through per-destination sender
    # WORKERS (the round loop enqueues and immediately starts the next
    # local round; retries/backoff/detector feeding run in the worker),
    # and the leader drains arrivals on an INTAKE thread into a
    # double-buffered FedBuff buffer (merge/verify consumes a swapped-out
    # buffer while intake keeps filling the standby one). False = the
    # PR 7-10 serial loop, bit-compatible — the wire_perf.py A/B baseline.
    pipeline: bool = True
    # bounded per-destination handoff queue depth for the sender workers:
    # when a destination is slower than the round loop, enqueue BLOCKS
    # after this many frames (back-pressure) instead of buffering
    # model-sized trees without bound
    pipeline_depth: int = 2
    # periodic host-resource sampling (metrics.ResourceMonitor sampling
    # mode): every this-many seconds each peer emits a catalogued
    # `resource` telemetry event (RSS, windowed CPU%) so the live
    # monitor's health series can track drift across a long soak.
    # 0.0 (default) = off; ignored when telemetry is off.
    resource_sample_s: float = 0.0
    # --- dispatch mode (RUNTIME.md "Gossip dispatch") ---
    # "leader" = the FedBuff path above: min reachable id owns the merge,
    # the robust votes, and the reputation clock for its component.
    # "gossip" = leaderless epidemic exchange (bcfl_tpu.dist.gossip): every
    # peer samples seeded neighbors per local round, pushes its full state,
    # and merges arrivals with a commutative version-vector rule — no
    # privileged process, elastic membership (bcfl_tpu.dist.membership).
    dispatch: str = "leader"
    # gossip neighbors contacted per local round (epidemic fan-out, or the
    # ring successor count under gossip_topology="ring")
    gossip_fanout: int = 2
    # neighbor-sampling topology: "epidemic" draws gossip_fanout live peers
    # from a PRNG keyed (seed, round, peer) — replayable; "ring" takes the
    # next gossip_fanout successors around the sorted live view
    gossip_topology: str = "epidemic"
    # HELLO beacon cadence (seconds): each peer periodically hellos one
    # sampled neighbor and any peer answers with a state+chain sync — the
    # steady-state resync that makes join/leave mid-run continuous
    gossip_hello_interval_s: float = 5.0

    def __post_init__(self):
        if self.peers < 2:
            raise ValueError(
                f"runtime='dist' needs >= 2 peers, got {self.peers}")
        if self.buffer < 0 or self.buffer > self.peers:
            raise ValueError(
                f"dist buffer {self.buffer} must be in [0, peers="
                f"{self.peers}] (it counts buffered PEER updates)")
        for name in ("buffer_timeout_s", "idle_timeout_s",
                     "peer_deadline_s", "retry_base_s", "retry_max_s",
                     "send_deadline_s", "probe_interval_s"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be > 0")
        if self.send_retries < 0:
            raise ValueError(
                f"send_retries must be >= 0, got {self.send_retries}")
        if self.suspect_after < 1:
            raise ValueError(
                f"suspect_after must be >= 1, got {self.suspect_after}")
        if self.down_after < self.suspect_after:
            raise ValueError(
                f"down_after {self.down_after} must be >= suspect_after "
                f"{self.suspect_after} (a peer is SUSPECT before DOWN)")
        if self.detector not in ("phi", "fixed"):
            raise ValueError(
                f"dist detector must be 'phi' or 'fixed', got "
                f"{self.detector!r}")
        for name in ("phi_suspect", "phi_window_floor_s",
                     "deadline_floor_s", "min_bandwidth_bps",
                     "gossip_hedge_phi"):
            if getattr(self, name) <= 0:
                raise ValueError(f"{name} must be > 0")
        if self.phi_down < self.phi_suspect:
            raise ValueError(
                f"phi_down {self.phi_down} must be >= phi_suspect "
                f"{self.phi_suspect} (a peer is SUSPECT before DOWN)")
        if self.phi_window_ceil_s < self.phi_window_floor_s:
            raise ValueError(
                f"phi_window_ceil_s {self.phi_window_ceil_s} must be >= "
                f"phi_window_floor_s {self.phi_window_floor_s}")
        if self.deadline_ceil_s < self.deadline_floor_s:
            raise ValueError(
                f"deadline_ceil_s {self.deadline_ceil_s} must be >= "
                f"deadline_floor_s {self.deadline_floor_s}")
        for name in ("dedup_window", "inbox_max"):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        if self.report_every_rounds < 0:
            raise ValueError(
                f"report_every_rounds must be >= 0, got "
                f"{self.report_every_rounds}")
        if self.checkpoint_keep_last < 0:
            raise ValueError(
                f"checkpoint_keep_last must be >= 0 (0 keeps all), got "
                f"{self.checkpoint_keep_last}")
        if not 0.0 < self.quorum_frac <= 1.0:
            raise ValueError(
                f"quorum_frac must be in (0, 1], got {self.quorum_frac}")
        if self.pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {self.pipeline_depth}")
        if self.resource_sample_s < 0:
            raise ValueError(
                f"resource_sample_s must be >= 0, got "
                f"{self.resource_sample_s}")
        if self.dispatch not in ("leader", "gossip"):
            raise ValueError(
                f"dist dispatch must be 'leader' or 'gossip', got "
                f"{self.dispatch!r}")
        if self.gossip_topology not in ("epidemic", "ring"):
            raise ValueError(
                f"gossip_topology must be 'epidemic' or 'ring', got "
                f"{self.gossip_topology!r}")
        if self.gossip_fanout < 1:
            raise ValueError(
                f"gossip_fanout must be >= 1, got {self.gossip_fanout}")
        if self.dispatch == "gossip" and self.gossip_fanout >= self.peers:
            raise ValueError(
                f"gossip_fanout {self.gossip_fanout} must be < peers "
                f"{self.peers} (a peer cannot gossip to more neighbors "
                "than exist besides itself)")
        if self.gossip_hello_interval_s <= 0:
            raise ValueError(
                f"gossip_hello_interval_s must be > 0, got "
                f"{self.gossip_hello_interval_s}")


# --- runtime capability table (RUNTIME.md §2) --------------------------------
# Every (feature x runtime) combination is either SUPPORTED or rejected by
# this one declared table — the single capability check the acceptance
def parse_lora_ranks(spec: str) -> Tuple[int, ...]:
    """Parse a ``lora_ranks`` spec ("2,4,8") into a tuple of positive ints.
    The spec is cycled over the stacked client axis: client ``i`` trains at
    ``spec[i % len(spec)]``. Raises with the offending token on bad input."""
    try:
        ranks = tuple(int(tok) for tok in spec.split(","))
    except ValueError:
        raise ValueError(
            f"lora_ranks must be comma-separated positive ints "
            f"(e.g. '2,4,8'), got {spec!r}")
    if not ranks or any(r <= 0 for r in ranks):
        raise ValueError(
            f"lora_ranks entries must all be > 0, got {spec!r}")
    return ranks


# contract names. Each row is ``(feature, active, {runtime: verdict})``:
# ``active(cfg)`` says whether the feature is requested, a ``True`` verdict
# means the runtime supports it, and a string verdict is the rejection
# reason raised at config time. ``capability_table(cfg)`` renders the whole
# matrix for docs/tests; FedConfig.__post_init__ walks it once.
RUNTIME_CAPS: Tuple = (
    ("serverless gossip mode",
     lambda c: c.mode == "serverless",
     {"local": True,
      "dist": "the dist runtime's serverless analogue is the leaderless "
              "dispatch, not the local ring-gossip diffusion — use "
              "mode='server' with dist.dispatch='gossip'"}),
    ("simulated-clock sync rounds",
     lambda c: c.sync == "sync",
     {"local": True,
      "dist": "runtime='dist' IS the real-clock async runtime (RUNTIME.md); "
              "set sync='async' — there is no synchronous barrier to run"}),
    ("simulated-clock async (sync='async')",
     lambda c: c.sync == "async",
     {"local": True, "dist": True}),
    ("faithful host-sequential mode",
     lambda c: c.faithful,
     {"local": True,
      "dist": "faithful mode mutates ONE shared model host-sequentially; "
              "peers on different hosts cannot share that model"}),
    ("cohort registry sampling",
     lambda c: c.registry_size > 0,
     {"local": True,
      "dist": "registry sampling re-deals the client set per round; a "
              "peer's client slice is its persistent identity (data, "
              "ledger keys, checkpoints)"}),
    ("tensor parallelism (tp > 1)",
     lambda c: c.tp > 1,
     {"local": True,
      "dist": "each peer builds a single-host client mesh; inner tp "
              "sharding across peers is not implemented"}),
    ("sequence parallelism (sp > 1)",
     lambda c: c.sp > 1,
     {"local": True,
      "dist": "each peer builds a single-host client mesh; inner sp "
              "sharding across peers is not implemented"}),
    ("pod-spanning mesh",
     lambda c: c.pod,
     {"local": True,
      "dist": "runtime='dist' is its own multi-process deployment (one "
              "process per peer group); pod=True is the single-program "
              "jax.distributed path — pick one"}),
    ("buffer donation",
     lambda c: c.donate,
     {"local": True,
      "dist": "peers re-enter their round programs for the whole run; "
              "donated-away input buffers would fail on the second round"}),
    ("fused multi-round dispatch",
     lambda c: c.rounds_per_dispatch > 1,
     {"local": True,
      "dist": "every peer round ends at the transport (send/receive is "
              "host work by construction); there is nothing to fuse "
              "across"}),
    ("anomaly filter",
     lambda c: c.topology.anomaly_filter is not None,
     {"local": True,
      "dist": "anomaly filters gate on the SIMULATED latency graph; the "
              "dist runtime measures real transport and has no global "
              "per-round view to filter"}),
    ("reputation lifecycle",
     lambda c: c.reputation.enabled,
     {"local": True, "dist": True}),  # dist: per-PEER tracker fed by wire
    # evidence (ledger refingerprint mismatches, robust-merge outlier
    # flags, staleness/replay, detector transitions); quarantine refusals
    # are post-ack gate drops and transitions commit to the ledger
    # (bcfl_tpu.reputation.dist, RUNTIME.md §5)
    ("robust aggregators",
     lambda c: c.aggregator != "mean",
     {"local": True, "dist": True}),  # dist: the robust rules run host-
    # side over the buffered ARRIVAL set (bcfl_tpu.dist.robust) —
    # supported WITH declared preconditions on the merge buffer, enforced
    # below at config time (trimmed_mean/median need buffer >= 3; krum
    # needs buffer >= 2f+3 for f = ceil(trim * buffer))
    ("communication compression",
     lambda c: c.compression.enabled,
     {"local": True, "dist": True}),
    ("hash-chained ledger",
     lambda c: c.ledger.enabled,
     {"local": True, "dist": True}),
    ("chaos: transport partition",
     lambda c: c.faults.partitions,
     {"local": True, "dist": True}),  # dist: enforced at the socket layer,
    # groups name PEERS; each connected component forks the ledger chain
    ("chaos: stragglers",
     lambda c: c.faults.straggler_prob > 0,
     {"local": True, "dist": True}),  # dist: a REAL pre-send sleep — the
    # injected delay shows up in the measured staleness distribution
    ("chaos: client dropout",
     lambda c: c.faults.dropout_prob > 0,
     {"local": True,
      "dist": "per-round dropout is a mask over a global stacked round; "
              "dist peers have no global round to mask — not implemented"}),
    ("chaos: transport corruption / flaky bursts",
     lambda c: c.faults.corrupts,
     {"local": True,
      "dist": "per-client corruption scales act on the engine's stacked "
              "in-graph transport stage, which dist rounds never run; use "
              "the wire lane instead (wire_corrupt_prob flips real frame "
              "bytes in flight; the frame CRC and the ledger verify path "
              "catch them)"}),
    ("chaos: wire faults (drop/dup/reorder/delay/corrupt)",
     lambda c: c.faults.wire_enabled,
     {"local": "the local engine has no socket boundary to inject at — "
               "the wire lane acts on real TCP frames in the dist "
               "transport (PeerTransport); use corrupt_prob for the "
               "simulated-transport analogue",
      "dist": True}),
    ("chaos: byzantine peers",
     lambda c: c.faults.byz_enabled,
     {"local": "byzantine behaviors forge the dist update exchange's wire "
               "headers and payloads (stale lineage, digest forgeries, "
               "per-destination equivocation); the local engine exchanges "
               "none of those — use corrupt_prob/flaky_* for the "
               "simulated in-graph analogue",
      "dist": True}),  # injected above the wire (dist/byzantine.py),
    # composable with the wire lane; ROBUSTNESS.md §8 names what evidence
    # catches each behavior
    ("chaos: churn",
     lambda c: c.faults.churns,
     {"local": True,
      "dist": "peer-level churn is the crash/rejoin path (kill and "
              "restart a peer process; scripts/dist_async.py --kill-peer "
              "drives it), not a mask schedule"}),
    ("chaos: host crash",
     lambda c: c.faults.crash_at_round is not None,
     {"local": True,
      "dist": "kill the peer PROCESS instead (scripts/dist_async.py "
              "--kill-peer): a real crash is the thing itself, not a "
              "simulated one"}),
    ("chaos: storage faults",
     lambda c: c.faults.storage_enabled,
     {"local": "the storage lane damages a peer's durable checkpoint/"
               "ledger state at the post-commit seam and exercises the "
               "scrub + STATE_SYNC repair path; the local engine has no "
               "per-peer durable state or peers to repair from — dist "
               "only",
      "dist": True}),  # injected in _maybe_checkpoint after commit+fsync
    # (faults/plan.py lane 8); detection is the startup scrub +
    # restore-time classification, recovery is the ledger-authenticated
    # STATE_SYNC transfer (ROBUSTNESS.md §10)
    ("chaos: limp faults (gray failures)",
     lambda c: c.faults.limp_enabled,
     {"local": "the limp lane stalls a PEER's train seam and throttles "
               "its real TCP links, graded by the adaptive failure "
               "detector and w_slow down-weighting — the local engine "
               "has neither a wire nor a detector; use straggler_prob "
               "for the simulated-clock analogue",
      "dist": True}),  # stall at the train seam, direction-keyed
    # throttle in the transport, SIGSTOP pauses via the harness
    # (faults/plan.py lane 9; ROBUSTNESS.md §11)
    ("chaos: resource faults (ENOSPC/EMFILE)",
     lambda c: c.faults.resource_enabled,
     {"local": "the resource lane fails a peer's durable writes "
               "(checkpoint commit, ledger append, event flush) and "
               "grades the emergency-GC → telemetry-shed → exit ladder; "
               "the local engine has no per-peer durable-write seams — "
               "dist only",
      "dist": True}),  # drawn per (seam, counter, peer) at the write
    # seams (faults/plan.py lane 10; ROBUSTNESS.md §11)
    # --- gossip-dispatch composition rows (RUNTIME.md "Gossip dispatch"):
    # active only when the dist runtime is asked for dispatch='gossip', so
    # they never fire for local runs or the leadered dist path ---
    ("communication compression under gossip dispatch",
     lambda c: c.compression.enabled and c.dist.dispatch == "gossip",
     {"local": True,
      "dist": "the codec wire encodes DELTAS against a shared adopted "
              "base version; gossip peers merge concurrently with no "
              "common base to delta against — use compress='none'"}),
    ("krum under gossip dispatch",
     lambda c: c.aggregator == "krum" and c.dist.dispatch == "gossip",
     {"local": True,
      "dist": "krum selects ONE vote from a population; over a gossip "
              "peer's tiny neighbor arrival set the selection guarantee "
              "is vacuous and the merge would just adopt one neighbor "
              "verbatim — use trimmed_mean or median"}),
    ("chaos: transport partition under gossip dispatch",
     lambda c: c.faults.partitions and c.dist.dispatch == "gossip",
     {"local": True, "dist": True}),  # dist: supported LEADERLESSLY
    # (RUNTIME.md §9, ROBUSTNESS.md §6): during the span each component
    # keeps converging on its own clocks — neighbor draws stay inside
    # the gate component, the merge seam rejects frames buffered across
    # the cut (the gossip scope of no_cross_partition_merge), and a
    # component below the robust vote floor degrades to the commutative
    # mean with a catalogued gossip.vote_floor event. The heal has no
    # arbiter: HELLO probes re-establish contact (the dormant-peer probe
    # lane prevents split-brain-forever), version-vector merges absorb
    # the other side's frontier, and per-peer chains reconcile pairwise
    # through fork_point/verify_segment/merge_rows/adopt_merge.
    # Preconditions: partition_groups name PEERS and the span is keyed
    # on each peer's OWN autonomous round clock (validated below);
    # proven by the chaos_smoke gossip-partition leg and
    # scripts/dist_soak.py --partition
    ("per-round central eval",
     lambda c: c.eval_every != 0,
     {"local": True,
      "dist": "per-round central eval would serialize the async runtime "
              "behind the leader; set eval_every=0 — the leader "
              "evaluates the final global once at shutdown"}),
    ("LoRA adapter exchange",
     lambda c: c.lora_rank > 0 or bool(c.lora_ranks),
     {"local": True, "dist": True}),  # dist: with lora_rank > 0 the
    # trainable tree IS the adapter tree, so update/broadcast frames,
    # leader refingerprint, robust merge votes, byzantine evidence, and
    # HELLO/checkpoint resync all carry KB-scale adapter payloads — the
    # full-model frame never crosses the wire (RUNTIME.md, COMPRESSION.md
    # "Adapter exchange"; gated by scripts/lora_comm.py)
    ("heterogeneous LoRA ranks",
     lambda c: bool(c.lora_ranks) and len(set(parse_lora_ranks(c.lora_ranks))) > 1,
     {"local": True,
      "dist": "each dist peer compiles round programs over its own client "
              "slice; the rank-aware padded aggregation (RBLA) is defined "
              "over the single-process stacked client axis — use a uniform "
              "lora_rank"}),
)


def capability_table(cfg: "FedConfig") -> Tuple[Tuple[str, bool, object], ...]:
    """The resolved (feature, active, verdict) rows for ``cfg``'s runtime —
    ``verdict`` is True (supported) or the rejection reason string."""
    return tuple(
        (feature, bool(active(cfg)), verdicts[cfg.runtime])
        for feature, active, verdicts in RUNTIME_CAPS)


@dataclasses.dataclass(frozen=True)
class FedConfig:
    # --- experiment identity ---
    name: str = "fed"
    seed: int = 42  # reference seeds dataset shuffle with 42 (server_IID_IMDB.py:68)
    # typed-key PRNG implementation: None = jax's default (threefry).
    # "rbg" opts into the TPU hardware generator — dropout RNG is +38% of
    # step time under threefry (PERF.md). Both are deterministic given the
    # seed, but they are DIFFERENT streams: changing this mid-experiment is
    # like changing the seed (checkpoints record it; resume verifies).
    prng_impl: Optional[str] = None

    # --- data ---
    dataset: str = "synthetic"  # key into bcfl_tpu.data.datasets registry
    text_col: str = "text"
    label_col: str = "labels"
    num_labels: int = 2
    seq_len: int = 128
    batch_size: int = 32  # reference: batch_size=32 (server_IID_IMDB.py:96-99)
    vocab_size: int = 8192  # hash-tokenizer vocab (HF tokenizers override this)
    tokenizer: str = "hash"  # "hash" | HF tokenizer name

    # --- task ---
    # "classification" = the reference's task (sequence classification);
    # "causal_lm" = federated next-token fine-tuning on the client corpora
    # (llama family only — the capability the BASELINE.json Llama-LoRA
    # config exists for; labels columns are ignored, ids are the targets)
    task: str = "classification"

    # --- model ---
    model: str = "tiny-bert"  # key into bcfl_tpu.models registry
    hf_checkpoint: Optional[str] = None  # e.g. "albert-base-v2" to import weights
    lora_rank: int = 0  # 0 = full fine-tune (reference behaviour); >0 = LoRA
    # per-client LoRA rank spec for HETEROGENEOUS fleets (RBLA, arXiv
    # 2408.08699): comma-separated ints cycled over the stacked client axis
    # — "2,4,8" means client i trains at rank spec[i % 3]. Mutually
    # exclusive with lora_rank; __post_init__ canonicalizes lora_rank to
    # max(spec) so every existing `lora_rank > 0` switch (adapter-tree
    # trainable, tp gating, dist adapter wire) sees the cohort ceiling.
    # Clients are materialized zero-padded at that max rank; the padding
    # mask is static in this spec, so heterogeneous fleets add zero
    # per-round retraces. "" = uniform (lora_rank applies to everyone).
    lora_ranks: str = ""
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # None = the model family's default (llama: flash on from seq 512;
    # encoders: dense). True forces the O(S)-memory blockwise/Pallas
    # attention path — the long-context switch, reachable from the CLI
    use_flash: Optional[bool] = None
    # per-layer activation rematerialization: recompute activations in the
    # backward instead of storing them — O(num_layers) less activation HBM
    # for ~1/3 more FLOPs, so more full-fine-tune clients stack per chip
    remat: bool = False
    # donate each round's input param/opt buffers to the round program:
    # XLA aliases them into the outputs, halving per-round peak HBM (the
    # difference between 10 x BERT-base full fine-tune fitting a 16 GB chip
    # or not). The engine chains carries, so semantics are unchanged; the
    # one restriction is that engine.run() is single-shot (round 1 consumes
    # the initial tree) — a second run() raises instead of recomputing.
    # Scope: the sync server/gossip round programs (per-round and fused,
    # incl. the fused ledger *_fp path). The async/faithful paths and the
    # per-round split-phase ledger flow run undonated programs — there the
    # flag is a warning-emitting no-op.
    donate: bool = False

    # --- scale-out (SURVEY.md §2.5: the two axes the reference lacks) ---
    # tensor-parallel shards per client: tp > 1 builds a 2-D (clients, tp)
    # mesh, shards the FROZEN base megatron-style (requires lora_rank > 0 —
    # adapters stay per-client), and runs the same GSPMD round programs
    tp: int = 1
    # sequence-parallel shards per client: sp > 1 builds a 2-D
    # (clients, seq) mesh and swaps the model's attention for exact ring
    # attention over the seq axis (bcfl_tpu.parallel.sp) — each client's
    # ACTIVATIONS shard over the sequence, params stay replicated in the
    # group. Long-document federated fine-tuning; both model families
    # (encoders ride the non-causal ring).
    sp: int = 1
    # build the mesh over every host in the pod (jax.distributed must be
    # initialized first — core.mesh.distributed_init); devices are ordered
    # hosts-major so collectives ride ICI and cross DCN once
    pod: bool = False

    # --- federated topology ---
    # "local" = the whole federation runs in THIS process (simulated clock
    # for sync="async"; every pre-existing behaviour, bit-for-bit).
    # "dist"  = real multi-process async P2P runtime (bcfl_tpu.dist,
    # RUNTIME.md): each peer is an OS process owning a client slice, update
    # exchange rides length-prefixed TCP (the codec wire format + ledger
    # fingerprint digests), aggregation is FedBuff-buffered with MEASURED
    # staleness, and a transport partition genuinely forks the ledger
    # chain per connected component. Feature composition is governed by
    # RUNTIME_CAPS below — one declared capability check at config time.
    runtime: str = "local"
    mode: str = "server"  # "server" (centralized FedAvg) | "serverless" (P2P gossip)
    # "sync" | "async". With runtime="local", async is SIMULATED asynchrony
    # under a deterministic network clock: one buffered (FedBuff-style)
    # aggregation event per engine round, arrival order from the latency
    # graph + chaos straggler delays, staleness decay on merged deltas. It
    # is NOT wall-clock concurrency — see PARALLELISM.md "Async semantics"
    # for the real-clock vs simulated-clock contract side by side. For
    # actual wall-clock concurrency (measured staleness, real transport)
    # use runtime="dist", which REQUIRES sync="async".
    sync: str = "sync"
    num_clients: int = 4
    # --- cohort-batched client scale-out (SCALING.md "Cohort mode") ---
    # registry_size > 0 turns on client sampling: the run simulates a
    # registry of this many clients (data-partition identity, PRNG streams,
    # fault schedules, reputation and error-feedback state are all keyed by
    # registry id — host arrays sized by the registry), while each round a
    # seeded sampler draws only `sample_clients` of them onto the stacked
    # mesh axis. Device/HBM cost is bounded by the cohort, not the registry;
    # per-round wall scales with the sampled cohort (sublinear in registry
    # size). 0 = off (every client is a mesh slot every round — the
    # pre-cohort behaviour, unchanged).
    registry_size: int = 0
    # per-round sampled cohort size (the stacked client axis width when
    # sampling); 0 = fall back to num_clients. Must be <= registry_size.
    sample_clients: int = 0
    # clients stacked per device (the vmapped axis per mesh shard): > 0 pins
    # the mesh to exactly sample_clients/cohort_size devices instead of the
    # largest-divisor default. Must divide the sampled cohort size.
    cohort_size: int = 0
    num_rounds: int = 2
    local_epochs: int = 1  # reference: 1 epoch per round (server_IID_IMDB.py:172)
    max_local_batches: Optional[int] = None  # cap scan length (static shape)
    # fuse up to this many federated rounds into ONE XLA dispatch when the
    # host isn't needed between them (sync server FedAvg or sync parallel
    # serverless gossip — not faithful mode; no ledger, no anomaly filter) —
    # amortizes dispatch/transfer overhead, which dominates on tunnelled or
    # high-latency hosts. Chunks never cross an eval or checkpoint boundary,
    # so observable cadence is unchanged.
    rounds_per_dispatch: int = 1
    # True  = example-weighted FedAvg (Flower's aggregate, server mode)
    # False = unweighted mean (reference serverless ":296" semantics)
    weighted_agg: bool = True
    # Byzantine-robust aggregation rule, compiled INTO the round programs
    # (ROBUSTNESS.md). "mean" is the reference behaviour; the robust rules
    # are per-coordinate order statistics / update selection over the
    # PARTICIPATING clients (mask/auth-aware) and deliberately ignore
    # example weighting (weighted_agg) — order statistics have no sound
    # notion of fractional votes:
    #   trimmed_mean — drop the ceil(aggregator_trim * k) highest and lowest
    #                  values per coordinate, mean the rest,
    #   median       — coordinate-wise median of participating updates,
    #   krum         — select the single update closest to its k-f-2 nearest
    #                  neighbours (f = ceil(aggregator_trim * k)).
    # In sync="async" mode the participation-only rule also flattens the
    # PER-CLIENT staleness decay inside the merge (a stale arrival votes at
    # full strength); the global step-size rescale (_async_merge_scale)
    # still shrinks the applied delta, so staleness dampens the step, not
    # the vote. gspmd impl only (the default); impl="shard_map" supports
    # "mean" only.
    aggregator: str = "mean"
    # assumed Byzantine fraction for trimmed_mean/krum, in [0, 0.5)
    aggregator_trim: float = 0.2
    # faithful=True reproduces the reference serverless quirk where clients
    # sequentially mutate ONE shared model within a round
    # (serverless_NonIID_IMDB.py:288 — see SURVEY.md §3.2)
    faithful: bool = False

    # --- optimizer (reference: fresh AdamW lr=5e-5 each round, server_IID_IMDB.py:109) ---
    learning_rate: float = 5e-5
    optimizer: str = "adamw"
    max_grad_norm: float = 0.0  # 0 = off (reference has no clipping)

    # --- async scheduling ---
    async_buffer: int = 0  # aggregate when this many clients arrived (0 = num_clients)
    staleness_decay: float = 0.5  # weight = decay ** staleness
    # server step size along the staleness-weighted mean client delta
    # (FedBuff-style buffered aggregation)
    async_server_lr: float = 1.0

    # --- sub-configs ---
    partition: PartitionConfig = dataclasses.field(default_factory=PartitionConfig)
    topology: TopologyConfig = dataclasses.field(default_factory=TopologyConfig)
    ledger: LedgerConfig = dataclasses.field(default_factory=LedgerConfig)
    # fault-injection schedule (bcfl_tpu.faults, ROBUSTNESS.md); the default
    # plan injects nothing
    faults: FaultPlan = dataclasses.field(default_factory=FaultPlan)
    # peer-lifecycle reputation (bcfl_tpu.reputation, ROBUSTNESS.md §6):
    # EWMA trust over per-round evidence (ledger-auth failures, anomaly
    # flags, corruption hits, staleness) drives HEALTHY -> SUSPECT ->
    # QUARANTINED -> PROBATION -> HEALTHY; quarantined peers are excluded
    # from aggregation for a configurable window and readmitted at reduced
    # vote weight. Host-side state, checkpointed; disabled by default.
    reputation: ReputationConfig = dataclasses.field(
        default_factory=ReputationConfig)
    # communication compression for the update exchange (COMPRESSION.md):
    # kind ∈ none/int8/topk/int8+topk — quantized and/or sparsified client
    # deltas with error-feedback residuals, compiled INTO the round
    # programs. 'none' (default) is bit-identical to the uncompressed
    # programs. gspmd impl only; the faithful host-sequential mode has no
    # transport stage to compress (rejected below). kernel_impl ∈
    # auto/xla/pallas selects the codec kernels (PERF.md "Custom
    # kernels"); every impl's payload is byte-identical, so it never
    # affects wire bytes, digests, or resume.
    compression: CompressionConfig = dataclasses.field(
        default_factory=CompressionConfig)

    # multi-process P2P runtime knobs (runtime="dist" only; RUNTIME.md)
    dist: DistConfig = dataclasses.field(default_factory=DistConfig)

    # --- checkpoint / metrics ---
    checkpoint_dir: Optional[str] = None
    checkpoint_every: int = 0  # rounds; 0 = off
    # evaluate every Nth round (the final round always evaluates);
    # 0 = never evaluate, INCLUDING the final round (pure-throughput runs)
    eval_every: int = 1
    # cap the central-eval set to this many batches (None = the full test
    # split, the reference's evaluate_global_model behaviour); small hosts
    # use a cap so per-round eval doesn't dominate wall-clock
    max_eval_batches: Optional[int] = None
    # jax.profiler trace output dir (TensorBoard/Perfetto); None = off.
    # The reference's only profiling is psutil+wall-clock (SURVEY.md §5).
    profile_dir: Optional[str] = None

    # --- event telemetry (OBSERVABILITY.md) ---
    # crash-safe per-process JSONL event streams (bcfl_tpu.telemetry):
    # round/phase spans, transport send/retry/ack/dedup, failure-detector
    # transitions, chaos injections, FedBuff merge lineage, ledger
    # commit/fork/heal, checkpoint and reputation events — collated into
    # one causally-ordered timeline by `bcfl-tpu trace`.
    #   None  = the dist runtime streams into its run dir (telemetry is
    #           how chaos runs are gated, so it defaults ON there); the
    #           local engine emits nothing,
    #   "off" = disabled everywhere (the overhead-measurement setting),
    #   path  = stream into this directory on both runtimes.
    telemetry_dir: Optional[str] = None
    # deterministic sampling rate in [0, 1] for HIGH-RATE transport events
    # (per-attempt outcomes, chaos draws). Invariant-grade events (final
    # send outcomes, receive dispositions, merge lineage) are never
    # sampled — the invariant checks stay exact at any setting.
    telemetry_sample: float = 1.0

    def __post_init__(self):
        if self.lora_ranks:
            spec = parse_lora_ranks(self.lora_ranks)  # validates the spec
            if self.lora_rank > 0:
                raise ValueError(
                    "set lora_ranks OR lora_rank, not both: lora_ranks is "
                    "the per-client spec and canonicalizes lora_rank to "
                    "max(spec)")
            # canonicalize BEFORE the capability walk so every existing
            # `lora_rank > 0` switch sees the cohort max rank
            object.__setattr__(self, "lora_rank", max(spec))
        if self.runtime not in ("local", "dist"):
            raise ValueError(f"unknown runtime: {self.runtime!r}")
        if self.mode not in ("server", "serverless"):
            raise ValueError(f"unknown mode: {self.mode!r}")
        if self.sync not in ("sync", "async"):
            raise ValueError(f"unknown sync: {self.sync!r}")
        # the ONE capability check (RUNTIME_CAPS above): every requested
        # feature is either supported on this runtime or rejected here with
        # the table's declared reason — no per-path surprise rejections later
        for feature, active, verdict in capability_table(self):
            if active and verdict is not True:
                raise ValueError(
                    f"{feature} is not supported on runtime="
                    f"{self.runtime!r}: {verdict}")
        if self.runtime == "dist":
            if self.num_clients % self.dist.peers:
                raise ValueError(
                    f"num_clients {self.num_clients} must split evenly "
                    f"over {self.dist.peers} peers (each peer owns a "
                    "fixed client slice)")
            if self.faults.partitions and self.faults.partition_groups:
                bad = [i for g in self.faults.partition_groups for i in g
                       if i >= self.dist.peers]
                if bad:
                    raise ValueError(
                        f"dist partition_groups name PEERS; ids {bad} are "
                        f">= peers={self.dist.peers}")
            if (self.faults.partitions
                    and self.faults.partition_count > self.dist.peers):
                raise ValueError(
                    f"dist partition_count {self.faults.partition_count} "
                    f"> peers {self.dist.peers}")
            if self.faults.byz_enabled:
                bad = [p for p in self.faults.byz_peers
                       if p >= self.dist.peers]
                if bad:
                    raise ValueError(
                        f"byz_peers name PEERS; ids {bad} are >= peers="
                        f"{self.dist.peers}")
                if len(self.faults.byz_peers) >= self.dist.peers:
                    raise ValueError(
                        "byz_peers lists EVERY peer: an all-adversarial "
                        "federation has no honest majority for any rule "
                        "to defend — leave at least one peer honest")
            if self.faults.storage_enabled:
                if self.faults.storage_peers:
                    bad = [p for p in self.faults.storage_peers
                           if p >= self.dist.peers]
                    if bad:
                        raise ValueError(
                            f"storage_peers name PEERS; ids {bad} are >= "
                            f"peers={self.dist.peers}")
                for srv, req in (self.faults.sync_tamper or ()):
                    if srv >= self.dist.peers or req >= self.dist.peers:
                        raise ValueError(
                            f"sync_tamper pair ({srv}, {req}) names PEERS; "
                            f"ids must be < peers={self.dist.peers}")
                if not self.dist.checkpoint_every_versions:
                    raise ValueError(
                        "the storage fault lane injects at the checkpoint "
                        "commit seam; checkpoint_every_versions=0 never "
                        "writes one, so the lane would silently never "
                        "fire — enable checkpointing or drop the lane")
                if not self.ledger.enabled:
                    raise ValueError(
                        "the storage lane's repair path authenticates "
                        "STATE_SYNC transfers against the hash chain "
                        "(commitment rows + verify_segment); without "
                        "ledger.enabled there is no root of trust to "
                        "verify a transfer against — enable the ledger "
                        "or drop the lane")
            if self.faults.limp_enabled and self.faults.limp_peers:
                bad = [p for p in self.faults.limp_peers
                       if p >= self.dist.peers]
                if bad:
                    raise ValueError(
                        f"limp_peers name PEERS; ids {bad} are >= peers="
                        f"{self.dist.peers}")
            if self.faults.resource_enabled and self.faults.resource_peers:
                bad = [p for p in self.faults.resource_peers
                       if p >= self.dist.peers]
                if bad:
                    raise ValueError(
                        f"resource_peers name PEERS; ids {bad} are >= "
                        f"peers={self.dist.peers}")
            if self.aggregator != "mean":
                # robust aggregators are supported on dist WITH declared
                # preconditions on the merge buffer (RUNTIME.md §5): the
                # arrival set is the estimator's population, so the
                # buffer target must be large enough for the rule's
                # breakdown point to mean anything. Quorum degradation
                # can still shrink a given merge below these minima at
                # runtime — such merges aggregate with clamped trim and
                # are recorded `robust_degraded`.
                # the precondition math lives in bcfl_tpu.dist.robust
                # (MIN_ORDER_VOTES / krum_min_buffer) — the same source
                # the runtime's robust_degraded threshold reads, so
                # config-time acceptance and runtime grading can't drift
                from bcfl_tpu.dist.robust import (
                    MIN_ORDER_VOTES,
                    krum_min_buffer,
                )

                if self.dist.dispatch == "gossip":
                    # gossip has no leader buffer: the rule's population
                    # is a peer's local round arrival set — at most its
                    # sampled neighbors plus its own state. krum is
                    # already rejected by the caps table above.
                    if self.dist.gossip_fanout + 1 < MIN_ORDER_VOTES:
                        raise ValueError(
                            f"aggregator={self.aggregator!r} under "
                            f"dispatch='gossip' needs gossip_fanout >= "
                            f"{MIN_ORDER_VOTES - 1} (got "
                            f"{self.dist.gossip_fanout}): the rule's "
                            "population is a peer's neighbor arrival set "
                            "plus itself, and an order statistic over < "
                            f"{MIN_ORDER_VOTES} votes excludes nothing")
                eff = self.dist.buffer or 1
                if (self.aggregator in ("trimmed_mean", "median")
                        and self.dist.dispatch != "gossip"):
                    if eff < MIN_ORDER_VOTES:
                        raise ValueError(
                            f"aggregator={self.aggregator!r} on "
                            f"runtime='dist' needs dist.buffer >= "
                            f"{MIN_ORDER_VOTES} (got {eff}): the rule's "
                            "population is the buffered arrival set, and "
                            f"an order statistic over < {MIN_ORDER_VOTES} "
                            "votes excludes nothing")
                if self.aggregator == "krum":
                    need = krum_min_buffer(eff, self.aggregator_trim)
                    if eff < need:
                        raise ValueError(
                            f"aggregator='krum' on runtime='dist' needs "
                            f"dist.buffer >= 2f+3 = {need} for f = "
                            f"ceil(aggregator_trim * buffer) "
                            f"(got buffer {eff}): below that the "
                            "classical selection guarantee is vacuous")
        if self.num_clients < 1 or self.num_rounds < 1:
            raise ValueError("num_clients and num_rounds must be >= 1")
        if self.eval_every < 0:
            # 0 = never evaluate (pure-throughput runs); negative cadences
            # would silently produce modulo surprises
            raise ValueError(f"eval_every must be >= 0, got {self.eval_every}")
        if not 0.0 <= self.telemetry_sample <= 1.0:
            raise ValueError(
                f"telemetry_sample must be in [0, 1], got "
                f"{self.telemetry_sample}")
        if self.task not in ("classification", "causal_lm"):
            raise ValueError(f"unknown task: {self.task!r}")
        if self.prng_impl not in (None, "threefry", "rbg", "unsafe_rbg"):
            raise ValueError(
                "prng_impl must be None/threefry/rbg/unsafe_rbg, "
                f"got {self.prng_impl!r}")
        for field in ("param_dtype", "compute_dtype"):
            if getattr(self, field) not in ("float32", "bfloat16", "float16"):
                raise ValueError(
                    f"{field} must be float32/bfloat16/float16, "
                    f"got {getattr(self, field)!r}")
        if self.tp < 1 or self.sp < 1:
            raise ValueError(f"tp/sp must be >= 1, got {self.tp}/{self.sp}")
        if self.tp > 1 and self.sp > 1:
            raise ValueError("pick ONE inner mesh axis per run: tp or sp")
        if self.sp > 1 and self.seq_len % self.sp:
            raise ValueError(
                f"seq_len {self.seq_len} must be divisible by sp={self.sp} "
                "(ring attention shards the sequence into sp equal blocks)")
        if self.aggregator not in ("mean", "trimmed_mean", "median", "krum"):
            raise ValueError(
                "aggregator must be mean/trimmed_mean/median/krum, "
                f"got {self.aggregator!r}")
        if not 0.0 <= self.aggregator_trim < 0.5:
            # >= 0.5 would trim every client (2t >= k) / assume a Byzantine
            # majority, which no aggregation rule can survive
            raise ValueError(
                f"aggregator_trim must be in [0, 0.5), got "
                f"{self.aggregator_trim}")
        if self.faults.corrupts and self.faithful:
            raise ValueError(
                "FaultPlan corruption (incl. flaky bursts) models transport "
                "of the parallel paths' stacked updates; faithful "
                "(host-sequential) mode has no transport stage — use the "
                "tamper_hook shim there")
        if self.faults.partitions and self.runtime == "local":
            # the partition lane routes partitioned rounds through the
            # stacked split-phase flow with per-component aggregation
            # (ROBUSTNESS.md §6); paths with no per-component form are
            # rejected here rather than silently aggregating across a
            # partition that is supposed to exist. runtime='dist' is exempt
            # from this block: there the partition is enforced at the
            # SOCKET layer over peers (RUNTIME.md) and composes with the
            # real-clock async exchange by construction.
            if self.sync == "async":
                raise ValueError(
                    "chaos partition is not implemented for sync='async': "
                    "the buffered FedBuff merge has one global arrival "
                    "queue, and per-component queues would be a different "
                    "algorithm, not a fault model")
            if self.faithful:
                raise ValueError(
                    "chaos partition is not implemented for faithful "
                    "(host-sequential) mode — clients share ONE model, so "
                    "there is nothing to partition")
            if self.mode == "serverless" and self.topology.gossip_steps > 0:
                raise ValueError(
                    "chaos partition with ring-gossip diffusion "
                    "(gossip_steps > 0) would need a per-component ring — "
                    "a mesh reshape the fault model forbids; use "
                    "gossip_steps=0 (exact mean) for partitioned "
                    "serverless runs")
        if self.aggregator != "mean" and self.faithful:
            # the faithful path averages snapshots host-side with a plain
            # weighted sum; silently running that under a robust-aggregator
            # label would fake Byzantine protection
            raise ValueError(
                f"aggregator={self.aggregator!r} is not implemented for "
                "faithful (host-sequential) mode — it always aggregates "
                "with the reference's plain mean")
        if self.compression.enabled and self.faithful:
            # the faithful path host-sequentially mutates ONE shared model;
            # there is no per-client update exchange, so 'compressing the
            # wire' would be a label with no wire under it
            raise ValueError(
                f"compress={self.compression.kind!r} is not implemented for "
                "faithful (host-sequential) mode — it exchanges no update "
                "trees to compress")
        if self.tp > 1 and self.lora_rank <= 0:
            raise ValueError(
                "tp > 1 tensor-shards the FROZEN base and keeps per-client "
                "LoRA adapters; set lora_rank > 0 (full fine-tune is 1-D "
                "clients-only)")
        if self.lora_ranks and len(set(parse_lora_ranks(self.lora_ranks))) > 1:
            # heterogeneous ranks: the stacked adapter tree carries
            # STRUCTURAL zero padding per client (models/lora.py), and only
            # the rank-aware RBLA mean knows which coordinates are padding
            if self.aggregator != "mean":
                raise ValueError(
                    f"aggregator={self.aggregator!r} does not compose with "
                    "heterogeneous lora_ranks: order statistics have no "
                    "sound definition over structural zero padding (a "
                    "low-rank client's padded coordinate would vote an "
                    "exact 0 into every trim/median/krum decision) — use "
                    "aggregator='mean' (the rank-aware RBLA rule)")
            if self.mode != "server":
                raise ValueError(
                    "heterogeneous lora_ranks require mode='server': ring "
                    "gossip mixes whole neighbor trees, and the rank-aware "
                    "padded aggregation (RBLA) has no per-edge ring form")
            if self.faithful:
                raise ValueError(
                    "heterogeneous lora_ranks are not implemented for "
                    "faithful (host-sequential) mode — it averages host-"
                    "side with the reference's plain mean, which would "
                    "dilute low-rank clients' padded coordinates")
            if self.registry_size > 0:
                raise ValueError(
                    "heterogeneous lora_ranks do not compose with registry "
                    "sampling: ranks are cycled over the FIXED stacked "
                    "client slots, while sampling re-deals which registry "
                    "client sits in each slot every round — a client's "
                    "rank would change under it")
        if self.async_buffer < 0:
            raise ValueError(
                f"async_buffer must be >= 0, got {self.async_buffer}")
        if self.async_buffer > self.num_clients:
            # an oversized buffer can never fill: K arrivals would be waited
            # on forever while only num_clients exist — fail at config time
            # instead of silently degenerating
            raise ValueError(
                f"async_buffer {self.async_buffer} > num_clients "
                f"{self.num_clients}: the buffer could never fill (use 0 "
                "for 'aggregate when everyone arrived')")
        # --- cohort-mode capability table (SCALING.md "Cohort mode") ---
        for field in ("registry_size", "sample_clients", "cohort_size"):
            if getattr(self, field) < 0:
                raise ValueError(
                    f"{field} must be >= 0, got {getattr(self, field)}")
        if self.registry_size == 0 and (self.sample_clients
                                        or self.cohort_size):
            raise ValueError(
                "sample_clients/cohort_size have no effect without "
                "registry_size > 0 (they shape the sampled cohort of a "
                "client registry) — the same fail-loudly stance as the "
                "codec sub-flags")
        if self.registry_size > 0:
            active = self.sample_clients or self.num_clients
            if active > self.registry_size:
                raise ValueError(
                    f"sampled cohort {active} > registry_size "
                    f"{self.registry_size}: cannot draw without replacement")
            if self.cohort_size and active % self.cohort_size:
                raise ValueError(
                    f"cohort_size {self.cohort_size} must divide the "
                    f"sampled cohort size {active} (it is the per-device "
                    "stack of the cohort mesh)")
            if self.cohort_size and self.pod:
                # the pin truncates the device list to exactly
                # cohort/cohort_size shards; on a multi-host pod that can
                # exclude another process's addressable devices, which
                # fails at first dispatch with an opaque device-assignment
                # error — reject here instead
                raise ValueError(
                    "cohort_size is a single-host per-device-stack pin and "
                    "does not compose with pod=True (truncating the "
                    "hosts-major pod device list would strand other "
                    "processes' devices); leave cohort_size=0 and let "
                    "client_mesh lay the cohort over the full pod")
            # declared capability table: what composes with sampling today.
            # Aggregators (incl. robust rules), compression, ledger auth,
            # reputation, and the dropout/straggler/corrupt/churn/flaky
            # chaos lanes all compose (ids are registry ids). The paths
            # below hold per-client state the registry cannot carry — they
            # are rejected loudly rather than silently resampling it away.
            if self.mode != "server":
                raise ValueError(
                    "registry sampling requires mode='server': serverless "
                    "peers carry persistent per-client params, which a "
                    "registry >> cohort cannot keep resident (the stacked "
                    "tree IS the peer state)")
            if self.sync != "sync":
                raise ValueError(
                    "registry sampling is not implemented for sync='async': "
                    "the simulated network clock tracks per-client "
                    "completion/staleness for a FIXED client set, and a "
                    "per-round cohort would redefine that state each round")
            if self.faithful:
                raise ValueError(
                    "registry sampling is not implemented for faithful "
                    "(host-sequential) mode")
            if self.faults.partitions:
                raise ValueError(
                    "chaos partition does not compose with registry "
                    "sampling: components are defined over the full client "
                    "set, and a per-round cohort would dissolve them")

    @property
    def resolved_prng_impl(self) -> Optional[str]:
        """jax's registered name for ``prng_impl``: the config (and CLI)
        accept the colloquial ``"threefry"``, but jax registers the impl as
        ``"threefry2x32"`` — passing the config value straight to
        ``jax.random.key(impl=...)`` raised on the documented default's
        explicit spelling. None passes through (jax's process default)."""
        return ("threefry2x32" if self.prng_impl == "threefry"
                else self.prng_impl)

    @property
    def lora_rank_spec(self) -> Optional[Tuple[int, ...]]:
        """Parsed ``lora_ranks`` tuple, or None when unset (uniform rank)."""
        return parse_lora_ranks(self.lora_ranks) if self.lora_ranks else None

    @property
    def client_lora_ranks(self) -> Optional[Tuple[int, ...]]:
        """Per-client rank assignment — the spec cycled over the stacked
        client axis (length ``num_clients``), or None when uniform. This
        tuple is the static input to the padding mask and the program-cache
        key, so same spec + same fleet = same compiled program."""
        spec = self.lora_rank_spec
        if spec is None:
            return None
        return tuple(spec[i % len(spec)] for i in range(self.num_clients))

    def replace(self, **kw) -> "FedConfig":
        return dataclasses.replace(self, **kw)
