from bcfl_tpu.core.mesh import ClientMesh, client_mesh  # noqa: F401
from bcfl_tpu.core.prng import client_round_keys, fold_round  # noqa: F401
