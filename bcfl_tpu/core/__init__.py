from bcfl_tpu.core.mesh import (  # noqa: F401
    ClientMesh,
    client_mesh,
    distributed_init,
    fed_tp_mesh,
    pod_client_mesh,
    pod_devices,
)
from bcfl_tpu.core.prng import client_round_keys, fold_round  # noqa: F401
