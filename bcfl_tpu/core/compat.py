"""jax version-compatibility shims.

The codebase targets the post-0.6 public API surface (``jax.shard_map`` with
``check_vma``); older installs still ship ``shard_map`` under
``jax.experimental.shard_map`` with the same semantics behind the
``check_rep`` keyword. Import :func:`shard_map` from here instead of from
``jax`` so one module owns the dispatch — on a current jax this is a pure
pass-through.
"""

from __future__ import annotations

try:  # jax >= 0.6: public API, check_vma keyword
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax < 0.6: experimental home, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(f, **kw):
    """``jax.shard_map`` across jax versions. Accepts the modern
    ``check_vma`` keyword and translates it for installs whose shard_map
    still calls it ``check_rep``."""
    if "check_vma" in kw and _CHECK_KW != "check_vma":
        kw[_CHECK_KW] = kw.pop("check_vma")
    return _shard_map(f, **kw)
