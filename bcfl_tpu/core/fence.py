"""Real device-completion fence for tunnelled backends.

``jax.block_until_ready`` is a NO-OP for the remote arrays of the tunnelled
TPU backend this repo benches on (measured: 8 chained 4096^3 bf16 matmuls
"block" in 3 ms, then a 1-element host fetch waits 1.9 s for the actual
compute). Anything that attributes wall time to a phase — StepClock spans,
bench timing loops, async-dispatch barriers — must therefore fence with a
host readback, which is the one operation the tunnel cannot answer before
the device finishes.

``fence`` does both: ``block_until_ready`` (the correct, cheap fence on
normal backends) plus a single-element ``device_get`` of one leaf. Cost on
the tunnel is ~1-3 RTTs (a few ms) — negligible against the multi-second
dispatches it fences, but callers should still keep it OUT of per-op inner
loops.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def fence(tree) -> None:
    """Block the host until every array in ``tree`` is actually computed."""
    jax.block_until_ready(tree)
    for leaf in jax.tree.leaves(tree):
        if not isinstance(leaf, jax.Array):
            # host value (python scalar, numpy array): already materialized
            # — and reading IT back would satisfy the fence without
            # touching the device leaves
            continue
        if getattr(leaf, "size", 0) == 0:
            # a 0-byte fetch is answerable without waiting — i.e. exactly
            # the lie block_until_ready tells; pick a non-empty leaf
            continue
        # one leaf's readiness fences the XLA program that produced it
        # (outputs of a dispatch complete as a unit) — callers here pass
        # single-program outputs. 1-element slice keeps the host transfer
        # to a single scalar instead of a (possibly ~90 MB) leaf
        if jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
            np.asarray(jax.random.key_data(leaf).ravel()[0:1])
        else:
            np.asarray(leaf.ravel()[0:1])
        return
