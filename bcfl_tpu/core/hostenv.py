"""Host-environment knobs shared by the CPU-mesh drivers (scripts/)."""

from __future__ import annotations

import os


def _jaxlib_version() -> tuple:
    try:
        import jaxlib  # does NOT initialize the backend

        return tuple(int(x) for x in jaxlib.__version__.split(".")[:2])
    except Exception:
        return (0, 0)


def raise_cpu_collective_timeouts() -> None:
    """Raise XLA's CPU collective-rendezvous timeouts BEFORE backend init.

    On a CPU mesh the collective rendezvous aborts the whole process if any
    device thread lags >40s behind the others (rendezvous.cc terminate
    timeout) — easily hit on a shared/loaded 1-core host where 8 device
    threads compete through a multi-round scan. No-op if the caller already
    set the terminate flag (idempotent, and respects explicit tuning).

    Version-gated: the ``--xla_cpu_collective_call_*`` flags only exist in
    the XLA bundled with jaxlib >= 0.5, and older XLA FATALs the process on
    any unknown XLA_FLAGS entry — injecting them on jaxlib 0.4.x kills the
    run it was meant to protect (observed: every scripts/run_scaling.py
    invocation on the 0.4.36 image died at backend init)."""
    if _jaxlib_version() < (0, 5):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "collective_call_terminate" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
            " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
