"""Host-environment knobs shared by the CPU-mesh drivers (scripts/)."""

from __future__ import annotations

import os


def raise_cpu_collective_timeouts() -> None:
    """Raise XLA's CPU collective-rendezvous timeouts BEFORE backend init.

    On a CPU mesh the collective rendezvous aborts the whole process if any
    device thread lags >40s behind the others (rendezvous.cc terminate
    timeout) — easily hit on a shared/loaded 1-core host where 8 device
    threads compete through a multi-round scan. No-op if the caller already
    set the terminate flag (idempotent, and respects explicit tuning)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "collective_call_terminate" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
            " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
