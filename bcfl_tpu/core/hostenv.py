"""Host-environment knobs shared by the CPU-mesh drivers (scripts/)."""

from __future__ import annotations

import os
import threading
import time


def backend_preflight(timeout_s: float = None, exit_code: int = 3) -> float:
    """bench.py's backend-init preflight for the live driver scripts.

    ``jax.devices()`` — the call a wedged TPU tunnel actually hangs in —
    plus one tiny ``device_put`` + host readback, all under a hard watchdog
    deadline. A healthy tunnelled init is 20-40 s; a wedge previously hung
    run_results/tpu_perf/worker_pair SILENTLY for hours (the BENCH_r03-r05
    "stage made no progress" artifacts). On expiry this prints a one-line
    diagnostic and ``os._exit(exit_code)`` — fail fast with an attributable
    message instead of eating the caller's whole time budget.

    Call AFTER platform selection (``jax.config.update("jax_platforms",..)``)
    and before any real work. Returns the measured init seconds. Deadline:
    ``timeout_s`` arg, else ``BCFL_BENCH_PREFLIGHT_S``, else an explicit
    ``BCFL_BENCH_INIT_TIMEOUT_S``, else 90 s — bench.py's own precedence,
    deliberately mirrored (bench keeps an inline copy because its contract
    is an error JSON line and it may import nothing before its watchdog is
    armed; change the policy or the probe in BOTH places).
    """
    if timeout_s is None:
        timeout_s = float(os.environ.get(
            "BCFL_BENCH_PREFLIGHT_S",
            os.environ.get("BCFL_BENCH_INIT_TIMEOUT_S", "90")))

    def _fire():
        print(f"PREFLIGHT: backend init made no progress within "
              f"{timeout_s:.0f}s (wedged TPU tunnel?); exiting "
              f"{exit_code} — nothing was run, no artifact was written",
              flush=True)
        os._exit(exit_code)

    timer = threading.Timer(timeout_s, _fire)
    timer.daemon = True
    timer.start()
    t0 = time.time()
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        devices = jax.devices()  # the backend-initializing call
        probe = np.asarray(jax.device_put(jnp.arange(16, dtype=jnp.int32)))
        if int(probe.sum()) != 120:
            raise RuntimeError(f"preflight readback mismatch: {probe!r}")
    finally:
        timer.cancel()
    dt = time.time() - t0
    print(f"preflight: backend alive ({len(devices)} x "
          f"{devices[0].device_kind}, {dt:.1f}s)", flush=True)
    return dt


def _jaxlib_version() -> tuple:
    try:
        import jaxlib  # does NOT initialize the backend

        return tuple(int(x) for x in jaxlib.__version__.split(".")[:2])
    except Exception:
        return (0, 0)


def raise_cpu_collective_timeouts() -> None:
    """Raise XLA's CPU collective-rendezvous timeouts BEFORE backend init.

    On a CPU mesh the collective rendezvous aborts the whole process if any
    device thread lags >40s behind the others (rendezvous.cc terminate
    timeout) — easily hit on a shared/loaded 1-core host where 8 device
    threads compete through a multi-round scan. No-op if the caller already
    set the terminate flag (idempotent, and respects explicit tuning).

    Version-gated: the ``--xla_cpu_collective_call_*`` flags only exist in
    the XLA bundled with jaxlib >= 0.5, and older XLA FATALs the process on
    any unknown XLA_FLAGS entry — injecting them on jaxlib 0.4.x kills the
    run it was meant to protect (observed: every scripts/run_scaling.py
    invocation on the 0.4.36 image died at backend init)."""
    if _jaxlib_version() < (0, 5):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "collective_call_terminate" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=120"
            " --xla_cpu_collective_call_terminate_timeout_seconds=1200")
