"""Device mesh with a ``clients`` axis — the spine of the framework.

The reference parallelizes clients with Ray actors (server mode,
``src/Servercase/server_IID_IMDB.py:211-218`` — effectively serialized, since
``ray_init_args={"num_cpus": 1}``) or a plain Python loop (serverless mode,
``src/Serverlesscase/serverless_NonIID_IMDB.py:286``). Here clients live on a
``jax.sharding.Mesh`` axis: per-client params/opt-state/batches carry a leading
client dimension sharded across the axis, and a whole federated round — every
client's local training plus the aggregation collective — is ONE compiled XLA
program. With fewer devices than clients, each device vmaps a stack of clients.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENT_AXIS = "clients"


def _largest_divisor_leq(n: int, cap: int) -> int:
    for d in range(min(n, cap), 0, -1):
        if n % d == 0:
            return d
    return 1


@dataclasses.dataclass(frozen=True)
class ClientMesh:
    """A 1-D mesh over ``n_devices`` devices hosting ``num_clients`` clients.

    ``per_device`` clients are stacked on each device (leading array dim);
    collectives over :data:`CLIENT_AXIS` combine across devices, a reduction
    over the stacked dim combines within a device.
    """

    mesh: Mesh
    num_clients: int
    per_device: int

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def axis(self) -> str:
        return CLIENT_AXIS

    def client_sharding(self) -> NamedSharding:
        """Sharding for arrays with a leading (num_clients-sized) client dim."""
        return NamedSharding(self.mesh, P(CLIENT_AXIS))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_clients(self, tree):
        """Device-put a pytree whose leaves all have leading dim num_clients."""
        return jax.device_put(tree, self.client_sharding())

    def replicate(self, tree):
        return jax.device_put(tree, self.replicated())

    def global_client_ids(self) -> np.ndarray:
        """[num_clients] array mapping stacked order -> global client id.

        Layout is device-major: device d holds clients
        ``[d*per_device, (d+1)*per_device)``.
        """
        return np.arange(self.num_clients)


def client_mesh(
    num_clients: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> ClientMesh:
    """Build the clients mesh.

    Uses the largest divisor of ``num_clients`` that fits the available device
    count, so any client count runs on any device count (num_clients=10 on 8
    CPU devices -> 5 mesh devices x 2 stacked clients; 32 clients on a v5e-32
    -> 1 client per chip, the BASELINE.json north star).
    """
    devices = list(devices if devices is not None else jax.devices())
    d = _largest_divisor_leq(num_clients, len(devices))
    mesh = Mesh(np.array(devices[:d]), (CLIENT_AXIS,))
    return ClientMesh(mesh=mesh, num_clients=num_clients, per_device=num_clients // d)
