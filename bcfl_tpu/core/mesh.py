"""Device mesh with a ``clients`` axis — the spine of the framework.

The reference parallelizes clients with Ray actors (server mode,
``src/Servercase/server_IID_IMDB.py:211-218`` — effectively serialized, since
``ray_init_args={"num_cpus": 1}``) or a plain Python loop (serverless mode,
``src/Serverlesscase/serverless_NonIID_IMDB.py:286``). Here clients live on a
``jax.sharding.Mesh`` axis: per-client params/opt-state/batches carry a leading
client dimension sharded across the axis, and a whole federated round — every
client's local training plus the aggregation collective — is ONE compiled XLA
program. With fewer devices than clients, each device vmaps a stack of clients.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

CLIENT_AXIS = "clients"


def _largest_divisor_leq(n: int, cap: int) -> int:
    for d in range(min(n, cap), 0, -1):
        if n % d == 0:
            return d
    return 1


@dataclasses.dataclass(frozen=True)
class ClientMesh:
    """A mesh hosting ``num_clients`` clients on its ``clients`` axis.

    ``per_device`` clients are stacked on each clients-axis shard (leading
    array dim); collectives over :data:`CLIENT_AXIS` combine across shards, a
    reduction over the stacked dim combines within a shard.

    ``tp > 1`` makes the mesh 2-D ``(clients, tp)``: each client's
    forward/backward spans ``tp`` chips via megatron tensor-parallel param
    shardings (``bcfl_tpu.models.tp_param_specs``) on the FROZEN base, while
    per-client arrays stay ``P(clients)`` (replicated over tp). The same
    GSPMD round programs run unchanged — XLA inserts the tp collectives from
    the sharding annotations (this is the composition the reference cannot
    express at all: many clients x a model bigger than one chip).
    """

    mesh: Mesh
    num_clients: int
    per_device: int
    tp: int = 1
    sp: int = 1  # sequence-parallel shards per client ('seq' axis)

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    @property
    def axis(self) -> str:
        return CLIENT_AXIS

    def client_sharding(self) -> NamedSharding:
        """Sharding for arrays with a leading (num_clients-sized) client dim."""
        return NamedSharding(self.mesh, P(CLIENT_AXIS))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_clients(self, tree):
        """Device-put a pytree whose leaves all have leading dim num_clients."""
        return jax.device_put(tree, self.client_sharding())

    def shard_round_clients(self, tree):
        """Device-put leaves shaped [R, num_clients, ...] (round-leading,
        client dim sharded) — the multi-round program's input layout."""
        return jax.device_put(
            tree, NamedSharding(self.mesh, P(None, CLIENT_AXIS)))

    def replicate(self, tree):
        return jax.device_put(tree, self.replicated())

    def global_client_ids(self) -> np.ndarray:
        """[num_clients] array mapping stacked order -> global client id.

        Layout is device-major: device d holds clients
        ``[d*per_device, (d+1)*per_device)``.
        """
        return np.arange(self.num_clients)


def distributed_init(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Gated ``jax.distributed.initialize`` for multi-host pods.

    The reference has no cross-machine communication at all — Flower runs as
    a single-process Ray simulation (SURVEY.md §2.5). Here multi-host is the
    DCN story: call this once per host process before any backend use, then
    build meshes from :func:`pod_devices`. Parameters default to the
    ``BCFL_COORDINATOR`` / ``BCFL_NUM_PROCESSES`` / ``BCFL_PROCESS_ID`` env
    vars; a single-process setting (the common case, and every CI run) is a
    no-op returning False.
    """
    import os

    if num_processes is None:
        num_processes = int(os.environ.get("BCFL_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return False
    if process_id is None:
        pid = os.environ.get("BCFL_PROCESS_ID")
        if pid is None:
            # defaulting to 0 would make EVERY host register as process 0 and
            # hang the coordinator barrier with no useful error
            raise ValueError(
                "multi-process init needs a distinct process_id per host: "
                "pass process_id= or set BCFL_PROCESS_ID")
        process_id = int(pid)
    jax.distributed.initialize(
        coordinator_address=coordinator_address
        or os.environ.get("BCFL_COORDINATOR"),
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def pod_devices() -> list:
    """Global devices ordered hosts-major (DCN-outermost).

    Laying the 1-D ``clients`` axis over this order means: FedAvg ``psum``
    reduces over ICI within each host before crossing DCN once, and ring
    gossip ``ppermute`` neighbors are intra-host except a single DCN hop per
    host boundary — the layout rule 'collectives ride ICI, not DCN'.
    Single-process: plain ``jax.devices()``.
    """
    if jax.process_count() == 1:
        return list(jax.devices())
    from jax.experimental import mesh_utils

    per_host = jax.device_count() // jax.process_count()
    # granule = PROCESS (host), not TPU slice: a multi-host single-slice pod
    # (e.g. v4-16: 2 hosts, one slice) has process_count() granules of
    # per-host devices, and CPU multi-process rigs have no slice_index at all
    grid = mesh_utils.create_hybrid_device_mesh(
        (per_host,), (jax.process_count(),), process_is_granule=True)
    return list(grid.reshape(-1))


def pod_client_mesh(num_clients: int, tp: int = 1) -> ClientMesh:
    """clients mesh spanning every host in the pod (see :func:`pod_devices`)."""
    return client_mesh(num_clients, devices=pod_devices(), tp=tp)


def fed_tp_mesh(client_shards: int, tp: int,
                devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """2-D ``(clients, tp)`` mesh: each client spans ``tp`` chips for
    megatron tensor parallelism (``bcfl_tpu.models.llama.tp_specs``), clients
    are parallel across the first axis. tp is innermost so a client's
    tensor-parallel collectives ride adjacent-ICI links.
    Used by :mod:`bcfl_tpu.parallel.fed_tp`."""
    devices = list(devices) if devices is not None else pod_devices()
    need = client_shards * tp
    if len(devices) < need:
        raise ValueError(
            f"fed_tp_mesh needs {need} devices ({client_shards} client shards"
            f" x tp={tp}), have {len(devices)}")
    return Mesh(np.asarray(devices[:need]).reshape(client_shards, tp),
                (CLIENT_AXIS, "tp"))


def client_mesh(
    num_clients: int,
    devices: Optional[Sequence[jax.Device]] = None,
    tp: int = 1,
    sp: int = 1,
) -> ClientMesh:
    """Build the clients mesh.

    Uses the largest divisor of ``num_clients`` that fits the available device
    count, so any client count runs on any device count (num_clients=10 on 8
    CPU devices -> 5 mesh devices x 2 stacked clients; 32 clients on a v5e-32
    -> 1 client per chip, the BASELINE.json north star).

    ``tp > 1`` reserves that many devices per client shard on an inner ``tp``
    axis (2-D ``(clients, tp)`` mesh — tp innermost so a client's
    tensor-parallel collectives ride adjacent-ICI links; see
    :class:`ClientMesh`). ``sp > 1`` instead reserves an inner ``seq`` axis:
    each client's ACTIVATIONS shard over the sequence (ring attention,
    :mod:`bcfl_tpu.parallel.sp`) while params stay replicated within the
    group — the long-document federated composition.
    """
    devices = list(devices if devices is not None else jax.devices())
    if tp < 1 or sp < 1:
        raise ValueError(f"tp/sp must be >= 1, got tp={tp} sp={sp}")
    if tp > 1 and sp > 1:
        raise ValueError(
            "compose one inner axis per run: tp x sp 3-D meshes are not "
            "supported (pick tensor OR sequence parallelism per client)")
    inner_n, inner_axis = (tp, "tp") if tp > 1 else (sp, "seq")
    if inner_n > 1:
        if len(devices) < inner_n:
            raise ValueError(
                f"{inner_axis}={inner_n} needs at least that many devices, "
                f"have {len(devices)}")
        d = _largest_divisor_leq(num_clients, len(devices) // inner_n)
        mesh = Mesh(np.asarray(devices[:d * inner_n]).reshape(d, inner_n),
                    (CLIENT_AXIS, inner_axis))
        return ClientMesh(mesh=mesh, num_clients=num_clients,
                          per_device=num_clients // d, tp=tp, sp=sp)
    d = _largest_divisor_leq(num_clients, len(devices))
    mesh = Mesh(np.array(devices[:d]), (CLIENT_AXIS,))
    return ClientMesh(mesh=mesh, num_clients=num_clients, per_device=num_clients // d)
