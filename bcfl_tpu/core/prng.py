"""Deterministic PRNG threading.

The reference seeds dataset shuffles (``seed=42``,
``src/Servercase/server_IID_IMDB.py:68``) but draws client subsets with an
unseeded ``random.sample`` (``:79-80``), so runs are not reproducible. Here one
root key is folded per (round, client) so every sampling decision is
deterministic and independent across clients and rounds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def fold_round(key: jax.Array, round_idx: int) -> jax.Array:
    return jax.random.fold_in(key, round_idx)


def client_round_keys(key: jax.Array, clients, round_idx: int) -> jax.Array:
    """[num_clients, 2] stacked keys, one per client, distinct per round.

    ``clients`` is a count (keys for ids ``0..n-1``) or an explicit id
    vector — cohort mode (SCALING.md) passes the round's sampled REGISTRY
    ids, so a client's stream depends only on ``(seed, id, round)``, never
    on which cohort slot it landed in."""
    rk = fold_round(key, round_idx)
    ids = (jnp.arange(clients) if isinstance(clients, (int, np.integer))
           else jnp.asarray(np.asarray(clients), jnp.int32))
    return jax.vmap(lambda c: jax.random.fold_in(rk, c))(ids)
