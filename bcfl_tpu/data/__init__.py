from bcfl_tpu.data.tokenizer import HashTokenizer, get_tokenizer  # noqa: F401
from bcfl_tpu.data.partition import (  # noqa: F401
    iid_indices,
    contiguous_indices,
    Partitioner,
)
from bcfl_tpu.data.datasets import TextDataset, load_dataset, register_dataset  # noqa: F401
from bcfl_tpu.data.pipeline import TokenCache, client_batches  # noqa: F401
