"""Synthetic data augmentation (C20).

The reference ships pre-generated augmented CSVs under
``Dataset/Augmeted_datasets/`` — CTGAN, GaussianCopula, and random-shuffle
variants of the self-driving-car sentiment set (SURVEY.md §2.2 C20) produced
offline with the SDV library. Here augmentation is a live, seeded capability
over any :class:`~bcfl_tpu.data.datasets.TextDataset`:

- ``shuffle``  — label-preserving word-order shuffles (the reference's
  random-shuffle CSV),
- ``markov``   — per-class bigram Markov chains sampled into new documents
  (the generative CTGAN-class capability, text-native),
- ``copula``   — Gaussian-copula sampling over per-document token-frequency
  feature vectors, decoded back to text by nearest-frequency vocabulary draw
  (the GaussianCopula-class capability).

All numpy, host-side, deterministic under one seed — augmentation happens
before tokenization so the TPU pipeline is unchanged.
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Dict, List, Tuple

import numpy as np

from bcfl_tpu.data.datasets import TextDataset

METHODS = ("shuffle", "markov", "copula")


def _split_words(text: str) -> List[str]:
    return text.split()


def shuffle_texts(texts: List[str], rng: np.random.Generator) -> List[str]:
    out = []
    for t in texts:
        w = _split_words(t)
        rng.shuffle(w)
        out.append(" ".join(w))
    return out


def _markov_tables(texts: List[str]):
    """Bigram transition table + start distribution for one class."""
    starts: Dict[str, int] = defaultdict(int)
    trans: Dict[str, Dict[str, int]] = defaultdict(lambda: defaultdict(int))
    lengths = []
    for t in texts:
        w = _split_words(t)
        if not w:
            continue
        lengths.append(len(w))
        starts[w[0]] += 1
        for a, b in zip(w, w[1:]):
            trans[a][b] += 1
    return starts, trans, lengths or [8]


def _sample_markov(starts, trans, lengths, rng: np.random.Generator) -> str:
    skeys = list(starts)
    sp = np.array([starts[k] for k in skeys], np.float64)
    word = skeys[rng.choice(len(skeys), p=sp / sp.sum())]
    n = int(rng.choice(lengths))
    out = [word]
    for _ in range(n - 1):
        nxt = trans.get(word)
        if not nxt:
            break
        keys = list(nxt)
        p = np.array([nxt[k] for k in keys], np.float64)
        word = keys[rng.choice(len(keys), p=p / p.sum())]
        out.append(word)
    return " ".join(out)


def _copula_sample(texts: List[str], n: int, rng: np.random.Generator,
                   vocab_cap: int = 256) -> List[str]:
    """Gaussian copula over token-count feature vectors: estimate the
    empirical marginals + correlation of per-document counts for the class's
    top-``vocab_cap`` tokens, draw correlated normals, map back through the
    marginal quantiles, and emit each token ``count`` times (order by
    frequency — bag-of-words synthesis, like the reference's tabular SDV
    usage applied to text)."""
    vocab: Dict[str, int] = defaultdict(int)
    for t in texts:
        for w in _split_words(t):
            vocab[w] += 1
    top = sorted(vocab, key=vocab.get, reverse=True)[:vocab_cap]
    if not top:
        return [""] * n
    idx = {w: i for i, w in enumerate(top)}
    X = np.zeros((len(texts), len(top)), np.float64)
    for r, t in enumerate(texts):
        for w in _split_words(t):
            if w in idx:
                X[r, idx[w]] += 1
    # gaussianize the rank (copula) marginals, estimate correlation
    U = (np.argsort(np.argsort(X, axis=0), axis=0) + 0.5) / len(texts)
    Zn = _norm_ppf(np.clip(U, 1e-4, 1 - 1e-4))
    C = np.corrcoef(Zn, rowvar=False)
    C = np.atleast_2d(np.nan_to_num(C, nan=0.0))
    np.fill_diagonal(C, 1.0)
    # nearest PSD: clip eigenvalues before the Cholesky
    vals, vecs = np.linalg.eigh(C)
    C = (vecs * np.maximum(vals, 1e-6)) @ vecs.T
    L = np.linalg.cholesky(C + 1e-9 * np.eye(len(top)))
    draws = rng.standard_normal((n, len(top))) @ L.T
    # map correlated normals back through the empirical marginal quantiles
    Xs = np.sort(X, axis=0)
    u = _norm_cdf(draws)
    pos = np.clip((u * (len(texts) - 1)).astype(int), 0, len(texts) - 1)
    counts = Xs[pos, np.arange(len(top))[None, :]]
    out = []
    for r in range(n):
        words = []
        for j, w in enumerate(top):
            words.extend([w] * int(round(counts[r, j])))
        rng.shuffle(words)
        out.append(" ".join(words) if words else top[0])
    return out


def _norm_cdf(x: np.ndarray) -> np.ndarray:
    from math import sqrt

    try:
        from scipy.special import ndtr

        return ndtr(x)
    except ImportError:
        import math

        return np.vectorize(lambda v: 0.5 * (1 + math.erf(v / sqrt(2))))(x)


def _norm_ppf(u: np.ndarray) -> np.ndarray:
    try:
        from scipy.special import ndtri

        return ndtri(u)
    except ImportError:
        # Acklam's rational approximation — |rel err| < 1.15e-9, plenty for
        # rank gaussianization
        a = [-3.969683028665376e+01, 2.209460984245205e+02,
             -2.759285104469687e+02, 1.383577518672690e+02,
             -3.066479806614716e+01, 2.506628277459239e+00]
        b = [-5.447609879822406e+01, 1.615858368580409e+02,
             -1.556989798598866e+02, 6.680131188771972e+01,
             -1.328068155288572e+01]
        c = [-7.784894002430293e-03, -3.223964580411365e-01,
             -2.400758277161838e+00, -2.549732539343734e+00,
             4.374664141464968e+00, 2.938163982698783e+00]
        d = [7.784695709041462e-03, 3.224671290700398e-01,
             2.445134137142996e+00, 3.754408661907416e+00]
        u = np.asarray(u, np.float64)
        out = np.empty_like(u)
        lo, hi = 0.02425, 1 - 0.02425
        low, high = u < lo, u > hi
        mid = ~(low | high)
        q = np.sqrt(-2 * np.log(np.where(low, u, 0.5)))
        out[low] = (((((c[0]*q+c[1])*q+c[2])*q+c[3])*q+c[4])*q+c[5])[low] / \
                   ((((d[0]*q+d[1])*q+d[2])*q+d[3])*q+1)[low]
        q = u - 0.5
        r = q * q
        out[mid] = ((((((a[0]*r+a[1])*r+a[2])*r+a[3])*r+a[4])*r+a[5])*q)[mid] / \
                   (((((b[0]*r+b[1])*r+b[2])*r+b[3])*r+b[4])*r+1)[mid]
        q = np.sqrt(-2 * np.log(np.where(high, 1 - u, 0.5)))
        out[high] = -((((((c[0]*q+c[1])*q+c[2])*q+c[3])*q+c[4])*q+c[5])[high] /
                      ((((d[0]*q+d[1])*q+d[2])*q+d[3])*q+1)[high])
        return out


def augment_dataset(
    ds: TextDataset,
    method: str = "shuffle",
    factor: float = 0.5,
    seed: int = 42,
) -> TextDataset:
    """Return ``ds`` with ``factor * n_train`` synthetic rows appended to the
    train split (class-balanced over the original label distribution)."""
    if method not in METHODS:
        raise ValueError(f"unknown augmentation {method!r}; have {METHODS}")
    rng = np.random.default_rng(seed)
    n_new = int(ds.n_train * factor)
    by_class: Dict[int, List[str]] = defaultdict(list)
    for t, y in zip(ds.train_texts, ds.train_labels):
        by_class[int(y)].append(t)
    labels = list(by_class)
    probs = np.array([len(by_class[c]) for c in labels], np.float64)
    probs = probs / probs.sum()

    new_texts: List[str] = []
    new_labels: List[int] = []
    draw = rng.choice(len(labels), size=n_new, p=probs)
    per_class = defaultdict(int)
    for d in draw:
        per_class[labels[d]] += 1

    for c, cnt in per_class.items():
        src = by_class[c]
        if method == "shuffle":
            picks = rng.choice(len(src), size=cnt)
            new_texts.extend(shuffle_texts([src[i] for i in picks], rng))
        elif method == "markov":
            starts, trans, lengths = _markov_tables(src)
            if not starts:
                continue
            new_texts.extend(
                _sample_markov(starts, trans, lengths, rng) for _ in range(cnt))
        else:  # copula
            new_texts.extend(_copula_sample(src, cnt, rng))
        new_labels.extend([c] * cnt)

    return dataclasses.replace(
        ds,
        name=f"{ds.name}+{method}",
        train_texts=list(ds.train_texts) + new_texts,
        train_labels=np.concatenate(
            [ds.train_labels,
             np.asarray(new_labels, ds.train_labels.dtype)]),
    )
