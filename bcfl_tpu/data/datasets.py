"""Dataset registry.

The reference pulls four HF-hub datasets (``imdb``,
``bhargavi909/Medical_Transcriptions_upsampled``, ``bhargavi909/covid_final``,
``bhargavi909/cancer_classification`` — SURVEY.md §2.1) and ships local CSVs
under ``Dataset/``. This module exposes them behind one registry:

- ``synthetic`` — generated classification corpus with class-correlated token
  patterns (learnable), used by tests/benches and as the offline stand-in,
- ``medical_transcriptions`` — the reference's on-disk CSVs
  (``Dataset/train_file_mt.csv`` / ``test_file_mt.csv``: columns
  ``description`` -> ``medical_specialty`` in [0, 40)),
- ``covid`` — ``Dataset/sentiment_analysis_self_driving_vehicles.csv``-style
  local CSV fallback,
- ``imdb`` / ``cancer`` / any HF-hub name — via ``datasets.load_dataset`` when
  the hub is reachable, else a deterministic synthetic stand-in with the same
  label space (zero-egress environments).

Every dataset resolves to a :class:`TextDataset`: plain lists of strings +
int labels for train/test. Tokenization happens once, downstream, in
:mod:`bcfl_tpu.data.pipeline`.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Callable, Dict, List, Optional

import numpy as np

REFERENCE_DATASET_DIR = "/root/reference/Dataset"


@dataclasses.dataclass
class TextDataset:
    name: str
    train_texts: List[str]
    train_labels: np.ndarray  # int32 [N]
    test_texts: List[str]
    test_labels: np.ndarray
    num_labels: int

    @property
    def n_train(self) -> int:
        return len(self.train_texts)

    @property
    def n_test(self) -> int:
        return len(self.test_texts)


_REGISTRY: Dict[str, Callable[..., TextDataset]] = {}


def register_dataset(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def load_dataset(name: str, **kw) -> TextDataset:
    if name in _REGISTRY:
        return _REGISTRY[name](**kw)
    return _load_hf(name, **kw)


# --------------------------------------------------------------------------
# synthetic corpus: class-correlated unigrams over a fixed wordlist, so a
# linear-ish classifier reaches high accuracy in a few hundred steps -- the
# role the (tiny) reference subsets play in its smoke runs.
# --------------------------------------------------------------------------

_WORDS = [
    "alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf", "hotel",
    "india", "juliet", "kilo", "lima", "mike", "november", "oscar", "papa",
    "quebec", "romeo", "sierra", "tango", "uniform", "victor", "whiskey",
    "xray", "yankee", "zulu", "amber", "birch", "cedar", "dune", "ember",
    "fjord", "grove", "harbor", "isle", "jade", "krill", "lagoon", "mesa",
    "nectar", "onyx", "prairie", "quartz", "reef", "summit", "tundra",
    "umbra", "vale", "willow", "zenith",
]


def _synthetic_split(rng: np.random.Generator, n: int, num_labels: int, doc_len: int):
    texts, labels = [], np.empty((n,), dtype=np.int32)
    n_words = len(_WORDS)
    for i in range(n):
        y = int(rng.integers(num_labels))
        labels[i] = y
        # each class prefers a distinct band of the wordlist; 60% signal words
        band = [
            _WORDS[(y * 7 + j) % n_words] for j in rng.integers(0, 12, size=doc_len).tolist()
        ]
        noise = [_WORDS[int(k)] for k in rng.integers(0, n_words, size=doc_len).tolist()]
        pick = rng.random(doc_len) < 0.6
        words = [b if p else m for b, m, p in zip(band, noise, pick)]
        texts.append(" ".join(words))
    return texts, labels


@register_dataset("synthetic")
def _synthetic(
    num_labels: int = 2,
    n_train: int = 4096,
    n_test: int = 1024,
    doc_len: int = 32,
    seed: int = 42,
    name: str = "synthetic",
) -> TextDataset:
    rng = np.random.default_rng(seed)
    tr_t, tr_y = _synthetic_split(rng, n_train, num_labels, doc_len)
    te_t, te_y = _synthetic_split(rng, n_test, num_labels, doc_len)
    return TextDataset(name, tr_t, tr_y, te_t, te_y, num_labels)


# --------------------------------------------------------------------------
# reference CSVs (medical transcriptions really exists on disk)
# --------------------------------------------------------------------------


def _read_csv(path: str, text_col: str, label_col: str):
    import pandas as pd

    df = pd.read_csv(path)
    texts = df[text_col].astype(str).tolist()
    labels = df[label_col].astype(np.int32).to_numpy()
    return texts, labels


@register_dataset("medical_transcriptions")
def _medical(
    data_dir: str = REFERENCE_DATASET_DIR,
    num_labels: int = 40,
    **_,
) -> TextDataset:
    """Reference: ``bhargavi909/Medical_Transcriptions_upsampled`` on the hub
    (``src/Servercase/server_iid_medical_transcirptions.py:48``); its on-disk
    twin is ``Dataset/train_file_mt.csv`` (12,021 rows) / ``test_file_mt.csv``
    (3,003 rows) with ``description`` -> ``medical_specialty``."""
    tr = os.path.join(data_dir, "train_file_mt.csv")
    te = os.path.join(data_dir, "test_file_mt.csv")
    if not (os.path.exists(tr) and os.path.exists(te)):
        return _synthetic(num_labels=num_labels, name="medical_transcriptions")
    tr_t, tr_y = _read_csv(tr, "description", "medical_specialty")
    te_t, te_y = _read_csv(te, "description", "medical_specialty")
    n = int(max(tr_y.max(), te_y.max())) + 1
    return TextDataset("medical_transcriptions", tr_t, tr_y, te_t, te_y, max(n, num_labels))


@register_dataset("imdb")
def _imdb(num_labels: int = 2, **kw) -> TextDataset:
    """Reference: HF-hub ``imdb`` (``server_IID_IMDB.py:66``). The repo's
    ``imdb_Test.csv`` was stripped from the mirror (``.MISSING_LARGE_BLOBS``),
    so offline we fall back to a synthetic 2-class stand-in."""
    return _load_hf_or_synthetic("imdb", text_col="text", label_col="label",
                                 num_labels=num_labels, **kw)


@register_dataset("cancer")
def _cancer(num_labels: int = 41, **kw) -> TextDataset:
    """Reference: ``bhargavi909/cancer_classification``, ``input`` -> ``labels``
    (``serverless_caner_classification_iid.py:49,53``)."""
    return _load_hf_or_synthetic(
        "bhargavi909/cancer_classification", text_col="input", label_col="labels",
        num_labels=num_labels, alias="cancer", **kw,
    )


@register_dataset("covid")
def _covid(num_labels: int = 41, **kw) -> TextDataset:
    """Reference: ``bhargavi909/covid_final``, ``text`` -> ``sentiment``
    (``serverless_covid_iid.py:49,65-66``)."""
    return _load_hf_or_synthetic(
        "bhargavi909/covid_final", text_col="text", label_col="sentiment",
        num_labels=num_labels, alias="covid", **kw,
    )


def _load_hf(name: str, text_col: str = "text", label_col: str = "label",
             num_labels: int = 2, alias: Optional[str] = None, seed: int = 42) -> TextDataset:
    import datasets as hf_datasets

    ds = hf_datasets.load_dataset(name)
    train, test = ds["train"], ds.get("test", ds["train"])
    tr_y = np.asarray(train[label_col], dtype=np.int32)
    te_y = np.asarray(test[label_col], dtype=np.int32)
    n = int(max(tr_y.max(), te_y.max())) + 1
    return TextDataset(
        alias or name,
        list(train[text_col]), tr_y,
        list(test[text_col]), te_y,
        max(n, num_labels),
    )


def _load_hf_or_synthetic(name: str, *, text_col: str, label_col: str,
                          num_labels: int, alias: Optional[str] = None,
                          seed: int = 42, **_) -> TextDataset:
    try:
        return _load_hf(name, text_col=text_col, label_col=label_col,
                        num_labels=num_labels, alias=alias, seed=seed)
    except Exception as e:
        # zero-egress environment: deterministic stand-in, same label space.
        # Loud and distinguishable — the name carries the stand-in marker so a
        # run can never silently report hub-dataset accuracy on filler text.
        warnings.warn(
            f"could not load HF dataset {name!r} ({type(e).__name__}: {e}); "
            "using a deterministic synthetic stand-in with the same label space",
            stacklevel=2,
        )
        return _synthetic(num_labels=num_labels, seed=seed,
                          name=f"{alias or name}:synthetic-standin")
