"""Dataset registry.

The reference pulls four HF-hub datasets (``imdb``,
``bhargavi909/Medical_Transcriptions_upsampled``, ``bhargavi909/covid_final``,
``bhargavi909/cancer_classification`` — SURVEY.md §2.1) and ships local CSVs
under ``Dataset/``. This module exposes them behind one registry:

- ``synthetic`` — generated classification corpus with class-correlated token
  patterns (learnable), used by tests/benches and as the offline stand-in,
- ``medical_transcriptions`` — the reference's on-disk CSVs
  (``Dataset/train_file_mt.csv`` / ``test_file_mt.csv``: columns
  ``description`` -> ``medical_specialty`` in [0, 40)),
- ``covid`` — ``Dataset/sentiment_analysis_self_driving_vehicles.csv``-style
  local CSV fallback,
- ``imdb`` / ``cancer`` / any HF-hub name — via ``datasets.load_dataset`` when
  the hub is reachable, else a deterministic synthetic stand-in with the same
  label space (zero-egress environments).

Every dataset resolves to a :class:`TextDataset`: plain lists of strings +
int labels for train/test. Tokenization happens once, downstream, in
:mod:`bcfl_tpu.data.pipeline`.
"""

from __future__ import annotations

import dataclasses
import os
import warnings
from typing import Callable, Dict, List, Optional

import numpy as np

REFERENCE_DATASET_DIR = "/root/reference/Dataset"


@dataclasses.dataclass
class TextDataset:
    name: str
    train_texts: List[str]
    train_labels: np.ndarray  # int32 [N]
    test_texts: List[str]
    test_labels: np.ndarray
    num_labels: int

    @property
    def n_train(self) -> int:
        return len(self.train_texts)

    @property
    def n_test(self) -> int:
        return len(self.test_texts)


_REGISTRY: Dict[str, Callable[..., TextDataset]] = {}


def register_dataset(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def load_dataset(name: str, **kw) -> TextDataset:
    if name.startswith("csv:"):
        return _load_csv_spec(name[4:], **kw)
    # "name+variant" selects a loader's augmentation variant from config
    # (e.g. "self_driving_sentiment+ctgan"); only loaders that declare an
    # ``augmented`` parameter accept one
    base, plus, variant = name.partition("+")
    if plus and not variant:
        raise ValueError(f"dataset name {name!r} has a trailing '+' with no "
                         "variant")
    if base in _REGISTRY:
        # registry datasets own their reference column mappings (SURVEY.md
        # §2.1 matrix); config-level text_col/label_col only applies to
        # csv:/hub datasets
        kw.pop("text_col", None)
        kw.pop("label_col", None)
        if variant:
            import inspect

            if "augmented" in kw:
                raise ValueError(
                    f"dataset {name!r} has a +variant suffix AND an explicit "
                    f"augmented={kw['augmented']!r} kwarg; pass one or the "
                    "other")
            params = inspect.signature(_REGISTRY[base]).parameters
            if "augmented" not in params:
                raise ValueError(
                    f"dataset {base!r} has no augmentation variants "
                    f"(got {name!r})")
            kw["augmented"] = variant
        return _REGISTRY[base](**kw)
    return _load_hf(name, **kw)


def _map_labels(raw, lut: Optional[Dict[str, int]] = None) -> tuple:
    """Raw label column -> (int32 array, num_labels, lut). Integer labels
    pass through (lut None); float columns are accepted only when exactly
    integral (pandas upcasts int columns with a missing value to float —
    silently string-sorting "10.0" before "2.0" would corrupt every label);
    string labels map by sorted unique value (deterministic). Pass ``lut`` to
    reuse an existing mapping (e.g. augmentation files must share the base
    file's classes)."""
    arr = np.asarray(raw)
    if arr.dtype.kind in "iu":
        labels = arr.astype(np.int32)
        return labels, int(labels.max()) + 1, None
    if arr.dtype.kind == "f":
        if np.isnan(arr).any():
            raise ValueError("label column contains NaN/missing values")
        if not (arr == np.round(arr)).all():
            raise ValueError(
                "label column is float with non-integral values; map your "
                "labels to ints or strings explicitly")
        labels = arr.astype(np.int32)
        return labels, int(labels.max()) + 1, None
    if arr.dtype.kind not in "OUS":
        raise ValueError(f"unsupported label dtype {arr.dtype}")
    if lut is None:
        values = sorted({str(v) for v in arr})
        lut = {v: i for i, v in enumerate(values)}
    try:
        mapped = [lut[str(v)] for v in arr]
    except KeyError as e:
        raise ValueError(f"label {e.args[0]!r} not in mapping {sorted(lut)}")
    return np.asarray(mapped, np.int32), len(lut), lut


def _holdout_split(texts, labels, test_frac: float, seed: int):
    rng = np.random.default_rng(seed)
    idx = rng.permutation(len(texts))
    n_test = max(int(len(texts) * test_frac), 1)
    te, tr = idx[:n_test], idx[n_test:]
    return ([texts[i] for i in tr], labels[tr],
            [texts[i] for i in te], labels[te])


def _load_csv_spec(spec: str, text_col: str = "text", label_col: str = "label",
                   num_labels: int = 0, test_frac: float = 0.2,
                   seed: int = 42, **_) -> TextDataset:
    """Generic local-CSV dataset: ``dataset="csv:<train.csv>"`` (deterministic
    holdout split) or ``csv:<train.csv>::<test.csv>``; column names come from
    the config's ``text_col`` / ``label_col``. This is the offline answer to
    the reference's hub datasets — any corpus a user has on disk runs through
    the same pipeline (string labels map to ints by sorted unique value)."""
    parts = spec.split("::")
    tr_t, tr_raw = _read_raw_csv(parts[0], text_col, label_col)
    if len(parts) > 1:
        te_t, te_raw = _read_raw_csv(parts[1], text_col, label_col)
        labels, n, _ = _map_labels(list(tr_raw) + list(te_raw))
        tr_y, te_y = labels[:len(tr_t)], labels[len(tr_t):]
    else:
        labels, n, _ = _map_labels(tr_raw)
        tr_t, tr_y, te_t, te_y = _holdout_split(tr_t, labels, test_frac, seed)
    name = "csv:" + os.path.basename(parts[0])
    return TextDataset(name, tr_t, tr_y, te_t, te_y, max(n, num_labels))


# --------------------------------------------------------------------------
# synthetic corpus: class-correlated unigrams over a fixed wordlist, so a
# linear-ish classifier reaches high accuracy in a few hundred steps -- the
# role the (tiny) reference subsets play in its smoke runs.
# --------------------------------------------------------------------------

_WORDS = [
    "alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf", "hotel",
    "india", "juliet", "kilo", "lima", "mike", "november", "oscar", "papa",
    "quebec", "romeo", "sierra", "tango", "uniform", "victor", "whiskey",
    "xray", "yankee", "zulu", "amber", "birch", "cedar", "dune", "ember",
    "fjord", "grove", "harbor", "isle", "jade", "krill", "lagoon", "mesa",
    "nectar", "onyx", "prairie", "quartz", "reef", "summit", "tundra",
    "umbra", "vale", "willow", "zenith",
]


def _synthetic_split(rng: np.random.Generator, n: int, num_labels: int, doc_len: int):
    texts, labels = [], np.empty((n,), dtype=np.int32)
    n_words = len(_WORDS)
    for i in range(n):
        y = int(rng.integers(num_labels))
        labels[i] = y
        # each class prefers a distinct band of the wordlist; 60% signal words
        band = [
            _WORDS[(y * 7 + j) % n_words] for j in rng.integers(0, 12, size=doc_len).tolist()
        ]
        noise = [_WORDS[int(k)] for k in rng.integers(0, n_words, size=doc_len).tolist()]
        pick = rng.random(doc_len) < 0.6
        words = [b if p else m for b, m, p in zip(band, noise, pick)]
        texts.append(" ".join(words))
    return texts, labels


@register_dataset("synthetic")
def _synthetic(
    num_labels: int = 2,
    n_train: int = 4096,
    n_test: int = 1024,
    doc_len: int = 32,
    seed: int = 42,
    name: str = "synthetic",
    **_,
) -> TextDataset:
    rng = np.random.default_rng(seed)
    tr_t, tr_y = _synthetic_split(rng, n_train, num_labels, doc_len)
    te_t, te_y = _synthetic_split(rng, n_test, num_labels, doc_len)
    return TextDataset(name, tr_t, tr_y, te_t, te_y, num_labels)


# --------------------------------------------------------------------------
# reference CSVs (medical transcriptions really exists on disk)
# --------------------------------------------------------------------------


def _read_raw_csv(path: str, text_col: str, label_col: str):
    import pandas as pd

    df = pd.read_csv(path)
    for col in (text_col, label_col):
        if col not in df.columns:
            raise ValueError(
                f"{path}: column {col!r} not found; have {df.columns.tolist()}")
    return df[text_col].astype(str).tolist(), df[label_col].tolist()


@register_dataset("medical_transcriptions")
def _medical(
    data_dir: str = REFERENCE_DATASET_DIR,
    num_labels: int = 40,
    **_,
) -> TextDataset:
    """Reference: ``bhargavi909/Medical_Transcriptions_upsampled`` on the hub
    (``src/Servercase/server_iid_medical_transcirptions.py:48``); its on-disk
    twin is ``Dataset/train_file_mt.csv`` (12,000 records) / ``test_file_mt.csv``
    (3,000 records) with ``description`` -> ``medical_specialty``."""
    tr = os.path.join(data_dir, "train_file_mt.csv")
    te = os.path.join(data_dir, "test_file_mt.csv")
    if not (os.path.exists(tr) and os.path.exists(te)):
        return _synthetic(num_labels=num_labels, name="medical_transcriptions")
    tr_t, tr_raw = _read_raw_csv(tr, "description", "medical_specialty")
    te_t, te_raw = _read_raw_csv(te, "description", "medical_specialty")
    # ONE lut over train+test: mapping the two splits independently would
    # silently mis-join their label spaces for string-labeled variants (the
    # shipped MT CSVs carry ints, where either way coincides — but the
    # reference maps specialty STRINGS, server_iid_medical_transcirptions
    # .py:56,68, and a user's own CSV may too)
    labels, n, _ = _map_labels(list(tr_raw) + list(te_raw))
    tr_y, te_y = labels[:len(tr_t)], labels[len(tr_t):]
    return TextDataset("medical_transcriptions", tr_t, tr_y, te_t, te_y, max(n, num_labels))


@register_dataset("imdb")
def _imdb(num_labels: int = 2, **kw) -> TextDataset:
    """Reference: HF-hub ``imdb`` (``server_IID_IMDB.py:66``). The repo's
    ``imdb_Test.csv`` was stripped from the mirror (``.MISSING_LARGE_BLOBS``),
    so offline we fall back to a synthetic 2-class stand-in."""
    return _load_hf_or_synthetic("imdb", text_col="text", label_col="label",
                                 num_labels=num_labels, **kw)


@register_dataset("cancer")
def _cancer(num_labels: int = 41, **kw) -> TextDataset:
    """Reference: ``bhargavi909/cancer_classification``, text column ``input``
    (``serverless_caner_classification_iid.py:49,53``); the hub label column
    is ``label``, which the reference renames to ``labels``
    (``serverless_caner_classification_iid.py:66``)."""
    return _load_hf_or_synthetic(
        "bhargavi909/cancer_classification", text_col="input", label_col="label",
        num_labels=num_labels, alias="cancer", **kw,
    )


@register_dataset("covid")
def _covid(num_labels: int = 41, **kw) -> TextDataset:
    """Reference: ``bhargavi909/covid_final``, ``text`` -> ``sentiment``
    (``serverless_covid_iid.py:49,65-66``)."""
    return _load_hf_or_synthetic(
        "bhargavi909/covid_final", text_col="text", label_col="sentiment",
        num_labels=num_labels, alias="covid", **kw,
    )


@register_dataset("self_driving_sentiment")
def _self_driving(
    data_dir: str = REFERENCE_DATASET_DIR,
    num_labels: int = 3,
    augmented: Optional[str] = None,  # None | "ctgan" | "copula" | "shuffle"
    test_frac: float = 0.2,
    seed: int = 42,
    **_,
) -> TextDataset:
    """Reference: ``Dataset/sentiment_analysis_self_driving_vehicles.csv``
    (500 rows, ``Text`` -> ``Sentiment`` in {Negative, Neutral, Positive})
    plus the synthetic-augmentation variants under ``Augmeted_datasets/``
    (CTGAN / GaussianCopula / random-shuffle — SURVEY.md C20). ``augmented``
    APPENDS the chosen augmentation file to the train split (the augmentation
    use-case); the holdout test split always comes from the real rows."""
    files = {
        "ctgan": "Augmeted_datasets/CTGAN_self_driving_vehicles.csv",
        "copula": "Augmeted_datasets/output_Gaussiancopula_self_driving.csv",
        "shuffle": "Augmeted_datasets/output_file_path_random_counts.csv",
    }
    if augmented is not None and augmented not in files:
        raise ValueError(
            f"unknown augmentation {augmented!r}; have {sorted(files)}")
    variant = f"+{augmented}" if augmented else ""
    base = os.path.join(data_dir, "sentiment_analysis_self_driving_vehicles.csv")
    if not os.path.exists(base):
        warnings.warn(
            f"{base} not found; using a deterministic synthetic stand-in",
            stacklevel=2)
        return _synthetic(
            num_labels=num_labels, seed=seed,
            name=f"self_driving_sentiment{variant}:synthetic-standin")
    texts, raw = _read_raw_csv(base, "Text", "Sentiment")
    labels, n, lut = _map_labels(raw)
    tr_t, tr_y, te_t, te_y = _holdout_split(texts, labels, test_frac, seed)
    if augmented is not None:
        aug_t, aug_raw = _read_raw_csv(
            os.path.join(data_dir, files[augmented]), "Text", "Sentiment")
        aug_y, _n, _ = _map_labels(aug_raw, lut)  # base file's class mapping
        tr_t = tr_t + aug_t
        tr_y = np.concatenate([tr_y, aug_y]).astype(np.int32)
    return TextDataset(f"self_driving_sentiment{variant}",
                       tr_t, tr_y, te_t, te_y, max(n, num_labels))


def _load_hf(name: str, text_col: str = "text", label_col: str = "label",
             num_labels: int = 2, alias: Optional[str] = None, seed: int = 42) -> TextDataset:
    import datasets as hf_datasets

    ds = hf_datasets.load_dataset(name)
    train, test = ds["train"], ds.get("test", ds["train"])

    # the config defaults are reference-flavored (label_col="labels"); hub
    # datasets mostly use "label" — resolve against what actually exists so
    # a bare hub name works without per-dataset column config
    def resolve(col, alts):
        if col in train.column_names:
            return col
        for a in alts:
            if a in train.column_names:
                return a
        raise ValueError(
            f"{name}: column {col!r} not found; have {train.column_names}")

    text_col = resolve(text_col, ("text", "sentence"))
    label_col = resolve(label_col, ("label", "labels"))
    # _map_labels handles int, integral-float (pandas NaN-upcast guard), and
    # string label columns; train/test must share one mapping
    tr_y, _, lut = _map_labels(train[label_col])
    te_y, _, _ = _map_labels(test[label_col], lut)
    n = int(max(tr_y.max(), te_y.max())) + 1
    return TextDataset(
        alias or name,
        list(train[text_col]), tr_y,
        list(test[text_col]), te_y,
        max(n, num_labels),
    )


def _load_hf_or_synthetic(name: str, *, text_col: str, label_col: str,
                          num_labels: int, alias: Optional[str] = None,
                          seed: int = 42, **_) -> TextDataset:
    try:
        return _load_hf(name, text_col=text_col, label_col=label_col,
                        num_labels=num_labels, alias=alias, seed=seed)
    except Exception as e:
        # zero-egress environment: deterministic stand-in, same label space.
        # Loud and distinguishable — the name carries the stand-in marker so a
        # run can never silently report hub-dataset accuracy on filler text.
        warnings.warn(
            f"could not load HF dataset {name!r} ({type(e).__name__}: {e}); "
            "using a deterministic synthetic stand-in with the same label space",
            stacklevel=2,
        )
        return _synthetic(num_labels=num_labels, seed=seed,
                          name=f"{alias or name}:synthetic-standin")
