"""Client data partitioners (IID random / Non-IID contiguous).

Reproduces both partition schedules of the reference exactly (so accuracy
curves are comparable), but deterministically keyed — the reference's IID
sampling uses an unseeded ``random.sample``
(``src/Servercase/server_IID_IMDB.py:79-80``).

- IID: ``n`` random indices per client (reference draws 100 for IMDB
  ``serverless_IID_IMDB.py:60-65``, 500 for medical/cancer/covid
  ``Serverless_iid_Medical_transcriptions.py:54-55``), optionally resampled
  every round (``serverless_IID_IMDB.py:258``).
- Non-IID contiguous, trailing test: client ``k`` gets train
  ``[stride*k, stride*k+train_span)`` of the train split and test
  ``[stride*k+train_span, stride*(k+1))`` of the test split — the 300k/240
  IMDB schedule (``serverless_NonIID_IMDB.py:59-60``).
- Non-IID contiguous, fixed test: train ``[stride*i, stride*i+train_span)``,
  test ``[0, test_span)`` shared by all clients — the 500i/400 medical
  schedule (``Serverless_NonIID_Medical_transcriptions.py:55-56``).

Indices are into the train/test splits respectively; slices are clipped (with
wraparound for fully out-of-range clients) instead of silently producing empty
loaders like the reference would.
"""

from __future__ import annotations

from typing import Tuple

import jax
import numpy as np

from bcfl_tpu.config import PartitionConfig
from bcfl_tpu.core.prng import fold_round


def iid_indices(key: jax.Array, n_total: int, n_samples: int) -> np.ndarray:
    """Random sample without replacement, deterministic under ``key``."""
    n_samples = min(n_samples, n_total)
    perm = jax.random.permutation(key, n_total)
    return np.asarray(perm[:n_samples])


def _clip_or_wrap(lo: int, span: int, n_total: int) -> np.ndarray:
    idx = np.arange(lo, min(lo + span, n_total))
    if idx.size == 0 and n_total > 0:
        lo = lo % n_total
        idx = np.arange(lo, min(lo + span, n_total))
    return idx


def contiguous_indices(
    client: int,
    stride: int,
    train_span: int,
    test_span: int,
    n_train: int,
    n_test: int,
    test_mode: str = "trailing",
) -> Tuple[np.ndarray, np.ndarray]:
    """Reference Non-IID slice arithmetic, clipped to each split's length."""
    train = _clip_or_wrap(stride * client, train_span, n_train)
    if test_mode == "trailing":
        test = _clip_or_wrap(stride * client + train_span, test_span, n_test)
    else:  # fixed shared test slice
        test = np.arange(0, min(test_span, n_test))
    return train, test


class Partitioner:
    """Per-(client, round) index selection driven by :class:`PartitionConfig`."""

    def __init__(self, cfg: PartitionConfig, n_train: int, n_test: int, key: jax.Array):
        self.cfg = cfg
        self.n_train = n_train
        self.n_test = n_test
        self.key = key

    def train_test_indices(self, client: int, round_idx: int) -> Tuple[np.ndarray, np.ndarray]:
        c = self.cfg
        if c.kind == "iid":
            r = round_idx if c.resample_each_round else 0
            k = jax.random.fold_in(fold_round(self.key, r), client)
            k_train, k_test = jax.random.split(k)
            n_test = c.iid_samples if c.iid_test_samples is None else c.iid_test_samples
            return (
                iid_indices(k_train, self.n_train, c.iid_samples),
                iid_indices(k_test, self.n_test, n_test),
            )
        return contiguous_indices(
            client, c.stride, c.train_span, c.test_span, self.n_train, self.n_test, c.test_mode
        )
