"""Tokenize-once cache + static-shape federated batch stacks.

Fixes the reference's biggest data-path waste: serverless mode re-tokenizes the
ENTIRE dataset once per client per round (``load_data_clients`` called inside
the round loop, ``src/Serverlesscase/serverless_NonIID_IMDB.py:287`` — 200 full
passes for 10 clients x 20 rounds). Here the corpus is tokenized exactly once
into ``[N, seq_len]`` int32 arrays; per-(client, round) selection is pure
index gather.

Batch stacks are fully static-shaped for XLA: a round's training input is one
``[num_clients, steps, batch, seq_len]`` array (sharded over the clients mesh
axis), where ``steps`` is fixed across clients; clients with fewer examples
wrap around (the per-example loss mask keeps metrics honest).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from bcfl_tpu.data.datasets import TextDataset
from bcfl_tpu.data.partition import Partitioner


@dataclasses.dataclass
class TokenCache:
    """One-shot tokenization of a :class:`TextDataset`."""

    train_ids: np.ndarray  # [N_train, L] int32
    train_mask: np.ndarray
    train_labels: np.ndarray  # [N_train] int32
    test_ids: np.ndarray
    test_mask: np.ndarray
    test_labels: np.ndarray
    num_labels: int

    @classmethod
    def build(cls, ds: TextDataset, tokenizer, seq_len: int) -> "TokenCache":
        tr_ids, tr_mask = tokenizer.encode_batch(ds.train_texts, seq_len)
        te_ids, te_mask = tokenizer.encode_batch(ds.test_texts, seq_len)
        return cls(tr_ids, tr_mask, ds.train_labels, te_ids, te_mask, ds.test_labels,
                   ds.num_labels)


def _gather_batches(ids, mask, labels, idx: np.ndarray, batch: int, steps: int):
    """[steps*batch] indices (wrapping) -> ids/mask/labels/example-mask stacks."""
    need = steps * batch
    if idx.size == 0:
        idx = np.zeros((1,), dtype=np.int64)
        valid = np.zeros((need,), dtype=np.float32)
    else:
        valid = (np.arange(need) < idx.size).astype(np.float32)
    take = idx[np.arange(need) % idx.size]
    shape = (steps, batch)
    return (
        ids[take].reshape(shape + ids.shape[1:]),
        mask[take].reshape(shape + mask.shape[1:]),
        labels[take].reshape(shape),
        valid.reshape(shape),
    )


def client_batches(
    cache: TokenCache,
    part: Partitioner,
    num_clients,
    round_idx: int,
    batch_size: int,
    max_batches: Optional[int] = None,
    split: str = "train",
) -> Tuple[dict, np.ndarray]:
    """Build the round's stacked per-client batches.

    ``num_clients`` is a count (clients ``0..n-1``, the classic layout) or
    an explicit client-id vector — cohort mode (SCALING.md) passes the
    round's sampled REGISTRY ids, so each stacked slot carries that
    registry client's own data partition.

    Returns ``(batch_tree, num_examples)`` where ``batch_tree`` leaves are
    ``[num_clients, steps, batch, ...]`` numpy arrays (``ids``, ``mask``,
    ``labels``, ``example_mask``) and ``num_examples[c]`` is the true example
    count per client (the FedAvg weighting the Flower strategy uses —
    ``weighted_average``, ``src/Servercase/server_IID_IMDB.py:199-204``).
    """
    if split == "train":
        ids, mask, labels = cache.train_ids, cache.train_mask, cache.train_labels
    else:
        ids, mask, labels = cache.test_ids, cache.test_mask, cache.test_labels

    client_ids = (range(num_clients)
                  if isinstance(num_clients, (int, np.integer))
                  else np.asarray(num_clients).tolist())
    per_client_idx = []
    for c in client_ids:
        tr, te = part.train_test_indices(int(c), round_idx)
        per_client_idx.append(tr if split == "train" else te)
    num_clients = len(per_client_idx)

    sizes = [max(i.size, 1) for i in per_client_idx]
    steps = int(np.ceil(max(sizes) / batch_size))
    if max_batches is not None:
        steps = min(steps, max_batches)
    steps = max(steps, 1)

    out_ids, out_mask, out_labels, out_emask = [], [], [], []
    n_examples = np.zeros((num_clients,), dtype=np.float32)
    for c, idx in enumerate(per_client_idx):
        n_examples[c] = idx.size
        b_ids, b_mask, b_labels, b_emask = _gather_batches(
            ids, mask, labels, idx, batch_size, steps
        )
        out_ids.append(b_ids)
        out_mask.append(b_mask)
        out_labels.append(b_labels)
        out_emask.append(b_emask)

    tree = {
        "ids": np.stack(out_ids),
        "mask": np.stack(out_mask),
        "labels": np.stack(out_labels),
        "example_mask": np.stack(out_emask),
    }
    return tree, n_examples


def central_eval_batches(cache: TokenCache, batch_size: int, max_batches: Optional[int] = None):
    """Whole-test-set batches for global-model evaluation (reference:
    ``evaluate_global_model`` on a fresh IID loader,
    ``serverless_IID_IMDB.py:232-249``)."""
    n = cache.test_ids.shape[0]
    steps = int(np.ceil(n / batch_size))
    if max_batches is not None:
        steps = min(steps, max_batches)
    idx = np.arange(n)
    ids, mask, labels, emask = _gather_batches(
        cache.test_ids, cache.test_mask, cache.test_labels, idx, batch_size, steps
    )
    return {"ids": ids, "mask": mask, "labels": labels, "example_mask": emask}
