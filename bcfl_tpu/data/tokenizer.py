"""Tokenization with TPU-friendly static shapes.

The reference tokenizes with HF ``AutoTokenizer`` + per-batch dynamic padding
(``DataCollatorWithPadding``, ``src/Servercase/server_IID_IMDB.py:96-99``) —
and re-tokenizes the full dataset once per client per round in serverless mode
(``serverless_NonIID_IMDB.py:287`` calls ``load_data_clients`` inside the round
loop: 200 full tokenization passes per run — see SURVEY.md §3.2). Dynamic
padding is hostile to XLA (every batch shape recompiles), so here:

- tokenize ONCE into a cached ``[N, seq_len]`` int32 array + mask,
- pad/truncate to a fixed ``seq_len`` (reference truncates at the model max of
  512 anyway; one variant attempts ``max_length=500``,
  ``Serverless_NonIID_Medical_transcriptions.py:83``).

Two tokenizers:

- :class:`HashTokenizer` — dependency-free deterministic whitespace+hash
  word tokenizer. Used offline (no HF hub egress) and in tests/benches.
- HF tokenizers via :func:`get_tokenizer` when a pretrained vocab is
  available locally, for checkpoint-faithful runs.
"""

from __future__ import annotations

import re
import zlib
from typing import Sequence, Tuple

import numpy as np

PAD_ID = 0
UNK_ID = 1
CLS_ID = 2
SEP_ID = 3
N_SPECIAL = 4

_WORD_RE = re.compile(r"[a-z0-9']+|[^\sa-z0-9']")


class HashTokenizer:
    """Deterministic hashing word tokenizer (feature-hashing vocab).

    No trained vocab file is needed: token id = crc32(word) % (vocab - 4) + 4.
    Collisions are benign at the classification fidelity the reference targets
    and the mapping is stable across processes/hosts (crc32, not Python hash).
    """

    def __init__(self, vocab_size: int = 8192):
        if vocab_size <= N_SPECIAL:
            raise ValueError("vocab_size too small")
        self.vocab_size = vocab_size

    def _word_id(self, w: str) -> int:
        return zlib.crc32(w.encode("utf-8")) % (self.vocab_size - N_SPECIAL) + N_SPECIAL

    def encode(self, text: str, seq_len: int) -> Tuple[np.ndarray, np.ndarray]:
        words = _WORD_RE.findall(text.lower())
        ids = ([CLS_ID] + [self._word_id(w) for w in words[: max(seq_len - 2, 0)]] + [SEP_ID])[
            :seq_len
        ]
        n = len(ids)
        out = np.full((seq_len,), PAD_ID, dtype=np.int32)
        out[:n] = ids
        mask = np.zeros((seq_len,), dtype=np.int32)
        mask[:n] = 1
        return out, mask

    def encode_batch(self, texts: Sequence[str], seq_len: int) -> Tuple[np.ndarray, np.ndarray]:
        native = self._encode_batch_native(texts, seq_len)
        if native is not None:
            return native
        ids = np.empty((len(texts), seq_len), dtype=np.int32)
        mask = np.empty((len(texts), seq_len), dtype=np.int32)
        for i, t in enumerate(texts):
            ids[i], mask[i] = self.encode(t, seq_len)
        return ids, mask

    def _encode_batch_native(self, texts: Sequence[str], seq_len: int):
        """C++ cache-build hot loop (`native/tokenizer.cc`), bit-for-bit
        equal to :meth:`encode` (pinned by tests/test_native_tokenizer.py).
        Unicode lowercasing stays HERE (Python's full case rules); the core
        gets the lowered UTF-8 bytes. Returns None without a toolchain."""
        import ctypes

        from bcfl_tpu.native.build import load_tokenizer_lib

        lib = load_tokenizer_lib()
        if lib is None or seq_len <= 0 or len(texts) == 0:
            return None
        try:
            blobs = [t.lower().encode("utf-8") for t in texts]
        except UnicodeEncodeError:
            # lone surrogates (e.g. errors='surrogateescape' reads) can't
            # cross the UTF-8 boundary; the Python path handles them
            return None
        offsets = np.zeros((len(blobs) + 1,), dtype=np.int64)
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        buf = b"".join(blobs)
        n = len(blobs)
        ids = np.empty((n, seq_len), dtype=np.int32)
        mask = np.empty((n, seq_len), dtype=np.int32)
        lib.bcfl_hash_tokenize(
            buf, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, seq_len, self.vocab_size,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return ids, mask


class HFTokenizerAdapter:
    """Wraps a HF fast tokenizer into the fixed-shape interface."""

    def __init__(self, name: str):
        from transformers import AutoTokenizer  # local import: optional dep

        self._tok = AutoTokenizer.from_pretrained(name)
        # len() includes added/special tokens; .vocab_size does not, and ids
        # can exceed it -> silent OOB-clamped embedding gathers on TPU
        self.vocab_size = len(self._tok)

    def encode_batch(self, texts: Sequence[str], seq_len: int) -> Tuple[np.ndarray, np.ndarray]:
        enc = self._tok(
            list(texts),
            truncation=True,
            max_length=seq_len,
            padding="max_length",
            return_tensors="np",
        )
        return enc["input_ids"].astype(np.int32), enc["attention_mask"].astype(np.int32)


def get_tokenizer(name: str, vocab_size: int = 8192):
    """``"hash"`` -> :class:`HashTokenizer`; anything else -> HF tokenizer."""
    if name == "hash":
        return HashTokenizer(vocab_size)
    return HFTokenizerAdapter(name)
