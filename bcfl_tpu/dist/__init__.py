"""Real multi-host async P2P runtime (``FedConfig.runtime="dist"``).

Each peer is an OS process owning a fixed slice of the clients; the update
exchange rides length-prefixed TCP over loopback/DCN carrying the codec
wire format (:mod:`bcfl_tpu.compression.codecs`) plus ledger fingerprint
digests; aggregation is FedBuff-style buffered async with MEASURED
staleness; a transport partition genuinely forks the ledger chain per
connected component and the heal reconciles the forks. See RUNTIME.md.
"""

from bcfl_tpu.dist.harness import free_ports, reap_all, run_dist
from bcfl_tpu.dist.launch import cfg_from_json, cfg_to_json
from bcfl_tpu.dist.transport import (
    FailureDetector,
    PartitionGate,
    PeerTransport,
    TransportError,
    WireChaos,
)
from bcfl_tpu.dist.wire import (
    frame_prefix,
    pack_frame,
    read_frame,
    unpack_frame,
    write_frame,
)

__all__ = [
    "FailureDetector", "PartitionGate", "PeerTransport", "TransportError",
    "WireChaos", "cfg_from_json", "cfg_to_json", "frame_prefix",
    "free_ports", "pack_frame", "read_frame", "reap_all", "run_dist",
    "unpack_frame", "write_frame",
]
