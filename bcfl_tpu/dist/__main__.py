"""``python -m bcfl_tpu.dist`` — one peer process of the dist runtime."""

import sys

from bcfl_tpu.dist.runtime import peer_main

if __name__ == "__main__":
    sys.exit(peer_main())
