"""Adversarial-peer injection for the dist runtime — the byzantine lane.

The wire lane (PR 8) attacks the NETWORK: frames are dropped, duplicated,
corrupted in flight — and the CRC/retry/dedup transport heals all of it,
because a damaged frame is detectably damaged. This module attacks the
PEER: a :class:`ByzantineAdversary` rewrites its own outbound updates
*above* the wire, so every frame is well-formed, correctly CRC'd, acked,
and deduped — the transport delivers the lie perfectly. What catches it is
the application layer this PR adds: the leader's refingerprint-on-arrival
(ledger evidence), the robust buffered merge (outlier evidence), the
measured-staleness lineage checks (replay evidence), and the
:class:`bcfl_tpu.reputation.dist.DistReputationTracker` that folds all of
it into quarantine.

Behaviors (drawn per (peer, round) by :meth:`FaultPlan.byz_action`,
ROBUSTNESS.md §8 "Adversary model"):

- ``scale`` / ``sign_flip`` / ``garbage`` — **poisoning**: the payload's
  float parts are scaled / negated / replaced with seeded noise, and the
  announced digests are RE-COMPUTED over the poisoned payload (the caller
  re-fingerprints), so ledger authentication PASSES — this is the attack
  only the robust merge rules and the outlier evidence can catch,
- ``digest_forge`` — **forgery**: the announced digests stay the honest
  payload's, the shipped bytes are poisoned — announce one fingerprint,
  ship another; the leader's commit→refingerprint→verify order catches it
  as a per-client auth failure (the hard evidence lane),
- ``replay`` — **staleness attack**: an earlier update (header AND
  payload, recorded verbatim at send time) is resent under a fresh
  transport identity; the stale ``base_version``/``lineage`` either
  rejects at the leader's lineage check or merges at an outlier staleness
  — both are reputation evidence,
- ``equivocate`` — **split-brain**: the payload each DESTINATION receives
  is perturbed with destination-keyed seeded noise under one announced
  digest, so two receivers of "the same" update hold different bytes and
  each sees its own digest mismatch.

Determinism contract (pinned in tests/test_dist_byzantine.py): identical
``(plan seed, round, peer, destination)`` coordinates always produce the
identical mutated bytes, and a disabled lane returns the caller's objects
UNTOUCHED (the clean-twin bit-match gate) — the lane is exactly as absent
as its config says.
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from bcfl_tpu.faults import FaultPlan
from bcfl_tpu import telemetry


def _map_floats(tree, fn):
    """Apply ``fn`` to every float ndarray leaf of a (nested dict) host
    tree — the same "perturb the float parts" semantics as the corruption
    lanes: quantized int8 codes / int payloads ride along untouched, the
    scales/values that reconstruct the update are what get poisoned.

    Keys are visited in SORTED order: ``fn`` consumes seeded RNG draws
    per leaf (garbage/equivocate), so the visit order IS part of the
    determinism contract — insertion order would tie the mutated bytes to
    however the host happened to build the tree, not to the (seed, round,
    peer, dst) coordinates."""
    if isinstance(tree, dict):
        return {k: _map_floats(tree[k], fn) for k in sorted(tree)}
    arr = np.asarray(tree)
    if np.issubdtype(arr.dtype, np.floating):
        return fn(arr)
    return tree


class ByzantineAdversary:
    """Binds the FaultPlan byzantine lane to ONE peer process.

    Constructed by every peer (cheap); :meth:`corrupt_update` is the one
    injection seam — a no-op identity for honest peers and disabled lanes.
    ``clock_fn`` is the peer's local round (the same autonomous span clock
    the partition/wire lanes use)."""

    #: how many of its own past sends the adversary remembers for replay
    REPLAY_DEPTH = 8

    def __init__(self, plan: Optional[FaultPlan], peer_id: int,
                 clock_fn: Callable[[], int]):
        self.plan = plan if plan is not None else FaultPlan()
        self.peer_id = int(peer_id)
        self.clock_fn = clock_fn
        # (header, wire_tree) of past HONEST sends, oldest first — the
        # replay corpus (deep copies: the runtime mutates nothing, but a
        # replayed header must carry the ORIGINAL round/base/lineage)
        self._history: List[Tuple[Dict, Dict]] = []
        self.injected: Dict[str, int] = {b: 0 for b in
                                         self.plan.byz_behaviors}

    @property
    def armed(self) -> bool:
        return (self.plan.byz_enabled
                and self.peer_id in (self.plan.byz_peers or ()))

    def corrupt_update(self, header: Dict, wire_tree: Dict,
                       dst: int) -> Tuple[Dict, Dict, Optional[Dict]]:
        """Maybe-rewrite one outbound update bound for peer ``dst``.

        Returns ``(header, wire_tree, action)`` — the INPUT objects,
        untouched, with ``action=None`` when the peer behaves honestly
        this round (lane off / not this peer / span not due / prob draw);
        otherwise fresh mutated copies plus the drawn action dict.
        ``action["reannounce"]`` tells the caller whether the announced
        digests must be recomputed over the mutated payload (the
        poisoning behaviors, which must PASS ledger auth) or left as the
        honest announcement (forgery/equivocation, which must FAIL the
        leader's refingerprint)."""
        rnd = int(self.clock_fn())
        act = self.plan.byz_action(rnd, self.peer_id)
        if not self.armed or act is None:
            # honest this round: record it as replay corpus and pass the
            # caller's objects through IDENTICALLY (bit-match contract)
            if self.armed:
                self._remember(header, wire_tree)
            return header, wire_tree, None
        behavior = act["behavior"]
        scale = act["scale"]
        rng = self.plan.byz_rng(rnd, self.peer_id, int(dst))
        if behavior == "replay" and not self._history:
            # nothing recorded yet to replay: behave HONESTLY this round
            # (recording it as corpus) rather than substitute a behavior
            # the plan may have excluded — at byz_prob=1.0 this is every
            # adversary's first acting round, after which the corpus is
            # never empty again (acting rounds record their honest input
            # below)
            self._remember(header, wire_tree)
            return header, wire_tree, None
        out_header, out_tree = dict(header), wire_tree
        reannounce = False
        if behavior == "scale":
            out_tree = _map_floats(wire_tree,
                                   lambda a: (a * scale).astype(a.dtype))
            reannounce = True
        elif behavior == "sign_flip":
            out_tree = _map_floats(wire_tree, lambda a: -a)
            reannounce = True
        elif behavior == "garbage":
            out_tree = _map_floats(
                wire_tree,
                lambda a: (rng.standard_normal(a.shape) * scale).astype(
                    a.dtype))
            reannounce = True
        elif behavior == "digest_forge":
            # announce the honest digests, ship a poisoned payload: the
            # leader's refingerprint of what ARRIVED must mismatch
            out_tree = _map_floats(wire_tree,
                                   lambda a: (a * scale).astype(a.dtype))
        elif behavior == "equivocate":
            # destination-keyed noise under the honest announcement: two
            # destinations receive different bytes for "one" update
            out_tree = _map_floats(
                wire_tree,
                lambda a: (a + rng.standard_normal(a.shape)).astype(
                    a.dtype))
        elif behavior == "replay":
            old_header, old_tree = self._history[0]
            # the stale header verbatim (old round/base_version/lineage/
            # digests/sent_at) — the transport stamps a fresh msg identity
            out_header = dict(old_header)
            out_tree = copy.deepcopy(old_tree)
        # every round's HONEST input feeds the replay corpus — an
        # always-acting adversary (byz_prob=1.0, the harness default)
        # must still accumulate stale updates to resend
        self._remember(header, wire_tree)
        self.injected[behavior] = self.injected.get(behavior, 0) + 1
        telemetry.emit("byz.inject", behavior=behavior, round=rnd,
                       dst=int(dst), reannounce=reannounce)
        return out_header, out_tree, dict(act, behavior=behavior,
                                          reannounce=reannounce)

    def _remember(self, header: Dict, wire_tree: Dict) -> None:
        self._history.append((copy.deepcopy(header),
                              copy.deepcopy(wire_tree)))
        while len(self._history) > self.REPLAY_DEPTH:
            self._history.pop(0)

    def stats(self) -> Dict:
        """Per-behavior injection counts for the peer report (the baseline
        legs gate these at exactly zero with the lane off)."""
        return {"armed": self.armed, "injected": dict(self.injected),
                "total": int(sum(self.injected.values()))}
