"""Leaderless gossip dispatch — the dist runtime's third execution mode
(``DistConfig.dispatch='gossip'``, RUNTIME.md "Gossip dispatch").

The leadered path (runtime.py) funnels every update through one privileged
process per component: the min-reachable-id leader owns the FedBuff merge,
the robust votes, and the reputation clock — one slow or SIGKILLed leader
stalls its whole component until failover. Here NO peer is special:

- **Exchange** — after each local round a peer pushes its full merged
  state to ``gossip_fanout`` neighbors drawn by :func:`sample_neighbors`
  from a PRNG keyed ``(seed, round, peer)`` over the LIVE membership view
  (epidemic draw or ring successors) — the topology is replayable given
  the seed and the membership history.
- **Merge** — arrivals fold in through :func:`merge_states`, a
  commutative, versioned rule: every state carries a per-source **version
  vector** (``vv[p]`` = rounds of peer p's training incorporated), the
  merged vv is the elementwise max, and each input is weighted by its
  example mass x ``staleness_decay ** lag`` (lag = how far its vv trails
  the union) x the local trust gate. Inputs are reduced in canonical
  (peer id, msg identity) order, so ``merge(a, b) == merge(b, a)``
  bitwise — there is no merge clock to agree on.
- **Robustness** — with a robust aggregator configured, the trimming rule
  (bcfl_tpu.dist.robust) runs PEER-LOCALLY over the round's arrival set
  plus the peer's own state; outlier flags feed the local reputation
  tracker only. Arrivals authenticate against their announced
  :func:`state_digest`; a mismatch is local ledger-auth evidence. No
  global verdicts exist — each peer quarantines on what IT saw.
- **Membership is elastic** (bcfl_tpu.dist.membership): the live view
  shrinks on failure-detector DOWN transitions and explicit "leaving"
  messages, re-grows on ANY received frame, and a periodic HELLO beacon
  (answered by anyone with a state+chain sync) makes join/resync a
  steady-state event. Neighbor sampling always draws over the live view,
  so a SIGKILLed peer stops being gossiped at within the detector window
  — zero round stall, no failover protocol.
- **Ledger** — each peer extends its OWN chain (own client digests plus
  accepted arrivals' announced state digests); replicas reconcile
  pairwise through the existing fork/merge API (``fork_point`` /
  ``verify_segment`` / ``merge_rows`` / ``adopt_merge``) whenever a sync
  lands, instead of converging on one consensus head.
- **Partitions heal leaderlessly** (RUNTIME.md §9): the FaultPlan
  partition lane's :class:`~bcfl_tpu.dist.transport.PartitionGate` cuts
  the socket for any dispatch; here each component keeps converging on
  its own clocks — neighbor draws stay inside the gate component, the
  merge seam rejects frames buffered across the cut (the gossip scope of
  ``no_cross_partition_merge``), and a component too small for the
  configured robust rule degrades to the commutative mean with a
  catalogued ``gossip.vote_floor`` event. The heal has NO arbiter: on
  span exit the peer HELLO-probes everyone the cut hid, the answering
  syncs fold in through the ordinary version-vector merge, and the chain
  replicas reconcile pairwise through the fork/merge API above. A
  periodic anti-entropy probe at one seeded DORMANT peer
  (:func:`probe_targets`) backstops the beacon: the HELLO lane samples
  the LIVE view only, so two detector-shrunk views would otherwise never
  rediscover each other — split-brain forever. Trust stays on local wire
  evidence, with one amnesty: a peer the cut (or the detector) hid takes
  no staleness/outlier evidence until it arrives caught up
  (:class:`RejoinGrace` — a partition is not malice).

Termination is leaderless too: each peer trains its ``num_rounds`` local
rounds (version == local merge count), drains briefly so late exchanges
still get served, announces "leaving", and exits 0 on its own clock.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from bcfl_tpu import telemetry
from bcfl_tpu.dist.membership import MembershipView
from bcfl_tpu.dist.runtime import (DurabilityError, MergeRecord,
                                   PeerRuntime, logger)

# rng lane tags: the neighbor draw and the hello-target draw must be
# DIFFERENT streams of the same seed (same (seed, round, peer) coordinates,
# different purpose), like the faults/plan.py lane constants
GOSSIP_LANE = 71
HELLO_LANE = 72
HEDGE_LANE = 73
PROBE_LANE = 74


def _walk_sorted(tree, prefix: str = ""):
    """Yield ``(path, ndarray)`` leaves of a nested host tree in sorted-key
    order — the same canonical visit order as dist/robust.py's flatten, so
    a digest is a function of the VALUES, not of host dict insertion."""
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from _walk_sorted(tree[k], prefix + "/" + str(k))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            yield from _walk_sorted(v, prefix + "/" + str(i))
    else:
        yield prefix, np.asarray(tree)


def state_digest(tree) -> bytes:
    """SHA-256 over a host state tree (paths + dtypes + shapes + bytes,
    sorted-key order): the ONE announced digest a gossip update carries.
    The receiver recomputes it over what ARRIVED — announce one state,
    ship another, and the mismatch is ledger-auth evidence, exactly the
    leadered path's commit->refingerprint->verify order with the
    per-client fingerprint program replaced by a whole-state hash (gossip
    ships merged states, which have no per-client rows to fingerprint)."""
    h = hashlib.sha256()
    for path, leaf in _walk_sorted(tree):
        h.update(path.encode())
        h.update(str(leaf.dtype).encode())
        h.update(str(leaf.shape).encode())
        h.update(np.ascontiguousarray(leaf).tobytes())
    return h.digest()


def sample_neighbors(seed: int, round_idx: int, peer: int,
                     live: Tuple[int, ...], fanout: int,
                     topology: str = "epidemic",
                     lane: int = GOSSIP_LANE) -> Tuple[int, ...]:
    """The seeded neighbor draw for one ``(round, peer)`` coordinate over
    the LIVE membership view — replayable: same seed + same view => same
    neighbors, on every host. ``ring`` takes the next ``fanout``
    successors around the sorted live view; ``epidemic`` draws ``fanout``
    distinct live peers (excluding self) without replacement."""
    view = tuple(sorted(int(p) for p in live))
    others = [p for p in view if p != int(peer)]
    if not others:
        return ()
    k = min(int(fanout), len(others))
    if topology == "ring":
        if int(peer) in view:
            i = view.index(int(peer))
            ring = [p for p in view[i + 1:] + view[:i] if p != int(peer)]
        else:
            ring = others
        return tuple(ring[:k])
    rng = np.random.default_rng(
        (int(seed), int(lane), int(round_idx), int(peer)))
    pick = rng.choice(len(others), size=k, replace=False)
    return tuple(others[i] for i in sorted(pick))


def probe_targets(seed: int, seq: int, peer: int,
                  dormant: Tuple[int, ...], k: int = 1) -> Tuple[int, ...]:
    """The seeded anti-entropy probe draw: up to ``k`` DORMANT peers
    (static ids the live view does not currently contain) to HELLO at
    this beacon tick. The beacon itself samples the LIVE view only, so
    after a partition heals two detector-shrunk views would never
    rediscover each other without this lane — split-brain forever. Keyed
    ``(seed, PROBE_LANE, seq, peer)`` like every other topology draw:
    same seed + same dormant set => same probes, on every host."""
    pool = sorted(int(p) for p in dormant if int(p) != int(peer))
    if not pool:
        return ()
    kk = min(int(k), len(pool))
    rng = np.random.default_rng((int(seed), PROBE_LANE, int(seq),
                                 int(peer)))
    pick = rng.choice(len(pool), size=kk, replace=False)
    return tuple(pool[i] for i in sorted(pick))


def hedge_neighbors(seed: int, round_idx: int, peer: int,
                    live: Tuple[int, ...], nbrs: Tuple[int, ...],
                    suspicion: Dict[int, float],
                    threshold: float) -> Tuple[Tuple[int, ...],
                                               Tuple[int, ...]]:
    """Suspicion-hedged redraw of one round's sampled neighbors
    (ROBUSTNESS.md §11): a sampled neighbor whose phi suspicion has
    crossed ``threshold`` is DROPPED and a replacement is drawn — from
    its own seeded lane, so the hedge is replayable like the sample it
    amends — out of the non-suspicious remainder of the live view. When
    the replacement pool is empty the fanout simply shrinks: gossiping
    to fewer healthy peers beats insisting on a limping one (the paced
    send would eat the round's wall budget for an exchange the next
    round's draw retries anyway). Returns ``(new_nbrs, dropped)``;
    with nothing suspicious the sample passes through untouched."""
    dropped = tuple(n for n in nbrs
                    if suspicion.get(int(n), 0.0) >= threshold)
    if not dropped:
        return tuple(nbrs), ()
    kept = [int(n) for n in nbrs if n not in dropped]
    pool = [p for p in sorted(int(x) for x in live)
            if p != int(peer) and p not in kept
            and suspicion.get(p, 0.0) < threshold]
    k = min(len(dropped), len(pool))
    if k > 0:
        rng = np.random.default_rng(
            (int(seed), HEDGE_LANE, int(round_idx), int(peer)))
        pick = rng.choice(len(pool), size=k, replace=False)
        kept.extend(pool[i] for i in sorted(pick))
    return tuple(kept), dropped


def merge_states(items: List[Dict], decay: float):
    """The commutative, versioned gossip merge.

    Each item is ``{"peer", "order", "state" (host tree), "vv" (int64
    array over the static id space), "mass" (example weight), "trust"}``.
    The merged version vector is the elementwise max (union of
    incorporated training); each item's weight is
    ``mass * decay ** lag * trust`` where ``lag`` is how far its vv total
    trails the union's — a staleness decay with no leader clock, measured
    against the information frontier of THIS merge. States reduce as a
    normalized weighted sum in canonical ``(peer, order)`` order, so the
    result is bitwise independent of arrival order (tested).

    Returns ``(merged_state, union_vv, weights)`` with ``weights`` aligned
    to the canonical order's peer ids."""
    items = sorted(items, key=lambda it: (int(it["peer"]),
                                          tuple(it.get("order") or ())))
    vvs = [np.asarray(it["vv"], np.int64) for it in items]
    union = vvs[0].copy()
    for v in vvs[1:]:
        union = np.maximum(union, v)
    total = int(union.sum())
    weights = []
    for it, v in zip(items, vvs):
        lag = max(total - int(v.sum()), 0)
        weights.append(float(it["mass"]) * float(decay) ** lag
                       * float(it.get("trust", 1.0)))
    wsum = sum(weights)
    if wsum <= 0.0:
        # every input eliminated (trust/decay underflow): keep the first
        # canonical state rather than divide by zero — the caller records
        # the merge as degraded
        return items[0]["state"], union, weights
    norm = [w / wsum for w in weights]

    def _reduce(*leaves):
        first = np.asarray(leaves[0])
        if not np.issubdtype(first.dtype, np.floating):
            return first  # non-float leaves (masks, ids) ride the first item
        acc = first.astype(np.float32) * np.float32(norm[0])
        for leaf, w in zip(leaves[1:], norm[1:]):
            acc = acc + np.asarray(leaf, np.float32) * np.float32(w)
        return acc.astype(first.dtype)

    import jax

    merged = jax.tree.map(_reduce, *[it["state"] for it in items])
    return merged, union, weights


class RejoinGrace:
    """Trust-evidence amnesty for peers a partition (or the failure
    detector) hid — the partition-is-not-malice pin (ROBUSTNESS.md §6,
    the slowness_is_not_malice precedent one lane over).

    A peer that just re-entered the live view arrives STALE and, after a
    long cut, state-DIVERGENT by construction. Without grace its first
    contact draws exactly the evidence a byzantine peer draws: the
    staleness lane, and — fatally — the outlier lane, whose weight
    (``w_anomaly`` 0.5 >= ``strike_threshold`` 0.5) strikes a
    probationary peer straight back to quarantine on ONE flag. Grace
    suppresses those two gossip-path lanes for a rejoiner until its
    first arrival lands within the staleness limit (caught up), at which
    point normal evidence resumes. The detector-DOWN lane is untouched:
    it is the one weak lane a cut is ALLOWED to charge, and it cannot
    quarantine on its own (EWMA floor 1 - w_staleness stays above the
    quarantine threshold). Merge weighting is untouched too — staleness
    decay still crushes genuinely old state; grace only withholds the
    *reputation* charge.

    Thread-safe: rejoins land on the intake thread (``note_alive`` in
    ``_intake_update``), clears on the main merge thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self._graced: set = set()

    def note_rejoin(self, peer: int) -> None:
        with self._lock:
            self._graced.add(int(peer))

    def note_caught_up(self, peer: int) -> None:
        with self._lock:
            self._graced.discard(int(peer))

    def active(self, peer: int) -> bool:
        with self._lock:
            return int(peer) in self._graced

    def report(self) -> List[int]:
        with self._lock:
            return sorted(self._graced)


class GossipPeerRuntime(PeerRuntime):
    """One peer process of the leaderless dispatch. Subclasses
    :class:`PeerRuntime` for everything that is not leader-shaped — the
    transport (retries/detector/chaos/dedup), the embedded engine, the
    watchdogs, checkpoint/restore, reports — and replaces the FedBuff
    funnel with the epidemic exchange + commutative merge above."""

    #: post-target drain window: keep serving hellos/exchanges this long
    #: after the last local round so slower peers' beacons still land
    DRAIN_S = 2.0

    def __init__(self, cfg, peer_id: int, ports: List[int], run_dir: str,
                 resume: bool = False, bootstrap: bool = False):
        # _restore (called inside super().__init__ when resume=True) runs
        # the _restore_extra hook before any subclass attribute exists —
        # pre-seed the one slot it writes
        self._gossip_restored_vv = None
        super().__init__(cfg, peer_id, ports, run_dir, resume=resume,
                         bootstrap=bootstrap)
        self.membership = MembershipView(self.peers, self.peer_id)
        # per-source version vector: vv[p] = local training rounds of peer
        # p this state has incorporated (directly or transitively)
        self.vv = np.zeros(self.peers, np.int64)
        if self._gossip_restored_vv is not None:
            self.vv = np.asarray(self._gossip_restored_vv,
                                 np.int64).copy()
        self._mem_seen = 0       # detector transitions folded into membership
        self._hello_seq = 0      # hello-beacon lane counter
        self._last_hello_beacon = 0.0
        self._self_mass = float(self.local_clients)  # last round's example mass
        self._state_np = None    # host copy of the current state (send/merge)
        self._exchanges = 0
        self._auth_rejects = 0
        self._chain_merges = 0
        self._peers_done: set = set()
        self._draining = False
        self._drain_started = 0.0
        self._grace = RejoinGrace()
        self._vote_floor_active = False  # rising-edge latch (vote_floor)

    # ------------------------------------------------------------- hooks

    def _checkpoint_extra(self) -> Dict:
        return {"gossip_vv": np.asarray(self.vv, np.int64).copy()}

    def _restore_extra(self, state: Dict) -> None:
        if state.get("gossip_vv") is not None:
            self._gossip_restored_vv = np.asarray(state["gossip_vv"],
                                                  np.int64)

    def _sync_targets(self) -> List[int]:
        """Gossip's membership join path: each STATE_SYNC attempt asks ONE
        peer drawn seeded from the LIVE view (hello lane, keyed by the
        attempt counter) — same replayable topology discipline as the
        beacon, no leader to prefer."""
        mem = getattr(self, "membership", None)
        live = (self._reachable_live() if mem is not None
                else tuple(range(self.peers)))
        return list(sample_neighbors(self.cfg.seed, self._sync_target_i,
                                     self.peer_id, live, 1, "epidemic",
                                     lane=HELLO_LANE))

    def _sync_serve_extra(self, header_out: Dict) -> None:
        # the served state incorporates this peer's training frontier —
        # ship the vv so the adopter's staleness lag starts truthful
        header_out["vv"] = [int(x) for x in self.vv]

    def _adopt_extra(self, header: Dict, trees: Dict) -> None:
        import jax

        self._state_np = jax.tree.map(np.asarray, trees["model"])
        vv = header.get("vv")
        if vv is not None and len(vv) == self.peers:
            self.vv = np.maximum(self.vv, np.asarray(vv, np.int64))

    def _report_extra(self) -> Dict:
        # the deadline Timer can fire between super().__init__ and the
        # subclass attributes existing — report what is there
        mem = getattr(self, "membership", None)
        vv = getattr(self, "vv", None)
        return {
            "dispatch": "gossip",
            "membership": mem.report() if mem is not None else None,
            "vv": [int(x) for x in vv] if vv is not None else None,
            "gossip": {
                "exchanges": getattr(self, "_exchanges", 0),
                "auth_rejects": getattr(self, "_auth_rejects", 0),
                "chain_merges": getattr(self, "_chain_merges", 0),
                "peers_done": sorted(getattr(self, "_peers_done", ())),
                "rejoin_graced": (self._grace.report()
                                  if getattr(self, "_grace", None)
                                  is not None else []),
                "fork": getattr(self, "fork", None),
            },
        }

    # ------------------------------------------------------- train + exchange

    def _train_once(self):
        """One gossip local round: every local client fine-tunes from the
        peer's CURRENT state, the client deltas fold in locally (the
        staleness-0 FedBuff step — no leader to send them to), the vv
        advances, and the merged state ships to the round's sampled
        neighbors."""
        import jax
        import jax.numpy as jnp

        from bcfl_tpu.core import client_round_keys
        from bcfl_tpu.data import client_batches
        from bcfl_tpu.fed.engine import _tree_axpy, _tree_sub

        cfg = self.cfg
        rnd = self.local_round
        t0 = time.time()
        tree, n_ex = client_batches(
            self.eng.cache, self.eng.partitioner, self.global_ids, rnd,
            cfg.batch_size, max_batches=cfg.max_local_batches)
        batches = self._to_device(tree)
        keys = client_round_keys(
            jax.random.fold_in(self.eng.root_key, 4), self.global_ids, rnd)
        rngs = self.eng.mesh.shard_clients(jax.random.key_data(keys))
        base = self.eng.progs.broadcast(self.trainable)
        post, _stats = self.eng.progs.local_updates(
            base, self.eng.frozen, batches, rngs)
        # the engine's exchange seam still produces the per-client ledger
        # fingerprints binding this round into the peer's OWN chain
        # (commit=False: the dist layer owns the chain writes)
        ex = self.eng._exchange_updates(
            rnd, post, base, rngs, None, mode="async", commit=False)
        n = np.asarray(n_ex, np.float32)
        w = n if cfg.weighted_agg else np.ones_like(n)
        # local fold: the async_server_lr step along the weighted-mean
        # client delta — the same math as one FedBuff merge of one fresh
        # (staleness 0) update, applied where it was produced
        deltas = _tree_sub(post, base)
        w_dev = self.eng.mesh.shard_clients(jnp.asarray(w))
        zero = jax.tree.map(jnp.zeros_like, self.trainable)
        step = self.eng.progs.collapse(deltas, w_dev, zero)
        self.trainable = _tree_axpy(self.trainable, step,
                                    cfg.async_server_lr)
        self._self_mass = float(w.sum()) or 1.0
        self.vv[self.peer_id] += 1
        if self.chain is not None and ex.fp is not None:
            # own training attested on the peer's OWN chain — per-peer
            # chains diverge by construction and reconcile on sync
            for c in range(self.local_clients):
                self.chain.append_digest(
                    int(rnd), int(self.global_ids[c]),
                    self.eng._entry_digest(ex.wire_kind, ex.fp[c]),
                    self.eng._client_payload_bytes)
            telemetry.emit("ledger", op="commit", round=int(rnd),
                           n=self.local_clients, chain_len=len(self.chain),
                           rewrite=False,
                           head8=self.chain.head.hex()[:16])
        self.local_round += 1
        telemetry.emit("round", round=rnd, wall_s=time.time() - t0,
                       base_version=int(self.version))

        # chaos straggler lane: a REAL pre-send sleep, same as leadered
        delays = cfg.faults.straggler_delays(rnd, self.peers)
        if delays is not None and delays[self.peer_id] > 0:
            time.sleep(float(delays[self.peer_id]))
        # limp lane (gray failures, ROBUSTNESS.md §11): same real train-
        # seam stall as the leadered path — never sampled, the soak
        # gates count stalls exactly
        limp_act = cfg.faults.limp_action(rnd, self.peer_id)
        if limp_act is not None and limp_act["stall_s"] > 0:
            telemetry.emit("limp.inject", kind="stall", round=int(rnd),
                           stall_s=float(limp_act["stall_s"]))
            time.sleep(float(limp_act["stall_s"]))

        self._state_np = jax.tree.map(np.asarray,
                                      jax.device_get(self.trainable))
        live = self._reachable_live()
        nbrs = sample_neighbors(cfg.seed, rnd, self.peer_id, live,
                                cfg.dist.gossip_fanout,
                                cfg.dist.gossip_topology)
        # suspicion hedge (gossip_hedge_phi > 0, phi detector only): a
        # sampled neighbor the estimator already suspects is redrawn
        # from the healthy remainder BEFORE any bytes move — proportional
        # degradation at the topology layer, seeded and replayable
        hedged = ()
        det = self.transport.detector
        hedge_phi = float(cfg.dist.gossip_hedge_phi)
        if nbrs and hedge_phi > 0 and hasattr(det, "phi"):
            suspicion = {int(p): float(det.phi(int(p)))
                         for p in live if int(p) != self.peer_id}
            nbrs, hedged = hedge_neighbors(
                cfg.seed, rnd, self.peer_id, live, nbrs, suspicion,
                hedge_phi)
        telemetry.emit("gossip.exchange", round=int(rnd),
                       neighbors=list(nbrs), live=list(live),
                       fanout=int(cfg.dist.gossip_fanout),
                       topology=cfg.dist.gossip_topology,
                       hedged=list(hedged),
                       vv=[int(x) for x in self.vv])
        header0 = {
            "type": "update", "round": int(rnd),
            "vv": [int(x) for x in self.vv],
            "n_ex": self._self_mass,
            "digest": state_digest(self._state_np).hex(),
            "sent_at": time.time(),
        }
        for nbr in nbrs:
            header, out_tree = dict(header0), self._state_np
            if self.byz is not None:
                # same injection seam as the leadered path: above the
                # wire, per destination. Poisoning behaviors re-announce
                # over the mutated state so auth PASSES (trimming catches
                # them); forgery/equivocation keep the honest digest so
                # the receiver's re-hash fails (ledger evidence); replay
                # resends an old header whose stale vv the staleness
                # decay crushes.
                header, out_tree, act = self.byz.corrupt_update(
                    header, out_tree, dst=nbr)
                if act is not None and act.get("reannounce"):
                    header = dict(header,
                                  digest=state_digest(out_tree).hex())
            if cfg.dist.pipeline:
                self.transport.send_async(nbr, header,
                                          {"payload": out_tree})
            else:
                self.transport.send(nbr, header, {"payload": out_tree})
            self._exchanges += 1

    # ------------------------------------------------------------ merging

    def _prepare_gossip_arrival(self, header: Dict, trees: Dict,
                                recv_t: float) -> Dict:
        """Authenticate + weigh one buffered arrival. Mirrors the leadered
        ``_prepare_update`` with the per-client machinery replaced by the
        whole-state digest and the version-vector lag."""
        src = int(header.get("from", -1))
        rec = {"peer": src, "msg_id": header.get("msg_id"),
               "msg_epoch": header.get("msg_epoch"),
               "round": int(header.get("round", -1)),
               "latency_s": max(
                   recv_t - float(header.get("sent_at", recv_t)), 0.0)}
        vv = np.asarray(header.get("vv", ()), np.int64)
        if vv.shape != (self.peers,):
            rec["rejected"] = "malformed version vector"
            rec["staleness"] = 0
            return {"ok": False, "rec": rec}
        # lag vs THIS peer's frontier (the merge recomputes vs the union;
        # this is the observable staleness statistic)
        lag = max(int(self.vv.sum()) - int(vv.sum()), 0)
        rec["staleness"] = lag
        if self.gate.components() is not None:
            # merge-seam twin of the socket gate: a frame buffered BEFORE
            # the cut opened (or raced past the recv gate) must not cross
            # it at merge time — this is what makes the gossip scope of
            # no_cross_partition_merge hold for real, not by construction
            comp = self.gate.component_of(self.peer_id) or ()
            if src not in comp:
                rec["rejected"] = "cross-partition (span active)"
                return {"ok": False, "rec": rec}
        if (self.rep is not None and src != self.peer_id
                and self.rep.is_quarantined(src)):
            # post-ack quarantine gate at merge time — the seam the
            # no_quarantined_merge invariant holds the stream to
            with self._qdrop_lock:
                self.rep.quarantine_drops += 1
            rec["rejected"] = "peer quarantined (post-ack gate)"
            return {"ok": False, "rec": rec}
        state_np = trees["payload"]
        announced = header.get("digest")
        if announced is not None:
            actual = state_digest(state_np).hex()
            rec["auth"] = [1.0 if actual == announced else 0.0]
            if actual != announced:
                # announce one state, ship another: the gossip form of
                # the ledger-auth evidence lane (digest_forge/equivocate/
                # wire damage past the CRC)
                self._auth_rejects += 1
                rec["rejected"] = "state digest mismatch"
                if self.rep is not None and src != self.peer_id:
                    self.rep.note_auth_failure(src, 1.0)
                return {"ok": False, "rec": rec}
            if self.chain is not None:
                # the accepted arrival's ANNOUNCED digest joins this
                # peer's own chain (client slot = the sender's first
                # global client id — one state row per arrival)
                self.chain.append_digest(
                    max(int(header.get("round", 0)), 0),
                    src * self.local_clients, bytes.fromhex(announced),
                    self.eng._client_payload_bytes)
                telemetry.emit("ledger", op="commit",
                               round=max(int(header.get("round", 0)), 0),
                               n=1, chain_len=len(self.chain),
                               rewrite=False,
                               head8=self.chain.head.hex()[:16])
        graced = src != self.peer_id and self._grace.active(src)
        if graced and (self.rep is None
                       or lag <= self.rep.cfg.staleness_limit):
            # caught up: the amnesty lifts and normal evidence resumes
            # (lag at/below the limit draws none anyway)
            self._grace.note_caught_up(src)
            graced = False
        if graced:
            # partition-is-not-malice: a rejoiner is stale by construction
            # — no staleness charge until it arrives caught up (weight
            # decay below still crushes genuinely old state)
            rec["graced"] = True
        elif self.rep is not None and src != self.peer_id:
            self.rep.note_staleness(src, lag)
        trust = 1.0
        if self.rep is not None:
            trust = float(self.rep.gate(src))
            rec["trust"] = round(trust, 6)
        mass = float(header.get("n_ex", 1.0))
        weight = mass * (self.cfg.staleness_decay ** lag) * trust
        if weight <= 0.0:
            rec["rejected"] = "eliminated (trust/decay)"
            return {"ok": False, "rec": rec}
        rec["weight"] = float(weight)
        return {"ok": True, "rec": rec, "peer": src, "state": state_np,
                "vv": vv, "mass": mass, "trust": trust,
                "order": (int(header.get("msg_epoch") or 0),
                          int(header.get("msg_id") or 0))}

    def _gossip_merge(self):
        """One peer-local merge: fold the round's arrivals (possibly none)
        into this peer's state with the commutative vv rule (or the robust
        trimming rule over the arrival set + self), advance the version,
        clock the reputation tracker, checkpoint. This runs after EVERY
        local round — solo when nothing arrived — so a peer's version is
        its own merge count and no other process can stall it."""
        cfg = self.cfg
        t0 = time.time()
        with self._buffer_lock:
            buf, self._buffer = self._buffer, []
        self._drain_membership_transitions()
        arrivals, rejected, items = [], [], []
        for header, trees, recv_t in buf:
            out = self._prepare_gossip_arrival(header, trees, recv_t)
            (arrivals if out.get("ok") else rejected).append(out["rec"])
            if out.get("ok"):
                items.append(out)
        robust_info = None
        robust_degraded = False
        if items:
            self_item = {"peer": self.peer_id, "order": (),
                         "state": self._state_np, "vv": self.vv.copy(),
                         "mass": self._self_mass, "trust": 1.0}
            if cfg.aggregator != "mean":
                robust_info, robust_degraded = self._apply_robust_gossip(
                    items, self_item)
            else:
                merged, union, _w = merge_states([self_item] + items,
                                                 cfg.staleness_decay)
                self.vv = union
                self.trainable = self.eng.mesh.replicate(
                    self._cast(merged))
                self._state_np = merged
        self.version += 1
        if cfg.aggregator != "mean":
            self._note_vote_floor(len(items) + 1)
        # the component this merge claims: during an active span on this
        # peer's own clock, the gate component (the scope the
        # no_cross_partition_merge invariant checks arrivals against);
        # otherwise the full static id space. NOT the live view unioned
        # with the arrivals — that made the cross-partition check vacuous
        # under gossip (every arrival was inside its own union)
        comp = sorted(self.gate.component_of(self.peer_id) or ())
        rec = MergeRecord(
            version=self.version, leader=self.peer_id, arrivals=arrivals,
            rejected=rejected, wall_s=time.time() - t0,
            solo=not arrivals, degraded=False, quorum=None,
            robust=robust_info, robust_degraded=robust_degraded)
        self.merges.append(rec)
        trust_map = ({str(p): round(float(self.rep.tracker.trust[p]), 6)
                      for p in range(self.peers)}
                     if self.rep is not None else None)
        telemetry.emit(
            "gossip.merge", version=rec.version, leader=self.peer_id,
            arrivals=arrivals, rejected=rejected, solo=rec.solo,
            degraded=False, component=comp, wall_s=rec.wall_s,
            vv=[int(x) for x in self.vv], trust=trust_map,
            robust=robust_info, robust_degraded=robust_degraded,
            **({"chain_len": len(self.chain),
                "head8": self.chain.head.hex()[:16], "rewrite": False}
               if self.chain is not None else {}))
        self._observe_gray_health()
        if self.rep is not None:
            # the peer-local merge IS the observation clock (there is no
            # leader clock to borrow): drain detector evidence, fold the
            # round's observations, commit any transitions to the OWN
            # chain — verdicts travel inside the chain rows every sync
            # reconciles, so they spread epidemically like the states do
            self._drain_detector_evidence()
            arrived = ([a["peer"] for a in arrivals]
                       + [r["peer"] for r in rejected])
            transitions = self.rep.observe_merge(arrived)
            if transitions and self.chain is not None:
                self.rep.commit_transitions(self.chain, self.version,
                                            transitions)
                telemetry.emit("ledger", op="rep_transition",
                               n=len(transitions),
                               chain_len=len(self.chain), rewrite=False,
                               head8=self.chain.head.hex()[:16])
        self._note_version()
        self._maybe_checkpoint()

    def _note_vote_floor(self, votes: int) -> None:
        """Rising-edge catalogue of the RUNTIME vote floor: the static
        config check (``gossip_fanout + 1 >= MIN_ORDER_VOTES``) only
        guarantees the TOPOLOGY can feed the robust rule — a partition
        (or churn) can still shrink the reachable cohort below it, at
        which point the merge degrades to the commutative mean (solo
        merges and the ``robust_degraded`` fallback). This event marks
        each degradation episode's entry so a soak can count windows
        without diffing per-merge records."""
        from bcfl_tpu.dist.robust import MIN_ORDER_VOTES

        if votes < MIN_ORDER_VOTES:
            if not self._vote_floor_active:
                self._vote_floor_active = True
                telemetry.emit(
                    "gossip.vote_floor", votes=int(votes),
                    need=int(MIN_ORDER_VOTES), version=int(self.version),
                    component=sorted(self.gate.component_of(self.peer_id)
                                     or ()),
                    rule=self.cfg.aggregator)
        else:
            self._vote_floor_active = False

    def _apply_robust_gossip(self, items: List[Dict], self_item: Dict):
        """Peer-local robust trimming: one vote per source (the sender's
        whole state), the configured order-statistic rule over the
        arrival set + self. Below MIN_ORDER_VOTES the rule is vacuous —
        fall back to the commutative mean merge, recorded
        ``robust_degraded`` (same grading as the leadered path)."""
        from bcfl_tpu.dist.robust import MIN_ORDER_VOTES, robust_merge

        cfg = self.cfg
        votes_in = sorted([self_item] + items,
                          key=lambda it: (int(it["peer"]),
                                          tuple(it.get("order") or ())))
        if len(votes_in) < MIN_ORDER_VOTES:
            merged, union, _w = merge_states(votes_in,
                                             cfg.staleness_decay)
            self.vv = union
            self.trainable = self.eng.mesh.replicate(self._cast(merged))
            self._state_np = merged
            return {"k": len(votes_in), "rule": cfg.aggregator,
                    "fallback": "mean"}, True
        votes = [it["state"] for it in votes_in]
        vote_w = [float(it["mass"]) * float(it.get("trust", 1.0))
                  for it in votes_in]
        agg, flags, info = robust_merge(votes, vote_w, cfg.aggregator,
                                        cfg.aggregator_trim)
        info["votes_by_peer"] = {str(int(it["peer"])): 1
                                 for it in votes_in}
        dists = info.get("distances")
        for j, it in enumerate(votes_in):
            if not flags[j]:
                continue
            p = int(it["peer"])
            if p == self.peer_id:
                continue  # never against self (non-iid honest outliers)
            for a in items:
                if a is it:
                    a["rec"]["outlier"] = True
            if self.rep is not None and not self._grace.active(p):
                # a rejoiner's first post-heal state IS the cohort
                # outlier by construction — keep the flag (trimming still
                # protects the merge) but charge no trust evidence while
                # graced: w_anomaly (0.5) >= strike_threshold (0.5) would
                # send an honest probationary peer straight back to
                # quarantine on one flag
                self.rep.note_outlier(
                    p, distance=(dists[j] if dists else None))
        union = self.vv.copy()
        for it in votes_in:
            union = np.maximum(union, np.asarray(it["vv"], np.int64))
        self.vv = union
        if agg is not None:
            # the trimmed aggregate IS the new state (states are points,
            # not deltas — coordinate-wise trimming of points is the
            # gossip form of the rule)
            self.trainable = self.eng.mesh.replicate(self._cast(agg))
            import jax

            self._state_np = jax.tree.map(np.asarray, agg)
        return info, False

    # --------------------------------------------------- partition lifecycle

    def _reachable_live(self) -> Tuple[int, ...]:
        """The live view restricted to this peer's own partition component
        while a span is active on its OWN round clock (outside a span the
        live view passes through untouched). Sampling inside it keeps
        every fanout slot useful during a cut — a draw at a peer across
        the cut would only be dropped at the socket gate anyway."""
        live = self.membership.live()
        if self.gate.components() is None:
            return live
        comp = self.gate.component_of(self.peer_id) or ()
        return tuple(p for p in live if p in comp)

    def _probe(self, target: int) -> None:
        """One anti-entropy HELLO at a peer the live view does not reach.
        Cheap even when the target is dead: once the detector marks it
        DOWN the circuit breaker skips the send (one budgeted probe per
        ``probe_interval_s``), and a not-yet-DOWN dead target costs at
        most one ``send_deadline_s``-bounded retry loop."""
        header = {"type": "hello", "version": int(self.version),
                  "probe": True}
        if self.cfg.dist.pipeline:
            self.transport.send_async(target, header)
        else:
            self.transport.send(target, header)

    def _update_partition_state(self):
        """Leaderless partition lifecycle: the SAME span observation as
        the leadered path — the gate's components evaluated on this
        peer's own autonomous round clock — but the heal has no arbiter.
        On span entry the fork is catalogued (``fork.begin`` with
        ``leaderless=True``); on exit nobody elects a reconcile leader
        and nothing is offered to peer 0: the peer HELLO-probes every
        peer the cut hid, the answering syncs fold in through the
        ordinary version-vector merge, the chain replicas reconcile
        pairwise in ``_handle_sync``, and the hidden peers enter rejoin
        grace so their first (stale, divergent) contact draws no
        staleness/outlier evidence."""
        comps = self.gate.components()
        if comps is not None and not self._partitioned:
            self._partitioned = True
            self._fork_comps = comps
            comp = list(self.gate.component_of(self.peer_id) or ())
            self.fork = {
                "at_version": int(self.version),
                "fork_base": (int(len(self.chain))
                              if self.chain is not None else None),
                "head_at_fork": self._head(),
                "component": comp,
            }
            telemetry.emit("fork.begin", at_version=int(self.version),
                           component=comp, leaderless=True,
                           head8=(self._head() or "")[:16],
                           fork_base=self.fork["fork_base"])
            logger.info("peer %d: partition began at version %d "
                        "(component %s, leaderless)", self.peer_id,
                        self.version, comp)
        elif comps is None and self._partitioned:
            self._partitioned = False
            self.fork["head_before_heal"] = self._head()
            old_comp = set(self.fork.get("component") or ())
            telemetry.emit("fork.heal", at_version=int(self.version),
                           leaderless=True,
                           head8=(self._head() or "")[:16])
            hidden = [p for p in range(self.peers)
                      if p != self.peer_id and p not in old_comp
                      and p not in self._peers_done]
            for p in hidden:
                self._grace.note_rejoin(p)
                self._probe(p)
            logger.info("peer %d: partition healed at version %d — "
                        "probing %s for anti-entropy", self.peer_id,
                        self.version, hidden)

    # -------------------------------------------------- membership + resync

    def _drain_membership_transitions(self):
        """Fold NEW failure-detector DOWN transitions into the live view
        (its own cursor, parallel to the reputation tracker's)."""
        det = self.transport.detector
        new = det.transitions_total - self._mem_seen
        if new <= 0:
            return
        self._mem_seen = det.transitions_total
        from bcfl_tpu.dist.transport import DOWN

        recent = list(det.transitions)[-min(new, len(det.transitions)):]
        for t in recent:
            if t.get("to") == DOWN:
                self.membership.note_leave(t["peer"], "detector_down")

    def _maybe_hello(self):
        """The HELLO beacon (steady state, not a rejoin special case):
        every ``gossip_hello_interval_s`` ping one seeded live neighbor;
        whoever receives it answers with a full state+chain sync. On the
        same tick, outside any partition span, one seeded DORMANT peer is
        probed too (:func:`probe_targets`) — the anti-entropy backstop
        that rediscovers peers the detector dropped (during a span the
        probe is withheld: the gate would drop it at the socket)."""
        now = time.time()
        if now - self._last_hello_beacon < self.cfg.dist.gossip_hello_interval_s:
            return
        self._last_hello_beacon = now
        self._hello_seq += 1
        nbrs = sample_neighbors(self.cfg.seed, self._hello_seq,
                                self.peer_id, self._reachable_live(), 1,
                                "epidemic", lane=HELLO_LANE)
        if nbrs:
            self.transport.send(nbrs[0], {"type": "hello",
                                          "version": int(self.version)})
        if self.gate.components() is None:
            # departed peers are dormant-but-done: never probed
            dormant = tuple(p for p in self.membership.dormant()
                            if p not in self._peers_done)
            for t in probe_targets(self.cfg.seed, self._hello_seq,
                                   self.peer_id, dormant):
                self._probe(t)

    def _handle_gossip_hello(self, header: Dict):
        """ANY peer answers a hello (no leader gate): reply with the full
        current state, vv, and chain — the sync a joiner folds in."""
        if self._needs_bootstrap:
            return  # nothing trustworthy to serve while damaged
        src = int(header["from"])
        if self._state_np is None:
            import jax

            self._state_np = jax.tree.map(np.asarray,
                                          jax.device_get(self.trainable))
        reply = {
            "type": "sync", "round": int(self.local_round),
            "vv": [int(x) for x in self.vv], "n_ex": self._self_mass,
            "digest": state_digest(self._state_np).hex(),
            "sent_at": time.time(),
            "chain": (self.chain.segment(0)
                      if self.chain is not None else None),
        }
        self.transport.send(src, reply, {"payload": self._state_np})

    def _handle_sync(self, header: Dict, trees: Dict):
        """Fold a hello reply in: reconcile the chain replicas through the
        fork/merge API (per-peer chains converge pairwise, no consensus
        head), absorb committed reputation rows, then queue the carried
        state as a normal arrival for the next merge."""
        from bcfl_tpu.ledger import Ledger

        if self._needs_bootstrap:
            # a damaged peer adopts state ONLY through the verified
            # STATE_SYNC gates (commitment row + refingerprint) — the
            # hello-sync fold has no state commitment to check against
            return
        src = int(header.get("from", -1))
        rows = header.get("chain")
        if rows and self.chain is not None:
            their_heads = [bytes.fromhex(r["head"]) for r in rows]
            fork = self.chain.fork_point(their_heads)
            bad = Ledger.verify_segment(self.chain.head_at(fork),
                                        rows[fork:],
                                        self.cfg.ledger.use_native)
            if bad == -1:
                merged = Ledger.merge_rows(self.chain.segment(fork),
                                           rows[fork:])
                self.chain.adopt_merge(fork, merged)
                self.eng.ledger = self.chain
                self._chain_merges += 1
                telemetry.emit("ledger", op="adopt_merge",
                               chain_len=len(self.chain), rewrite=True,
                               head8=self.chain.head.hex()[:16],
                               fork_point=fork)
                if self.rep is not None:
                    self.rep.absorb_rows(rows)
            else:
                telemetry.emit("warn", what="gossip_sync_segment_rejected",
                               peer_from=src, link=int(bad))
                logger.warning("peer %d: rejected tampered sync segment "
                               "from %d (link %d)", self.peer_id, src, bad)
        # the sync's state joins the next merge like any gossip arrival
        # (the transport already stamped from/msg_id/msg_epoch)
        self._buffer_push((dict(header, type="update"), trees,
                           time.time()))

    # ---------------------------------------------------------- main loop

    def _intake_update(self, header: Dict, trees: Dict):
        """Gossip intake: EVERY peer buffers (no leader check); any frame
        re-attests its sender into the live view (a detector-hidden peer
        re-entering it gets rejoin grace — partition is not malice)."""
        src = int(header.get("from", -1))
        if self.membership.note_alive(src):
            self._grace.note_rejoin(src)
        if (self.rep is not None and src != self.peer_id
                and self.rep.is_quarantined(src)):
            with self._qdrop_lock:
                self.rep.quarantine_drops += 1
            return
        self._buffer_push((header, trees, time.time()))

    def _handle(self, header: Dict, trees: Dict):
        kind = header.get("type")
        src = int(header.get("from", -1))
        if src >= 0 and kind not in ("shutdown", "leaving"):
            if self.membership.note_alive(src):
                self._grace.note_rejoin(src)
        if kind == "update":
            self._intake_update(header, trees)
        elif kind == "ping":
            pass
        elif kind == "hello":
            self._handle_gossip_hello(header)
        elif kind == "sync":
            self._handle_sync(header, trees)
        elif kind == "state_sync_req":
            self._handle_state_sync_req(header)
        elif kind == "state_sync":
            self._handle_state_sync(header, trees)
        elif kind == "leaving":
            self._peers_done.add(src)
            self.membership.note_leave(src, "leaving")
        elif kind == "shutdown":
            # honored for harness compatibility (scripts can still stop a
            # fleet), though no gossip peer ever originates one
            self._stop = True
        else:
            logger.warning("peer %d: unknown message type %r",
                           self.peer_id, kind)

    def _maybe_depart(self):
        """Leaderless termination: after the version target, evaluate
        once, drain ``DRAIN_S`` so in-flight beacons still get served,
        announce "leaving" to the live view, and stop on our own clock."""
        if not self._draining:
            self._draining = True
            self._drain_started = time.time()
            loss = acc = None
            try:
                loss, acc = self.eng._global_eval(self.trainable)
            except Exception as e:  # an eval failure must not eat the report
                logger.warning("peer %d: final eval failed (%s)",
                               self.peer_id, e)
            self._final_eval = {"loss": loss, "acc": acc}
            return
        if time.time() - self._drain_started < self.DRAIN_S:
            time.sleep(0.05)
            return
        self.transport.flush_sends(timeout_s=self.cfg.dist.send_deadline_s)
        for p in self.membership.live():
            if p == self.peer_id:
                continue
            self.transport.send(p, {"type": "leaving",
                                    "version": int(self.version)})
        self._stop = True

    def run(self) -> int:
        import threading

        logger.info("peer %d/%d up (gossip): clients %s, version %d%s",
                    self.peer_id, self.peers, list(self.global_ids),
                    self.version, " (resumed)" if self._resumed else "")
        telemetry.emit("run.start", role="peer", peers=self.peers,
                       resumed=self._resumed, version=int(self.version),
                       epoch=self.transport.epoch,
                       pipeline=bool(self.cfg.dist.pipeline),
                       dispatch="gossip")
        self.transport.start()
        self._resmon = None
        if (self.cfg.dist.resource_sample_s > 0
                and self.events_path is not None):
            try:
                from bcfl_tpu.metrics.metrics import ResourceMonitor

                self._resmon = ResourceMonitor(run_dir=self.run_dir)
                self._resmon.start_sampling(
                    self.cfg.dist.resource_sample_s)
            except Exception as e:  # noqa: BLE001 — psutil absence never kills a peer
                logger.warning("resource sampling unavailable: %s", e)
        if self.cfg.dist.pipeline:
            self._intake_thread = threading.Thread(
                target=self._intake_loop, daemon=True,
                name=f"bcfl-gossip-intake-{self.peer_id}")
            self._intake_thread.start()
        self._write_report(status="running")
        if self._resumed and not self._needs_bootstrap:
            # a rejoiner's first beacon is immediate: it re-enters every
            # live view it touches and gets a sync back
            self._last_hello_beacon = 0.0
            self._maybe_hello()
        try:
            while not self._stop:
                self._check_watchdogs()
                self._maybe_flush_report()
                msg = self._next_ctrl(timeout_s=0.0)
                while msg is not None:
                    self._handle(*msg)
                    msg = self._next_ctrl(timeout_s=0.0)
                if self._stop:
                    break
                if self._needs_bootstrap:
                    # damaged/empty durable state: neither train, beacon,
                    # nor serve until a verified STATE_SYNC is adopted
                    self._maybe_request_sync()
                    time.sleep(0.05)
                    continue
                self._update_partition_state()
                self._maybe_hello()
                if self.version < self.cfg.num_rounds:
                    # train, then merge whatever arrived meanwhile: the
                    # version IS this peer's merge count — it advances
                    # every round, arrivals or not, so no other process
                    # can stall it (the zero-round-stall property)
                    self._train_once()
                    self._gossip_merge()
                else:
                    self._maybe_depart()
        except DurabilityError as e:
            # the ENOSPC/EMFILE ladder exhausted every remedy: the peer
            # cannot persist state, so it leaves with the distinct
            # un-durable exit code rather than limping on volatile-only
            logger.error("peer %d un-durable: %s", self.peer_id, e)
            self._write_report(status="undurable")
            return DurabilityError.EXIT_CODE
        finally:
            self.transport.flush_sends(timeout_s=2.0)
            self.transport.close()
            self._deadline_timer.cancel()
            if self._resmon is not None:
                self._resmon.stop_sampling()
        self._write_report(status="ok")
        return 0
