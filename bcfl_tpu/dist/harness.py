"""Spawn/supervise/reap the peer processes (RUNTIME.md §7).

The supervisor side of the dist runtime: write the config JSON, pick free
ports, spawn one ``python -m bcfl_tpu.dist`` subprocess per peer, enforce a
hard wall deadline, and REAP stragglers — a hung peer fails the run, it
never wedges it. Every spawned process is tracked in a module-level
registry with an ``atexit`` hook (and the test conftest calls
:func:`reap_all` at session teardown), so an interrupted supervisor cannot
leave orphan peers burning CPU behind a CI job.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

# every live peer Popen, registered at spawn and discarded at reap — the
# orphan-reaper registry (tests/conftest.py drains it at session teardown)
_LIVE: set = set()


def reap_all() -> int:
    """SIGKILL every still-running registered peer; returns how many."""
    killed = 0
    for proc in list(_LIVE):
        if proc.poll() is None:
            try:
                proc.kill()
                proc.wait(timeout=10)
                killed += 1
            except OSError:
                pass
        _LIVE.discard(proc)
    return killed


atexit.register(reap_all)


def free_ports(n: int, host: str = "127.0.0.1") -> List[int]:
    """``n`` distinct currently-free TCP ports (bound-then-released)."""
    socks, ports = [], []
    try:
        for _ in range(n):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.bind((host, 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
    finally:
        for s in socks:
            s.close()
    return ports


def _peer_env(platform: Optional[str]) -> Dict[str, str]:
    env = dict(os.environ)
    # the peers build their own single-host meshes: the test conftest's
    # 8-virtual-device XLA flag must not leak in (it would 8x every compile
    # for a 2-client slice)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        env["XLA_FLAGS"] = " ".join(
            f for f in flags.split()
            if "xla_force_host_platform_device_count" not in f)
    if platform:
        env["JAX_PLATFORMS"] = platform
    return env


def spawn_peer(cfg_path: str, peer_id: int, ports: List[int], run_dir: str,
               resume: bool = False, bootstrap: bool = False,
               platform: Optional[str] = None,
               repo_root: Optional[str] = None) -> subprocess.Popen:
    log_path = os.path.join(run_dir, f"peer{peer_id}.log")
    cmd = [sys.executable, "-m", "bcfl_tpu.dist",
           "--config", cfg_path, "--peer-id", str(peer_id),
           "--ports", ",".join(str(p) for p in ports),
           "--run-dir", run_dir]
    if resume:
        cmd.append("--resume")
    if bootstrap:
        cmd.append("--bootstrap")
    if platform:
        cmd.extend(["--platform", platform])
    log = open(log_path, "ab")
    proc = subprocess.Popen(
        cmd, stdout=log, stderr=subprocess.STDOUT,
        env=_peer_env(platform), cwd=repo_root or os.getcwd())
    proc._bcfl_log = log  # keep the handle; closed at reap/collect
    _LIVE.add(proc)
    return proc


def run_dist(cfg, run_dir: str, deadline_s: Optional[float] = None,
             platform: Optional[str] = None,
             kill_peer: Optional[int] = None,
             kill_after_version: int = 1,
             restart_delay_s: float = 2.0,
             restart_killed: bool = True,
             churn: Optional[Dict] = None,
             limp: Optional[Dict] = None) -> Dict:
    """Run one full dist federation: spawn ``cfg.dist.peers`` peer
    processes, supervise them under a hard deadline, optionally SIGKILL
    ``kill_peer`` mid-run once its checkpoint has reached
    ``kill_after_version`` and restart it with ``--resume`` (the
    crash/rejoin leg), and collect the per-peer reports.

    ``restart_killed=False`` leaves the killed peer dead — the quorum-
    degradation leg (``scripts/dist_chaos.py``): the survivors' failure
    detectors must mark it DOWN and the leader must complete the run on
    the reachable quorum instead of stalling. The overall ``ok`` is False
    by construction there (the corpse's returncode and missing report);
    that leg's caller grades the survivors' reports instead.

    ``churn`` drives REPEATED supervised kill/rejoin cycles of one peer —
    the long-soak churn lane (scripts/dist_soak.py). RUNTIME_CAPS rejects
    ``faults.churns`` on the dist runtime by design: peer-level churn IS
    the crash/rejoin path, and this is it, exercised in a loop. A dict
    ``{"peer", "cycles", "period_s", "downtime_s", "stop_after_s"}``:
    every ``period_s`` seconds (measured from the peer's last restart),
    while a checkpoint exists for it, the leader is still alive, and
    fewer than ``cycles`` kills have fired (and, when ``stop_after_s`` is
    set, only inside that window — the last rejoin must land well before
    the leader finalizes, or the orphan re-joins a dead mesh), the peer
    is SIGKILLed, left down ``downtime_s``, and restarted with
    ``--resume``. Cycle records land under ``result["churn"]``.

    Two optional churn keys drive the storage-chaos variant
    (scripts/dist_soak.py --storage, ROBUSTNESS.md §10): ``"damage"`` —
    a list of damage class names (checkpoint.STORAGE_CLASSES) applied to
    the downed peer's checkpoint directory WHILE IT IS DOWN, cycled one
    class per kill (supervisor-side injection: deterministic coverage of
    every listed class, complementing the in-process seeded lane 8) —
    and ``"bootstrap"`` — restart the peer with ``--resume --bootstrap``
    so a scrub that finds nothing usable repairs over STATE_SYNC instead
    of exiting with ResumeError.EXIT_CODE.

    ``limp`` drives supervised SIGSTOP/SIGCONT pause cycles of one peer —
    the gray-failure limp lane (ROBUSTNESS.md §11): unlike a SIGKILL the
    peer never dies and never resumes from checkpoint, it just goes
    SILENT for ``pause_s`` seconds and then continues exactly where it
    was — the canonical limping-process signature (GC stall, CPU
    starvation, a VM freeze) that fixed-timeout detectors flap on. A
    dict ``{"peer", "pause_s", "period_s", "cycles", "stop_after_s"}``:
    every ``period_s`` seconds, while fewer than ``cycles`` pauses have
    fired, peer 0 and the target are still alive, and (when
    ``stop_after_s`` is set) only inside that window, the peer is
    SIGSTOPped, left frozen ``pause_s``, and SIGCONTed. Cycle records
    land under ``result["limp"]``. Composes freely with ``churn`` as
    long as they target different peers.

    Returns ``{"ok", "returncodes", "reports", "run_dir", ...}``; raises
    nothing on peer failure — the caller inspects the result (and the logs
    under ``run_dir``)."""
    from bcfl_tpu.dist.launch import cfg_to_json

    os.makedirs(run_dir, exist_ok=True)
    n = cfg.dist.peers
    ports = ([cfg.dist.base_port + i for i in range(n)]
             if cfg.dist.base_port else free_ports(n, cfg.dist.host))
    cfg_path = os.path.join(run_dir, "config.json")
    with open(cfg_path, "w") as f:
        f.write(cfg_to_json(cfg))
    deadline_s = deadline_s or (cfg.dist.peer_deadline_s + 60.0)

    procs = {p: spawn_peer(cfg_path, p, ports, run_dir, platform=platform)
             for p in range(n)}
    rcs: Dict[int, Optional[int]] = {p: None for p in range(n)}
    killed_restarted = False
    kill_record = None
    churn_records: List[Dict] = []
    limp_records: List[Dict] = []
    t0 = time.time()
    churn_next = (t0 + float(churn.get("period_s", 45.0))
                  if churn else None)
    limp_next = (t0 + float(limp.get("period_s", 20.0))
                 if limp else None)
    while time.time() - t0 < deadline_s:
        for p, proc in list(procs.items()):
            rc = proc.poll()
            if rc is not None and rcs[p] is None:
                rcs[p] = rc
                _LIVE.discard(proc)
                getattr(proc, "_bcfl_log", None) and proc._bcfl_log.close()
        if (kill_peer is not None and not killed_restarted
                and rcs.get(kill_peer) is None):
            ckpt = os.path.join(run_dir, f"ckpt_peer{kill_peer}",
                                f"round_{kill_after_version:06d}")
            if os.path.isdir(ckpt):
                proc = procs[kill_peer]
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=30)
                _LIVE.discard(proc)
                getattr(proc, "_bcfl_log", None) and proc._bcfl_log.close()
                kill_record = {"peer": kill_peer,
                               "killed_at_s": time.time() - t0,
                               "checkpoint_seen": ckpt,
                               "restarted": restart_killed}
                if restart_killed:
                    time.sleep(restart_delay_s)
                    procs[kill_peer] = spawn_peer(
                        cfg_path, kill_peer, ports, run_dir, resume=True,
                        platform=platform)
                    rcs[kill_peer] = None
                else:
                    rcs[kill_peer] = proc.returncode
                killed_restarted = True
        if (churn_next is not None and time.time() >= churn_next
                and len(churn_records) < int(churn.get("cycles", 3))
                and rcs.get(0) is None
                and rcs.get(int(churn["peer"])) is None):
            cp = int(churn["peer"])
            stop_after = churn.get("stop_after_s")
            if (stop_after is not None
                    and time.time() - t0 > float(stop_after)):
                churn_next = None   # window closed: no further cycles
            else:
                # checkpoint guard: only kill a peer that can resume
                ckdir = os.path.join(run_dir, f"ckpt_peer{cp}")
                # a round is only fair game once FULLY committed (tree dir
                # AND meta sidecar) — killing inside the commit window
                # would leave the damage lane nothing to damage
                if os.path.isdir(ckdir) and any(
                        name.startswith("round_")
                        and name.endswith(".meta.json")
                        and os.path.isdir(os.path.join(
                            ckdir, name[:-len(".meta.json")]))
                        for name in os.listdir(ckdir)):
                    proc = procs[cp]
                    proc.send_signal(signal.SIGKILL)
                    proc.wait(timeout=30)
                    _LIVE.discard(proc)
                    getattr(proc, "_bcfl_log", None) \
                        and proc._bcfl_log.close()
                    damage = None
                    classes = churn.get("damage")
                    if classes:
                        # storage-chaos churn: damage the corpse's durable
                        # state while it is down, one class per cycle in
                        # list order (deterministic coverage of every
                        # listed class across the soak)
                        from bcfl_tpu.checkpoint import apply_storage_fault
                        cls = classes[len(churn_records) % len(classes)]
                        frac = round(
                            ((len(churn_records) + 1) * 0.31) % 1.0, 3)
                        try:
                            damage = apply_storage_fault(
                                ckdir, {"cls": cls, "frac": frac,
                                        "delete_last": 1})
                        except (OSError, ValueError) as e:
                            damage = {"cls": cls, "error": str(e)}
                    time.sleep(float(churn.get("downtime_s", 2.0)))
                    procs[cp] = spawn_peer(
                        cfg_path, cp, ports, run_dir, resume=True,
                        bootstrap=bool(churn.get("bootstrap")),
                        platform=platform)
                    churn_records.append(
                        {"peer": cp, "cycle": len(churn_records) + 1,
                         "killed_at_s": round(time.time() - t0, 3),
                         **({"damage": damage} if damage else {})})
                    churn_next = (time.time()
                                  + float(churn.get("period_s", 45.0)))
        if (limp_next is not None and time.time() >= limp_next
                and len(limp_records) < int(limp.get("cycles", 3))
                and rcs.get(0) is None
                and rcs.get(int(limp["peer"])) is None):
            lp = int(limp["peer"])
            stop_after = limp.get("stop_after_s")
            if (stop_after is not None
                    and time.time() - t0 > float(stop_after)):
                limp_next = None   # window closed: no further pauses
            else:
                proc = procs[lp]
                pause_s = float(limp.get("pause_s", 3.0))
                try:
                    # freeze, not kill: the peer's sockets stay open and
                    # its kernel buffers keep accepting — peers talking to
                    # it see silence and backpressure, not a reset
                    proc.send_signal(signal.SIGSTOP)
                    time.sleep(pause_s)
                finally:
                    if proc.poll() is None:
                        proc.send_signal(signal.SIGCONT)
                limp_records.append(
                    {"peer": lp, "cycle": len(limp_records) + 1,
                     "paused_at_s": round(time.time() - t0 - pause_s, 3),
                     "pause_s": pause_s})
                limp_next = time.time() + float(limp.get("period_s", 20.0))
        if all(rc is not None for rc in rcs.values()):
            break
        time.sleep(0.25)
    else:
        # deadline: reap whoever is still running — they exit nonzero
        for p, proc in procs.items():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
                rcs[p] = proc.returncode
                _LIVE.discard(proc)
                getattr(proc, "_bcfl_log", None) and proc._bcfl_log.close()

    reports = {}
    for p in range(n):
        path = os.path.join(run_dir, f"report_peer{p}.json")
        if os.path.exists(path):
            with open(path) as f:
                reports[p] = json.load(f)
    logs = {}
    for p in range(n):
        lp = os.path.join(run_dir, f"peer{p}.log")
        if os.path.exists(lp):
            with open(lp, errors="replace") as f:
                logs[p] = f.read()[-2000:]
    ok = (all(rc == 0 for rc in rcs.values())
          and all(reports.get(p, {}).get("status") == "ok"
                  for p in range(n)))
    # per-peer telemetry streams (OBSERVABILITY.md): collate with
    # bcfl_tpu.telemetry.collate / `bcfl-tpu trace`. Scanned via the
    # same resolver the peers write through, so the two can't drift
    from bcfl_tpu.telemetry import find_streams, resolve_stream_dir

    tele_dir = resolve_stream_dir(cfg.telemetry_dir, run_dir)

    return {
        "ok": ok,
        "process_count": n,
        "returncodes": {str(p): rcs[p] for p in range(n)},
        "reports": reports,
        "log_tails": logs,
        "kill": kill_record,
        "churn": churn_records,
        "limp": limp_records,
        "run_dir": run_dir,
        "event_streams": (find_streams(tele_dir)
                          if tele_dir is not None else []),
        "wall_s": time.time() - t0,
    }
