"""Config serialization for peer processes.

The supervisor (CLI / scripts/dist_async.py) holds one :class:`FedConfig`;
each peer process must reconstruct it exactly (same seed, same codec, same
fault plan — every digest and schedule is derived from it), so the config
crosses the process boundary as JSON of the dataclass tree. Tuples become
JSON lists; the rebuild re-tuples the FaultPlan schedule fields (the frozen
plan requires hashable members)."""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict

from bcfl_tpu.compression import CompressionConfig
from bcfl_tpu.config import (
    DistConfig,
    FedConfig,
    LedgerConfig,
    PartitionConfig,
    TopologyConfig,
)
from bcfl_tpu.faults import FaultPlan
from bcfl_tpu.reputation import ReputationConfig


def cfg_to_json(cfg: FedConfig) -> str:
    return json.dumps(dataclasses.asdict(cfg), indent=2, sort_keys=True)


def _tupleize(v: Any) -> Any:
    if isinstance(v, list):
        return tuple(_tupleize(x) for x in v)
    return v


def _rebuild(cls, data: Dict) -> Any:
    fields = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - fields
    if unknown:
        raise ValueError(f"{cls.__name__} JSON has unknown fields {unknown} "
                         "(config written by a newer build?)")
    return cls(**data)


def cfg_from_json(s: str) -> FedConfig:
    data = json.loads(s)
    data["partition"] = _rebuild(PartitionConfig, data["partition"])
    data["topology"] = _rebuild(TopologyConfig, data["topology"])
    data["ledger"] = _rebuild(LedgerConfig, data["ledger"])
    data["faults"] = _rebuild(FaultPlan, {
        k: _tupleize(v) for k, v in data["faults"].items()})
    data["reputation"] = _rebuild(ReputationConfig, data["reputation"])
    data["compression"] = _rebuild(CompressionConfig, data["compression"])
    data["dist"] = _rebuild(DistConfig, data["dist"])
    return _rebuild(FedConfig, data)
