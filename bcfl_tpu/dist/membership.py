"""Elastic membership for the leaderless gossip dispatch (RUNTIME.md
"Gossip dispatch").

A :class:`MembershipView` is one peer's LOCAL belief about which peers are
currently part of the federation. There is no global registry and no
consensus round: the view starts optimistic (every statically configured
peer is live), shrinks when the transport's failure detector drives a peer
to DOWN or a peer announces it is leaving, and re-grows the moment any
frame arrives from a departed peer (the HELLO beacon makes that a
steady-state event, not a special rejoin protocol). Neighbor sampling
(:func:`bcfl_tpu.dist.gossip.sample_neighbors`) always draws over
``live()``, so a SIGKILLed peer stops being gossiped at within the
failure-detector window and a rejoining one is folded back in by its first
beacon — membership stretches and shrinks with zero privileged process.

Thread safety: ``note_alive`` is called from the pipelined intake thread
(any received update re-attests liveness) while ``note_leave``/``live``
run on the main loop — all state moves under one internal lock. Join and
leave transitions are emitted as ``membership.join`` / ``membership.leave``
telemetry events (OBSERVABILITY.md), which is how the soak gates count
churn cycles on a gossip run.
"""

from __future__ import annotations

import threading
from typing import Tuple

from bcfl_tpu import telemetry


class MembershipView:
    """One peer's live-peer view over the static id space ``range(peers)``."""

    def __init__(self, peers: int, self_id: int):
        self.peers = int(peers)
        self.self_id = int(self_id)
        self._lock = threading.Lock()
        self._live = set(range(self.peers))  # guarded-by: _lock
        self.joins = 0    # guarded-by: _lock (writes)
        self.leaves = 0   # guarded-by: _lock (writes)

    def live(self) -> Tuple[int, ...]:
        """Sorted tuple of peers this view currently believes live
        (always includes self)."""
        with self._lock:
            return tuple(sorted(self._live))

    def is_live(self, p: int) -> bool:
        with self._lock:
            return int(p) in self._live

    def dormant(self) -> Tuple[int, ...]:
        """Sorted static ids currently ABSENT from the live view — the
        anti-entropy probe lane's candidate pool
        (:func:`bcfl_tpu.dist.gossip.probe_targets`). The HELLO beacon
        only samples ``live()``, so without a periodic probe at a dormant
        peer two detector-shrunk views could never rediscover each other
        after a partition heals — split-brain forever."""
        with self._lock:
            return tuple(p for p in range(self.peers)
                         if p not in self._live)

    def note_alive(self, p: int) -> bool:
        """A frame arrived from ``p``: fold it (back) into the live view.
        Returns True when this was a re-entry (a join transition)."""
        p = int(p)
        if p < 0 or p >= self.peers:
            return False
        with self._lock:
            if p in self._live:
                return False
            self._live.add(p)
            self.joins += 1
            live = sorted(self._live)
        telemetry.emit("membership.join", member=p, live=live)
        return True

    def note_leave(self, p: int, reason: str) -> bool:
        """Drop ``p`` from the live view (detector DOWN transition or an
        explicit leaving announcement). Self never leaves its own view.
        Returns True when this was an actual departure transition."""
        p = int(p)
        if p == self.self_id or p < 0 or p >= self.peers:
            return False
        with self._lock:
            if p not in self._live:
                return False
            self._live.discard(p)
            self.leaves += 1
            live = sorted(self._live)
        telemetry.emit("membership.leave", member=p, reason=reason,
                       live=live)
        return True

    def report(self) -> dict:
        with self._lock:
            return {"live": sorted(self._live), "joins": self.joins,
                    "leaves": self.leaves}
