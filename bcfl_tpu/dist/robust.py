"""Byzantine-robust aggregation over the FedBuff ARRIVAL set (host side).

The local engine compiles trimmed_mean/median/krum into device programs
over a FIXED stacked client axis (``bcfl_tpu.parallel.gspmd``) — that is
why the capability table used to reject them on ``runtime="dist"``: the
buffered merge's arrival set has a different, runtime-variable population
(one entry per buffered PEER update, its size set by arrival order and
quorum). This module is the port: the same estimators, re-expressed over
the host-side arrival trees the leader already holds at merge time.

Semantics (the dist twin of ROBUSTNESS.md §2, declared differences):

- each PEER contributes ONE vote — its buffered updates are first
  weight-combined into one delta (:func:`combine_votes`; each update is
  that peer's collapsed client-slice delta, auth/trust masked) — so
  ``k`` is the number of distinct senders in the merge, and the "f of k
  are Byzantine" breakdown arithmetic is over peers, never inflatable by
  one sender's message rate,
- ``weights`` (staleness decay × examples × auth × trust, summed over the
  slice) act as a PARTICIPATION mask for the order statistics, exactly
  like the local rules: a positive weight is a full vote, zero is
  excluded. The applied global step still shrinks with staleness via the
  ``_async_merge_scale`` rescale in the runtime — staleness dampens the
  step, not the vote,
- a merge with fewer arrivals than the config-time precondition (quorum
  degradation, buffer timeout) still aggregates — the estimators clamp
  their trim exactly like the device versions — but the runtime records
  it ``robust_degraded`` (the guarantee, not the math, degraded).

Besides the aggregate, every call returns per-arrival **outlier flags**:
arrivals whose delta sits far from the robust aggregate (squared distance
> ``OUTLIER_MULT`` × the median arrival distance, only judged for k >= 3).
These are the "robust-aggregator outlier flags" evidence lane the
DistReputationTracker consumes — the poisoning behaviors (scaled /
sign-flipped / garbage payloads re-announce matching digests, so ledger
auth passes) are visible ONLY here.

Everything is plain numpy over trees the merge already materialized: the
arrival set is small (<= peers), so no device program or retrace concern
exists on this path.
"""

from __future__ import annotations

import math
from typing import Dict, List, Tuple

import numpy as np

# an arrival whose squared distance to the robust aggregate exceeds this
# multiple of the median arrival distance is flagged as an outlier
# (evidence, not exclusion — exclusion is the aggregator's own job)
OUTLIER_MULT = 4.0

RULES = ("trimmed_mean", "median", "krum")

# minimum distinct peer votes for an order statistic to exclude anything
# — the trimmed_mean/median config-time precondition AND the runtime's
# robust_degraded threshold (one source, so the two can't drift)
MIN_ORDER_VOTES = 3


def _flatten(tree) -> np.ndarray:
    """Concatenate every leaf of a (nested dict) host tree into one f64
    vector, in sorted-key order (deterministic across arrivals — all
    arrivals share one tree structure)."""
    if isinstance(tree, dict):
        parts = [_flatten(tree[k]) for k in sorted(tree)]
        return (np.concatenate(parts) if parts
                else np.zeros((0,), np.float64))
    return np.asarray(tree, np.float64).reshape(-1)


def _unflatten_like(tree, flat: np.ndarray, pos: int = 0):
    if isinstance(tree, dict):
        out = {}
        for k in sorted(tree):
            out[k], pos = _unflatten_like(tree[k], flat, pos)
        return out, pos
    arr = np.asarray(tree)
    n = arr.size
    return flat[pos:pos + n].reshape(arr.shape).astype(arr.dtype), pos + n


def trim_count(k: int, trim: float) -> int:
    """ceil(trim * k) clamped so at least one vote survives — the same
    clamp as the device ``gspmd._trim_count``."""
    t = int(math.ceil(trim * k))
    return max(0, min(t, (k - 1) // 2))


def krum_min_buffer(buffer: int, trim: float) -> int:
    """The classical Krum precondition ``k >= 2f + 3`` for a buffer of
    ``k`` arrivals under an assumed Byzantine fraction ``trim`` —
    config-time validation quotes this."""
    return 2 * int(math.ceil(trim * buffer)) + 3


def combine_votes(deltas: List, weights: List[float]):
    """Weighted mean of ONE peer's buffered update deltas — the peer's
    single vote. The robust rules' breakdown point is stated over PEERS
    (``f`` of ``k`` participants are Byzantine), so a sender that parks
    several updates in one merge window must still speak with one voice:
    without this collapse, a fast adversary could outvote the honest
    cohort simply by sending more often than anyone else."""
    if not deltas:
        raise ValueError("combine_votes needs at least one delta")
    w = np.asarray(weights, np.float64)
    total = float(w.sum())
    w = (w / total) if total > 0 else np.full_like(w, 1.0 / len(deltas))
    X = np.stack([_flatten(d) for d in deltas])
    out, _ = _unflatten_like(deltas[0], (w[:, None] * X).sum(axis=0))
    return out


def robust_merge(deltas: List, weights: List[float], rule: str,
                 trim: float = 0.2) -> Tuple[Dict, List[bool], Dict]:
    """Aggregate the arrival set with a robust rule.

    ``deltas`` are the per-update collapsed delta trees (host numpy, one
    per buffered update), ``weights`` their total merge weights (used as
    the participation mask; zero-weight arrivals are excluded and
    auto-flagged). Returns ``(aggregate_tree, outlier_flags, info)`` where
    ``info`` records the realized estimator parameters for the merge
    record (``k``, ``trim_t`` / ``krum_selected`` / ``krum_scores``).
    ``krum_selected`` is a POSITION in ``deltas`` — a caller whose votes
    map to senders must translate it (the runtime records the peer id as
    ``krum_selected_peer``)."""
    if rule not in RULES:
        raise ValueError(f"unknown robust rule {rule!r} (one of {RULES})")
    if not deltas:
        raise ValueError("robust_merge needs at least one arrival")
    X = np.stack([_flatten(d) for d in deltas])  # [k_all, D]
    w = np.asarray(weights, np.float64)
    active = w > 0
    idx = np.nonzero(active)[0]
    k = int(idx.size)
    info: Dict = {"rule": rule, "k": k}
    if k == 0:
        # every arrival eliminated (auth/trust): nothing to aggregate —
        # the caller treats this like the all-masked degraded round
        return None, [False] * len(deltas), dict(info, empty=True)
    A = X[idx]
    if rule == "trimmed_mean":
        t = trim_count(k, trim)
        info["trim_t"] = t
        S = np.sort(A, axis=0)
        agg = S[t:k - t].mean(axis=0)
    elif rule == "median":
        agg = np.median(A, axis=0)
    else:  # krum
        sq = (A * A).sum(axis=1)
        D = sq[:, None] + sq[None, :] - 2.0 * (A @ A.T)
        np.fill_diagonal(D, np.inf)
        D = np.maximum(D, 0.0)
        f = trim_count(k, trim)
        m = max(k - f - 2, 1)
        scores = np.sort(D, axis=1)[:, :m].sum(axis=1)
        sel = int(np.argmin(scores))
        info["krum_selected"] = int(idx[sel])
        info["krum_scores"] = [float(s) for s in scores]
        agg = A[sel]
    # outlier evidence: distance of every ACTIVE arrival to the aggregate,
    # judged against the cohort's own scale (median distance). k < 3 has
    # no meaningful cohort to stand out from — no flags, no false
    # evidence from a degraded two-arrival merge. Zero-weight arrivals
    # are NOT flagged: they were excluded (auth/trust), which is its own
    # already-recorded evidence, not an outlier observation.
    flags = [False] * len(deltas)
    if k >= 3:
        d2 = ((A - agg[None, :]) ** 2).sum(axis=1)
        med = float(np.median(d2))
        floor = 1e-12
        # aligned with `deltas` (None for excluded arrivals), so callers
        # can zip distances against the arrival records directly
        dist_full: List = [None] * len(deltas)
        for j, i in enumerate(idx):
            dist_full[int(i)] = float(d2[j])
            if d2[j] > OUTLIER_MULT * max(med, floor):
                flags[int(i)] = True
        info["distances"] = dist_full
    out, _ = _unflatten_like(deltas[0], agg)
    return out, flags, info
