"""PeerRuntime — one OS process of the real multi-host async runtime.

Each peer owns a fixed slice of the global client set and drives its own
local training loop on its own JAX backend; peers exchange updates over
:mod:`bcfl_tpu.dist.transport` and aggregate FedBuff-style at a **component
leader** (the lowest peer id reachable in the peer's connected component —
peer 0 when the network is whole). See RUNTIME.md for the protocol.

The essentials, and how they map onto the existing machinery:

- **Training + wire encode** go through the engine's update-exchange seam
  (:meth:`bcfl_tpu.fed.engine.FedEngine._exchange_updates`, ``commit=False``):
  the wire quantity is exactly what the local split-phase rounds exchange —
  the codec payload (encoded delta vs the peer's adopted base) under
  compression, the post-train stacked params otherwise — and the announced
  ledger digests are the same ``entry_digest`` binding the local flow
  chains.
- **Buffered async aggregation** mirrors ``FedEngine._async_round``'s math
  with MEASURED staleness: an update's staleness is the leader's version
  minus the sender's base version at the moment it is merged (arrival
  order, not a simulated clock), its merge weight is
  ``staleness_decay ** staleness`` (times example counts under
  ``weighted_agg``), and the global takes an ``async_server_lr`` step along
  the weighted-mean delta with the ``_async_merge_scale`` rescale.
- **Ledger forking is real**: the leader commits each merged update's
  ANNOUNCED digests to its chain and verifies what ARRIVED; during a
  transport partition each component's leader extends its own chain from
  the common prefix (two distinct heads exist), and the heal runs the
  segment-verified deterministic merge (:meth:`Ledger.merge_rows` /
  ``adopt_merge``) plus a participation-weighted model consensus through
  the engine's ``collapse`` program.
- **Crash/rejoin** rides the existing checkpoint store: every adopted or
  produced version is checkpointed (``save_checkpoint``); a restarted peer
  restores the newest valid state (``restore_latest``), HELLOs the leader,
  and re-enters with a verified chain replica.
- **Nothing can wedge**: a hard per-process deadline, an idle watchdog (no
  version progress), and a parent-death check each force a nonzero exit,
  and the spawning harness reaps stragglers.
- **Everything is traced** (OBSERVABILITY.md): each peer writes an
  append-only ``events_peer{p}.jsonl`` stream (bcfl_tpu.telemetry) —
  train-round spans, transport send/recv/detector/chaos events, FedBuff
  merges with full lineage (which ``(peer, msg_epoch, msg_id)`` updates at
  what measured staleness and weight composed each version), ledger
  commit/fork/heal, checkpoint and quorum events — which ``bcfl-tpu
  trace`` collates into one causally-ordered cross-peer timeline and
  checks the delivery-contract invariants against. The peer also rewrites
  its JSON report periodically (``DistConfig.report_every_rounds``) and on
  SIGTERM, so a killed or stalled peer leaves a current partial report
  instead of nothing.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue
import signal
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from bcfl_tpu import telemetry

logger = logging.getLogger(__name__)


class ResumeError(RuntimeError):
    """``--resume`` found no usable durable state and ``--bootstrap`` was
    not given. Distinct exit code so supervisors distinguish "my state is
    gone" (operator decision needed: accept peer repair or investigate)
    from every crash/stall/deadline failure mode — a peer must never
    silently re-enter the fleet with zero state (RUNTIME.md "State-sync
    protocol")."""

    EXIT_CODE = 8


class DurabilityError(RuntimeError):
    """A durable write (checkpoint commit / ledger high-water) kept
    failing after every rung of the resource-lane response ladder
    (emergency retention GC, then telemetry shed — ROBUSTNESS.md §11).
    Distinct exit code so supervisors distinguish "this host cannot make
    rounds durable" (disk full / fd table exhausted: an operator must
    free resources) from every crash/stall/deadline failure mode — a
    peer must never silently keep committing un-durable state."""

    EXIT_CODE = 9


@dataclasses.dataclass
class MergeRecord:
    version: int
    leader: int
    arrivals: List[Dict]  # per merged update: peer/msg_id/staleness/latency/auth
    rejected: List[Dict]  # updates excluded (stale lineage, auth failure)
    wall_s: float
    solo: bool  # produced while partitioned (a fork extension)
    degraded: bool = False  # merged on a reduced quorum (some peer DOWN)
    quorum: Optional[Dict] = None  # {"component", "alive", "down"} when degraded
    robust: Optional[Dict] = None  # robust-rule info (k, trim_t/krum_*) when armed
    robust_degraded: bool = False  # fewer arrivals than the declared precondition


def _tamper_tree(tree, frac: float):
    """Flip one byte of one leaf (both chosen by ``frac``) — the seeded
    in-flight corruption of a served STATE_SYNC transfer
    (``FaultPlan.sync_tamper``). Deterministic pure function of the input
    draw; the original tree is not mutated."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    idx = min(int(frac * len(leaves)), len(leaves) - 1)
    arr = np.asarray(leaves[idx])
    raw = bytearray(arr.tobytes())
    if raw:
        pos = min(int(frac * len(raw)), len(raw) - 1)
        raw[pos] ^= 0xFF
    leaves = list(leaves)
    leaves[idx] = np.frombuffer(bytes(raw),
                                arr.dtype).reshape(arr.shape).copy()
    return jax.tree_util.tree_unflatten(treedef, leaves)


def measured_staleness(leader_version: int, base_version: int):
    """``(staleness, clamped)`` of one arrival: the leader's version minus
    the sender's base version, clamped to >= 0.

    The raw difference CAN be negative after a leader restart: the leader
    restores the newest durable checkpoint, whose version counter may sit
    BELOW the base version a concurrent sender already adopted from a
    later (lost-to-the-crash) broadcast. ``decay ** negative`` would
    INFLATE that update's merge weight (1/decay per lost version) — the
    opposite of what staleness decay is for — so the exponent clamps to 0
    (a from-the-future update is at worst "fresh") and the clamp is
    surfaced (``clamped=True`` -> a `warn` telemetry event + the arrival
    record) instead of silently normalizing the disagreement away."""
    raw = int(leader_version) - int(base_version)
    return max(raw, 0), raw < 0


def _peer_engine_cfg(cfg, local_clients: int):
    """The embedded per-peer engine config: the peer's own client slice on a
    plain local mesh. The dist layer owns async/partition/eval semantics, so
    the inner engine runs the vanilla sync-server build (its round LOOP is
    never used — only its data/program/ledger/exchange machinery).

    The aggregator is pinned to "mean": the robust rules on this runtime
    act over the buffered ARRIVAL set host-side (bcfl_tpu.dist.robust),
    while the inner engine's ``collapse`` program must stay the plain
    weighted mean that reduces one peer's client slice to its vote.
    Reputation is likewise pinned off: the dist layer runs its own
    per-PEER tracker (bcfl_tpu.reputation.dist); the engine's per-client
    lifecycle has no role inside a peer."""
    from bcfl_tpu.faults import FaultPlan
    from bcfl_tpu.reputation import ReputationConfig

    return cfg.replace(
        runtime="local", sync="sync", mode="server",
        num_clients=local_clients, eval_every=0,
        aggregator="mean", reputation=ReputationConfig(),
        faults=FaultPlan(),  # partition/straggler lanes act at the transport
        checkpoint_dir=None, checkpoint_every=0,
        rounds_per_dispatch=1, donate=False)


class PeerRuntime:
    def __init__(self, cfg, peer_id: int, ports: List[int], run_dir: str,
                 resume: bool = False, bootstrap: bool = False):
        import jax

        from bcfl_tpu.dist.transport import (
            LimpChaos,
            PartitionGate,
            PeerTransport,
            WireChaos,
        )
        from bcfl_tpu.fed.engine import FedEngine

        self.cfg = cfg
        self.peer_id = int(peer_id)
        self.peers = cfg.dist.peers
        self.run_dir = run_dir
        os.makedirs(run_dir, exist_ok=True)
        # per-process event stream (OBSERVABILITY.md): ON by default for
        # the dist runtime — the chaos proofs and their invariant gates
        # are queries over these streams. telemetry_dir="off" disables
        # (the overhead-measurement setting); a path overrides the run
        # dir. Installed before the transport exists so its serve threads
        # always see the writer.
        self.events_path = None
        stream_dir = telemetry.resolve_stream_dir(cfg.telemetry_dir,
                                                  run_dir)
        if stream_dir is not None:
            self.events_path = os.path.join(
                stream_dir, f"events_peer{self.peer_id}.jsonl")
            telemetry.install(telemetry.EventWriter(
                self.events_path, peer=self.peer_id, run=cfg.name,
                sample=cfg.telemetry_sample))
        # resource lane, events seam: the EventWriter's flush-time fault
        # hook consults the seeded per-flush draw (the writer's own errno
        # handler sheds sampled telemetry in response — the stream never
        # takes down the run, so this seam never reaches the exit rung)
        self._events_flush_n = 0
        self._events_fault_busy = False
        if cfg.faults.resource_enabled:
            w = telemetry.get_writer()
            if w is not None:
                w.write_fault = self._events_write_fault
        k = cfg.num_clients // self.peers
        self.local_clients = k
        self.global_ids = np.arange(self.peer_id * k, (self.peer_id + 1) * k)

        self.eng = FedEngine(_peer_engine_cfg(cfg, k))
        self._jax = jax
        if self.eng._comp is not None:
            self.eng._ef = self.eng.progs.ef_init(self.eng.trainable0)

        self.trainable = self.eng.trainable0
        self.version = 0
        self.local_round = 0
        self.chain = self.eng.ledger  # the peer's chain replica (or None)
        # version -> (model tree or None, chain-head hex at creation): what
        # an uncompressed update's delta is computed against at the leader,
        # lineage-checked so a fork-based update can never merge into the
        # wrong component's history (compressed runs keep only the head —
        # see _note_version)
        self.history: Dict[int, tuple] = {
            0: (self.trainable if self.eng._comp is None else None,
                self._head())}
        self.history_limit = 16

        self.merges: List[MergeRecord] = []
        self.adopted: List[int] = []
        self._last_broadcast_len = 0  # suffix base of the next chain broadcast
        self._last_hello = 0.0
        self.fork: Optional[Dict] = None
        self.reconcile: Optional[Dict] = None
        self._below_quorum = False
        self._below_quorum_events = 0  # episodes, not loop polls
        self._buffer: List[tuple] = []  # guarded-by: _buffer_lock — (header, trees, recv_time)
        # shed count: writes under the buffer lock; the report's read is
        # a GIL-atomic snapshot (hence the (writes) qualifier)
        self._buffer_shed = 0  # guarded-by: _buffer_lock (writes)
        # double-buffered intake (cfg.dist.pipeline, RUNTIME.md §4): an
        # intake thread drains the transport inbox continuously — UPDATE
        # arrivals land in self._buffer under this lock (the active
        # arrival buffer), everything else routes to the control queue
        # the main loop drains. _maybe_merge SWAPS the arrival buffer out
        # under the lock and merges the swapped-out one while intake
        # keeps filling the fresh standby — merge/verify overlaps intake
        # instead of serializing behind it.
        self._buffer_lock = threading.Lock()
        self._ctrl: "queue.Queue" = queue.Queue()
        self._intake_thread: Optional[threading.Thread] = None
        # quarantine_drops is bumped from the intake thread (_intake_update)
        # AND the main merge thread (_prepare_update): a plain += there is
        # a racy read-add-store, same class transport._bump guards against
        self._qdrop_lock = threading.Lock()
        # when the CURRENT merge window opened (first entry into an empty
        # buffer): the buffer_timeout_s clock. Deliberately not the oldest
        # surviving entry's timestamp — the intake cap sheds oldest-first,
        # so under flood that timestamp keeps advancing and a timeout
        # measured from it can never fire (a dead peer holding
        # distinct < want would park merges forever)
        self._buffer_since = 0.0  # guarded-by: _buffer_lock
        self._partitioned = False
        self._fork_comps = None
        self._pending_reconcile = False
        self._last_reconcile_try = 0.0
        self._stop = False
        self._resumed = False
        # --- durable-state repair (RUNTIME.md "State-sync protocol") ---
        # set by _restore when the scrub finds nothing usable (--bootstrap)
        # or the monotone-incarnation guard detects a rollback; while set,
        # the peer neither trains nor announces — it requests STATE_SYNC
        # from live peers until a verified transfer is adopted
        self.bootstrap = bool(bootstrap)
        self._needs_bootstrap = False
        self._bootstrap_reason: Optional[str] = None
        self._repaired: Optional[Dict] = None
        self._last_sync_req = 0.0
        self._sync_target_i = 0
        self._sync_serves: Dict[int, int] = {}  # requester -> serves so far

        # per-PEER reputation (reputation/dist.py): wire evidence ->
        # quarantine, transitions committed to the chain, state
        # checkpointed bit-for-bit. Every peer runs one; the leader's is
        # the one that gates merges.
        self.rep = None
        if cfg.reputation.enabled:
            from bcfl_tpu.reputation.dist import DistReputationTracker

            self.rep = DistReputationTracker(cfg.reputation, self.peers,
                                             self.peer_id)
        self._det_seen = 0  # detector transitions already fed as evidence
        # byzantine lane (dist/byzantine.py): constructed only when the
        # plan arms it — the injection seam in _train_once is otherwise
        # absent, not merely inert
        self.byz = None
        if cfg.faults.byz_enabled:
            from bcfl_tpu.dist.byzantine import ByzantineAdversary

            self.byz = ByzantineAdversary(
                cfg.faults, self.peer_id,
                clock_fn=lambda: self.local_round)
        # the robust rules' declared arrival-count precondition (validated
        # against cfg.dist.buffer at config time); a merge below it still
        # aggregates with clamped trim but is recorded robust_degraded
        self._robust_min = 0
        if cfg.aggregator != "mean":
            from bcfl_tpu.dist.robust import (
                MIN_ORDER_VOTES,
                krum_min_buffer,
            )

            self._robust_min = (
                krum_min_buffer(cfg.dist.buffer or 1, cfg.aggregator_trim)
                if cfg.aggregator == "krum" else MIN_ORDER_VOTES)

        plan = cfg.faults if cfg.faults.partitions else None
        # the span clock is the peer's LOCAL ROUND: it advances autonomously
        # with the peer's own training loop, so every peer traverses the
        # partition span even while cross-partition messages are dropped (a
        # version-keyed clock can deadlock: versions only advance via the
        # very messages the partition blocks)
        self.gate = PartitionGate(plan, self.peers,
                                  version_fn=lambda: self.local_round)
        # the wire chaos lane shares the gate's autonomous span clock (the
        # peer's local round); an all-defaults plan injects nothing
        chaos = (WireChaos(cfg.faults, clock_fn=lambda: self.local_round)
                 if cfg.faults.wire_enabled else None)
        # the limp lane shares the same autonomous span clock: its
        # direction-keyed link throttles are consumed inside the
        # transport's attempt loop (a paced send, never a silent stall)
        limp = (LimpChaos(cfg.faults, clock_fn=lambda: self.local_round)
                if cfg.faults.limp_enabled else None)
        host = cfg.dist.host
        # transport incarnation epoch: a file-backed restart counter, NOT
        # wall clock — a backward clock step between a crash and its
        # restart must not make receivers treat the new incarnation's
        # messages as a dead one's stragglers
        epoch_path = os.path.join(run_dir, f"epoch_peer{self.peer_id}")
        try:
            with open(epoch_path) as f:
                epoch = int(f.read().strip()) + 1
        except (OSError, ValueError):
            epoch = 1
        with open(epoch_path, "w") as f:
            f.write(str(epoch))
        self.transport = PeerTransport(
            self.peer_id, [(host, p) for p in ports], gate=self.gate,
            io_timeout_s=min(60.0, cfg.dist.peer_deadline_s),
            chaos=chaos, limp=limp, policy=cfg.dist, epoch=epoch)

        self.ckpt_dir = os.path.join(run_dir, f"ckpt_peer{self.peer_id}")
        # monotone-incarnation high-water marker: like the transport epoch
        # file, a tiny supervisor-domain record OUTSIDE the checkpoint dir
        # — the newest (version, chain_len) this peer ever made durable.
        # A restore landing BELOW it means the durable state was rolled
        # back (or fell back past damage) and must resync forward before
        # announcing anything (see _restore).
        self._hw_path = os.path.join(run_dir, f"highwater_peer{self.peer_id}")
        if resume:
            self._restore()

        # --- watchdogs: a hung peer FAILS, it never wedges the run ---
        self._t0 = time.time()
        self._last_version_change = time.time()
        self._ppid = os.getppid()
        self._deadline_timer = threading.Timer(
            cfg.dist.peer_deadline_s, self._deadline_fire)
        self._deadline_timer.daemon = True
        self._deadline_timer.start()
        # partial-report cadence (report_every_rounds): what the report
        # loop compares against to decide a periodic rewrite is due.
        # Reentrant lock: the deadline Timer thread, the main loop's
        # periodic flush, and the SIGTERM handler (which interrupts the
        # main thread mid-frame) all write the same report file.
        self._report_lock = threading.RLock()
        # cadence markers: written by whichever thread rewrites the
        # report; the main loop's due-check reads are snapshots
        self._report_round = -1    # guarded-by: _report_lock (writes)
        self._report_version = -1  # guarded-by: _report_lock (writes)
        self._report_terminal = False  # guarded-by: _report_lock
        self._chain_ok_cache: Optional[bool] = None  # guarded-by: _report_lock
        # SIGTERM leaves a current report + flushed event stream behind
        # (SIGKILL cannot be caught — there the periodic rewrites are the
        # whole story). Registered in the peer's main thread.
        try:
            signal.signal(signal.SIGTERM, self._sigterm)
        except ValueError:
            pass  # not the main thread (embedded/test use): skip

    # ------------------------------------------------------------- watchdogs

    def _deadline_fire(self):
        logger.error("peer %d: hard deadline %.0fs expired; exiting",
                     self.peer_id, self.cfg.dist.peer_deadline_s)
        self._write_report(status="deadline")
        os._exit(3)

    def _sigterm(self, signum, frame):
        logger.error("peer %d: SIGTERM; writing final partial report",
                     self.peer_id)
        try:
            self._write_report(status="sigterm")
        finally:
            # unconditional: a reentrancy hiccup in the report/telemetry
            # write must not swallow the termination itself
            os._exit(7)

    def _maybe_flush_report(self):
        """Periodic partial-report rewrite: every ``report_every_rounds``
        local rounds and on every version change — a SIGKILLed peer's
        newest report is at most one cadence stale, instead of absent.
        ``report_every_rounds=0`` is the documented off-switch for ALL
        mid-run rewrites (startup/terminal writes remain)."""
        every = self.cfg.dist.report_every_rounds
        due = every > 0 and (
            self.version != self._report_version
            or self.local_round - self._report_round >= every)
        if due:
            self._write_report(status="running")

    def _check_watchdogs(self):
        if os.getppid() != self._ppid:
            logger.error("peer %d: supervisor died; exiting", self.peer_id)
            self._write_report(status="orphaned")
            os._exit(5)
        if (time.time() - self._last_version_change
                > self.cfg.dist.idle_timeout_s):
            logger.error("peer %d: no version progress for %.0fs; exiting",
                         self.peer_id, self.cfg.dist.idle_timeout_s)
            self._write_report(status="stalled")
            os._exit(4)

    # ------------------------------------------------------------------ utils

    def _head(self) -> Optional[str]:
        return self.chain.head.hex() if self.chain is not None else None

    def _component(self):
        return self.gate.component_of(self.peer_id)

    def _leader(self) -> int:
        return min(self._component())

    def _note_version(self):
        self._last_version_change = time.time()
        # the model part of a history entry is only ever read by the
        # UNCOMPRESSED delta path (_prepare_update); compressed runs keep
        # just the lineage head — never 16 pinned copies of the params
        model = self.trainable if self.eng._comp is None else None
        self.history[self.version] = (model, self._head())
        for v in sorted(self.history):
            if len(self.history) <= self.history_limit:
                break
            del self.history[v]

    # --- dispatch-mode extension hooks (gossip.py overrides these) ---

    def _checkpoint_extra(self) -> Dict:
        """Extra keys a dispatch subclass folds into the checkpoint state."""
        return {}

    def _restore_extra(self, state: Dict) -> None:
        """Dispatch-subclass twin of :meth:`_checkpoint_extra` on restore.
        Called from ``_restore`` (inside ``__init__`` when resume=True), so
        subclasses must pre-set any attributes it touches BEFORE super()."""

    def _report_extra(self) -> Dict:
        """Extra keys a dispatch subclass folds into the peer report."""
        return {}

    def _sync_serve_extra(self, header_out: Dict) -> None:
        """Extra header keys a dispatch subclass ships with a STATE_SYNC
        serve (gossip adds its version vector)."""

    def _adopt_extra(self, header: Dict, trees: Dict) -> None:
        """Dispatch-subclass hook after a verified STATE_SYNC adoption
        (gossip refreshes its host state copy and version vector)."""

    def _cast(self, tree):
        import jax.numpy as jnp

        pd = jnp.dtype(self.cfg.param_dtype)
        return self._jax.tree.map(
            lambda x: jnp.asarray(x, pd)
            if jnp.issubdtype(np.asarray(x).dtype, np.floating)
            else jnp.asarray(x), tree)

    def _to_device(self, tree_np):
        import jax.numpy as jnp

        return self.eng.mesh.shard_clients(
            self._jax.tree.map(jnp.asarray, tree_np))

    # ----------------------------------------------------------- train + send

    def _train_once(self):
        """One local round: every local client fine-tunes from the peer's
        current base; the wire payload comes out of the engine's shared
        update-exchange seam."""
        import jax

        from bcfl_tpu.core import client_round_keys
        from bcfl_tpu.data import client_batches

        cfg = self.cfg
        rnd = self.local_round
        t0 = time.time()
        tree, n_ex = client_batches(
            self.eng.cache, self.eng.partitioner, self.global_ids, rnd,
            cfg.batch_size, max_batches=cfg.max_local_batches)
        batches = self._to_device(tree)
        keys = client_round_keys(
            jax.random.fold_in(self.eng.root_key, 4), self.global_ids, rnd)
        rngs = self.eng.mesh.shard_clients(jax.random.key_data(keys))
        base = self.eng.progs.broadcast(self.trainable)
        post, _stats = self.eng.progs.local_updates(
            base, self.eng.frozen, batches, rngs)
        ex = self.eng._exchange_updates(
            rnd, post, base, rngs, None, mode="async", commit=False)
        digests = None
        if ex.fp is not None:
            digests = [
                self.eng._entry_digest(ex.wire_kind, ex.fp[c]).hex()
                for c in range(self.local_clients)]
        header = {
            "type": "update", "base_version": int(self.version),
            "round": int(rnd), "wire_kind": ex.wire_kind,
            "lineage": self.history[self.version][1],
            "n_ex": [int(x) for x in np.asarray(n_ex)],
            "digests": digests, "sent_at": time.time(),
        }
        wire_tree = jax.tree.map(np.asarray, jax.device_get(ex.sent))
        self.local_round += 1
        telemetry.emit("round", round=rnd, wall_s=time.time() - t0,
                       base_version=int(self.version))

        # chaos straggler lane, driven for REAL at the transport: the
        # injected delay is an actual pre-send sleep, so it shows up in the
        # measured staleness/latency distribution instead of a simulated one
        delays = cfg.faults.straggler_delays(rnd, self.peers)
        if delays is not None and delays[self.peer_id] > 0:
            time.sleep(float(delays[self.peer_id]))
        # limp lane (gray failures, ROBUSTNESS.md §11): the CPU-starved/
        # swapping case — a REAL stall at the train seam, so the phi
        # detector and the w_slow response are graded against measured
        # slowness. Never sampled: the soak gates count stalls exactly.
        limp_act = cfg.faults.limp_action(rnd, self.peer_id)
        if limp_act is not None and limp_act["stall_s"] > 0:
            telemetry.emit("limp.inject", kind="stall", round=int(rnd),
                           stall_s=float(limp_act["stall_s"]))
            time.sleep(float(limp_act["stall_s"]))

        leader = self._leader()
        if self.byz is not None:
            # the byzantine lane's ONE injection seam: above the wire,
            # below the honest training — the frame the transport ships is
            # well-formed, the content lies (dist/byzantine.py). The
            # poisoning behaviors re-announce digests over the mutated
            # payload so ledger auth PASSES (the robust merge catches
            # them); forgery/equivocation keep the honest announcement so
            # the leader's refingerprint fails (the ledger catches them).
            header, wire_tree, act = self.byz.corrupt_update(
                header, wire_tree, dst=leader)
            if act is not None and act["reannounce"] and header.get(
                    "digests") is not None:
                header = dict(header, digests=self._announce_digests(
                    header["wire_kind"], wire_tree))
        if leader == self.peer_id:
            # the leader's own update gets a real (from, msg_id) identity
            # too, so EVERY merged update is dedup-accountable
            self._buffer_push((dict(header, **{
                "from": self.peer_id,
                "msg_id": self.transport.alloc_msg_id(self.peer_id),
                "msg_epoch": self.transport.epoch}),
                {"payload": wire_tree}, time.time()))
        elif self.cfg.dist.pipeline:
            # pipelined: hand the frame to the per-destination sender
            # worker and immediately start the next local round — the
            # retry/backoff/detector protocol runs in the worker while
            # this peer trains (comms/compute overlap, RUNTIME.md §4).
            # The bounded handoff blocks when the link is slower than
            # training (back-pressure), so frames can't pile up.
            self.transport.send_async(leader, header,
                                      {"payload": wire_tree})
        else:
            # serial (pipeline=False): the transport's retrying seam owns
            # failure handling inline; an undelivered update simply
            # rebases on the next global broadcast
            self.transport.send(leader, header, {"payload": wire_tree})

    def _announce_digests(self, wire_kind: str, tree_np) -> List[str]:
        """Per-client entry digests of a wire payload, recomputed through
        the same device fingerprint program the honest announcement uses —
        what the poisoning behaviors re-announce so their mutated payload
        authenticates."""
        fp = np.asarray(self.eng.progs.fingerprint(self._to_device(tree_np)))
        return [self.eng._entry_digest(wire_kind, fp[c]).hex()
                for c in range(self.local_clients)]

    # ------------------------------------------------------- leader: merging

    def _buffer_push(self, entry: tuple):
        """Leader-side FedBuff intake, BOUNDED: while merges are parked
        (below quorum) the leader still trains and followers still send,
        and each entry holds a model-sized wire tree — an uncapped list
        would grow to OOM before the idle watchdog fires. Shed the OLDEST
        (its stale lineage would be the first rejected at the eventual
        merge anyway). Called from the main loop AND (pipeline on) the
        intake thread — all buffer state moves under the buffer lock."""
        cap = max(4, 2 * self.peers, 2 * (self.cfg.dist.buffer or 1))
        with self._buffer_lock:
            if not self._buffer:
                self._buffer_since = entry[2]  # a new merge window opens
            self._buffer.append(entry)
            while len(self._buffer) > cap:
                self._buffer.pop(0)
                self._buffer_shed += 1

    def _maybe_merge(self):
        import math

        from bcfl_tpu.dist.transport import DOWN

        cfg = self.cfg
        comp = self._component()
        # quorum degradation (RUNTIME.md "Delivery contract"): peers the
        # failure detector holds DOWN don't count toward the buffer target
        # — the leader proceeds on the reachable quorum instead of paying
        # buffer_timeout_s per merge for updates that can never arrive.
        # Below quorum_frac of the component it refuses to advance the
        # global at all (the idle watchdog bounds that wait).
        states = self.transport.detector.states()
        down = [p for p in comp
                if p != self.peer_id and states.get(p) == DOWN]
        # QUARANTINED peers count like DOWN ones toward the merge target:
        # their arrivals are refused post-ack, so waiting buffer_timeout_s
        # for updates that can never buffer would hand the adversary a
        # denial-of-service for free. They still count against the quorum
        # DENOMINATOR — quarantining more than (1 - quorum_frac) of the
        # component parks the leader, by design (a distrusted majority is
        # not a quorum).
        quarantined = ([p for p in self.rep.quarantined_peers()
                        if p in comp and p != self.peer_id]
                       if self.rep is not None else [])
        alive = [p for p in comp if p not in down and p not in quarantined]
        if len(alive) < max(1, math.ceil(cfg.dist.quorum_frac * len(comp))):
            # count EPISODES (entries into the below-quorum state), not
            # main-loop polls — the surfaced number must not depend on
            # how fast the host spins the loop
            if not self._below_quorum:
                self._below_quorum = True
                self._below_quorum_events += 1
                telemetry.emit("quorum.below", component=len(comp),
                               alive=len(alive), down=list(down))
            # with merges (and so broadcasts) parked, nothing else on the
            # leader sends — so nothing would ever probe the DOWN peers
            # and the below-quorum state would be ABSORBING even after
            # the network heals. Ping them directly: send() rate-limits
            # to one probe per probe_interval_s, a success flips the peer
            # REACHABLE, and the next poll restores quorum.
            for p in down:
                self.transport.send(p, {"type": "ping"})
            return
        self._below_quorum = False
        # the buffer target counts DISTINCT senders, not buffered entries:
        # a fast peer (or a flooding adversary) can park several of its own
        # updates before a slow peer lands one, and a robust rule graded
        # on "f of k votes are bad" is only meaningful when the vote
        # population is PEERS — k entries from one sender are one voice
        # (and one vote: _apply_robust_merge groups by sender). The
        # buffer_timeout still bounds the wait for stragglers.
        # Target check and swap are ONE critical section: the intake
        # thread keeps pushing concurrently, and the swap hands merge a
        # consistent snapshot while arrivals land in the fresh standby
        # buffer (the double-buffer seam).
        want = min(cfg.dist.buffer or 1, len(alive))
        with self._buffer_lock:
            if not self._buffer:
                return
            distinct = len({int(h.get("from", -1))
                            for h, _, _ in self._buffer})
            if (distinct < want and time.time() - self._buffer_since
                    < cfg.dist.buffer_timeout_s):
                return
            buf, self._buffer = self._buffer, []
        t0 = time.time()
        arrivals, rejected, weighted = [], [], []
        for header, trees, recv_t in buf:
            out = self._prepare_update(header, trees, recv_t)
            (arrivals if out.get("ok") else rejected).append(out["rec"])
            if out.get("ok"):
                weighted.append(out)
        robust_info = None
        if weighted:
            if cfg.aggregator != "mean":
                robust_info = self._apply_robust_merge(weighted)
            else:
                self._apply_merge(weighted)
        self.version += 1
        rec = MergeRecord(
            version=self.version, leader=self.peer_id, arrivals=arrivals,
            rejected=rejected, wall_s=time.time() - t0,
            solo=self.gate.components() is not None,
            degraded=bool(down),
            quorum=({"component": len(comp), "alive": len(alive),
                     "down": down, "quarantined": quarantined}
                    if (down or quarantined) else None),
            robust=robust_info,
            # the precondition is stated over distinct peer VOTES (the
            # rule's population), not buffered entries
            robust_degraded=bool(
                robust_info is not None
                and robust_info.get("k", 0) < self._robust_min))
        self.merges.append(rec)
        # health-series extras (OBSERVABILITY.md §6): the leader's current
        # per-peer trust vector and, when LoRA is on, the merged global
        # adapter's effective rank (the rank-collapse guard statistic) —
        # the live monitor folds both into health.jsonl per round
        trust_map = ({str(p): round(float(self.rep.tracker.trust[p]), 6)
                      for p in range(self.peers)}
                     if self.rep is not None else None)
        eff_rank = None
        if self.eng._eff_rank is not None:
            try:
                eff_rank = float(self.eng._eff_rank(self.trainable))
            except Exception:  # noqa: BLE001 — a health stat is never merge-fatal
                pass
        # the FedBuff lineage event (OBSERVABILITY.md): which (peer,
        # msg_epoch, msg_id) updates, at what measured staleness and
        # merge weight, composed this model version — plus the chain
        # state it committed, for the monotone-heads invariant
        telemetry.emit(
            "merge", version=rec.version, leader=rec.leader,
            arrivals=rec.arrivals, rejected=rec.rejected, solo=rec.solo,
            degraded=rec.degraded, component=list(comp),
            quorum=rec.quorum, wall_s=rec.wall_s,
            robust=rec.robust, robust_degraded=rec.robust_degraded,
            trust=trust_map, effective_rank=eff_rank,
            **({"chain_len": len(self.chain),
                "head8": self.chain.head.hex()[:16], "rewrite": False}
               if self.chain is not None else {}))
        # gray-failure observation shares the merge clock whether or not
        # reputation is armed: phi samples land in the stream either way
        self._observe_gray_health()
        if self.rep is not None:
            # the merge IS the observation clock: fold the pending wire
            # evidence (auth/outlier/staleness/replay + drained detector
            # transitions) into the per-peer state machine, AFTER this
            # merge's event (a quarantine this merge triggers must gate
            # the NEXT merge, not retroactively taint this one), and
            # commit any transitions to the chain BEFORE the broadcast so
            # the suffix every follower adopts carries them.
            self._drain_detector_evidence()
            arrived = ([a["peer"] for a in arrivals]
                       + [r["peer"] for r in rejected])
            transitions = self.rep.observe_merge(arrived)
            if transitions and self.chain is not None:
                self.rep.commit_transitions(self.chain, self.version,
                                            transitions)
                telemetry.emit("ledger", op="rep_transition",
                               n=len(transitions),
                               chain_len=len(self.chain), rewrite=False,
                               head8=self.chain.head.hex()[:16])
        # history snapshot AFTER any reputation rows hit the chain: the
        # broadcast ships the suffix INCLUDING those rows, so a follower's
        # recorded head for this version is the post-rep-rows head — the
        # leader's lineage record must match it, or every honest update
        # based on this version would bounce as "fork lineage mismatch"
        # (and feed the replay evidence lane!) after any transition
        self._note_version()
        self._maybe_checkpoint()
        self._broadcast_global(healed=False)

    def _drain_detector_evidence(self) -> None:
        """Feed NEW failure-detector transitions to the reputation
        tracker: a peer the circuit breaker drove to DOWN since the last
        merge is unreliability evidence (the weakest lane — peer death is
        not malice, but a flapping peer should not keep full merge
        weight)."""
        det = self.transport.detector
        new = det.transitions_total - self._det_seen
        if new <= 0:
            return
        self._det_seen = det.transitions_total
        from bcfl_tpu.dist.transport import DOWN as _DOWN

        recent = list(det.transitions)[-min(new, len(det.transitions)):]
        for t in recent:
            if t.get("to") == _DOWN:
                self.rep.note_detector_down(t["peer"])

    def _observe_gray_health(self) -> None:
        """Gray-failure observation, clocked by the merge (leadered) or
        the peer-local merge (gossip): sample the phi detector's per-peer
        suspicion into the stream and feed MEASURED slowness to the
        reputation tracker's w_slow lane. Severity is the WORST of three
        measurements, clamped to [0, 1]: phi normalized by the down
        threshold (liveness suspicion — silence, failed sends); the
        measured-throughput shortfall below ``min_bandwidth_bps`` (the
        config's own "slowest link we budget for": a link the estimator
        measures BELOW it is limping even when every adaptively-budgeted
        send still lands); and the measured-RTT excess beyond
        ``deadline_floor_s`` (a round trip consuming more than the
        fastest wall we would ever enforce — the stall/SIGSTOP signature:
        acks come back seconds late while throughput and phi both look
        healthy at the merge instant). All three are zero for a healthy
        peer, which is what lets the down-weight RECOVER when the limp
        clears.
        Structurally a down-weight only: ``note_slowness`` never touches
        the quarantine evidence path (the ``slowness_is_not_malice``
        invariant holds by construction, then gets checked anyway)."""
        det = self.transport.detector
        snap_fn = getattr(det, "phi_snapshot", None)
        if snap_fn is None:
            return  # detector="fixed": no continuous suspicion to sample
        phi_down = float(self.cfg.dist.phi_down)
        for key, info in snap_fn().items():
            p = int(key)
            if p == self.peer_id:
                continue
            telemetry.emit_sampled(
                "detector.phi", (int(self.version), p), target=p,
                phi=info["phi"], state=det.state_of(p),
                window_s=info.get("window_s"), rtt_s=info.get("rtt_s"),
                bps=info.get("bps"))
            if self.rep is not None:
                sev_phi = (min(float(info["phi"]) / phi_down, 1.0)
                           if phi_down > 0 else 0.0)
                bps = info.get("bps")
                min_bps = float(self.cfg.dist.min_bandwidth_bps)
                sev_bw = (max(0.0, 1.0 - float(bps) / min_bps)
                          if bps and min_bps > 0 else 0.0)
                rtt = info.get("rtt_s")
                floor = float(self.cfg.dist.deadline_floor_s)
                sev_rtt = (max(0.0, float(rtt) / floor - 1.0)
                           if rtt and floor > 0 else 0.0)
                self.rep.note_slowness(
                    p, min(1.0, max(sev_phi, sev_bw, sev_rtt)))

    def _apply_robust_merge(self, updates: List[Dict]) -> Dict:
        """Robust twin of :meth:`_apply_merge`: each buffered update is
        collapsed to its client-slice delta (the weighted mean through the
        same ``collapse`` program as the mean path), the deltas are
        grouped into one vote PER SENDING PEER (``combine_votes`` — the
        "f of k" breakdown arithmetic is over peers, so one sender's
        message rate must never inflate its vote count), the votes are
        aggregated host-side with the configured robust rule
        (bcfl_tpu.dist.robust), and the global takes the same
        ``async_server_lr`` × ``_async_merge_scale``-rescaled step along
        the robust estimate — staleness shrinks the applied STEP, the
        rule ignores it as a vote weight (the local robust contract,
        ROBUSTNESS.md §2). Outlier flags land on every flagged peer's
        arrival records and feed the reputation tracker."""
        import jax
        import jax.numpy as jnp

        from bcfl_tpu.dist.robust import combine_votes, robust_merge
        from bcfl_tpu.fed.engine import _tree_axpy

        zero = jax.tree.map(jnp.zeros_like, self.trainable)
        deltas_np, weights, base_total = [], [], 0.0
        for u in updates:
            w_dev = self.eng.mesh.shard_clients(jnp.asarray(u["alpha"]))
            vote = self.eng.progs.collapse(u["deltas"], w_dev, zero)
            deltas_np.append(jax.tree.map(np.asarray,
                                          jax.device_get(vote)))
            weights.append(float(np.asarray(u["alpha"]).sum()))
            base_total += u["base_w"]
        by_peer: Dict[int, List[int]] = {}
        for i, u in enumerate(updates):
            by_peer.setdefault(int(u["rec"]["peer"]), []).append(i)
        peer_order = sorted(by_peer)
        votes = [combine_votes([deltas_np[i] for i in by_peer[p]],
                               [weights[i] for i in by_peer[p]])
                 for p in peer_order]
        vote_w = [sum(weights[i] for i in by_peer[p]) for p in peer_order]
        agg, flags, info = robust_merge(
            votes, vote_w, self.cfg.aggregator, self.cfg.aggregator_trim)
        info["votes_by_peer"] = {str(p): len(by_peer[p])
                                 for p in peer_order}
        if "krum_selected" in info:
            # robust_merge speaks in vote positions; the lineage record
            # must name the PEER whose vote became the global (sender
            # sets are rarely contiguous from 0 — a position would
            # misattribute)
            info["krum_selected_peer"] = peer_order[info["krum_selected"]]
        dists = info.get("distances")
        for j, p in enumerate(peer_order):
            if not flags[j]:
                continue
            for i in by_peer[p]:
                updates[i]["rec"]["outlier"] = True
            # like every other evidence lane, never against self: under
            # non-iid slices the leader's own honest vote can sit far
            # from the aggregate, and a leader quarantining ITSELF while
            # remaining the component leader would wedge the run (the
            # flag still lands on the record for observability)
            if self.rep is not None and p != self.peer_id:
                self.rep.note_outlier(
                    p, distance=(dists[j] if dists else None))
        if agg is None:
            return info  # every vote eliminated: params kept (degraded)
        total = sum(weights)
        scale = total / max(base_total, 1e-9)
        agg_dev = self.eng.mesh.replicate(self._cast(agg))
        self.trainable = _tree_axpy(self.trainable, agg_dev,
                                    self.cfg.async_server_lr * scale)
        return info

    def _prepare_update(self, header: Dict, trees: Dict, recv_t: float):
        """Commit + verify + decode one buffered update. Returns a record
        and, when accepted, the per-client merge weights and delta rows."""
        cfg = self.cfg
        src = int(header["from"])
        base_v = int(header["base_version"])
        staleness, clamped = measured_staleness(self.version, base_v)
        rec = {"peer": src, "msg_id": header.get("msg_id"),
               "msg_epoch": header.get("msg_epoch"),
               "round": int(header["round"]),
               "base_version": base_v, "staleness": staleness,
               "latency_s": max(recv_t - float(header["sent_at"]), 0.0)}
        if clamped:
            # leader restarted onto an older version counter than this
            # sender's base (see measured_staleness): the decay exponent
            # is clamped — surfaced, never silently normalized
            rec["staleness_clamped"] = True
            telemetry.emit("warn", what="negative_staleness", peer_from=src,
                           leader_version=int(self.version),
                           base_version=base_v)
        # post-ack quarantine gate, second seam (the first is _handle):
        # an update BUFFERED before the quarantine transition must not
        # merge after it — this check runs at merge time, which is what
        # the no_quarantined_merge invariant holds the stream to
        if (self.rep is not None and src != self.peer_id
                and self.rep.is_quarantined(src)):
            with self._qdrop_lock:
                self.rep.quarantine_drops += 1
            rec["rejected"] = "peer quarantined (post-ack gate)"
            return {"ok": False, "rec": rec}
        # lineage check (BOTH wire formats) BEFORE anything touches the
        # chain: an update based on another fork's history must go through
        # the reconcile protocol, never a silent merge — and a protocol-
        # rejected update must leave NO chain entries (the chain attests
        # updates that entered aggregation, where auth failures are the
        # recorded evidence). The sender names the chain head of its base
        # version; it must match this leader's history for that version.
        hist = self.history.get(base_v)
        if hist is not None and hist[1] != header.get("lineage"):
            rec["rejected"] = "fork lineage mismatch"
            if self.rep is not None and src != self.peer_id:
                # the replay behavior's signature: a stale base's lineage
                # resent against rewritten/advanced history
                self.rep.note_replay(src, "fork lineage mismatch")
            return {"ok": False, "rec": rec}
        if self.eng._comp is None and hist is None:
            # uncompressed wire ships post-train params: the delta NEEDS
            # the base model, so an evicted base version is fatal here
            rec["rejected"] = "unknown base version"
            if self.rep is not None and src != self.peer_id:
                self.rep.note_replay(src, "unknown base version")
            return {"ok": False, "rec": rec}
        dev = self._to_device(trees["payload"])
        ids = [src * self.local_clients + c
               for c in range(self.local_clients)]
        auth = np.ones((self.local_clients,), np.float32)
        if self.chain is not None and header.get("digests"):
            # commit what the sender ANNOUNCED, then authenticate what
            # ARRIVED — the same commit -> transport -> verify order as the
            # local split-phase flow, but across a real wire
            kind = header["wire_kind"]
            for c, d in zip(ids, header["digests"]):
                self.chain.append_digest(int(header["round"]), int(c),
                                         bytes.fromhex(d),
                                         self.eng._client_payload_bytes)
            telemetry.emit("ledger", op="commit", round=int(header["round"]),
                           n=self.local_clients, chain_len=len(self.chain),
                           rewrite=False,
                           head8=self.chain.head.hex()[:16])
            fp = np.asarray(self.eng.progs.fingerprint(dev))
            for c in range(self.local_clients):
                recomputed = self.eng._entry_digest(kind, fp[c]).hex()
                if recomputed != header["digests"][c]:
                    auth[c] = 0.0
            rec["auth"] = auth.tolist()
            if (self.rep is not None and src != self.peer_id
                    and (auth == 0.0).any()):
                # the hard evidence lane: announced one fingerprint,
                # shipped another (digest forgery / equivocation / wire
                # damage past the CRC — repetition tells them apart).
                # Never against self (like every other lane): a leader
                # configured as the adversary must not quarantine ITSELF
                # while remaining leader — its forged self-update is
                # already auth-masked out of the merge above
                self.rep.note_auth_failure(
                    src, float((auth == 0.0).mean()))
        if self.eng._comp is None:
            # uncompressed wire ships post-train params: reconstruct the
            # delta against the (lineage-verified, above) base model
            from bcfl_tpu.fed.engine import _tree_sub

            deltas = _tree_sub(dev, self.eng.progs.broadcast(hist[0]))
        else:
            # compressed wire ships the encoded delta itself — FedBuff can
            # apply it without the base; a base evicted from the bounded
            # history merely can't be lineage-verified (recorded)
            if hist is None:
                rec["lineage_unverified"] = True
            deltas = self.eng.progs.decode_delta(
                dev, self.eng.progs.broadcast(self.trainable))
        if self.rep is not None and src != self.peer_id:
            # measured-staleness evidence: a chronically stale peer (real
            # slowness or deliberate replay) decays toward SUSPECT
            self.rep.note_staleness(src, staleness)
        n_ex = np.asarray(header["n_ex"], np.float32)
        alpha = auth * (cfg.staleness_decay ** staleness)
        base_w = n_ex if cfg.weighted_agg else np.ones_like(n_ex)
        alpha = alpha * base_w
        if self.rep is not None:
            # trust gates merge weight: the EWMA score scales this
            # update's whole vote (probation peers additionally carry the
            # probation_weight fold) — the dist analogue of the engine's
            # reputation-gate mask fold
            trust_mult = self.rep.gate(src)
            rec["trust"] = round(float(trust_mult), 6)
            alpha = alpha * np.float32(trust_mult)
        if float(alpha.sum()) <= 0.0:
            rec["rejected"] = "all clients eliminated (auth/trust)"
            return {"ok": False, "rec": rec}
        # the update's total merge weight (staleness decay x examples x
        # auth, summed over the peer's client slice): part of the merge
        # lineage — every composed model version is reconstructible from
        # the stream
        rec["weight"] = float(alpha.sum())
        return {"ok": True, "rec": rec, "deltas": deltas, "alpha": alpha,
                "base_w": float(base_w.sum())}

    def _apply_merge(self, updates: List[Dict]):
        """FedBuff step along the staleness-weighted mean delta — the
        measured-clock twin of ``FedEngine._async_round``'s merge."""
        import jax
        import jax.numpy as jnp

        from bcfl_tpu.fed.engine import _tree_axpy, _tree_wsum

        zero = jax.tree.map(jnp.zeros_like, self.trainable)
        merged_parts, weights, base_total = [], [], 0.0
        for u in updates:
            w_dev = self.eng.mesh.shard_clients(jnp.asarray(u["alpha"]))
            merged_parts.append(
                self.eng.progs.collapse(u["deltas"], w_dev, zero))
            weights.append(float(np.asarray(u["alpha"]).sum()))
            base_total += u["base_w"]
        total = sum(weights)
        merged = _tree_wsum(
            jnp.asarray([w / total for w in weights], jnp.float32),
            merged_parts)
        # decay shrinks the applied STEP, not just relative votes — the
        # _async_merge_scale rescale (PARALLELISM.md "Async semantics")
        scale = total / max(base_total, 1e-9)
        self.trainable = _tree_axpy(self.trainable, merged,
                                    self.cfg.async_server_lr * scale)

    def _broadcast_global(self, healed: bool, full: bool = False):
        import jax

        header = {
            "type": "global", "version": int(self.version),
            "healed": bool(healed),
        }
        if self.chain is not None:
            # normal merges broadcast only the chain SUFFIX since the last
            # broadcast (O(new entries), not O(chain)); heals broadcast the
            # full chain — the merge rewrote history past the fork point,
            # so no replica's suffix base is valid. A follower whose length
            # or head doesn't match the suffix base resyncs via HELLO.
            start = 0 if (healed or full) else self._last_broadcast_len
            header["chain_start"] = int(start)
            header["chain_prev_head"] = self.chain.head_at(start).hex()
            header["chain"] = self.chain.segment(start)
            self._last_broadcast_len = len(self.chain)
        else:
            header["chain"] = None
        telemetry.emit("broadcast", version=int(self.version),
                       healed=bool(healed), full=bool(healed or full))
        model = jax.tree.map(np.asarray, jax.device_get(self.trainable))
        for p in self._component():
            if p == self.peer_id:
                continue
            # retrying seam; a peer that misses the broadcast resyncs via
            # HELLO, and a dead one trips the detector toward DOWN. With
            # the pipeline on, broadcasts ride the same per-destination
            # sender workers as updates (FIFO per destination, so version
            # N always hits the wire before N+1) and the leader starts
            # its next round while the model streams out.
            if self.cfg.dist.pipeline:
                self.transport.send_async(p, header, {"model": model})
            else:
                self.transport.send(p, header, {"model": model})

    # --------------------------------------------------- partition lifecycle

    def _update_partition_state(self):
        comps = self.gate.components()
        if comps is not None and not self._partitioned:
            self._partitioned = True
            self._fork_comps = comps
            self.fork = {
                "at_version": int(self.version),
                "fork_base": (int(len(self.chain))
                              if self.chain is not None else None),
                "head_at_fork": self._head(),
                "component": list(self.gate.component_of(self.peer_id)),
            }
            telemetry.emit("fork.begin", at_version=int(self.version),
                           component=self.fork["component"],
                           head8=(self._head() or "")[:16],
                           fork_base=self.fork["fork_base"])
            logger.info("peer %d: partition began at version %d "
                        "(component %s)", self.peer_id, self.version,
                        self.fork["component"])
        elif comps is None and self._partitioned:
            self._partitioned = False
            self.fork["head_before_heal"] = self._head()
            self.fork["chain_len_before_heal"] = (
                int(len(self.chain)) if self.chain is not None else None)
            old_comp = next(c for c in self._fork_comps
                            if self.peer_id in c)
            if min(old_comp) == self.peer_id and self.peer_id != 0:
                # I led a fork component: initiate the reconcile handshake
                self._pending_reconcile = True
            telemetry.emit("fork.heal", at_version=int(self.version),
                           head8=(self._head() or "")[:16])
            logger.info("peer %d: partition healed at version %d (head %s)",
                        self.peer_id, self.version,
                        (self._head() or "")[:16])

    def _solo_weight(self) -> float:
        """Participation mass this peer's fork accumulated: merged arrivals
        across its solo merges — the reconcile consensus weight."""
        return float(sum(len(m.arrivals) for m in self.merges if m.solo)
                     or 1.0)

    def _try_reconcile(self):
        """Offer the fork to the global leader. Retried (throttled) until a
        post-heal GLOBAL supersedes it: a send can 'succeed' at the socket
        yet be dropped by the leader's own still-partitioned clock, so only
        an adopted global clears the pending flag."""
        import jax

        if not self.gate.allowed(self.peer_id, 0):
            return
        if time.time() - self._last_reconcile_try < 2.0:
            return
        self._last_reconcile_try = time.time()
        header = {
            "type": "reconcile", "version": int(self.version),
            "rows": self.chain.segment(0) if self.chain is not None else None,
            "weight": self._solo_weight(),
        }
        model = jax.tree.map(np.asarray, jax.device_get(self.trainable))
        # retrying seam; undelivered offers re-fire on the throttle until a
        # healed global supersedes them
        self.transport.send(0, header, {"model": model})

    def _handle_reconcile(self, header: Dict, trees: Dict):
        """Global leader's side of the heal: verify the fork segment, adopt
        the deterministic chain merge, reconcile the component models
        through the collapse consensus, and broadcast the healed global."""
        import jax.numpy as jnp

        from bcfl_tpu.fed.engine import _tree_wsum
        from bcfl_tpu.ledger import Ledger

        src = int(header["from"])
        t0 = time.time()
        rec = {"from_peer": src, "their_version": int(header["version"]),
               "my_version": int(self.version)}
        their_model = self._cast(trees["model"])
        their_weight = float(header.get("weight") or 1.0)
        my_weight = self._solo_weight()
        if self.chain is not None and header.get("rows") is not None:
            rows = header["rows"]
            their_heads = [bytes.fromhex(r["head"]) for r in rows]
            fork = self.chain.fork_point(their_heads)
            rec["fork_point"] = fork
            rec["my_head"] = self._head()
            rec["their_head"] = rows[-1]["head"] if rows else None
            rec["forked"] = (rec["my_head"] != rec["their_head"])
            bad = Ledger.verify_segment(
                self.chain.head_at(fork), rows[fork:],
                self.cfg.ledger.use_native)
            if bad != -1:
                # a tampered fork segment: never adopted — the requester is
                # told the CURRENT (unmerged) global instead
                rec["segment_rejected_at"] = int(bad)
                self.reconcile = rec
                logger.warning("peer %d: rejected tampered reconcile "
                               "segment from %d (link %d)",
                               self.peer_id, src, bad)
                self._broadcast_global(healed=False)
                return
            merged = Ledger.merge_rows(self.chain.segment(fork), rows[fork:])
            self.chain.adopt_merge(fork, merged)
            rec["merged_entries"] = len(merged)
            rec["merged_head"] = self._head()
            rec["chain_ok"] = (self.chain.verify_chain() == -1)
            # a declared history rewrite: the monotone-heads invariant
            # treats this (and only this kind of) length change as legal
            telemetry.emit("ledger", op="adopt_merge",
                           chain_len=len(self.chain), rewrite=True,
                           head8=(self._head() or "")[:16],
                           fork_point=fork)
        # model consensus across the healed components: the participation-
        # weighted mean of the two fork models (with aggregator pinned to
        # "mean" on this runtime, this IS what the collapse consensus
        # program computes — the direct form skips a one-off stacked-
        # program compile per heal)
        total = my_weight + their_weight
        self.trainable = _tree_wsum(
            jnp.asarray([my_weight / total, their_weight / total],
                        jnp.float32),
            [self.trainable, their_model])
        self.version = max(self.version, int(header["version"])) + 1
        self._note_version()
        rec["healed_version"] = int(self.version)
        rec["wall_s"] = time.time() - t0
        self.reconcile = rec
        telemetry.emit("reconcile", **rec)
        self._maybe_checkpoint()
        self._broadcast_global(healed=True)
        logger.info("peer %d: reconciled fork from peer %d -> version %d "
                    "(chain head %s)", self.peer_id, src, self.version,
                    (self._head() or "")[:16])

    # ------------------------------------------------------- follower: adopt

    def _request_resync(self, leader: int):
        """Ask the leader for a full-state GLOBAL (throttled): the suffix a
        broadcast carried didn't extend this replica — missed broadcasts,
        or a fork rewrite this peer hasn't seen yet."""
        if time.time() - self._last_hello < 2.0:
            return
        self._last_hello = time.time()
        self.transport.send(leader, {"type": "hello",
                                     "version": int(self.version)})

    def _handle_global(self, header: Dict, trees: Dict):
        from bcfl_tpu.ledger import Ledger

        version = int(header["version"])
        if self._needs_bootstrap:
            # repair in flight: globals are not commitment-refingerprinted,
            # so a bootstrapping peer adopts ONLY through the verified
            # STATE_SYNC path (repair_authenticated invariant)
            return
        if version <= self.version:
            return
        if self._pending_reconcile and not header.get("healed"):
            # a fork is pending: adopting an ordinary (pre-heal) global
            # would REPLACE this peer's fork chain — destroying the very
            # evidence the reconcile must deliver — and clearing the offer
            # here could cancel a reconcile the leader never received (its
            # receiver gate drops sends while ITS clock is still in the
            # span), deadlocking the leader's finalize guard. Defer: keep
            # retrying the offer; the leader cannot finalize before
            # handling it, and its HEALED broadcast supersedes everything.
            return
        if self.chain is not None and header.get("chain") is not None:
            rows = header["chain"]
            start = int(header.get("chain_start", 0))
            if start == 0:
                # full sync (heal / hello reply): rebuild and verify the
                # whole replica from genesis
                replica = Ledger(self.cfg.ledger.use_native)
                if replica.append_rows(rows) != -1:
                    logger.error("peer %d: global v%d carried an "
                                 "unverifiable chain; not adopting",
                                 self.peer_id, version)
                    return
                self.chain = replica
                self.eng.ledger = replica
                # full replica rebuild: a declared rewrite (heal / hello
                # resync may shorten a fork replica's chain legitimately)
                telemetry.emit("ledger", op="resync",
                               chain_len=len(self.chain), rewrite=True,
                               head8=self.chain.head.hex()[:16])
                if self.rep is not None:
                    # inherit the leader's committed reputation verdicts
                    # from the adopted chain — a REJOINING peer re-enters
                    # knowing who is quarantined instead of starting blind
                    self.rep.absorb_rows(rows)
            elif (start == len(self.chain)
                  and self.chain.head.hex() == header.get("chain_prev_head")):
                # contiguous suffix: verify incrementally as it lands
                if self.chain.append_rows(rows) != -1:
                    logger.error("peer %d: global v%d suffix failed link "
                                 "verification; resyncing", self.peer_id,
                                 version)
                    self._request_resync(int(header["from"]))
                    return
                telemetry.emit("ledger", op="append",
                               chain_len=len(self.chain), rewrite=False,
                               head8=self.chain.head.hex()[:16])
                if self.rep is not None:
                    # the suffix carries the leader's reputation rows too:
                    # every follower tracks its leader's verdicts from the
                    # broadcasts it already receives
                    self.rep.absorb_rows(rows)
            else:
                # gap or diverged base (missed broadcasts, fork rewrite):
                # never adopt a model whose chain this replica can't
                # verify — request the full state instead
                self._request_resync(int(header["from"]))
                return
        self.trainable = self.eng.mesh.replicate(self._cast(trees["model"]))
        self.version = version
        self.adopted.append(version)
        self._note_version()
        telemetry.emit("adopt", version=version,
                       healed=bool(header.get("healed")),
                       leader=int(header.get("from", -1)))
        if header.get("healed"):
            # ONLY the healed global clears a pending offer: it is the one
            # broadcast that provably incorporated this peer's fork
            self._pending_reconcile = False
        self._maybe_checkpoint()

    def _handle_hello(self, header: Dict):
        """A (re)joining peer announces itself; the leader replies with the
        full current state so the rejoiner re-enters verified."""
        if self._leader() != self.peer_id:
            return
        import jax

        src = int(header["from"])
        reply = {
            "type": "global", "version": int(self.version), "healed": False,
            "chain_start": 0,
        }
        if self.chain is not None:
            from bcfl_tpu.ledger import GENESIS

            reply["chain_prev_head"] = GENESIS.hex()
            reply["chain"] = self.chain.segment(0)
        else:
            reply["chain"] = None
        model = jax.tree.map(np.asarray, jax.device_get(self.trainable))
        # retrying seam; an undelivered reply re-fires on the rejoiner's
        # next throttled HELLO
        self.transport.send(src, reply, {"model": model})

    # ------------------------------------- state-sync repair (RUNTIME.md)

    def _sync_targets(self) -> List[int]:
        """Peers a bootstrap request cycles through: the leader first (it
        holds the authoritative state in leadered dispatch), then every
        other peer — any live peer can serve, so a damaged LEADER repairs
        from its followers. Gossip overrides this with a seeded neighbor
        sample."""
        leader = min(p for p in range(self.peers) if p != self.peer_id)
        rest = [p for p in range(self.peers)
                if p not in (self.peer_id, leader)]
        return [leader] + rest

    def _maybe_request_sync(self):
        """Throttled STATE_SYNC request loop: while ``_needs_bootstrap``,
        ask one live peer (cycling) for its full verified state. Runs from
        the main loop — the peer neither trains nor announces until a
        transfer is adopted."""
        if not self._needs_bootstrap:
            return
        if time.time() - self._last_sync_req < 2.0:
            return
        self._last_sync_req = time.time()
        targets = self._sync_targets()
        if not targets:
            return
        dst = targets[self._sync_target_i % len(targets)]
        self._sync_target_i += 1
        telemetry.emit("state.sync.request",
                       reason=self._bootstrap_reason or "empty",
                       to=int(dst), have_version=int(self.version),
                       have_len=(len(self.chain)
                                 if self.chain is not None else 0))
        self.transport.send(dst, {
            "type": "state_sync_req",
            "reason": self._bootstrap_reason or "empty",
            "version": int(self.version),
            "have_len": int(len(self.chain)) if self.chain is not None else 0,
        })

    def _handle_state_sync_req(self, header: Dict):
        """Serve a damaged/empty peer the full current state, anchored to
        the chain: a reserved commitment row (``Ledger.commit_state``)
        binding ``params_digest(state)`` at the current version is
        appended (once per distinct digest) BEFORE the transfer, so the
        receiver can verify the chain segment link-by-link and then
        refingerprint the tree against committed history — the transfer
        is trustless even though the server is just a peer."""
        import jax

        if self._needs_bootstrap:
            return  # damaged myself: the requester's cycle finds another
        from bcfl_tpu.ledger.ledger import Ledger, params_digest

        src = int(header["from"])
        model = jax.tree.map(np.asarray, jax.device_get(self.trainable))
        header_out = {"type": "state_sync", "version": int(self.version)}
        if self.chain is not None:
            digest = params_digest(model, self.cfg.ledger.use_native)
            rows = self.chain.segment(0)
            if Ledger.find_state_commitment(
                    rows, self.version, self.peer_id) != digest:
                self.chain.commit_state(self.version, self.peer_id, digest)
                telemetry.emit("ledger", op="commit_state",
                               chain_len=len(self.chain), rewrite=False,
                               head8=self.chain.head.hex()[:16])
            header_out["chain"] = self.chain.segment(0)
        else:
            header_out["chain"] = None
        self._sync_serve_extra(header_out)
        serial = self._sync_serves.get(src, 0)
        self._sync_serves[src] = serial + 1
        tam = self.cfg.faults.sync_tamper_action(self.peer_id, src, serial)
        if tam is not None:
            # seeded in-flight tamper (AFTER the digest was committed):
            # the refusal this provokes at the receiver is the proof the
            # refingerprint gate is load-bearing
            model = _tamper_tree(model, tam["frac"])
        telemetry.emit("state.sync.serve", to=src,
                       version=int(self.version),
                       chain_len=(len(self.chain)
                                  if self.chain is not None else 0),
                       tampered=tam is not None, serial=serial)
        self.transport.send(src, header_out, {"model": model})

    def _handle_state_sync(self, header: Dict, trees: Dict):
        """Adopt a served state — but only after BOTH verification gates
        pass: (1) the chain segment verifies link-by-link from genesis AND
        extends this peer's surviving prefix (a tampered row or a forked
        history fails here, via the existing verify_segment/fork_point
        API); (2) the received tree refingerprints to the state commitment
        row the chain carries for exactly this (version, server). Refusals
        re-enter the request cycle; nothing is adopted on faith.

        A serve landing AFTER a completed repair (the requester cycled
        targets and another peer answered first) is still pushed through
        the same gates so the evidence is durable — a tampered late
        transfer must surface as a state.sync.refuse, not vanish into
        the duplicate drop — but is never adopted, and a refused late
        serve does not re-enter the request cycle."""
        from bcfl_tpu.ledger.ledger import GENESIS, Ledger, params_digest

        adopting = self._needs_bootstrap
        server = int(header["from"])
        version = int(header["version"])
        rows = header.get("chain")
        refuse = None
        digest = recomputed = None
        if self.chain is not None:
            if not rows:
                refuse = "no_chain"
            elif Ledger.verify_segment(
                    GENESIS, rows, self.cfg.ledger.use_native) != -1:
                refuse = "bad_links"
            else:
                heads = [bytes.fromhex(r["head"]) for r in rows]
                if self.chain.fork_point(heads) < len(self.chain):
                    # the served history contradicts what this peer still
                    # durably holds — a fork (or a rolled-back server);
                    # never adopt a chain that rewrites a surviving prefix
                    refuse = "forked_prefix"
                else:
                    digest = Ledger.find_state_commitment(rows, version,
                                                          server)
                    if digest is None:
                        refuse = "no_commitment"
                    else:
                        recomputed = params_digest(
                            trees["model"], self.cfg.ledger.use_native)
                        if recomputed != digest:
                            refuse = "digest_mismatch"
        telemetry.emit("state.sync.verify", ok=refuse is None,
                       src=server, version=version,
                       digest8=(recomputed.hex()[:16]
                                if recomputed is not None else None),
                       reason=refuse)
        if refuse is not None:
            logger.warning("peer %d: refusing state_sync from %d (%s)",
                           self.peer_id, server, refuse)
            telemetry.emit("state.sync.refuse", reason=refuse, src=server,
                           version=version)
            if adopting:
                # re-request immediately from the next target in the cycle
                self._last_sync_req = 0.0
            return
        if not adopting:
            return  # clean late serve: audited above, nothing to adopt
        if self.chain is not None:
            replica = Ledger(self.cfg.ledger.use_native)
            replica.append_rows(rows)  # verified above; rebuild the heads
            self.chain = replica
            self.eng.ledger = replica
            telemetry.emit("ledger", op="resync", chain_len=len(self.chain),
                           rewrite=True, head8=self.chain.head.hex()[:16])
            if self.rep is not None:
                self.rep.absorb_rows(rows)
        self.trainable = self.eng.mesh.replicate(self._cast(trees["model"]))
        self.version = version
        self.adopted.append(version)
        self._note_version()
        self._adopt_extra(header, trees)
        self._needs_bootstrap = False
        reason = self._bootstrap_reason
        self._bootstrap_reason = None
        self._repaired = {"from": server, "version": version,
                          "reason": reason}
        telemetry.emit("state.sync.adopt", version=version, src=server,
                       digest8=(digest.hex()[:16]
                                if digest is not None else None),
                       chain_len=(len(self.chain)
                                  if self.chain is not None else 0),
                       reason=reason)
        logger.info("peer %d: repaired from peer %d at version %d (%s)",
                    self.peer_id, server, version, reason)
        self._maybe_checkpoint()

    # --------------------------------------------------- checkpoint / resume

    def _durable_write(self, seam: str, counter: int, fn):
        """One durable write through the resource-lane response ladder
        (ROBUSTNESS.md §11). The seeded draw decides whether this write's
        first ``depth`` attempts fail (ENOSPC/EMFILE raised cleanly,
        nothing landed — the commit protocol is all-or-nothing, so a
        retry is safe); each failure walks one rung — emergency retention
        GC, then telemetry shed — before retrying. A write still failing
        after every remedy raises :class:`DurabilityError`: the peer
        exits with the distinct durability code instead of silently
        committing un-durable state. A REAL (non-injected) ENOSPC/EMFILE
        out of ``fn`` walks the same ladder."""
        plan = self.cfg.faults
        act = (plan.resource_action(seam, counter, self.peer_id)
               if plan.resource_enabled else None)
        remedies = 0
        while True:
            try:
                if act is not None and remedies < act["depth"]:
                    err = 28 if act["cls"] == "enospc" else 24
                    telemetry.emit("resource.inject", seam=seam,
                                   cls=act["cls"], counter=int(counter),
                                   depth=int(act["depth"]),
                                   attempt=remedies, errno=err)
                    raise OSError(err, os.strerror(err))
                return fn()
            except OSError as e:
                if e.errno not in (28, 24):
                    raise
                if remedies == 0:
                    self._emergency_gc(seam)
                elif remedies == 1:
                    self._shed_telemetry(seam)
                else:
                    raise DurabilityError(
                        f"peer {self.peer_id}: durable write at the "
                        f"{seam!r} seam (counter {counter}) still failing "
                        f"(errno {e.errno}) after emergency GC and "
                        f"telemetry shed") from e
                remedies += 1

    def _emergency_gc(self, seam: str) -> None:
        """First ladder rung: free space NOW by dropping every retained
        checkpoint round except the newest — retention depth is a
        convenience, durability of the CURRENT round is the contract.
        The newest committed round always survives (the peer stays
        restorable even if the retry still fails)."""
        from bcfl_tpu.checkpoint.checkpoint import (
            _fsync_dir,
            _list_rounds,
            _remove_round,
        )

        rounds = _list_rounds(self.ckpt_dir)
        victims = rounds[:-1]
        for r in victims:
            _remove_round(self.ckpt_dir, r, keep_meta=False)
        if victims:
            _fsync_dir(self.ckpt_dir)
        telemetry.emit("gc.emergency", seam=seam, removed=len(victims),
                       kept=len(rounds) - len(victims))

    def _shed_telemetry(self, seam: str) -> None:
        """Second ladder rung: stop buffering SAMPLED telemetry (counted,
        never written) so durable bytes get whatever headroom remains.
        Never-sampled events keep flowing (the invariants read those) and
        ledger/checkpoint bytes are never shed — only the high-rate
        observability tail is."""
        w = telemetry.get_writer()
        if w is not None and w.begin_shed(seam):
            telemetry.emit("write.shed", seam=seam, mode="on")

    def _events_write_fault(self, nbytes: int) -> None:
        """Resource lane at the EventWriter flush seam: consult the
        seeded per-flush draw and fail the stream write cleanly. The
        writer's own errno handler sheds sampled telemetry in response —
        this seam never escalates to the exit rung (telemetry must never
        take down the run it observes). The counter is the seam's own
        flush sequence; the busy flag keeps the inject event's OWN flush
        from recursing into a second draw."""
        if self._events_fault_busy:
            return
        n = self._events_flush_n
        self._events_flush_n += 1
        act = self.cfg.faults.resource_action("events", n, self.peer_id)
        if act is None:
            return
        err = 28 if act["cls"] == "enospc" else 24
        self._events_fault_busy = True
        try:
            telemetry.emit("resource.inject", seam="events",
                           cls=act["cls"], counter=n,
                           depth=int(act["depth"]), errno=err,
                           nbytes=int(nbytes))
            raise OSError(err, os.strerror(err))
        finally:
            self._events_fault_busy = False

    def _maybe_checkpoint(self):
        cfg = self.cfg
        every = cfg.dist.checkpoint_every_versions
        if not every or self.version % every:
            return
        import jax

        from bcfl_tpu.checkpoint import save_checkpoint
        from bcfl_tpu.compression import codecs as cc

        state = {
            "trainable": jax.device_get(self.trainable),
            "version": np.int64(self.version),
            "local_round": np.int64(self.local_round),
            "seed": np.int64(cfg.seed),
            "compress_format": np.frombuffer(
                cc.wire_format(self.eng._comp).encode(), np.uint8).copy(),
            "ef_residual": (jax.device_get(self.eng._ef)
                            if self.eng._ef is not None else None),
        }
        if self.rep is not None:
            # the per-peer tracker rides the checkpoint bit-for-bit (the
            # same rep_* keys as the engine's per-client lifecycle): a
            # resumed leader re-enters with every trust score and
            # quarantine timer exactly where the crash left them
            state.update(self.rep.checkpoint_state())
        state.update(self._checkpoint_extra())
        # both durable seams run the resource-lane response ladder: the
        # checkpoint commit (payload + meta sidecar carrying the chain
        # bytes) and the ledger's durable commitment point (the
        # high-water marker the rollback guard reads)
        self._durable_write(
            "checkpoint", self.version,
            lambda: save_checkpoint(
                self.ckpt_dir, self.version, state,
                self.chain.to_json() if self.chain is not None else None,
                keep_last=cfg.dist.checkpoint_keep_last))
        self._durable_write(
            "ledger",
            len(self.chain) if self.chain is not None else self.version,
            self._write_highwater)
        # storage fault lane (ROBUSTNESS.md §10): damage the committed
        # durable state per the seeded (peer, version) draw — injected
        # AFTER the commit, the media-failure model
        action = cfg.faults.storage_action(self.version, self.peer_id)
        if action is not None:
            from bcfl_tpu.checkpoint import apply_storage_fault

            record = apply_storage_fault(self.ckpt_dir, action)
            if record is not None:
                telemetry.emit("chaos", lane="storage", action=record["cls"],
                               version=int(self.version), **{
                                   k: v for k, v in record.items()
                                   if k != "cls"})

    # ------------------------------------------- durable high-water marker

    def _read_highwater(self) -> Optional[Dict]:
        try:
            with open(self._hw_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _write_highwater(self):
        hw = self._read_highwater()
        cur = {"version": int(self.version),
               "chain_len": len(self.chain) if self.chain is not None else 0}
        if hw is not None and hw.get("version", -1) >= cur["version"]:
            return
        tmp = self._hw_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cur, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._hw_path)

    def _restore(self):
        from bcfl_tpu.checkpoint import restore_latest, scrub
        from bcfl_tpu.compression import codecs as cc
        from bcfl_tpu.ledger import Ledger

        report = scrub(self.ckpt_dir)
        restored = restore_latest(self.ckpt_dir)
        if restored is None:
            if not self.bootstrap:
                # loud by default: a --resume peer whose durable state is
                # gone or wholly damaged must not silently rejoin with
                # zero state — that is an operator decision (--bootstrap)
                raise ResumeError(
                    f"peer {self.peer_id}: --resume found no usable "
                    f"checkpoint in {self.ckpt_dir} "
                    f"(scrub: {'empty' if report['empty'] else 'damaged'}, "
                    f"damaged={list(report['damaged'])}, "
                    f"torn={list(report['torn'])}); pass --bootstrap to "
                    f"opt into ledger-authenticated peer repair")
            self._needs_bootstrap = True
            self._bootstrap_reason = ("empty" if report["empty"]
                                      else "damaged")
            logger.warning("peer %d: no usable checkpoint (%s); will "
                           "bootstrap from a live peer", self.peer_id,
                           self._bootstrap_reason)
            return
        _, state, ledger_json = restored
        ck_seed = state.get("seed")
        if ck_seed is not None and int(ck_seed) != self.cfg.seed:
            raise ValueError(
                f"peer checkpoint seed {int(ck_seed)} != config seed "
                f"{self.cfg.seed}: resuming would change every stream")
        ck_comp = state.get("compress_format")
        if ck_comp is not None:
            ck_comp = bytes(np.asarray(ck_comp, np.uint8)).decode()
            here = cc.wire_format(self.eng._comp)
            if ck_comp != here:
                raise ValueError(
                    f"peer checkpoint wire format {ck_comp!r} != this "
                    f"run's {here!r}")
        self.trainable = self.eng.mesh.replicate(self._cast(
            state["trainable"]))
        self.version = int(state["version"])
        self.local_round = int(state["local_round"])
        if state.get("ef_residual") is not None and self.eng._comp is not None:
            self.eng._ef = self._to_device(state["ef_residual"])
        if ledger_json and self.chain is not None:
            self.chain = Ledger.from_json(ledger_json,
                                          self.cfg.ledger.use_native)
            self.eng.ledger = self.chain
        if self.rep is not None and state.get("rep_trust") is not None:
            self.rep.restore(state)
            # the bit-identical-restore evidence: the EXACT restored
            # arrays, recorded before anything evolves them, for the
            # resume proof to compare against the checkpoint file
            self._restored_rep = self.rep.report()
            for p in self.rep.quarantined_peers():
                # re-declare restored quarantines into THIS incarnation's
                # stream: the no_quarantined_merge invariant is
                # pid-scoped, so without this a resumed leader's
                # post-restart merges would be judged against an empty
                # quarantine set. quarantine_evidence exempts the
                # from="restored" marker — a FOLLOWER restores verdicts
                # it absorbed from the leader's broadcast chain rows and
                # has no evidence events of its own to point at
                telemetry.emit(
                    "rep.transition", client=int(p), scope="peer",
                    **{"from": "restored", "to": "quarantined",
                       "trust": float(self.rep.tracker.trust[p])})
        self._restored_from_version = int(state["version"])
        self.history = {
            self.version: (self.trainable if self.eng._comp is None
                           else None, self._head())}
        self._restore_extra(state)
        self._resumed = True
        logger.info("peer %d: restored checkpoint at version %d "
                    "(round %d)", self.peer_id, self.version,
                    self.local_round)
        hw = self._read_highwater()
        if hw is not None and self.version < int(hw.get("version", -1)):
            # monotone-incarnation guard: this incarnation restored a state
            # OLDER than one a previous incarnation durably announced —
            # either the checkpoint dir was rolled back to a stale snapshot
            # or damage forced the restore past the newest round. Either
            # way the peer must resync FORWARD (verified STATE_SYNC) before
            # training or announcing: re-entering at the stale version
            # would re-announce old versions as new.
            self._needs_bootstrap = True
            self._bootstrap_reason = "rollback"
            logger.warning(
                "peer %d: restored version %d is below the durable "
                "high-water %d (rollback or damage fallback); resyncing "
                "forward before rejoining", self.peer_id, self.version,
                int(hw["version"]))

    # ------------------------------------------------------------- main loop

    def _intake_update(self, header: Dict, trees: Dict):
        """The UPDATE intake seam, shared by the serial path (_handle, main
        loop) and the pipelined intake thread: post-ack quarantine gate,
        then into the leader's locked arrival buffer."""
        src = int(header.get("from", -1))
        if (self.rep is not None and src != self.peer_id
                and self.rep.is_quarantined(src)):
            # quarantine refusal is POST-ACK, like a partition-gate
            # drop: the frame was delivered intact and the sender's
            # failure detector must not read distrust as peer death
            # (peer death != malice, and vice versa)
            with self._qdrop_lock:
                self.rep.quarantine_drops += 1
            return
        if self._leader() == self.peer_id:
            self._buffer_push((header, trees, time.time()))
        # an update addressed to a stale leader is dropped: the sender
        # will rebase on the next global broadcast

    def _intake_loop(self):
        """Pipelined intake (cfg.dist.pipeline): drain the transport inbox
        continuously — UPDATE frames go straight into the double-buffered
        arrival buffer (so the listener/inbox never backs up behind a
        merge), everything else routes to the control queue the main loop
        drains. Protocol handlers stay single-threaded in the main loop;
        only the buffer push crosses threads, under its lock."""
        while not self._stop:
            msg = self.transport.recv(timeout_s=0.05)
            if msg is None:
                continue
            header, trees = msg
            if header.get("type") == "update":
                self._intake_update(header, trees)
            else:
                self._ctrl.put(msg)

    def _next_ctrl(self, timeout_s: float):
        """Next message for the MAIN loop: the control queue when the
        intake thread owns the inbox, the inbox itself otherwise."""
        if self._intake_thread is not None:
            try:
                return self._ctrl.get(timeout=timeout_s)
            except queue.Empty:
                return None
        return self.transport.recv(timeout_s=timeout_s)

    def _handle(self, header: Dict, trees: Dict):
        kind = header.get("type")
        if kind == "update":
            # serial path only — with the pipeline on, updates were
            # already consumed by the intake thread and never reach here
            self._intake_update(header, trees)
        elif kind == "ping":
            pass  # liveness probe: delivery (the ack) was the answer
        elif kind == "global":
            self._handle_global(header, trees)
        elif kind == "reconcile":
            if self.peer_id == 0:
                self._handle_reconcile(header, trees)
        elif kind == "hello":
            self._handle_hello(header)
        elif kind == "state_sync_req":
            self._handle_state_sync_req(header)
        elif kind == "state_sync":
            self._handle_state_sync(header, trees)
        elif kind == "shutdown":
            self._stop = True
        else:
            logger.warning("peer %d: unknown message type %r",
                           self.peer_id, kind)

    def _finalize(self):
        loss = acc = None
        try:
            loss, acc = self.eng._global_eval(self.trainable)
        except Exception as e:  # an eval failure must not eat the report
            logger.warning("peer %d: final eval failed (%s)", self.peer_id, e)
        self._final_eval = {"loss": loss, "acc": acc}
        # drain the sender pipeline BEFORE the stop message: the final
        # global broadcast rides the per-destination workers, and a sync
        # shutdown racing past a queued broadcast would stop a follower
        # one version short of the state it was owed
        self.transport.flush_sends(
            timeout_s=self.cfg.dist.send_deadline_s)
        for p in range(self.peers):
            if p == self.peer_id:
                continue
            # retrying seam; a DOWN peer's circuit skips this instantly
            self.transport.send(p, {"type": "shutdown",
                                    "version": int(self.version)})
        self._stop = True

    def run(self) -> int:
        logger.info("peer %d/%d up: clients %s, version %d%s",
                    self.peer_id, self.peers, list(self.global_ids),
                    self.version, " (resumed)" if self._resumed else "")
        telemetry.emit("run.start", role="peer", peers=self.peers,
                       resumed=self._resumed, version=int(self.version),
                       epoch=self.transport.epoch,
                       pipeline=bool(self.cfg.dist.pipeline))
        self.transport.start()
        # periodic host-resource sampling (cfg.dist.resource_sample_s):
        # feeds the live monitor's health series. Only when this process
        # has an event stream — the sampler emits through the same seam.
        self._resmon = None
        if (self.cfg.dist.resource_sample_s > 0
                and self.events_path is not None):
            try:
                from bcfl_tpu.metrics.metrics import ResourceMonitor

                self._resmon = ResourceMonitor(run_dir=self.run_dir)
                self._resmon.start_sampling(self.cfg.dist.resource_sample_s)
            except Exception as e:  # noqa: BLE001 — psutil absence never kills a peer
                logger.warning("resource sampling unavailable: %s", e)
        if self.cfg.dist.pipeline:
            self._intake_thread = threading.Thread(
                target=self._intake_loop, daemon=True,
                name=f"bcfl-dist-intake-{self.peer_id}")
            self._intake_thread.start()
        # an immediate partial report: from this instant on, even a peer
        # SIGKILLed seconds into the run leaves evidence behind
        self._write_report(status="running")
        if self._resumed and self.peer_id != 0 and not self._needs_bootstrap:
            self.transport.send(0, {"type": "hello",
                                    "version": int(self.version)})
        try:
            while not self._stop:
                self._check_watchdogs()
                self._maybe_flush_report()
                msg = self._next_ctrl(timeout_s=0.05)
                while msg is not None:
                    self._handle(*msg)
                    msg = self._next_ctrl(timeout_s=0.0)
                if self._stop:
                    break
                if self._needs_bootstrap:
                    # damaged/empty/rolled-back durable state: repair FIRST.
                    # No training, merging, or announcing until a verified
                    # STATE_SYNC transfer is adopted — the idle watchdog
                    # still bounds a repair that never completes.
                    self._maybe_request_sync()
                    time.sleep(0.05)
                    continue
                self._update_partition_state()
                if self._pending_reconcile:
                    self._try_reconcile()
                if self._leader() == self.peer_id:
                    self._maybe_merge()
                if (self.peer_id == 0 and self.version >= self.cfg.num_rounds
                        and self.gate.components() is None
                        and (self.fork is None
                             or self.reconcile is not None)):
                    # target version count reached, mesh whole, and any fork
                    # this run produced has been reconciled: evaluate, tell
                    # everyone, stop. Never finalize mid-partition (a gate-
                    # blocked shutdown would strand the other components) or
                    # before the heal (the fork evidence would be lost).
                    self._finalize()
                if self._stop:
                    break
                if (self.version < self.cfg.num_rounds
                        or self.gate.components() is not None
                        or (self.peer_id == 0 and self.fork is not None
                            and self.reconcile is None)):
                    # keep training past the version target while a span is
                    # active or a fork is unresolved: the span clock IS the
                    # local round, so stopping here would freeze the peer
                    # inside the partition forever
                    self._train_once()
                else:
                    time.sleep(0.05)  # drained; waiting for shutdown/merges
        except DurabilityError as e:
            # the resource-lane exit rung: the host cannot make rounds
            # durable even after GC + shed — exit with the distinct code,
            # never silently commit un-durable state
            logger.error("%s", e)
            self._write_report(status="undurable")
            return DurabilityError.EXIT_CODE
        finally:
            # a short drain so a follower's last enqueued update isn't cut
            # off mid-stream by close (post-shutdown frames are moot, but
            # a half-written frame would show up as a receiver wire_drop)
            self.transport.flush_sends(timeout_s=2.0)
            self.transport.close()
            self._deadline_timer.cancel()
            if self._resmon is not None:
                self._resmon.stop_sampling()
        self._write_report(status="ok")
        return 0

    # ---------------------------------------------------------------- report

    def _write_report(self, status: str):
        """Atomic (tmp + rename) report write. ``status="running"`` is the
        periodic partial flush — the report a SIGKILLed peer leaves
        behind; any other status is terminal and also closes out the
        event stream (run.end + flush), so a cleanly-ended stream is a
        complete record.

        Serialized under a reentrant lock (watchdog Timer thread, main
        loop, SIGTERM handler share the tmp file), and terminal statuses
        win: once one is written, a periodic "running" rewrite can never
        overwrite it."""
        with self._report_lock:
            if self._report_terminal:
                return
            if status != "running":
                self._report_terminal = True
            self._write_report_locked(status)

    def _chain_ok(self, status: str) -> Optional[bool]:  # guarded-by: _report_lock
        if self.chain is None:
            return None
        if status != "running" or self._chain_ok_cache is None:
            self._chain_ok_cache = self.chain.verify_chain() == -1
        return self._chain_ok_cache

    def _write_report_locked(self, status: str):  # guarded-by: _report_lock
        self._report_round = self.local_round
        self._report_version = self.version
        staleness = [a["staleness"] for m in self.merges for a in m.arrivals]
        latencies = [a["latency_s"] for m in self.merges for a in m.arrivals]
        tstats = self.transport.stats()
        report = {
            "peer": self.peer_id,
            "peers": self.peers,
            "status": status,
            "pid": os.getpid(),
            "resumed": self._resumed,
            "final_version": int(self.version),
            "local_rounds": int(self.local_round),
            "merges": [dataclasses.asdict(m) for m in self.merges],
            "solo_merges": sum(1 for m in self.merges if m.solo),
            "degraded_merges": sum(1 for m in self.merges if m.degraded),
            "below_quorum_events": self._below_quorum_events,
            "buffer_shed": self._buffer_shed,
            "adopted_versions": self.adopted,
            "staleness_values": staleness,
            "arrival_latency_s": latencies,
            "transport": tstats,
            "send_failures": tstats["send_failures"],
            "dropped_by_gate": tstats["dropped_by_gate"],
            "fork": self.fork,
            "reconcile": self.reconcile,
            # byzantine-tolerance surfaces (ROBUSTNESS.md §8): the
            # per-peer tracker's state + the adversary's injection
            # counters (exactly zero with the lane off — the baseline
            # legs gate on these keys)
            "reputation": (self.rep.report()
                           if self.rep is not None else None),
            "restored_reputation": getattr(self, "_restored_rep", None),
            "restored_from_version": getattr(
                self, "_restored_from_version", None),
            # durable-state repair evidence (RUNTIME.md "State-sync
            # protocol"): why this peer bootstrapped and from whom —
            # what the storage soak's convergence gates read
            "bootstrap_reason": self._bootstrap_reason,
            "repaired": self._repaired,
            "byzantine": (self.byz.stats() if self.byz is not None
                          else {"armed": False, "injected": {},
                                "total": 0}),
            "chain_len": len(self.chain) if self.chain is not None else None,
            "chain_head": self._head(),
            # verify_chain re-hashes the WHOLE ledger — O(chain) per call,
            # quadratic if run on every periodic flush. Full verify on
            # terminal writes only; periodic reports carry the last
            # verified verdict (refreshed at startup and at exit)
            "chain_ok": self._chain_ok(status),
            "final_eval": getattr(self, "_final_eval", None),
            "events": self.events_path,
            "wall_s": time.time() - self._t0,
        }
        report.update(self._report_extra())
        path = os.path.join(self.run_dir, f"report_peer{self.peer_id}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2)
        os.replace(tmp, path)
        telemetry.emit("report.flush", status=status)
        if status != "running":
            # terminal: run.end marks the stream cleanly closed (the
            # acked_not_lost invariant only judges receivers bearing this
            # mark), and the flush makes it durable even on the os._exit
            # watchdog paths, which skip atexit hooks
            telemetry.emit("run.end", status=status,
                           version=int(self.version),
                           local_rounds=int(self.local_round))
        telemetry.flush()


def peer_main(argv=None) -> int:
    """Entry point of one peer process (``python -m bcfl_tpu.dist``)."""
    import argparse

    ap = argparse.ArgumentParser(prog="bcfl_tpu.dist")
    ap.add_argument("--config", required=True,
                    help="path to the supervisor-written FedConfig JSON")
    ap.add_argument("--peer-id", type=int, required=True)
    ap.add_argument("--ports", required=True,
                    help="comma-separated listen ports, one per peer")
    ap.add_argument("--run-dir", required=True)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--bootstrap", action="store_true",
                    help="with --resume: if no usable checkpoint survives, "
                         "repair from a live peer over verified STATE_SYNC "
                         "instead of failing loudly")
    ap.add_argument("--platform", default=None)
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format=f"[peer {args.peer_id}] %(levelname)s %(message)s")
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    from bcfl_tpu.dist.launch import cfg_from_json

    with open(args.config) as f:
        cfg = cfg_from_json(f.read())
    ports = [int(p) for p in args.ports.split(",")]
    if cfg.dist.dispatch == "gossip":
        # leaderless epidemic dispatch (RUNTIME.md "Gossip dispatch"):
        # same transport, same engine, no privileged process
        from bcfl_tpu.dist.gossip import GossipPeerRuntime as Runtime
    else:
        Runtime = PeerRuntime
    try:
        rt = Runtime(cfg, args.peer_id, ports, args.run_dir,
                     resume=args.resume, bootstrap=args.bootstrap)
    except ResumeError as e:
        # distinct exit code: "durable state unusable and repair not
        # authorized" is an operator decision, not a crash
        logger.error("%s", e)
        return ResumeError.EXIT_CODE
    try:
        return rt.run()
    except DurabilityError as e:
        # backstop for a durable write failing outside the main loop —
        # the same distinct "cannot make rounds durable" code
        logger.error("%s", e)
        return DurabilityError.EXIT_CODE
