"""Host-level peer transport: self-healing loopback/DCN TCP with an
injectable partition gate and a seeded wire-chaos lane (RUNTIME.md §3 and
"Delivery contract").

One :class:`PeerTransport` per peer process: a listener thread accepts
connections on the peer's own port and enqueues complete frames into a
BOUNDED inbox; sends open a fresh connection per message (loopback
connects are ~microseconds, and connection-per-message means a crashed
receiver can never wedge a cached socket). Every operation runs under a
hard deadline. Frames are STREAMED both ways (wire.write_frame /
read_frame): the send path never concatenates a payload into one bytes,
and the receive path decodes into preallocated arrays — peak
serialization memory is the skeleton, not a second model copy.

Two send seams share one reliable protocol (:meth:`_send_reliable`):
:meth:`PeerTransport.send` blocks until delivered/budget-expired (control
messages, probes, tests), and :meth:`send_async` — the comms/compute
overlap seam — enqueues onto a bounded per-destination sender WORKER and
returns, preserving per-destination msg-id order (allocation order ==
enqueue order == FIFO wire order) with block-on-full back-pressure
(``DistConfig.pipeline_depth``).

The delivery contract (all of it lives here, so the runtime's handlers
stay single-purpose):

- **At-least-once**: every frame carries a monotone per-destination
  ``(from, msg_id)`` (plus the sender's incarnation epoch, so a restarted
  peer's fresh counter cannot collide with its dead incarnation's ids)
  and a CRC32; delivery is confirmed by the receiver's 4-byte ack. A failed attempt (unreachable, timeout, CRC-dropped, chaos-
  dropped) retries with exponential backoff + deterministic jitter under a
  per-destination deadline budget — :meth:`PeerTransport.send` is the ONE
  reliable send seam and never raises on network failure.
- **Idempotent receive**: the receiver verifies the CRC before parsing a
  single field (damage -> ``crc_drops``, no ack, the sender retries),
  then dedups on a per-sender msg-id window (``dups_dropped``) — a
  retried or chaos-duplicated frame can never be handled twice, which is
  what makes the runtime's UPDATE merge / MODEL adopt / HELLO / reconcile
  handlers provably idempotent under this transport.
- **Failure detection**: every attempt outcome feeds a per-peer circuit
  breaker: consecutive failures move a peer REACHABLE -> SUSPECT -> DOWN;
  while DOWN the circuit is open and sends are skipped except one probe
  per interval, so a dead peer costs ~zero per message and a recovered
  one is re-detected within a probe interval. The detector's states and
  transition log ride the peer report — the evidence vocabulary quorum
  degradation and quarantine consume. Two implementations share that
  contract (``DistConfig.detector``): the adaptive phi-accrual-style
  estimator (:class:`PhiFailureDetector`, the default — continuous
  suspicion from failure pressure + silence beyond a learned per-peer
  window, plus per-destination send deadlines scaled by measured RTT /
  throughput and frame size) and the fixed consecutive counter
  (:class:`FailureDetector`, ``detector="fixed"`` — bit-compatible with
  pre-gray-failure replays).

The **partition gate** is the FaultPlan partition lane driven at the socket
level: a callable consulted on BOTH ends of every message — the sender
skips blocked destinations, and the receiver drops frames whose origin is
blocked *by its own clock* (authoritative, so a component can never merge a
cross-partition update even when the two peers disagree about exactly when
the span started). While the gate blocks a pair, the two sides genuinely
cannot exchange bytes — each connected component evolves (and extends its
ledger chain) independently, which is what makes the fork real. Gate drops
happen AFTER the ack (the frame was delivered intact; the application
discarded it), so a partition never masquerades as peer death to the
failure detector — the two failure modes stay distinguishable.

The **wire chaos lane** (:class:`WireChaos`, FaultPlan ``wire_*``) injects
drop / duplicate / reorder-hold / delay-jitter / byte-corruption per
transmission attempt, drawn from ``(seed, lane, round, src, dst, msg_id,
attempt)`` — deterministic and replayable given the same message
coordinates, which is what lets ``scripts/dist_chaos.py`` assert exact
self-healing behavior under an adversarial schedule.
"""

from __future__ import annotations

import logging
import queue
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from bcfl_tpu.config import DistConfig
from bcfl_tpu.telemetry import events as _telemetry
from bcfl_tpu.dist.wire import (
    CrcError,
    WireError,
    frame_prefix,
    read_ack,
    read_frame,
    write_ack,
    write_frame,
)
from bcfl_tpu.faults import FaultPlan

logger = logging.getLogger(__name__)


class TransportError(RuntimeError):
    """One send ATTEMPT failed (unreachable / refused / deadline / chaos
    drop / no ack). Internal to the retry seam — :meth:`PeerTransport.send`
    absorbs it into the backoff loop and the stats counters."""


# failure-detector states (RUNTIME.md "Delivery contract")
REACHABLE = "reachable"
SUSPECT = "suspect"
DOWN = "down"


class FailureDetector:
    """Per-peer circuit-breaker failure detector.

    Consecutive send-attempt failures move a peer REACHABLE -> SUSPECT
    (``suspect_after``) -> DOWN (``down_after``); any success snaps it back
    to REACHABLE, as does INBOUND traffic from the peer
    (:meth:`on_inbound`, called from the serving threads — hence the
    lock). While DOWN the circuit is open: :meth:`allow` returns False
    except for one probe per ``probe_interval_s``."""

    def __init__(self, peers: int, suspect_after: int = 2,
                 down_after: int = 6, probe_interval_s: float = 2.0):
        import collections

        self.suspect_after = int(suspect_after)
        self.down_after = int(down_after)
        self.probe_interval_s = float(probe_interval_s)
        self._lock = threading.Lock()
        self._state = {p: REACHABLE for p in range(int(peers))}  # guarded-by: _lock
        self._fails = {p: 0 for p in range(int(peers))}  # guarded-by: _lock
        self._last_probe = {p: 0.0 for p in range(int(peers))}  # guarded-by: _lock
        # bounded: a long-lived peer on a lossy link flaps at message
        # rate, and the full log is serialized into every report — keep
        # the recent window (enough for the chaos gates) plus a total.
        # Writes under the lock; external readers (the transport's stats
        # rollup, the runtime's evidence drain) take snapshot reads of
        # the deque/int, which CPython keeps tear-free.
        self.transitions = collections.deque(maxlen=256)  # guarded-by: _lock (writes)
        self.transitions_total = 0  # guarded-by: _lock (writes)

    def _set(self, peer: int, state: str) -> None:  # guarded-by: _lock
        old = self._state[peer]
        if old == state:
            return
        self._state[peer] = state
        self.transitions_total += 1
        self.transitions.append(
            {"peer": int(peer), "from": old, "to": state,
             "at": time.time()})
        # never sampled: the timeline's SUSPECT->REACHABLE roundtrip gate
        # and quorum analysis read these
        _telemetry.emit("detector",
                        **{"target": int(peer), "from": old, "to": state})

    def state_of(self, peer: int) -> str:
        with self._lock:
            return self._state[peer]

    def states(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._state)

    def on_success(self, peer: int) -> None:
        with self._lock:
            self._fails[peer] = 0
            self._set(peer, REACHABLE)

    def on_failure(self, peer: int) -> None:
        with self._lock:
            self._fails[peer] += 1
            if self._fails[peer] >= self.down_after:
                self._set(peer, DOWN)
            elif self._fails[peer] >= self.suspect_after:
                self._set(peer, SUSPECT)

    def on_inbound(self, peer: int) -> None:
        """Liveness evidence from the RECEIVE path: a CRC-valid frame from
        ``peer`` proves its process is up, so snap the circuit shut. A
        just-restarted peer must not have its repair traffic refused
        (``circuit_open``) for a whole probe interval by the stale DOWN
        verdict its crash earned — its state_sync_req IS the heartbeat.
        Unknown sender ids are ignored (hostile headers never grow the
        peer table)."""
        with self._lock:
            if peer not in self._state:
                return
            self._fails[peer] = 0
            self._set(peer, REACHABLE)

    def allow(self, peer: int) -> bool:
        """Should a send to ``peer`` be attempted now? True unless the
        circuit is open (DOWN) and no probe is due; a granted probe
        reserves the interval."""
        with self._lock:
            if self._state[peer] != DOWN:
                return True
            now = time.monotonic()
            if now - self._last_probe[peer] >= self.probe_interval_s:
                self._last_probe[peer] = now
                return True
            return False


class PhiFailureDetector(FailureDetector):
    """Adaptive phi-accrual-style failure detector (``detector="phi"``,
    RUNTIME.md "Timing contract"; after Hayashibara et al.'s phi-accrual
    design, adapted to bursty request/response traffic).

    Same public surface, state vocabulary, and transition/telemetry
    contract as the fixed counter, but suspicion is a CONTINUOUS per-peer
    level::

        phi(p) = consecutive_failures(p)
                 + max(0, silence(p) / window(p) - 1)

    where ``silence`` is the time since the last liveness evidence
    (successful send to, or CRC-valid inbound frame from, the peer) and
    ``window`` is the learned expected silence — EWMA mean + 3 sigma of
    the peer's inbound intervals, clamped to [window_floor_s,
    window_ceil_s] (the ceiling is also the prior before any sample, so
    an unheard-from peer accrues slowly instead of flapping at startup).
    phi is monotone between evidence — silence only grows it — and any
    liveness evidence snaps it back to 0 (REACHABLE). ``phi_suspect`` /
    ``phi_down`` replace ``suspect_after`` / ``down_after``: under pure
    send failures the defaults grade identically (1 phi unit per
    consecutive failure), while a peer that is merely SILENT — the
    SIGSTOP'd, swapping, or one-way-degraded gray failure — also accrues,
    which the fixed counter is structurally blind to.

    The estimator additionally learns per-destination RTT (EWMA +
    variance over per-attempt success wall times) and throughput (bytes/s
    over large frames), from which :meth:`send_budget_s` derives the
    ADAPTIVE per-destination send deadline: RTT headroom plus the frame's
    expected wire time at the measured (or assumed-minimum) throughput,
    clamped to [deadline_floor_s, deadline_ceil_s]. That is the
    large-frame starvation fix: a 32 MB frame on a slow link earns a
    size-proportional budget instead of starving under a latency-tuned
    constant.

    Estimates are measurements of the live run (wall clock in, wall
    clock out) — nothing here is part of the seeded-determinism scope;
    the seeded lanes INJECT slowness, this class measures it."""

    _ALPHA = 0.2   # EWMA weight for the interval/RTT/throughput estimates
    _THROUGHPUT_MIN_BYTES = 65536   # frames below this measure latency,
    # not bandwidth — keep them out of the throughput estimate

    def __init__(self, peers: int, phi_suspect: float = 2.0,
                 phi_down: float = 6.0, probe_interval_s: float = 2.0,
                 window_floor_s: float = 5.0, window_ceil_s: float = 120.0,
                 deadline_floor_s: float = 2.0,
                 deadline_ceil_s: float = 120.0,
                 min_bandwidth_bps: float = 1_048_576.0,
                 base_deadline_s: float = 20.0):
        super().__init__(peers, probe_interval_s=probe_interval_s)
        self.phi_suspect = float(phi_suspect)
        self.phi_down = float(phi_down)
        self.window_floor_s = float(window_floor_s)
        self.window_ceil_s = float(window_ceil_s)
        self.deadline_floor_s = float(deadline_floor_s)
        self.deadline_ceil_s = float(deadline_ceil_s)
        self.min_bandwidth_bps = float(min_bandwidth_bps)
        self.base_deadline_s = float(base_deadline_s)
        n = int(peers)
        now = time.monotonic()
        # all estimator state is guarded-by: _lock (inherited)
        self._last = {p: now for p in range(n)}        # guarded-by: _lock
        self._int_mean: Dict[int, Optional[float]] = \
            {p: None for p in range(n)}                # guarded-by: _lock
        self._int_var = {p: 0.0 for p in range(n)}     # guarded-by: _lock
        self._rtt_mean: Dict[int, Optional[float]] = \
            {p: None for p in range(n)}                # guarded-by: _lock
        self._rtt_var = {p: 0.0 for p in range(n)}     # guarded-by: _lock
        self._thr_mean: Dict[int, Optional[float]] = \
            {p: None for p in range(n)}                # guarded-by: _lock

    # --------------------------------------------------- evidence intake

    def _heard(self, peer: int) -> None:  # guarded-by: _lock
        """Liveness evidence: fold the silence gap into the interval
        estimate, reset the silence clock and failure pressure, snap the
        state shut."""
        now = time.monotonic()
        gap = now - self._last[peer]
        self._last[peer] = now
        m = self._int_mean[peer]
        if m is None:
            self._int_mean[peer] = gap
        else:
            d = gap - m
            self._int_mean[peer] = m + self._ALPHA * d
            self._int_var[peer] = ((1.0 - self._ALPHA)
                                   * (self._int_var[peer]
                                      + self._ALPHA * d * d))
        self._fails[peer] = 0
        self._set(peer, REACHABLE)

    def on_success(self, peer: int) -> None:
        with self._lock:
            self._heard(peer)

    def on_inbound(self, peer: int) -> None:
        with self._lock:
            if peer not in self._state:
                return
            self._heard(peer)

    def on_failure(self, peer: int) -> None:
        with self._lock:
            self._fails[peer] += 1
            self._refresh(peer)

    def note_rtt(self, peer: int, rtt_s: float, nbytes: int = 0) -> None:
        """One successful attempt's wall time (and frame size) feeds the
        per-destination RTT / throughput estimates the adaptive send
        deadline is derived from."""
        with self._lock:
            rtt_s = float(rtt_s)
            m = self._rtt_mean[peer]
            if m is None:
                self._rtt_mean[peer] = rtt_s
            else:
                d = rtt_s - m
                self._rtt_mean[peer] = m + self._ALPHA * d
                self._rtt_var[peer] = ((1.0 - self._ALPHA)
                                       * (self._rtt_var[peer]
                                          + self._ALPHA * d * d))
            if nbytes >= self._THROUGHPUT_MIN_BYTES and rtt_s > 0:
                bps = nbytes / rtt_s
                t = self._thr_mean[peer]
                self._thr_mean[peer] = (bps if t is None
                                        else t + self._ALPHA * (bps - t))

    # ------------------------------------------------------- suspicion

    def _window_s(self, peer: int) -> float:  # guarded-by: _lock
        m = self._int_mean[peer]
        if m is None:
            return self.window_ceil_s
        w = m + 3.0 * self._int_var[peer] ** 0.5
        return min(max(w, self.window_floor_s), self.window_ceil_s)

    def _phi_locked(self, peer: int) -> float:  # guarded-by: _lock
        silence = time.monotonic() - self._last[peer]
        return (float(self._fails[peer])
                + max(0.0, silence / self._window_s(peer) - 1.0))

    def _refresh(self, peer: int) -> None:  # guarded-by: _lock
        """Map the continuous phi onto the shared state vocabulary. phi
        never decreases between evidence (silence only grows, failures
        only accumulate), so thresholds only ever move the state UP here;
        the snap back down is _heard's job."""
        ph = self._phi_locked(peer)
        if ph >= self.phi_down:
            self._set(peer, DOWN)
        elif ph >= self.phi_suspect:
            self._set(peer, SUSPECT)

    def phi(self, peer: int) -> float:
        """The peer's current suspicion level (refreshes its state)."""
        with self._lock:
            self._refresh(peer)
            return self._phi_locked(peer)

    def state_of(self, peer: int) -> str:
        with self._lock:
            self._refresh(peer)
            return self._state[peer]

    def states(self) -> Dict[int, str]:
        with self._lock:
            for p in self._state:
                self._refresh(p)
            return dict(self._state)

    def allow(self, peer: int) -> bool:
        with self._lock:
            self._refresh(peer)
            if self._state[peer] != DOWN:
                return True
            now = time.monotonic()
            if now - self._last_probe[peer] >= self.probe_interval_s:
                self._last_probe[peer] = now
                return True
            return False

    # ------------------------------------------------ adaptive deadline

    def send_budget_s(self, peer: int, nbytes: int) -> float:
        """Adaptive per-destination send deadline: measured RTT headroom
        (mean + 4 sigma) plus the frame's expected wire time at the
        destination's measured throughput (halved for safety margin; the
        configured minimum-bandwidth assumption stands in before any
        measurement), clamped to [deadline_floor_s, deadline_ceil_s].
        Before any RTT sample the static base deadline is the headroom —
        first contact is never MORE aggressive than the fixed policy."""
        with self._lock:
            m = self._rtt_mean[peer]
            if m is None:
                base = self.base_deadline_s
            else:
                base = m + 4.0 * self._rtt_var[peer] ** 0.5
            thr = self._thr_mean[peer]
            if thr is None or thr <= 0:
                bps = self.min_bandwidth_bps
            else:
                bps = max(0.5 * thr, 1.0)
            budget = base + float(nbytes) / bps
            return min(max(budget, self.deadline_floor_s),
                       self.deadline_ceil_s)

    def phi_snapshot(self) -> Dict[str, Dict]:
        """Per-peer estimator snapshot for the report/telemetry rollup."""
        with self._lock:
            out = {}
            for p in self._state:
                self._refresh(p)
                out[str(p)] = {
                    "phi": round(self._phi_locked(p), 4),
                    "window_s": round(self._window_s(p), 4),
                    "rtt_s": (round(self._rtt_mean[p], 6)
                              if self._rtt_mean[p] is not None else None),
                    "bps": (round(self._thr_mean[p], 1)
                            if self._thr_mean[p] is not None else None),
                }
            return out


class PartitionGate:
    """FaultPlan partition lane, evaluated over PEER ids at the socket.

    ``components`` come from :meth:`FaultPlan.partition_components` with the
    peer count as the population; the span clock is the owning peer's
    **local round** (supplied via ``version_fn``), the dist analogue of
    the local engine's round index — it advances with the peer's own
    training loop, so both sides traverse the span as their own counter
    crosses ``partition_rounds`` even while cross-partition messages are
    dropped. That autonomy is what makes the gate dispatch-agnostic: a
    leadered peer and a gossip peer (whose clocks never synchronize by
    construction) each evaluate the SAME seeded component split against
    their own counter, so the two sides of a cut agree on span
    *membership* even when they disagree, briefly, on whether the span
    is active (skew shows up as one side dropping at send while the
    other still drops at recv — never as mismatched components).
    ``allowed(a, b)`` is False iff the span is active on *this* peer's
    clock and ``a``/``b`` sit in different components."""

    def __init__(self, plan: Optional[FaultPlan], peers: int,
                 version_fn: Callable[[], int]):
        self.plan = plan if plan is not None else FaultPlan()
        self.peers = int(peers)
        self.version_fn = version_fn

    def components(self) -> Optional[Tuple[Tuple[int, ...], ...]]:
        return self.plan.partition_components(int(self.version_fn()),
                                              self.peers)

    def component_of(self, peer: int) -> Optional[Tuple[int, ...]]:
        """The peer's component, or None for an id no component contains
        (an unknown/garbage sender — never a crash, see ``allowed``)."""
        comps = self.components()
        if comps is None:
            return tuple(range(self.peers))
        return next((c for c in comps if peer in c), None)

    def allowed(self, a: int, b: int) -> bool:
        comps = self.components()
        if comps is None:
            return True
        ca, cb = self.component_of(a), self.component_of(b)
        if ca is None or cb is None:
            # a frame with a missing/out-of-range "from" during an active
            # span: drop it (an unknown sender is by definition not in the
            # receiver's component) rather than crash the serving thread
            return False
        return ca == cb


class WireChaos:
    """FaultPlan wire lane bound to one sender: draws per-(message,
    attempt) socket faults with the peer's local round as the lane clock
    (the same autonomous clock the partition gate uses — it advances with
    the peer's own training loop, never via the faulted messages)."""

    def __init__(self, plan: Optional[FaultPlan],
                 clock_fn: Callable[[], int]):
        self.plan = plan if plan is not None else FaultPlan()
        self.clock_fn = clock_fn

    def actions(self, src: int, dst: int, msg_id: int, attempt: int,
                clock: Optional[int] = None) -> Optional[dict]:
        """Fault draw for one attempt. ``clock`` pins the lane clock to a
        caller-captured instant — the pipelined sender records it at
        ENQUEUE time, so a message's fate stays a deterministic function
        of (seed, round-it-was-produced, ids, attempt) no matter when the
        worker actually transmits it."""
        c = int(self.clock_fn()) if clock is None else int(clock)
        return self.plan.wire_actions(c, src, dst, msg_id, attempt)


class LimpChaos:
    """FaultPlan limp lane's THROTTLE seam bound to one sender: draws the
    direction-keyed link byte rate with the peer's local round as the
    lane clock (the same clock discipline as :class:`WireChaos` — the
    clock is pinned at enqueue time on the pipelined path, so a frame's
    fate is a deterministic function of the round that produced it). The
    draw degrades a DIRECTION: (src, dst) and (dst, src) draw
    independently, which is what makes one-way gray failures — A→B limps
    while B→A answers fine — injectable and replayable."""

    def __init__(self, plan: Optional[FaultPlan],
                 clock_fn: Callable[[], int]):
        self.plan = plan if plan is not None else FaultPlan()
        self.clock_fn = clock_fn

    def throttle_bps(self, src: int, dst: int,
                     clock: Optional[int] = None) -> Optional[float]:
        """Byte rate the src→dst direction is degraded to this round, or
        None when the direction is healthy / the lane is off."""
        c = int(self.clock_fn()) if clock is None else int(clock)
        return self.plan.limp_throttle(c, src, dst)


class PeerTransport:
    """Frame transport bound to one peer id.

    ``addrs[p]`` is peer ``p``'s ``(host, port)``; the transport listens on
    its own address and connects outward per send. ``gate`` (optional) is
    consulted on both send and receive; ``chaos`` (optional) is the wire
    fault lane; ``policy`` (a :class:`DistConfig`) carries the retry /
    detector / dedup / inbox knobs."""

    def __init__(self, peer_id: int, addrs: List[Tuple[str, int]],
                 gate: Optional[PartitionGate] = None,
                 connect_timeout_s: float = 5.0,
                 io_timeout_s: float = 60.0,
                 chaos: Optional[WireChaos] = None,
                 policy: Optional[DistConfig] = None,
                 epoch: Optional[int] = None,
                 limp: Optional[LimpChaos] = None):
        self.peer_id = int(peer_id)
        self.addrs = list(addrs)
        self.gate = gate
        self.chaos = chaos
        self.limp = limp
        self.policy = policy if policy is not None else DistConfig()
        self.connect_timeout_s = connect_timeout_s
        self.io_timeout_s = io_timeout_s
        self.inbox: "queue.Queue" = queue.Queue(
            maxsize=self.policy.inbox_max)
        if self.policy.detector == "phi":
            self.detector: FailureDetector = PhiFailureDetector(
                len(addrs),
                phi_suspect=self.policy.phi_suspect,
                phi_down=self.policy.phi_down,
                probe_interval_s=self.policy.probe_interval_s,
                window_floor_s=self.policy.phi_window_floor_s,
                window_ceil_s=self.policy.phi_window_ceil_s,
                deadline_floor_s=self.policy.deadline_floor_s,
                deadline_ceil_s=self.policy.deadline_ceil_s,
                min_bandwidth_bps=self.policy.min_bandwidth_bps,
                base_deadline_s=self.policy.send_deadline_s)
        else:
            self.detector = FailureDetector(
                len(addrs), self.policy.suspect_after,
                self.policy.down_after, self.policy.probe_interval_s)
        # receive-path counters are bumped from concurrent per-connection
        # serve threads AND (with the pipeline on) the sender workers: a
        # plain += is a racy read-add-store there. Writes go through
        # _bump / locked sections; stats() reads are GIL-atomic snapshots
        # (the (writes) qualifier states exactly that contract).
        self._stats_lock = threading.Lock()
        # --- observability counters (stats()) ---
        self.retries = 0            # guarded-by: _stats_lock (writes) — re-attempts
        self.send_failures = 0      # guarded-by: _stats_lock (writes) — budget exhausted
        self.dups_dropped = 0       # guarded-by: _stats_lock (writes) — dedup drops
        self.crc_drops = 0          # guarded-by: _stats_lock (writes) — CRC failures
        self.wire_drops = 0         # guarded-by: _stats_lock (writes) — malformed/stalled
        self.inbox_overflow = 0     # guarded-by: _stats_lock (writes) — bounded-inbox sheds
        self.reorders_held = 0      # guarded-by: _stats_lock (writes) — chaos holds
        self.circuit_skips = 0      # guarded-by: _stats_lock (writes) — open-circuit skips
        self.dropped_by_gate = 0    # guarded-by: _stats_lock (writes) — partition drops
        self.limp_paced = 0         # guarded-by: _stats_lock (writes) — limp throttle pacings
        self.chaos_injected = {"drop": 0, "dup": 0, "reorder": 0,  # guarded-by: _stats_lock (writes)
                               "delay": 0, "corrupt": 0}
        # the sender's incarnation epoch: part of the dedup identity, so a
        # restarted peer (fresh msg-id counter) opens a fresh window at
        # every receiver instead of colliding with its dead incarnation's
        # ids — crash/rejoin cannot have its first HELLOs eaten as "dups".
        # Callers that can persist state across restarts (PeerRuntime's
        # file-backed restart counter) pass ``epoch`` explicitly —
        # guaranteed monotone even when the wall clock steps backward
        # between incarnations; the wall-ms default covers ad-hoc use.
        self.epoch = (int(epoch) if epoch is not None
                      else time.time_ns() // 1_000_000)
        self._dedup_lock = threading.Lock()
        self._dedup_seen: Dict[int, set] = {}   # guarded-by: _dedup_lock
        self._dedup_max: Dict[int, int] = {}    # guarded-by: _dedup_lock
        self._dedup_epoch: Dict[int, int] = {}  # guarded-by: _dedup_lock
        self._server: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._closing = threading.Event()
        # --- pipelined sender (policy.pipeline; RUNTIME.md §4) ---
        # one worker + bounded handoff queue per destination: send_async
        # allocates the msg_id in the CALLER's thread (per-destination
        # allocation order == enqueue order == wire order, since the
        # worker drains FIFO) and returns immediately; the retry/backoff
        # loop, chaos draws, and detector feeding all run in the worker.
        # The bounded queue IS the back-pressure: a slow link blocks the
        # enqueuing round loop after pipeline_depth frames instead of
        # buffering model-sized trees without bound.
        self._send_lock = threading.Lock()  # msg-id alloc + worker spawn
        self._send_queues: Dict[int, "queue.Queue"] = {}  # guarded-by: _send_lock
        self._next_msg_id: Dict[int, int] = {}  # guarded-by: _send_lock
        self._inflight_cv = threading.Condition()
        self._inflight = 0  # guarded-by: _inflight_cv — sends enqueued or executing
        self.async_enqueued = 0     # guarded-by: _stats_lock (writes) — handed to a worker
        self.backpressure_blocks = 0  # guarded-by: _stats_lock (writes) — waited on full queue

    def _bump(self, name: str) -> None:
        with self._stats_lock:
            setattr(self, name, getattr(self, name) + 1)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        host, port = self.addrs[self.peer_id]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        srv.settimeout(0.25)  # so the accept loop notices close()
        self._server = srv
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"bcfl-dist-accept-{self.peer_id}")
        t.start()
        self._threads.append(t)

    def close(self) -> None:
        self._closing.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                # deadline: settimeout(0.25) on the listener in start()
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_one, args=(conn,),
                                 daemon=True)
            t.start()

    # --------------------------------------------------------------- receive

    def _serve_one(self, conn: socket.socket) -> None:
        """Receive pipeline for one connection. The ack is the LAST step:
        it confirms the frame was delivered AND accepted (enqueued, or
        deliberately discarded by gate/dedup/hostile-header policy — an
        application decision that must not feed the sender's failure
        detector). The one case that withholds the ack besides wire
        damage is inbox overflow: an acked-then-shed frame would be
        unrecoverable (the sender stops retrying and the dedup window
        would eat any retransmit), so overflow refuses the ack and
        un-records the id — at-least-once survives a full inbox."""
        try:
            with conn:
                header, trees = read_frame(conn, self.io_timeout_s)
                try:
                    # CRC is integrity, not authentication: a well-CRC'd
                    # frame can still carry hostile field TYPES
                    # ("from": "abc"). Coerce them here so a garbage
                    # header is a counted drop, never a dead serving
                    # thread.
                    src = int(header.get("from", -1))
                    msg_id = header.get("msg_id")
                    if msg_id is not None:
                        msg_id = int(msg_id)
                    epoch = int(header.get("msg_epoch") or 0)
                    hold = float(header.pop("chaos_hold_s", 0.0) or 0.0)
                except (TypeError, ValueError) as e:
                    self._bump("wire_drops")
                    logger.warning("peer %d: dropped frame with hostile "
                                   "header fields: %s", self.peer_id, e)
                    _telemetry.emit("recv", disposition="hostile")
                    self._ack(conn)  # delivered garbage: never retryable
                    return
                # even a frame the gate/dedup will discard is liveness
                # evidence: the sender's PROCESS is demonstrably up
                self.detector.on_inbound(src)
                if (self.gate is not None
                        and not self.gate.allowed(self.peer_id, src)):
                    # the RECEIVER'S clock is authoritative: a frame from
                    # across the partition is dropped before anything can
                    # merge it
                    self._bump("dropped_by_gate")
                    logger.info("peer %d: partition gate dropped %s from "
                                "peer %d", self.peer_id,
                                header.get("type"), src)
                    self._recv_event("gate", src, epoch, msg_id, header)
                    self._ack(conn)
                    return
                if msg_id is not None and not self._dedup_accept(
                        src, epoch, msg_id):
                    self._bump("dups_dropped")
                    logger.info("peer %d: dedup dropped duplicate %s "
                                "(%d, %d)", self.peer_id,
                                header.get("type"), src, msg_id)
                    self._recv_event("dedup", src, epoch, msg_id, header)
                    self._ack(conn)
                    return
                if hold > 0:
                    # chaos reorder: hold this frame so later arrivals
                    # overtake it in the inbox — the ordering scramble the
                    # idempotent handlers must tolerate. Capacity is
                    # checked NOW (the ack decision is due while the
                    # sender waits); a flood arriving during the hold can
                    # still shed the release — an accepted chaos-only
                    # residual.
                    if self.inbox.full():
                        self._shed_overflow(header, src, msg_id)
                        return
                    self._bump("reorders_held")
                    t = threading.Timer(hold, self._enqueue,
                                        args=(header, trees))
                    t.daemon = True
                    t.start()
                    # the frame IS accepted (it will enqueue after the
                    # hold) — emitted before the ack, like every accepted
                    # disposition, so an acked frame always left a recv
                    # event behind (the acked_not_lost invariant's ground)
                    self._recv_event("accepted", src, epoch, msg_id,
                                     header, held_s=hold)
                    self._ack(conn)
                elif self._enqueue(header, trees):
                    self._recv_event("accepted", src, epoch, msg_id,
                                     header)
                    self._ack(conn)
                else:
                    self._shed_overflow(header, src, msg_id,
                                        counted=True)
        except CrcError as e:
            self._bump("crc_drops")
            _telemetry.emit("recv", disposition="crc")
            logger.warning("peer %d: dropped corrupt inbound frame: %s",
                           self.peer_id, e)
        except (WireError, OSError, socket.timeout) as e:
            self._bump("wire_drops")
            _telemetry.emit("recv", disposition="wire")
            logger.warning("peer %d: dropped malformed/stalled inbound "
                           "frame: %s", self.peer_id, e)

    def _recv_event(self, disposition: str, src: int, epoch: int,
                    msg_id: Optional[int], header: Dict, **extra) -> None:
        """One receive-disposition event carrying the (src, msg_epoch,
        msg_id) transport identity — the receiver half of every
        cross-process correlation (never sampled)."""
        _telemetry.emit("recv", disposition=disposition, src=src,
                        msg_epoch=epoch, msg_id=msg_id,
                        type=header.get("type"), **extra)

    def _ack(self, conn: socket.socket) -> None:
        try:
            write_ack(conn)
        except OSError:
            # the sender vanished mid-handshake; it will retry and the
            # dedup window absorbs the duplicate
            pass

    def _shed_overflow(self, header: Dict, src: int,
                       msg_id: Optional[int],
                       counted: bool = False) -> None:
        """Bounded-inbox shed: count it, un-record the dedup id, and do
        NOT ack — the sender's retry (or a later retransmit) can still
        deliver once the inbox drains."""
        if not counted:
            self._bump("inbox_overflow")
        if msg_id is not None:
            self._dedup_unrecord(src, msg_id)
        # deliberately NO msg_id on the event: the frame was refused
        # (no ack), so its identity must not satisfy the acked_not_lost
        # lookup — the retransmit's accepted recv is the one that counts
        _telemetry.emit("recv", disposition="overflow", src=src,
                        type=header.get("type"))
        logger.warning("peer %d: inbox full (%d); refused %s (sender "
                       "will retry)", self.peer_id, self.policy.inbox_max,
                       header.get("type"))

    def _enqueue(self, header: Dict, trees: Dict) -> bool:
        try:
            self.inbox.put_nowait((header, trees))
            return True
        except queue.Full:
            self._bump("inbox_overflow")
            return False

    def _dedup_accept(self, src: int, epoch: int, msg_id: int) -> bool:
        """Record-and-test one (sender, epoch, msg_id): False for a
        duplicate or an id older than the window (treated as a duplicate —
        dropping a too-old retransmit is always safe under at-least-once).
        A NEWER sender epoch (process restart) resets the window; an older
        one is a dead incarnation's delayed frame and is never handled."""
        with self._dedup_lock:
            cur = self._dedup_epoch.get(src)
            if cur is None or epoch > cur:
                self._dedup_epoch[src] = epoch
                self._dedup_seen[src] = set()
                self._dedup_max[src] = -1
            elif epoch < cur:
                return False
            seen = self._dedup_seen.setdefault(src, set())
            newest = self._dedup_max.get(src, -1)
            if msg_id <= newest - self.policy.dedup_window or msg_id in seen:
                return False
            seen.add(msg_id)
            if msg_id > newest:
                self._dedup_max[src] = msg_id
            if len(seen) > 2 * self.policy.dedup_window:
                cut = self._dedup_max[src] - self.policy.dedup_window
                self._dedup_seen[src] = {i for i in seen if i > cut}
            return True

    def _dedup_unrecord(self, src: int, msg_id: int) -> None:
        """Forget a recorded id whose frame was shed before handling
        (inbox overflow): the sender's retransmit must not be rejected as
        a duplicate of a delivery that never happened."""
        with self._dedup_lock:
            self._dedup_seen.get(src, set()).discard(msg_id)

    def recv(self, timeout_s: float) -> Optional[Tuple[Dict, Dict]]:
        """Next inbound (header, trees), or None after ``timeout_s``."""
        try:
            return self.inbox.get(timeout=timeout_s)
        except queue.Empty:
            return None

    # ------------------------------------------------------------------ send

    def alloc_msg_id(self, to: int) -> int:
        """Next monotone message id for destination ``to`` (the leader also
        draws ids for its own self-buffered updates, so every merged update
        has a unique (from, msg_id) identity). Thread-safe: the round loop
        and the pipeline's enqueue path both allocate."""
        with self._send_lock:
            i = self._next_msg_id.get(to, 0)
            self._next_msg_id[to] = i + 1
            return i

    def send(self, to: int, header: Dict, trees: Optional[Dict] = None,
             timeout_s: Optional[float] = None) -> bool:
        """THE one reliable send seam (at-least-once). Stamps the frame
        with this peer's id and a monotone ``msg_id``, then retries failed
        attempts with exponential backoff + deterministic jitter under the
        per-destination deadline budget (``timeout_s`` or
        ``policy.send_deadline_s``), feeding every attempt outcome to the
        failure detector. BLOCKS until delivered or the budget expires;
        :meth:`send_async` is the pipelined fire-and-track variant.

        Returns True once the destination acked one copy; False when the
        partition gate blocks the pair, the circuit is open (peer DOWN, no
        probe due), or the retry budget expired. It never raises on
        network failure — call sites need no per-call error handling; the
        :meth:`stats` counters and the detector carry the evidence."""
        if self.gate is not None and not self.gate.allowed(self.peer_id, to):
            _telemetry.emit("send", to=to, type=header.get("type"),
                            ok=False, reason="gate", msg_id=None)
            return False
        msg_id = self.alloc_msg_id(to)
        header = dict(header, **{"from": self.peer_id, "msg_id": msg_id,
                                 "msg_epoch": self.epoch})
        return self._send_reliable(to, header, trees, timeout_s,
                                   time.time())

    # ------------------------------------------------- pipelined sender

    def send_async(self, to: int, header: Dict,
                   trees: Optional[Dict] = None,
                   timeout_s: Optional[float] = None) -> bool:
        """Enqueue one logical send on the per-destination sender worker
        and return immediately — the comms/compute overlap seam
        (RUNTIME.md §4): the round loop hands the frame off and starts the
        next local round while the worker runs the whole reliable-send
        protocol (retry/backoff/jitter, chaos draws, detector feeding,
        telemetry) in the background.

        Ordering and identity are exactly the synchronous seam's: the
        ``msg_id`` is allocated HERE in the caller's thread (so
        per-destination allocation order is enqueue order) and the worker
        drains its queue FIFO, so frames to one destination hit the wire
        in msg-id order. The handoff queue is bounded
        (``policy.pipeline_depth``): when the destination is slower than
        the round loop, the enqueue BLOCKS — back-pressure, so a dead or
        slow link can never buffer unbounded model-sized frames.

        Returns True when the frame was enqueued (the delivery outcome is
        reported through the detector/stats/event stream, like every
        at-least-once send); False when the partition gate blocks the pair
        at enqueue time or the transport is closing."""
        if self.gate is not None and not self.gate.allowed(self.peer_id, to):
            _telemetry.emit("send", to=to, type=header.get("type"),
                            ok=False, reason="gate", msg_id=None)
            return False
        with self._send_lock:
            q = self._send_queues.get(to)
            if q is None:
                q = queue.Queue(maxsize=max(1, self.policy.pipeline_depth))
                self._send_queues[to] = q
                t = threading.Thread(
                    target=self._sender_loop, args=(to, q), daemon=True,
                    name=f"bcfl-dist-send-{self.peer_id}-{to}")
                t.start()
                self._threads.append(t)
            i = self._next_msg_id.get(to, 0)
            self._next_msg_id[to] = i + 1
        # pin the chaos lane clock NOW: the message's fault fate must be a
        # deterministic function of the round that PRODUCED it, not of
        # when the worker happens to transmit it
        chaos_clock = (int(self.chaos.clock_fn())
                       if self.chaos is not None else None)
        item = (dict(header, **{"from": self.peer_id, "msg_id": i,
                                "msg_epoch": self.epoch}),
                trees, timeout_s, time.time(), chaos_clock)
        with self._inflight_cv:
            self._inflight += 1
        blocked = q.full()  # the enqueue is about to wait on the bound
        while not self._closing.is_set():
            try:
                # deadline: bounded handoff — block-on-full IS the
                # back-pressure contract; each wait re-checks closing
                q.put(item, timeout=0.25)
                with self._stats_lock:
                    self.async_enqueued += 1
                    if blocked:
                        self.backpressure_blocks += 1
                return True
            except queue.Full:
                blocked = True
        with self._inflight_cv:
            self._inflight -= 1
            self._inflight_cv.notify_all()
        return False

    def _sender_loop(self, to: int, q: "queue.Queue") -> None:
        """One destination's sender worker: drain the bounded queue FIFO,
        running the full reliable-send protocol per frame. Exits when the
        transport closes and the queue is drained."""
        while True:
            try:
                item = q.get(timeout=0.25)
            except queue.Empty:
                if self._closing.is_set():
                    return
                continue
            header, trees, timeout_s, t_start, chaos_clock = item
            try:
                self._send_reliable(to, header, trees, timeout_s, t_start,
                                    chaos_clock=chaos_clock)
            except Exception:  # noqa: BLE001 — a worker must never die
                logger.exception("peer %d: sender worker to %d failed",
                                 self.peer_id, to)
            finally:
                with self._inflight_cv:
                    self._inflight -= 1
                    self._inflight_cv.notify_all()

    def flush_sends(self, timeout_s: float = 30.0) -> bool:
        """Block until every async send has completed its protocol (queue
        drained AND workers idle), or ``timeout_s``. The runtime calls
        this before broadcasting shutdown (so a queued final update or
        global can't race the stop message) and before closing."""
        deadline = time.monotonic() + timeout_s
        with self._inflight_cv:
            while self._inflight > 0:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._inflight_cv.wait(min(left, 0.25))
        return True

    def _send_reliable(self, to: int, header: Dict, trees: Optional[Dict],
                       timeout_s: Optional[float], t_start: float,
                       chaos_clock: Optional[int] = None) -> bool:
        """The reliable-send protocol shared by the sync seam and the
        sender workers: circuit check, probe budgeting, retry loop with
        chaos draws, detector feeding, telemetry. ``header`` arrives
        already stamped with (from, msg_id, msg_epoch). Thread-safe: all
        counters go through the stats lock."""
        msg_id = header["msg_id"]
        if (self.gate is not None
                and not self.gate.allowed(self.peer_id, to)):
            # the async path re-checks at EXECUTION time: a partition span
            # can open between enqueue and dequeue, and skipping the
            # attempt keeps a blocked pair from burning its retry budget
            # (the receiver's own gate is still authoritative)
            _telemetry.emit("send", to=to, type=header.get("type"),
                            ok=False, reason="gate", msg_id=msg_id)
            return False
        if not self.detector.allow(to):
            self._bump("circuit_skips")
            _telemetry.emit("send", to=to, type=header.get("type"),
                            ok=False, reason="circuit_open", msg_id=msg_id)
            return False
        # a granted probe of a DOWN peer is a SINGLE attempt under a
        # probe-interval-bounded budget: a BLACK-HOLING corpse (SYNs
        # dropped, not refused — real DCN) must cost at most one probe
        # budget per interval, never connect_timeout_s inline in the peer
        # loop per message, and a full retry loop per probe would turn
        # "a corpse costs ~zero" into the leader spending its wall time
        # probing
        state = self.detector.state_of(to)
        probe = state == DOWN
        pol = self.policy
        # CRC ONCE per logical send: the prefix pass walks the leaf
        # buffers zero-copy; re-attempts of an unchanged frame (the common
        # case — only chaos reorder mutates the header) reuse it instead
        # of re-checksumming a potentially multi-hundred-MB tree. The
        # frame itself is never materialized — attempts stream straight
        # from the numpy buffers (wire.write_frame). Computed BEFORE the
        # budget: the adaptive deadline scales with the frame size.
        prefix = frame_prefix(header, trees)
        nbytes = len(prefix) + int.from_bytes(prefix[4:12], "little")
        if timeout_s is not None:
            budget_s = timeout_s
        else:
            # detector="phi": per-destination deadline from measured RTT /
            # throughput, proportional to THIS frame's size (the
            # large-frame starvation fix — RUNTIME.md "Timing contract");
            # detector="fixed" keeps the static policy deadline verbatim
            adapt = getattr(self.detector, "send_budget_s", None)
            budget_s = (adapt(to, nbytes) if adapt is not None
                        else pol.send_deadline_s)
        if probe:
            # bound the probe: a single cheap ping under a probe-interval
            # budget, never the full send deadline inline in the peer
            # loop. ONLY true probes (state DOWN) are capped — capping
            # SUSPECT sends too would starve any frame whose genuine
            # wire time exceeds the probe budget (a model-sized update
            # on a slow link would flap SUSPECT->DOWN->REACHABLE forever
            # while only tiny pings get through). The cost: a
            # black-holing destination can freeze the loop for up to
            # the send budget per send during the bounded SUSPECT
            # transient (at most ~down_after failed attempts) before the
            # circuit opens — the transient is bounded, starvation would
            # not be (and under detector="phi" the budget itself adapts
            # to the link)
            budget_s = min(budget_s, pol.probe_interval_s)
        deadline = time.monotonic() + budget_s
        # limp lane: direction-keyed throttle, drawn ONCE per logical send
        # (the draw is round-keyed, so per-attempt re-draws would be
        # identical anyway) on the same pinned clock as the wire lane
        limp_bps = (self.limp.throttle_bps(self.peer_id, to,
                                           clock=chaos_clock)
                    if self.limp is not None else None)
        attempt = 0
        while True:
            acts = (self.chaos.actions(self.peer_id, to, msg_id, attempt,
                                       clock=chaos_clock)
                    if self.chaos is not None else None)
            t_att = time.monotonic()
            try:
                self._attempt(to, header, trees, prefix, acts, deadline,
                              limp_bps=limp_bps)
                self.detector.on_success(to)
                note = getattr(self.detector, "note_rtt", None)
                if note is not None:
                    # per-attempt success wall (pacing included — an
                    # injected-slow link IS a slow link to the estimator)
                    note(to, time.monotonic() - t_att, nbytes)
                # stamped with the send's START instant (t_wall=t_start):
                # the causal timeline needs the send to precede the recv
                # it caused, and emission happens only after the ack
                _telemetry.emit(
                    "send", to=to, type=header.get("type"), ok=True,
                    msg_id=msg_id, msg_epoch=self.epoch,
                    attempts=attempt + 1, bytes=nbytes,
                    wall_s=time.time() - t_start, t_wall=t_start)
                return True
            except TransportError as e:
                self.detector.on_failure(to)
                attempt += 1
                backoff = min(pol.retry_base_s * (2 ** (attempt - 1)),
                              pol.retry_max_s)
                # deterministic jitter in [0.5, 1.5): desynchronizes
                # lockstep retries without a nondeterministic RNG. The
                # sender/destination ids are in the hash — every peer's
                # per-destination msg ids start at 0, so an id-only hash
                # would have all followers of a briefly-dead leader retry
                # in unison (the herd jitter exists to break up)
                backoff *= 0.5 + ((self.peer_id * 7919 + to * 104729
                                   + msg_id * 2654435761 + attempt * 97)
                                  % 1024) / 1024.0
                # per-attempt outcomes are the one high-rate stream —
                # routed through the sampling knob (telemetry_sample);
                # the final outcome below is never sampled
                _telemetry.emit_sampled(
                    "send.attempt", (self.peer_id, to, msg_id, attempt),
                    to=to, msg_id=msg_id, attempt=attempt,
                    outcome=str(e)[:200])
                if (probe or attempt > pol.send_retries
                        or time.monotonic() + backoff >= deadline):
                    self._bump("send_failures")
                    _telemetry.emit(
                        "send", to=to, type=header.get("type"), ok=False,
                        msg_id=msg_id, msg_epoch=self.epoch,
                        attempts=attempt, reason=str(e)[:200],
                        probe=probe, wall_s=time.time() - t_start,
                        t_wall=t_start)
                    # a failed probe of an already-DOWN peer is the
                    # expected steady state, not news — keep the warning
                    # for real delivery failures
                    logger.log(
                        logging.DEBUG if probe else logging.WARNING,
                        "peer %d -> %d: %s msg %d undelivered after %d "
                        "attempt(s): %s", self.peer_id, to,
                        header.get("type"), msg_id, attempt, e)
                    return False
                self._bump("retries")
                logger.debug("peer %d -> %d: attempt %d failed (%s); "
                             "retrying in %.2fs", self.peer_id, to,
                             attempt, e, backoff)
                time.sleep(backoff)

    def _attempt(self, to: int, header: Dict, trees: Optional[Dict],
                 prefix: bytes, acts: Optional[dict],
                 deadline: float, limp_bps: Optional[float] = None) -> None:
        """One transmission attempt: chaos injection, limp pacing,
        connect, stream the frame, ack. ``prefix`` is the pre-computed
        clean frame prefix (magic + length + CRC); only the chaos reorder
        path (header mutation) recomputes it. Raises
        :class:`TransportError` on any failure."""
        def _chaos(action: str, **extra) -> None:
            # per-injection events: high-rate under an armed lane, so
            # routed through the sampling knob; the lane/draw/target
            # coordinates make every injection replayable from the stream
            with self._stats_lock:
                self.chaos_injected[action] += 1
            _telemetry.emit_sampled(
                "chaos", (to, header.get("msg_id"), action),
                lane="wire", action=action, dst=to,
                msg_id=header.get("msg_id"), **extra)

        if acts is not None and acts["delay_s"] > 0:
            _chaos("delay", delay_s=acts["delay_s"])
            time.sleep(min(acts["delay_s"],
                           max(deadline - time.monotonic(), 0.0)))
        if acts is not None and acts["reorder_s"] > 0:
            _chaos("reorder", hold_s=acts["reorder_s"])
            header = dict(header, chaos_hold_s=acts["reorder_s"])
            prefix = frame_prefix(header, trees)
        corrupt = (acts["corrupt_pos"]
                   if acts is not None and acts["corrupt"] else None)
        if corrupt:
            _chaos("corrupt")
        if acts is not None and acts["drop"]:
            # the frame vanishes in the network: the receiver never sees
            # it and the sender learns only via the missing ack — modeled
            # without burning a real timeout so chaos runs stay fast
            _chaos("drop")
            raise TransportError(
                f"chaos wire lane dropped msg {header['msg_id']} "
                f"-> peer {to}")
        if limp_bps is not None and limp_bps > 0:
            # limp lane throttle: pace the attempt by the frame's wire
            # time at the degraded rate, bounded by the remaining budget
            # (an over-throttled frame runs out of budget in _deliver and
            # fails VISIBLY — the detector/w_slow evidence path, never a
            # silent stall past the deadline)
            nbytes = len(prefix) + int.from_bytes(prefix[4:12], "little")
            pace_s = min(nbytes / limp_bps,
                         max(deadline - time.monotonic(), 0.0))
            if pace_s > 0:
                self._bump("limp_paced")
                _telemetry.emit_sampled(
                    "limp.inject", (to, header.get("msg_id"), "throttle"),
                    kind="throttle", dst=to, msg_id=header.get("msg_id"),
                    bps=limp_bps, pace_s=round(pace_s, 4))
                time.sleep(pace_s)
        self._deliver(to, header, trees, prefix, corrupt, deadline)
        if acts is not None and acts["dup"]:
            # a duplicated delivery: second CLEAN copy of the same frame,
            # best-effort, bounded by the SAME deadline budget as the
            # main attempt — a stalling receiver must not hold the
            # sender past the send's wall budget. The receiver's dedup
            # window is what must absorb the copy.
            _chaos("dup")
            try:
                self._deliver(to, header, trees, prefix, None, deadline)
            except TransportError:
                pass

    def _deliver(self, to: int, header: Dict, trees: Optional[Dict],
                 prefix: bytes, corrupt: Optional[list],
                 deadline: float) -> None:
        """One physical delivery: connect, STREAM the frame straight from
        the numpy leaf buffers (wire.write_frame — the payload is never
        concatenated), read the ack — the single handshake both the real
        attempt and the chaos duplicate go through, every socket op capped
        by the remaining deadline budget. Raises :class:`TransportError`."""
        budget = deadline - time.monotonic()
        if budget <= 0:
            raise TransportError(f"send deadline budget exhausted "
                                 f"before attempt to peer {to}")
        host, port = self.addrs[to]
        try:
            with socket.create_connection(
                    (host, port),
                    timeout=min(self.connect_timeout_s, budget)) as sock:
                sock.settimeout(min(self.io_timeout_s, budget))
                write_frame(sock, header, trees, corrupt_frac=corrupt,
                            prefix=prefix)
                read_ack(sock, timeout_s=min(self.io_timeout_s, budget))
        except (OSError, socket.timeout, WireError) as e:
            raise TransportError(
                f"peer {self.peer_id} -> {to} ({host}:{port}): {e}") from e

    # ----------------------------------------------------------------- stats

    def stats(self) -> Dict:
        """Transport observability rollup for the peer report (and
        ``results/dist_async.json`` / ``results/dist_chaos.json``)."""
        return {
            "retries": self.retries,
            "send_failures": self.send_failures,
            "dups_dropped": self.dups_dropped,
            "crc_drops": self.crc_drops,
            "wire_drops": self.wire_drops,
            "inbox_overflow": self.inbox_overflow,
            "reorders_held": self.reorders_held,
            "circuit_skips": self.circuit_skips,
            "dropped_by_gate": self.dropped_by_gate,
            "limp_paced": self.limp_paced,
            "pipeline": {
                "async_enqueued": self.async_enqueued,
                "backpressure_blocks": self.backpressure_blocks,
                # lint: disable=guarded-by — len() snapshot for the
                # report rollup: a torn size is impossible (GIL) and a
                # stale one is acceptable observability lag
                "workers": len(self._send_queues),
            },
            "chaos_injected": dict(self.chaos_injected),
            "detector": {
                "states": {str(p): s
                           for p, s in self.detector.states().items()},
                "transitions": list(self.detector.transitions),
                **({"phi": self.detector.phi_snapshot()}
                   if isinstance(self.detector, PhiFailureDetector)
                   else {}),
            },
        }
