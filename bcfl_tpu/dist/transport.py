"""Host-level peer transport: loopback/DCN TCP with an injectable partition
gate (RUNTIME.md §3).

One :class:`PeerTransport` per peer process: a listener thread accepts
connections on the peer's own port and enqueues complete frames into an
inbox; sends open a fresh connection per message (loopback connects are
~microseconds, and connection-per-message means a crashed receiver can
never wedge a cached socket). Every operation runs under a hard deadline.

The **partition gate** is the FaultPlan partition lane driven at the socket
level: a callable consulted on BOTH ends of every message — the sender
skips blocked destinations, and the receiver drops frames whose origin is
blocked *by its own clock* (authoritative, so a component can never merge a
cross-partition update even when the two peers disagree about exactly when
the span started). While the gate blocks a pair, the two sides genuinely
cannot exchange bytes — each connected component evolves (and extends its
ledger chain) independently, which is what makes the fork real.
"""

from __future__ import annotations

import logging
import queue
import socket
import threading
from typing import Callable, Dict, List, Optional, Tuple

from bcfl_tpu.dist.wire import WireError, read_frame, write_frame
from bcfl_tpu.faults import FaultPlan

logger = logging.getLogger(__name__)


class TransportError(RuntimeError):
    """Send failed: destination unreachable / refused / deadline passed."""


class PartitionGate:
    """FaultPlan partition lane, evaluated over PEER ids at the socket.

    ``components`` come from :meth:`FaultPlan.partition_components` with the
    peer count as the population; the span clock is the owning peer's
    **model version** (supplied via ``version_fn``), the dist analogue of
    the local engine's round index — both sides traverse the span as their
    own version counter crosses ``partition_rounds``. ``allowed(a, b)`` is
    False iff the span is active on *this* peer's clock and ``a``/``b`` sit
    in different components."""

    def __init__(self, plan: Optional[FaultPlan], peers: int,
                 version_fn: Callable[[], int]):
        self.plan = plan if plan is not None else FaultPlan()
        self.peers = int(peers)
        self.version_fn = version_fn

    def components(self) -> Optional[Tuple[Tuple[int, ...], ...]]:
        return self.plan.partition_components(int(self.version_fn()),
                                              self.peers)

    def component_of(self, peer: int) -> Optional[Tuple[int, ...]]:
        """The peer's component, or None for an id no component contains
        (an unknown/garbage sender — never a crash, see ``allowed``)."""
        comps = self.components()
        if comps is None:
            return tuple(range(self.peers))
        return next((c for c in comps if peer in c), None)

    def allowed(self, a: int, b: int) -> bool:
        comps = self.components()
        if comps is None:
            return True
        ca, cb = self.component_of(a), self.component_of(b)
        if ca is None or cb is None:
            # a frame with a missing/out-of-range "from" during an active
            # span: drop it (an unknown sender is by definition not in the
            # receiver's component) rather than crash the serving thread
            return False
        return ca == cb


class PeerTransport:
    """Frame transport bound to one peer id.

    ``addrs[p]`` is peer ``p``'s ``(host, port)``; the transport listens on
    its own address and connects outward per send. ``gate`` (optional) is
    consulted on both send and receive."""

    def __init__(self, peer_id: int, addrs: List[Tuple[str, int]],
                 gate: Optional[PartitionGate] = None,
                 connect_timeout_s: float = 5.0,
                 io_timeout_s: float = 60.0):
        self.peer_id = int(peer_id)
        self.addrs = list(addrs)
        self.gate = gate
        self.connect_timeout_s = connect_timeout_s
        self.io_timeout_s = io_timeout_s
        self.inbox: "queue.Queue" = queue.Queue()
        self.dropped_by_gate = 0  # receiver-side partition drops (observability)
        self._server: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        self._closing = threading.Event()

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        host, port = self.addrs[self.peer_id]
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(16)
        srv.settimeout(0.25)  # so the accept loop notices close()
        self._server = srv
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"bcfl-dist-accept-{self.peer_id}")
        t.start()
        self._threads.append(t)

    def close(self) -> None:
        self._closing.set()
        if self._server is not None:
            try:
                self._server.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._closing.is_set():
            try:
                conn, _ = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_one, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            with conn:
                header, trees = read_frame(conn, self.io_timeout_s)
        except (WireError, OSError, socket.timeout) as e:
            logger.warning("peer %d: dropped malformed/stalled inbound "
                           "frame: %s", self.peer_id, e)
            return
        src = int(header.get("from", -1))
        if self.gate is not None and not self.gate.allowed(self.peer_id, src):
            # the RECEIVER'S clock is authoritative: a frame from across the
            # partition is dropped before anything can merge it
            self.dropped_by_gate += 1
            logger.info("peer %d: partition gate dropped %s from peer %d",
                        self.peer_id, header.get("type"), src)
            return
        self.inbox.put((header, trees))

    # ------------------------------------------------------------------ send

    def send(self, to: int, header: Dict, trees: Optional[Dict] = None,
             timeout_s: Optional[float] = None) -> bool:
        """Send one frame to peer ``to``. Returns False when the partition
        gate blocks the pair (not an error: the caller is supposed to act
        partitioned); raises :class:`TransportError` when the destination
        is genuinely unreachable within the deadline."""
        if self.gate is not None and not self.gate.allowed(self.peer_id, to):
            return False
        header = dict(header, **{"from": self.peer_id})
        host, port = self.addrs[to]
        try:
            with socket.create_connection(
                    (host, port), timeout=self.connect_timeout_s) as sock:
                write_frame(sock, header, trees,
                            timeout_s=timeout_s or self.io_timeout_s)
        except (OSError, socket.timeout) as e:
            raise TransportError(
                f"peer {self.peer_id} -> {to} ({host}:{port}): {e}") from e
        return True

    def recv(self, timeout_s: float) -> Optional[Tuple[Dict, Dict]]:
        """Next inbound (header, trees), or None after ``timeout_s``."""
        try:
            return self.inbox.get(timeout=timeout_s)
        except queue.Empty:
            return None
