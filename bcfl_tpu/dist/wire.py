"""Length-prefixed TCP wire format for the dist runtime (RUNTIME.md §3).

A **frame** is one protocol message: a small JSON header (message type,
peer id, versions, digests, ...) plus zero or more named **trees** — pytrees
of numpy arrays (a codec payload dict, a raw delta tree, a full model).
Everything is length-prefixed so a reader always knows exactly how many
bytes to wait for, and every read runs under a hard deadline — a stalled
sender produces a timeout, never a wedged peer.

Frame layout (all integers little-endian):

    MAGIC "BCF1"
    u64   frame_len                  # bytes after the crc field
    u32   crc32                      # zlib.crc32 over the whole payload
    u32   header_len, header JSON
    u32   ntrees
    per tree:
        u32  name_len, name (utf-8)
        u32  index_len, index JSON   # [{path, dtype, shape}] in body order
        u64  body_len, body          # concatenated raw C-order leaf bytes

The CRC covers every payload byte (header JSON included), so any in-flight
byte damage is rejected as :class:`CrcError` before a single field is
parsed — a corrupted frame can never half-deliver a tree or feed garbage
JSON to the handler. The receiver confirms an intact frame with a 4-byte
:data:`ACK`; the sender treats a missing ack as a failed attempt and
retries (at-least-once delivery — the transport's dedup window absorbs the
resulting duplicates). A malformed payload (hostile index JSON, truncated
tree, garbage dtype) always raises a clean :class:`WireError` — never a
hang, never a partially-built tree.

Trees are nested ``dict``s of arrays (flax param trees and codec payload
dicts both are); leaf paths join nesting keys with the ``\\x1f`` unit
separator — NOT ``"/"``, because codec payload dicts use leaf path names
like ``"layer/kernel"`` as single keys, and a ``/`` join would silently
re-nest them into a different structure on the receiver (breaking both the
decode program's payload lookup and structural equality). The round-trip is
bit- and structure-exact, so the ledger fingerprint digests computed on the
sender reproduce on the receiver unless the bytes really changed in flight.
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

MAGIC = b"BCF1"
ACK = b"BCFA"  # receiver's delivery confirmation for one intact frame
# bytes before the payload: magic (4) + u64 length (8) + u32 crc (4)
PREFIX_LEN = 16
# sanity cap: a corrupt/hostile length prefix must not OOM the peer. Full
# BERT-base f32 is ~0.44 GB; 4 GiB leaves headroom for any model this repo
# trains while still rejecting garbage lengths.
MAX_FRAME = 4 << 30


class WireError(RuntimeError):
    """Malformed frame (bad magic, oversized length, truncated stream)."""


class CrcError(WireError):
    """Frame payload failed its CRC — bytes changed in flight."""


SEP = "\x1f"  # key joiner; never appears in flax keys or codec path names


def _flatten(tree: Any, prefix: str = "") -> list:
    """Nested dicts of arrays -> [(path, np.ndarray)] in sorted key order
    (a canonical order, so sender and receiver agree byte-for-byte)."""
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            if SEP in k:
                raise WireError(f"tree key {k!r} contains the wire "
                                "separator")
            out.extend(_flatten(tree[k], f"{prefix}{k}{SEP}"))
        return out
    return [(prefix[:-1], np.ascontiguousarray(np.asarray(tree)))]


def pack_tree(tree: Any) -> Tuple[bytes, bytes]:
    """Tree -> (index JSON bytes, concatenated body bytes)."""
    leaves = _flatten(tree)
    index = [{"path": p, "dtype": a.dtype.str, "shape": list(a.shape)}
             for p, a in leaves]
    body = b"".join(a.tobytes() for _, a in leaves)
    return json.dumps(index).encode(), body


def _json_loads(raw: bytes, what: str) -> Any:
    """Decode hostile JSON into a value or a clean WireError — garbage
    bytes on the wire must never surface as a JSONDecodeError deep in a
    serving thread."""
    try:
        return json.loads(bytes(raw).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError(f"malformed {what} JSON: {e}") from None


def unpack_tree(index_json: bytes, body: bytes) -> Dict:
    """(index JSON, body) -> nested dict of numpy arrays. Any malformed
    index — non-list JSON, garbage dtype, negative/overflowing shape, a
    leaf extending past the body — raises :class:`WireError`; a partial
    tree is never returned."""
    out: Dict = {}
    off = 0
    rows = _json_loads(index_json, "tree index")
    try:
        for row in rows:
            dt = np.dtype(row["dtype"])
            shape = tuple(int(s) for s in row["shape"])
            if any(s < 0 for s in shape):
                raise WireError(f"negative dim in leaf shape {shape}")
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            if count < 0 or count * dt.itemsize > MAX_FRAME:
                raise WireError(f"leaf shape {shape} overflows MAX_FRAME")
            n = dt.itemsize * count
            if off + n > len(body):
                raise WireError(
                    f"tree body truncated at leaf {row['path']!r} "
                    f"(need {off + n}, have {len(body)})")
            arr = np.frombuffer(body, dt, count=count,
                                offset=off).reshape(shape).copy()
            off += n
            node = out
            parts = str(row["path"]).split(SEP)
            for k in parts[:-1]:
                node = node.setdefault(k, {})
                if not isinstance(node, dict):
                    raise WireError(f"leaf path {row['path']!r} descends "
                                    "through a non-dict node")
            node[parts[-1]] = arr
    except WireError:
        raise
    except (TypeError, ValueError, KeyError, AttributeError) as e:
        # hostile index rows (wrong types, unknown dtypes, missing keys)
        raise WireError(f"malformed tree index: {e}") from None
    if off != len(body):
        raise WireError(f"tree body has {len(body) - off} trailing bytes")
    return out


def pack_frame(header: Dict, trees: Optional[Dict[str, Any]] = None) -> bytes:
    hdr = json.dumps(header).encode()
    parts = [struct.pack("<I", len(hdr)), hdr,
             struct.pack("<I", len(trees or {}))]
    for name, tree in (trees or {}).items():
        nb = name.encode()
        index, body = pack_tree(tree)
        parts.extend([
            struct.pack("<I", len(nb)), nb,
            struct.pack("<I", len(index)), index,
            struct.pack("<Q", len(body)), body,
        ])
    payload = b"".join(parts)
    if len(payload) > MAX_FRAME:
        raise WireError(f"frame of {len(payload)} bytes exceeds MAX_FRAME")
    return (MAGIC + struct.pack("<Q", len(payload))
            + struct.pack("<I", zlib.crc32(payload)) + payload)


def unpack_frame(payload: bytes) -> Tuple[Dict, Dict[str, Any]]:
    """Bytes AFTER the magic+length+crc prefix -> (header, {name: tree})."""
    view = memoryview(payload)
    off = 0

    def take(n: int) -> memoryview:
        nonlocal off
        if off + n > len(view):
            raise WireError("frame truncated")
        out = view[off:off + n]
        off += n
        return out

    (hdr_len,) = struct.unpack("<I", take(4))
    header = _json_loads(take(hdr_len), "frame header")
    if not isinstance(header, dict):
        raise WireError(f"frame header is {type(header).__name__}, "
                        "expected an object")
    (ntrees,) = struct.unpack("<I", take(4))
    trees = {}
    for _ in range(ntrees):
        (name_len,) = struct.unpack("<I", take(4))
        try:
            name = bytes(take(name_len)).decode()
        except UnicodeDecodeError as e:
            raise WireError(f"malformed tree name: {e}") from None
        (idx_len,) = struct.unpack("<I", take(4))
        index = bytes(take(idx_len))
        (body_len,) = struct.unpack("<Q", take(8))
        trees[name] = unpack_tree(index, bytes(take(body_len)))
    return header, trees


def _read_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> bytes:
    """Read exactly ``n`` bytes before ``deadline`` (``time.monotonic``
    instant). The deadline bounds the WHOLE read, not each chunk — a
    trickling sender (1 byte per chunk, each inside a per-recv timeout)
    must still hit the frame deadline instead of holding the serving
    thread and its growing buffer forever. A peer closing mid-frame raises
    WireError instead of returning garbage."""
    import time

    chunks = []
    remaining = n
    while remaining:
        if deadline is not None:
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise socket.timeout(
                    f"frame deadline expired with {remaining} bytes unread")
            sock.settimeout(budget)
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise WireError(f"connection closed {remaining} bytes early")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def read_frame(sock: socket.socket,
               timeout_s: Optional[float] = None) -> Tuple[Dict, Dict]:
    """Read one frame under a hard WHOLE-FRAME deadline. Raises
    ``socket.timeout`` on deadline, :class:`CrcError` on in-flight byte
    damage, :class:`WireError` on any other malformed stream."""
    import time

    deadline = (time.monotonic() + timeout_s
                if timeout_s is not None else None)
    magic = _read_exact(sock, 4, deadline)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    (length,) = struct.unpack("<Q", _read_exact(sock, 8, deadline))
    if length > MAX_FRAME:
        raise WireError(f"frame length {length} exceeds MAX_FRAME")
    (crc,) = struct.unpack("<I", _read_exact(sock, 4, deadline))
    payload = _read_exact(sock, int(length), deadline)
    if zlib.crc32(payload) != crc:
        raise CrcError(f"payload CRC mismatch over {length} bytes")
    return unpack_frame(payload)


def write_ack(sock: socket.socket) -> None:
    """Confirm one intact frame back to the sender (4 bytes)."""
    sock.sendall(ACK)


def read_ack(sock: socket.socket, timeout_s: Optional[float] = None) -> None:
    """Wait for the receiver's :data:`ACK` under a hard deadline. Raises
    ``socket.timeout`` / :class:`WireError` when it never arrives — the
    sender's retry path treats either as a failed attempt."""
    import time

    deadline = (time.monotonic() + timeout_s
                if timeout_s is not None else None)
    got = _read_exact(sock, len(ACK), deadline)
    if got != ACK:
        raise WireError(f"bad ack {got!r}")
