"""Length-prefixed TCP wire format for the dist runtime (RUNTIME.md §3).

A **frame** is one protocol message: a small JSON header (message type,
peer id, versions, digests, ...) plus zero or more named **trees** — pytrees
of numpy arrays (a codec payload dict, a raw delta tree, a full model).
Everything is length-prefixed so a reader always knows exactly how many
bytes to wait for, and every read runs under a hard deadline — a stalled
sender produces a timeout, never a wedged peer.

Frame layout (all integers little-endian):

    MAGIC "BCF1"
    u64   frame_len                  # bytes after the crc field
    u32   crc32                      # zlib.crc32 over the whole payload
    u32   header_len, header JSON
    u32   ntrees
    per tree:
        u32  name_len, name (utf-8)
        u32  index_len, index JSON   # [{path, dtype, shape}] in body order
        u64  body_len, body          # concatenated raw C-order leaf bytes

The CRC covers every payload byte (header JSON included), so any in-flight
byte damage is rejected as :class:`CrcError` before a single field is
parsed — a corrupted frame can never half-deliver a tree or feed garbage
JSON to the handler. The receiver confirms an intact frame with a 4-byte
:data:`ACK`; the sender treats a missing ack as a failed attempt and
retries (at-least-once delivery — the transport's dedup window absorbs the
resulting duplicates). A malformed payload (hostile index JSON, truncated
tree, garbage dtype) always raises a clean :class:`WireError` — never a
hang, never a partially-built tree.

Trees are nested ``dict``s of arrays (flax param trees and codec payload
dicts both are); leaf paths join nesting keys with the ``\\x1f`` unit
separator — NOT ``"/"``, because codec payload dicts use leaf path names
like ``"layer/kernel"`` as single keys, and a ``/`` join would silently
re-nest them into a different structure on the receiver (breaking both the
decode program's payload lookup and structural equality). The round-trip is
bit- and structure-exact, so the ledger fingerprint digests computed on the
sender reproduce on the receiver unless the bytes really changed in flight.

**Streaming I/O** (the hot path — RUNTIME.md §3): :func:`write_frame`
streams a frame straight out of the numpy leaf buffers (``memoryview``s
over the arrays, CRC32 accumulated incrementally in a first zero-copy
pass) — the full payload is NEVER concatenated into one ``bytes`` on the
send path, so peak serialization allocation is the small skeleton (header
+ index JSON + length words), not a second copy of a model-sized body.
:func:`read_frame` decodes symmetrically: it parses the length-prefixed
stream as it arrives and reads each leaf's bytes DIRECTLY into its
preallocated array (``recv_into``), accumulating the same incremental
CRC. Because parsing now runs before the whole-payload CRC can be known,
a malformed stream is classified at the point of failure: the reader
drains the frame's remaining bytes (still under the frame deadline),
finishes the CRC, and raises :class:`CrcError` when the payload really
was damaged in flight — so corruption is still surfaced as a CRC drop,
never misfiled as a hostile sender — and :class:`WireError` when the
bytes arrived exactly as sent but are malformed. The on-wire layout is
byte-identical to :func:`pack_frame` (pinned by
``tests/test_wire_chaos.py::test_streamed_frame_bytes_identical``), so
ledger digests, dedup identities, and the PR 8 fuzz contracts all hold
unchanged. ``pack_frame``/``unpack_frame`` remain as the in-memory
reference implementation (tests, fuzzing, held-frame re-packs).
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, Dict, Optional, Tuple

import numpy as np

MAGIC = b"BCF1"
ACK = b"BCFA"  # receiver's delivery confirmation for one intact frame
# bytes before the payload: magic (4) + u64 length (8) + u32 crc (4)
PREFIX_LEN = 16
# sanity cap: a corrupt/hostile length prefix must not OOM the peer. Full
# BERT-base f32 is ~0.44 GB; 4 GiB leaves headroom for any model this repo
# trains while still rejecting garbage lengths.
MAX_FRAME = 4 << 30


class WireError(RuntimeError):
    """Malformed frame (bad magic, oversized length, truncated stream)."""


class CrcError(WireError):
    """Frame payload failed its CRC — bytes changed in flight."""


SEP = "\x1f"  # key joiner; never appears in flax keys or codec path names


def _flatten(tree: Any, prefix: str = "") -> list:
    """Nested dicts of arrays -> [(path, np.ndarray)] in sorted key order
    (a canonical order, so sender and receiver agree byte-for-byte)."""
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            if SEP in k:
                raise WireError(f"tree key {k!r} contains the wire "
                                "separator")
            out.extend(_flatten(tree[k], f"{prefix}{k}{SEP}"))
        return out
    return [(prefix[:-1], np.ascontiguousarray(np.asarray(tree)))]


def _tree_index(leaves) -> bytes:
    """Index JSON bytes for a flattened leaf list (shared by the in-memory
    reference pack and the streaming writer, so the two cannot drift)."""
    return json.dumps(
        [{"path": p, "dtype": a.dtype.str, "shape": list(a.shape)}
         for p, a in leaves]).encode()


def pack_tree(tree: Any) -> Tuple[bytes, bytes]:
    """Tree -> (index JSON bytes, concatenated body bytes). In-memory
    REFERENCE implementation — the transport's send path streams leaf
    buffers via :func:`write_frame` instead of concatenating them."""
    leaves = _flatten(tree)
    body = b"".join(a.tobytes() for _, a in leaves)
    return _tree_index(leaves), body


def _json_loads(raw: bytes, what: str) -> Any:
    """Decode hostile JSON into a value or a clean WireError — garbage
    bytes on the wire must never surface as a JSONDecodeError deep in a
    serving thread."""
    try:
        return json.loads(bytes(raw).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise WireError(f"malformed {what} JSON: {e}") from None


def unpack_tree(index_json: bytes, body: bytes) -> Dict:
    """(index JSON, body) -> nested dict of numpy arrays. Any malformed
    index — non-list JSON, garbage dtype, negative/overflowing shape, a
    leaf extending past the body — raises :class:`WireError`; a partial
    tree is never returned."""
    out: Dict = {}
    off = 0
    rows = _json_loads(index_json, "tree index")
    try:
        for row in rows:
            dt = np.dtype(row["dtype"])
            shape = tuple(int(s) for s in row["shape"])
            if any(s < 0 for s in shape):
                raise WireError(f"negative dim in leaf shape {shape}")
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            if count < 0 or count * dt.itemsize > MAX_FRAME:
                raise WireError(f"leaf shape {shape} overflows MAX_FRAME")
            n = dt.itemsize * count
            if off + n > len(body):
                raise WireError(
                    f"tree body truncated at leaf {row['path']!r} "
                    f"(need {off + n}, have {len(body)})")
            arr = np.frombuffer(body, dt, count=count,
                                offset=off).reshape(shape).copy()
            off += n
            node = out
            parts = str(row["path"]).split(SEP)
            for k in parts[:-1]:
                node = node.setdefault(k, {})
                if not isinstance(node, dict):
                    raise WireError(f"leaf path {row['path']!r} descends "
                                    "through a non-dict node")
            node[parts[-1]] = arr
    except WireError:
        raise
    except (TypeError, ValueError, KeyError, AttributeError,
            OverflowError) as e:
        # hostile index rows (wrong types, unknown dtypes, missing keys,
        # dims past int64 — np.prod raises OverflowError on those)
        raise WireError(f"malformed tree index: {e}") from None
    if off != len(body):
        raise WireError(f"tree body has {len(body) - off} trailing bytes")
    return out


def pack_frame(header: Dict, trees: Optional[Dict[str, Any]] = None) -> bytes:
    hdr = json.dumps(header).encode()
    parts = [struct.pack("<I", len(hdr)), hdr,
             struct.pack("<I", len(trees or {}))]
    for name, tree in (trees or {}).items():
        nb = name.encode()
        index, body = pack_tree(tree)
        parts.extend([
            struct.pack("<I", len(nb)), nb,
            struct.pack("<I", len(index)), index,
            struct.pack("<Q", len(body)), body,
        ])
    payload = b"".join(parts)
    if len(payload) > MAX_FRAME:
        raise WireError(f"frame of {len(payload)} bytes exceeds MAX_FRAME")
    return (MAGIC + struct.pack("<Q", len(payload))
            + struct.pack("<I", zlib.crc32(payload)) + payload)


def unpack_frame(payload: bytes) -> Tuple[Dict, Dict[str, Any]]:
    """Bytes AFTER the magic+length+crc prefix -> (header, {name: tree})."""
    view = memoryview(payload)
    off = 0

    def take(n: int) -> memoryview:
        nonlocal off
        if off + n > len(view):
            raise WireError("frame truncated")
        out = view[off:off + n]
        off += n
        return out

    (hdr_len,) = struct.unpack("<I", take(4))
    header = _json_loads(take(hdr_len), "frame header")
    if not isinstance(header, dict):
        raise WireError(f"frame header is {type(header).__name__}, "
                        "expected an object")
    (ntrees,) = struct.unpack("<I", take(4))
    trees = {}
    for _ in range(ntrees):
        (name_len,) = struct.unpack("<I", take(4))
        try:
            name = bytes(take(name_len)).decode()
        except UnicodeDecodeError as e:
            raise WireError(f"malformed tree name: {e}") from None
        (idx_len,) = struct.unpack("<I", take(4))
        index = bytes(take(idx_len))
        (body_len,) = struct.unpack("<Q", take(8))
        trees[name] = unpack_tree(index, bytes(take(body_len)))
    return header, trees


# ---------------------------------------------------------- streaming writer


def _frame_parts(header: Dict,
                 trees: Optional[Dict[str, Any]]) -> Tuple[list, int]:
    """The frame payload as an ordered list of buffers — small ``bytes``
    skeleton pieces (lengths, JSON) and zero-copy ``memoryview``s over the
    numpy leaf storage — plus the total payload length. Nothing here
    concatenates leaf bodies; the byte sequence is identical to
    :func:`pack_frame`'s payload by construction (same piece order)."""
    hdr = json.dumps(header).encode()
    parts: list = [struct.pack("<I", len(hdr)), hdr,
                   struct.pack("<I", len(trees or {}))]
    for name, tree in (trees or {}).items():
        nb = name.encode()
        leaves = _flatten(tree)
        index = _tree_index(leaves)
        body_len = sum(a.nbytes for _, a in leaves)
        parts.extend([
            struct.pack("<I", len(nb)), nb,
            struct.pack("<I", len(index)), index,
            struct.pack("<Q", body_len),
        ])
        # _flatten returned C-contiguous arrays: a flat byte view is a
        # borrow of the existing buffer, never a copy (0-d arrays go
        # through a reshape(1) view; zero-size leaves contribute no bytes
        # and memoryview.cast rejects them — skip)
        parts.extend(memoryview(a if a.ndim else a.reshape(1)).cast("B")
                     for _, a in leaves if a.nbytes)
    total = sum(len(p) if isinstance(p, bytes) else p.nbytes for p in parts)
    return parts, total


def frame_prefix(header: Dict,
                 trees: Optional[Dict[str, Any]] = None) -> bytes:
    """MAGIC + length + CRC prefix of the frame :func:`write_frame` would
    stream — the CRC pass without the write (used by tests and the perf
    bench to prove streamed == packed)."""
    parts, total = _frame_parts(header, trees)
    if total > MAX_FRAME:
        raise WireError(f"frame of {total} bytes exceeds MAX_FRAME")
    crc = 0
    for p in parts:
        crc = zlib.crc32(p, crc)
    return MAGIC + struct.pack("<Q", total) + struct.pack("<I", crc)


def write_frame(sock: socket.socket, header: Dict,
                trees: Optional[Dict[str, Any]] = None,
                corrupt_frac: Optional[list] = None,
                prefix: Optional[bytes] = None) -> int:
    """Stream one frame: CRC32 accumulated over the payload pieces in a
    first zero-copy pass (the prefix carries it, so it must be known before
    the first payload byte), then each piece written straight from its
    buffer — leaf bodies go out as ``memoryview``s over the numpy arrays,
    never concatenated. Small skeleton pieces are coalesced into one
    buffer between leaves to keep the syscall count low. Returns the
    total frame length (prefix + payload).

    ``prefix`` is an optional precomputed :func:`frame_prefix` for exactly
    this (header, trees): the transport's retry loop computes it once per
    logical send so re-attempts skip the CRC pass (the streaming analogue
    of "serialize once per logical send").

    ``corrupt_frac`` (the wire chaos lane's corruption hook) XOR-flips the
    payload byte at offset ``min(int(f * payload_len), payload_len - 1)``
    for each fraction — the same positions the pre-streaming
    ``_flip_payload_bytes`` produced — AFTER the CRC pass, so the receiver
    sees a well-framed message whose CRC no longer matches. Only the
    touched pieces are copied; the frame is never materialized."""
    parts, total = _frame_parts(header, trees)
    if total > MAX_FRAME:
        raise WireError(f"frame of {total} bytes exceeds MAX_FRAME")
    if prefix is None:
        crc = 0
        for p in parts:
            crc = zlib.crc32(p, crc)
        prefix = (MAGIC + struct.pack("<Q", total)
                  + struct.pack("<I", crc))
    if corrupt_frac and total > 0:
        parts = _corrupt_parts(
            parts, [min(int(f * total), total - 1) for f in corrupt_frac])
    sock.sendall(prefix)
    pending: list = []  # coalesce consecutive small pieces
    for p in parts:
        if isinstance(p, bytes) and len(p) < (1 << 16):
            pending.append(p)
            continue
        if pending:
            sock.sendall(b"".join(pending))  # skeleton only, never a body
            pending = []
        sock.sendall(p)
    if pending:
        sock.sendall(b"".join(pending))
    return PREFIX_LEN + total


def _corrupt_parts(parts: list, corrupt_pos: list) -> list:
    """Flip the payload byte at each absolute offset, copying only the
    pieces a flip lands in."""
    out = list(parts)
    offsets = []
    off = 0
    for p in out:
        offsets.append(off)
        off += len(p) if isinstance(p, bytes) else p.nbytes
    for pos in corrupt_pos:
        pos = min(int(pos), off - 1)
        if pos < 0:
            continue
        # find the piece containing pos (linear scan: few pieces)
        for i in range(len(out) - 1, -1, -1):
            if offsets[i] <= pos:
                buf = bytearray(out[i])
                buf[pos - offsets[i]] ^= 0xFF
                out[i] = bytes(buf)
                break
    return out


# ---------------------------------------------------------- streaming reader


class _FrameReader:
    """Incremental reader of one frame's payload: hands out exactly the
    requested bytes (or fills a caller-provided buffer in place), keeps a
    running CRC32 and a byte budget, and never reads past the declared
    payload length — trailing protocol bytes (the next frame, the ack
    channel) stay untouched."""

    CHUNK = 1 << 20

    def __init__(self, sock: socket.socket, length: int,
                 deadline: Optional[float]):
        self.sock = sock
        self.remaining = int(length)
        self.deadline = deadline
        self.crc = 0

    def _budget(self) -> None:
        import time

        if self.deadline is not None:
            budget = self.deadline - time.monotonic()
            if budget <= 0:
                raise socket.timeout(
                    f"frame deadline expired with {self.remaining} payload "
                    "bytes unread")
            self.sock.settimeout(budget)

    def take(self, n: int, what: str = "payload") -> bytes:
        """Exactly ``n`` payload bytes (skeleton pieces: lengths, JSON)."""
        if n < 0 or n > self.remaining:
            raise WireError(
                f"frame {what} of {n} bytes overruns the declared payload "
                f"({self.remaining} left)")
        chunks = []
        left = n
        while left:
            self._budget()  # deadline: settimeout from the frame budget
            chunk = self.sock.recv(min(left, self.CHUNK))
            if not chunk:
                raise WireError(f"connection closed {left} bytes early")
            chunks.append(chunk)
            left -= len(chunk)
        out = b"".join(chunks)
        self.crc = zlib.crc32(out, self.crc)
        self.remaining -= n
        return out

    def readinto(self, view: memoryview, what: str = "leaf") -> None:
        """Fill ``view`` (a leaf's preallocated array storage) directly from
        the socket — the receive-side zero-copy path."""
        n = view.nbytes
        if n > self.remaining:
            raise WireError(
                f"frame {what} of {n} bytes overruns the declared payload "
                f"({self.remaining} left)")
        off = 0
        while off < n:
            self._budget()
            got = self.sock.recv_into(view[off:off + self.CHUNK])
            if not got:
                raise WireError(
                    f"connection closed {n - off} bytes early")
            self.crc = zlib.crc32(view[off:off + got], self.crc)
            off += got
        self.remaining -= n

    def drain(self) -> None:
        """Consume (and CRC) the rest of the payload — the classification
        pass after a parse error: if the finished CRC mismatches the
        prefix, the bytes were damaged in flight (CrcError), otherwise the
        sender really sent a malformed frame (WireError). Bounded by the
        same frame deadline as every other read."""
        buf = bytearray(min(self.remaining, self.CHUNK))
        view = memoryview(buf)
        while self.remaining:
            self._budget()
            got = self.sock.recv_into(view[:min(self.remaining, len(buf))])
            if not got:
                raise WireError(
                    f"connection closed {self.remaining} bytes early")
            self.crc = zlib.crc32(view[:got], self.crc)
            self.remaining -= got


def _read_stream_tree(reader: _FrameReader) -> Dict:
    """One named tree off the stream: index JSON, then each leaf decoded
    straight into a preallocated array (``recv_into``). Every declared
    length is validated against the remaining payload BEFORE any
    allocation — a hostile index cannot make the receiver allocate more
    than the frame actually carries."""
    (idx_len,) = struct.unpack("<I", reader.take(4, "index length"))
    index = reader.take(idx_len, "tree index")
    (body_len,) = struct.unpack("<Q", reader.take(8, "body length"))
    if body_len > reader.remaining:
        raise WireError(
            f"tree body of {body_len} bytes overruns the declared payload "
            f"({reader.remaining} left)")
    rows = _json_loads(index, "tree index")
    out: Dict = {}
    consumed = 0
    try:
        for row in rows:
            dt = np.dtype(row["dtype"])
            shape = tuple(int(s) for s in row["shape"])
            if any(s < 0 for s in shape):
                raise WireError(f"negative dim in leaf shape {shape}")
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            if count < 0 or count * dt.itemsize > MAX_FRAME:
                raise WireError(f"leaf shape {shape} overflows MAX_FRAME")
            n = dt.itemsize * count
            if consumed + n > body_len:
                raise WireError(
                    f"tree body truncated at leaf {row['path']!r} "
                    f"(need {consumed + n}, have {body_len})")
            # allocation bounded by the validated body length above
            arr = np.empty(shape, dt)
            if arr.nbytes:  # zero-size leaves carry no bytes to read
                reader.readinto(
                    memoryview(arr if arr.ndim else arr.reshape(1))
                    .cast("B"),
                    what=f"leaf {row['path']!r}")
            consumed += n
            node = out
            parts = str(row["path"]).split(SEP)
            for k in parts[:-1]:
                node = node.setdefault(k, {})
                if not isinstance(node, dict):
                    raise WireError(f"leaf path {row['path']!r} descends "
                                    "through a non-dict node")
            node[parts[-1]] = arr
    except WireError:
        raise
    except (TypeError, ValueError, KeyError, AttributeError,
            OverflowError) as e:
        # hostile index rows — incl. dims past int64, where np.prod
        # raises OverflowError rather than ValueError
        raise WireError(f"malformed tree index: {e}") from None
    if consumed != body_len:
        raise WireError(
            f"tree body has {body_len - consumed} trailing bytes")
    return out


def _read_exact(sock: socket.socket, n: int,
                deadline: Optional[float] = None) -> bytes:
    """Read exactly ``n`` bytes before ``deadline`` (``time.monotonic``
    instant). The deadline bounds the WHOLE read, not each chunk — a
    trickling sender (1 byte per chunk, each inside a per-recv timeout)
    must still hit the frame deadline instead of holding the serving
    thread and its growing buffer forever. A peer closing mid-frame raises
    WireError instead of returning garbage."""
    import time

    chunks = []
    remaining = n
    while remaining:
        if deadline is not None:
            budget = deadline - time.monotonic()
            if budget <= 0:
                raise socket.timeout(
                    f"frame deadline expired with {remaining} bytes unread")
            sock.settimeout(budget)
        chunk = sock.recv(min(remaining, 1 << 20))
        if not chunk:
            raise WireError(f"connection closed {remaining} bytes early")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def read_frame(sock: socket.socket,
               timeout_s: Optional[float] = None) -> Tuple[Dict, Dict]:
    """Read one frame under a hard WHOLE-FRAME deadline, decoding the
    payload AS IT STREAMS — header and index JSON parsed off the socket,
    every leaf received straight into its preallocated array
    (``recv_into``), CRC32 accumulated incrementally. The whole payload is
    never held as one ``bytes``.

    Error contract (identical to the pre-streaming reader's, pinned by the
    fuzz suite): ``socket.timeout`` on deadline; :class:`CrcError` when the
    payload bytes were damaged in flight — on a parse failure the reader
    drains the frame's remaining bytes (same deadline) to finish the CRC
    and classify, so corruption that happens to land in a length word or
    the index JSON still surfaces as a CRC drop, not a hostile sender;
    :class:`WireError` for a stream that arrived exactly as sent but is
    malformed. A partial tree is never returned."""
    import time

    deadline = (time.monotonic() + timeout_s
                if timeout_s is not None else None)
    magic = _read_exact(sock, 4, deadline)
    if magic != MAGIC:
        raise WireError(f"bad magic {magic!r}")
    (length,) = struct.unpack("<Q", _read_exact(sock, 8, deadline))
    if length > MAX_FRAME:
        raise WireError(f"frame length {length} exceeds MAX_FRAME")
    (crc,) = struct.unpack("<I", _read_exact(sock, 4, deadline))
    reader = _FrameReader(sock, int(length), deadline)
    try:
        (hdr_len,) = struct.unpack("<I", reader.take(4, "header length"))
        header = _json_loads(reader.take(hdr_len, "frame header"),
                             "frame header")
        if not isinstance(header, dict):
            raise WireError(f"frame header is {type(header).__name__}, "
                            "expected an object")
        (ntrees,) = struct.unpack("<I", reader.take(4, "tree count"))
        trees: Dict = {}
        for _ in range(ntrees):
            (name_len,) = struct.unpack("<I", reader.take(4, "name length"))
            try:
                name = reader.take(name_len, "tree name").decode()
            except UnicodeDecodeError as e:
                raise WireError(f"malformed tree name: {e}") from None
            trees[name] = _read_stream_tree(reader)
        if reader.remaining:
            raise WireError(
                f"frame has {reader.remaining} trailing payload bytes")
    except WireError as parse_err:
        # classification pass: the payload was parsed before its CRC could
        # be known (that is what streaming means), so tell in-flight damage
        # apart from a genuinely hostile sender by finishing the CRC over
        # the undrained remainder. A drain failure (peer died mid-frame,
        # deadline) reports the original parse error.
        try:
            reader.drain()
        except (WireError, OSError, socket.timeout):
            raise parse_err from None
        if reader.crc != crc:
            raise CrcError(
                f"payload CRC mismatch over {length} bytes "
                f"(parse failed at: {parse_err})") from None
        raise
    if reader.crc != crc:
        raise CrcError(f"payload CRC mismatch over {length} bytes")
    return header, trees


def write_ack(sock: socket.socket) -> None:
    """Confirm one intact frame back to the sender (4 bytes)."""
    sock.sendall(ACK)


def read_ack(sock: socket.socket, timeout_s: Optional[float] = None) -> None:
    """Wait for the receiver's :data:`ACK` under a hard deadline. Raises
    ``socket.timeout`` / :class:`WireError` when it never arrives — the
    sender's retry path treats either as a failed attempt."""
    import time

    deadline = (time.monotonic() + timeout_s
                if timeout_s is not None else None)
    got = _read_exact(sock, len(ACK), deadline)
    if got != ACK:
        raise WireError(f"bad ack {got!r}")
