"""Entrypoints: presets (the 11 reference scripts) + one ``run(config)``."""

from bcfl_tpu.entrypoints.presets import (  # noqa: F401
    SWEEP_CLIENTS,
    build_presets,
    get_preset,
    list_presets,
)
from bcfl_tpu.entrypoints.run import format_report, run, run_sweep  # noqa: F401
