"""CLI: ``python -m bcfl_tpu.entrypoints --preset serverless_noniid_imdb``.

Replaces running the 11 reference scripts directly; every SURVEY.md §2.1
config knob is an override flag.

Subcommands: ``bcfl-tpu trace RUN_DIR`` collates a run's per-process event
streams into one causally-ordered timeline and runs the invariant checks
(bcfl_tpu.telemetry, OBSERVABILITY.md) — exit 1 on any violation.
``bcfl-tpu monitor RUN_DIR`` is the LIVE counterpart: incremental
collation + streaming invariants + the per-round health series over a run
that is still going (OBSERVABILITY.md §6).
``bcfl-tpu lint [PATHS]`` runs the AST static-analysis checkers over the
package (bcfl_tpu.analysis, ANALYSIS.md) — exit 1 on any unsuppressed
finding; ``--list-checkers`` prints the catalogue.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys

from bcfl_tpu.compression import KINDS as COMPRESS_KINDS
from bcfl_tpu.entrypoints.presets import _HF, get_preset, list_presets
from bcfl_tpu.entrypoints.run import run, run_sweep


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "trace":
        # the observability subcommand: no jax import, works on any
        # machine that can read the stream files
        from bcfl_tpu.telemetry import trace_main

        raise SystemExit(trace_main(argv[1:]))
    if argv and argv[0] == "monitor":
        # the LIVE observability subcommand (OBSERVABILITY.md §6): tails
        # a possibly-running fleet's streams; no jax import, exits 1 on
        # any invariant violation or unhealed critical alert
        from bcfl_tpu.telemetry.live import monitor_main

        raise SystemExit(monitor_main(argv[1:]))
    if argv and argv[0] == "lint":
        # the static-analysis subcommand (ANALYSIS.md): the checkers are
        # stdlib-ast only (the package import chain still pays the usual
        # bcfl_tpu config imports, like trace); exits nonzero on any
        # unsuppressed finding
        from bcfl_tpu.analysis import lint_main

        raise SystemExit(lint_main(argv[1:]))
    ap = argparse.ArgumentParser(prog="bcfl_tpu")
    ap.add_argument("--preset", default="smoke",
                    help=f"one of: {', '.join(list_presets())}")
    ap.add_argument("--hf", action="store_true",
                    help="import real HF checkpoint weights (needs hub access)")
    ap.add_argument("--sweep", action="store_true",
                    help="run the 5/10/20-worker sweep like "
                         "serverless_cancer_biobert_allclients.py")
    ap.add_argument("--resume", action="store_true")
    # common overrides (None = keep preset value)
    ap.add_argument("--clients", type=int, default=None)
    ap.add_argument("--rounds", type=int, default=None)
    ap.add_argument("--model", default=None)
    ap.add_argument("--dataset", default=None)
    ap.add_argument("--mode", choices=["server", "serverless"], default=None)
    # real multi-process async P2P runtime (bcfl_tpu.dist, RUNTIME.md):
    # spawns and supervises one OS process per peer over loopback TCP
    ap.add_argument("--runtime", choices=["local", "dist"], default=None,
                    help="'dist' runs the real multi-process async P2P "
                         "runtime: --peers OS processes, each owning a "
                         "client slice, exchanging updates over TCP with "
                         "FedBuff-buffered aggregation and MEASURED "
                         "staleness (implies sync=async, eval_every=0; "
                         "feature support per the config capability table)")
    ap.add_argument("--peers", type=int, default=None,
                    help="peer process count for --runtime dist "
                         "(num_clients must split evenly across them)")
    ap.add_argument("--dist-deadline", type=float, default=600.0,
                    help="hard per-peer wall deadline in seconds for "
                         "--runtime dist (a hung peer fails the run)")
    ap.add_argument("--dist-buffer", type=int, default=None,
                    metavar="N",
                    help="FedBuff merge target for --runtime dist, in "
                         "DISTINCT sending peers (0 = merge on every "
                         "arrival, the pure-async default; must be <= "
                         "peers). The robust --aggregator rules need "
                         ">= 3 (krum: >= 2f+3) — RUNTIME.md §5")
    ap.add_argument("--dist-quorum", type=float, default=None,
                    metavar="FRAC",
                    help="quorum fraction for --runtime dist leaders: the "
                         "merge target counts only peers the failure "
                         "detector does NOT hold DOWN, and below this "
                         "reachable fraction of the component the leader "
                         "stops advancing the global (default 0.5; "
                         "RUNTIME.md 'Delivery contract')")
    ap.add_argument("--no-dist-pipeline", action="store_true",
                    help="disable the comms/compute overlap pipeline for "
                         "--runtime dist (per-destination sender workers + "
                         "double-buffered merge intake, on by default — "
                         "RUNTIME.md §4); the serial PR 7-10 loop is the "
                         "wire_perf.py A/B baseline")
    ap.add_argument("--dist-pipeline-depth", type=int, default=None,
                    metavar="N",
                    help="bounded per-destination handoff queue for the "
                         "pipelined sender (default 2): a slow link blocks "
                         "the round loop after N queued frames "
                         "(back-pressure) instead of buffering unbounded "
                         "model-sized trees")
    ap.add_argument("--task", choices=["classification", "causal_lm"],
                    default=None,
                    help="causal_lm = federated next-token fine-tuning "
                         "(llama-family models; label columns ignored)")
    ap.add_argument("--sync", choices=["sync", "async"], default=None)
    ap.add_argument("--seq-len", type=int, default=None)
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--lr", type=float, default=None)
    ap.add_argument("--lora-rank", type=int, default=None)
    ap.add_argument("--lora-ranks", type=str, default=None,
                    help="heterogeneous per-client adapter ranks, e.g. "
                         "'2,4,8' cycled over clients (RBLA aggregation; "
                         "COMPRESSION.md 'Adapter exchange'). Exclusive "
                         "with --lora-rank")
    ap.add_argument("--max-local-batches", type=int, default=None)
    # cohort-batched client scale-out (SCALING.md "Cohort mode"): simulate
    # a registry far larger than the mesh; a seeded sampler draws each
    # round's active cohort onto the stacked axis
    ap.add_argument("--registry-size", type=int, default=None,
                    help="simulate a registry of N clients (host state "
                         "only); each round a seeded sampler draws "
                         "--sample-clients of them onto the mesh. Device "
                         "memory and per-round cost are bounded by the "
                         "cohort, not N. Requires mode=server")
    ap.add_argument("--sample-clients", type=int, default=None,
                    help="per-round sampled cohort size (the stacked "
                         "client-axis width) under --registry-size; "
                         "defaults to --clients")
    ap.add_argument("--cohort-size", type=int, default=None,
                    help="clients stacked (vmapped) per device: pins the "
                         "mesh to sample_clients/cohort_size devices; must "
                         "divide the sampled cohort size")
    ap.add_argument("--rounds-per-dispatch", type=int, default=None,
                    help="fuse up to N federated rounds into one XLA dispatch "
                         "(sync server FedAvg or parallel gossip; the ledger "
                         "fuses too via in-graph fingerprints — only anomaly "
                         "filters, tamper hooks, and faithful mode fall back "
                         "to per-round)")
    ap.add_argument("--sp", type=int, default=None,
                    help="sequence-parallel shards per client: 2-D "
                         "(clients, seq) mesh, ring attention over the seq "
                         "axis (llama causal / encoder non-causal)")
    ap.add_argument("--tp", type=int, default=None,
                    help="tensor-parallel shards per client (2-D clients x tp "
                         "mesh; requires --lora-rank > 0)")
    ap.add_argument("--pod", action="store_true",
                    help="span the mesh over every host in the pod "
                         "(jax.distributed must be initialized; see "
                         "core.mesh.distributed_init)")
    ap.add_argument("--eval-every", type=int, default=None,
                    help="evaluate every Nth round (per-round eval caps "
                         "fused dispatches at 1 round and dominates wall on "
                         "slow hosts; the final round always evaluates). "
                         "0 disables evaluation entirely — including the "
                         "final round (pure-throughput runs)")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--use-flash", choices=["on", "off"], default=None,
                    help="force the O(S)-memory blockwise/Pallas attention "
                         "path on or off (default: the model family's "
                         "choice — llama flashes from seq 512, encoders "
                         "stay dense)")
    ap.add_argument("--compute-dtype", default=None,
                    choices=["float32", "bfloat16", "float16"])
    ap.add_argument("--donate", action="store_true",
                    help="donate each round's input param/opt buffers to "
                         "the round program: half the per-round peak HBM "
                         "(one run per engine)")
    ap.add_argument("--remat", action="store_true",
                    help="per-layer activation rematerialization: less HBM "
                         "per client (more clients stack per chip) for "
                         "~1/3 more FLOPs")
    ap.add_argument("--prng-impl", default=None,
                    choices=["threefry", "rbg", "unsafe_rbg"],
                    help="typed-key PRNG: rbg = TPU hardware generator "
                         "(dropout RNG is +38%% of step time under the "
                         "threefry default; a different deterministic "
                         "stream, like changing the seed); unsafe_rbg "
                         "trades cross-version reproducibility for the "
                         "fastest fold/split path")
    ap.add_argument("--param-dtype", default=None,
                    choices=["float32", "bfloat16", "float16"])
    ap.add_argument("--faithful", action="store_true",
                    help="reference-exact sequential serverless semantics")
    ap.add_argument("--anomaly-filter",
                    choices=["pagerank", "dbscan", "zscore", "community", "none"],
                    default=None)
    ap.add_argument("--gossip-steps", type=int, default=None,
                    help="ring-gossip diffusion steps per serverless round "
                         "(0 = exact mask-weighted mean via the configured "
                         "--aggregator — required for --chaos-partition in "
                         "serverless mode; ring diffusion has no "
                         "per-component form)")
    ap.add_argument("--fused-tamper", action="append", default=None,
                    metavar="ROUND:CLIENT:SCALE",
                    help="inject a simulated transport corruption (additive "
                         "SCALE) into CLIENT's update in fused round ROUND "
                         "(repeatable). The corrupted update fails ledger "
                         "auth and is excluded from the aggregate — the "
                         "BC-FL tamper-resistance demo. Needs --ledger and "
                         "a fused dispatch (--rounds-per-dispatch > 1); a "
                         "request landing on a per-round-path round fails "
                         "loudly instead of being ignored")
    ap.add_argument("--ledger", action="store_true",
                    help="enable the hash-chained weight ledger (BC-FL)")
    ap.add_argument("--aggregator", default=None,
                    choices=["mean", "trimmed_mean", "median", "krum"],
                    help="aggregation rule compiled into the round program "
                         "(ROBUSTNESS.md): mean = reference FedAvg; the "
                         "robust rules survive up to an aggregator-trim "
                         "fraction of Byzantine clients without the ledger")
    ap.add_argument("--aggregator-trim", type=float, default=None,
                    help="assumed Byzantine fraction for trimmed_mean/krum "
                         "(default 0.2, must be < 0.5)")
    # communication compression (bcfl_tpu.compression, COMPRESSION.md):
    # quantized / top-k client deltas with error feedback, compiled into
    # the round programs; bytes-on-wire lands in the round records
    ap.add_argument("--compress", default=None,
                    choices=list(COMPRESS_KINDS),
                    help="compress the update exchange: int8 = per-chunk "
                         "quantized deltas (stochastic rounding), topk = "
                         "top-k sparsified deltas, int8+topk = both; error-"
                         "feedback residuals keep compression error from "
                         "accumulating. 'none' is bit-identical to the "
                         "uncompressed round programs")
    ap.add_argument("--compress-topk", type=float, default=None,
                    metavar="FRAC",
                    help="fraction of coordinates the topk codecs keep "
                         "(default 0.05)")
    ap.add_argument("--compress-chunk", type=int, default=None, metavar="N",
                    help="elements per int8 quantization chunk — one f32 "
                         "scale each (default 256)")
    ap.add_argument("--no-compress-ef", action="store_true",
                    help="disable the error-feedback residual (ablation; "
                         "compression error then accumulates)")
    # chaos harness (bcfl_tpu.faults.FaultPlan, ROBUSTNESS.md): seeded,
    # deterministic fault injection — the resilience demo knobs
    ap.add_argument("--chaos-dropout", type=float, default=None,
                    metavar="P", help="per-round per-client dropout "
                    "probability (fault injection)")
    ap.add_argument("--chaos-straggler", type=float, default=None,
                    metavar="P", help="per-round per-client straggler "
                    "probability (simulated-clock delay)")
    ap.add_argument("--chaos-straggler-delay", type=float, default=30.0,
                    metavar="SECONDS", help="injected straggler delay")
    ap.add_argument("--chaos-corrupt", type=float, default=None,
                    metavar="P", help="per-round per-client transport-"
                    "corruption probability; with --ledger corrupted "
                    "updates fail auth, without it use a robust "
                    "--aggregator")
    ap.add_argument("--chaos-crash-round", type=int, default=None,
                    metavar="N", help="inject a host crash at round N "
                    "(resume afterwards with --resume)")
    # partition / churn / flaky lanes (ROBUSTNESS.md §6)
    ap.add_argument("--chaos-partition", default=None, metavar="GROUPS",
                    help="split the mesh into isolated components for the "
                         "--chaos-partition-rounds span: explicit groups "
                         "like '0,1/2,3' (slash-separated; unlisted clients "
                         "form one extra component) or an integer N for a "
                         "seeded N-way split. Each component aggregates "
                         "independently with the configured --aggregator "
                         "and the components reconcile through the same "
                         "rule on heal")
    ap.add_argument("--chaos-partition-rounds", default=None,
                    metavar="START:END",
                    help="half-open round span the partition lasts, e.g. "
                         "'2:5' = rounds 2,3,4 (required with "
                         "--chaos-partition)")
    ap.add_argument("--chaos-churn-leave", action="append", default=None,
                    metavar="CLIENT:ROUND",
                    help="client CLIENT permanently leaves at round ROUND "
                         "(repeatable; the mesh never reshapes — the client "
                         "carries weight 0 from then on)")
    ap.add_argument("--chaos-churn-join", action="append", default=None,
                    metavar="CLIENT:ROUND",
                    help="client CLIENT joins late at round ROUND "
                         "(repeatable; absent — weight 0 — before it)")
    ap.add_argument("--chaos-flaky", default=None, metavar="CLIENTS",
                    help="comma-separated client ids that corrupt transport "
                         "in intermittent multi-round bursts — the "
                         "repeat-offender input reputation quarantine "
                         "exists for (see --reputation)")
    ap.add_argument("--chaos-flaky-burst", type=int, default=None,
                    metavar="N", help="rounds per flaky burst window "
                    "(default 3)")
    ap.add_argument("--chaos-flaky-on-prob", type=float, default=None,
                    metavar="P", help="probability each flaky window "
                    "actually bursts (default 0.5)")
    ap.add_argument("--chaos-wire", default=None, metavar="SPEC",
                    help="wire-fault lane for --runtime dist (RUNTIME.md "
                         "'Delivery contract'): comma list of K=V with K in "
                         "{drop,dup,reorder,delay,corrupt} (per-message "
                         "probabilities) plus optional delay-s / hold-s "
                         "(seconds), e.g. "
                         "'drop=0.2,dup=0.2,reorder=0.2,corrupt=0.05' — "
                         "seeded socket-level frame drop / duplication / "
                         "reorder-hold / delay-jitter / byte-corruption, "
                         "absorbed by the self-healing transport")
    ap.add_argument("--chaos-wire-rounds", default=None, metavar="START:END",
                    help="bound the wire lane to this half-open span of the "
                         "sender's local-round clock (default: every round)")
    ap.add_argument("--chaos-byz", default=None, metavar="PEERS",
                    help="byzantine lane for --runtime dist (ROBUSTNESS.md "
                         "§8): comma-separated ADVERSARIAL peer ids — each "
                         "rewrites its outbound updates above the wire "
                         "(scaled/sign-flipped/garbage payloads, stale "
                         "replays, digest forgeries, equivocation); caught "
                         "by the robust --aggregator rules, the ledger "
                         "refingerprint, and --reputation quarantine")
    ap.add_argument("--chaos-byz-behaviors", default=None, metavar="LIST",
                    help="comma subset of scale,sign_flip,garbage,replay,"
                         "digest_forge,equivocate (default: all)")
    ap.add_argument("--chaos-byz-prob", type=float, default=None,
                    metavar="P", help="per-(peer, round) probability an "
                    "adversarial peer acts (default 1.0)")
    ap.add_argument("--chaos-byz-scale", type=float, default=None,
                    metavar="S", help="payload perturbation magnitude for "
                    "the scale/garbage behaviors (default 25.0)")
    ap.add_argument("--chaos-byz-rounds", default=None, metavar="START:END",
                    help="bound the byzantine lane to this half-open span "
                         "of the adversary's local-round clock (default: "
                         "every round)")
    ap.add_argument("--chaos-seed", type=int, default=0,
                    help="seed of the chaos schedule (independent of --seed)")
    # peer-lifecycle reputation (bcfl_tpu.reputation, ROBUSTNESS.md §6)
    ap.add_argument("--reputation", action="store_true",
                    help="enable the peer-lifecycle state machine: EWMA "
                         "trust over per-round evidence (ledger-auth "
                         "failures, anomaly flags, corruption hits, "
                         "staleness) drives HEALTHY -> SUSPECT -> "
                         "QUARANTINED -> PROBATION; quarantined peers are "
                         "excluded for --reputation-quarantine-rounds and "
                         "readmitted at --reputation-probation-weight")
    ap.add_argument("--reputation-alpha", type=float, default=None,
                    metavar="A", help="EWMA trust update rate (default 0.4)")
    ap.add_argument("--reputation-suspect-below", type=float, default=None,
                    metavar="T", help="trust below T -> SUSPECT "
                    "(default 0.7)")
    ap.add_argument("--reputation-quarantine-below", type=float,
                    default=None, metavar="T",
                    help="trust below T -> QUARANTINED (default 0.4)")
    ap.add_argument("--reputation-quarantine-rounds", type=int, default=None,
                    metavar="N", help="rounds a quarantined peer sits out "
                    "(default 3)")
    ap.add_argument("--reputation-probation-rounds", type=int, default=None,
                    metavar="N", help="clean probation rounds before full "
                    "readmission (default 2)")
    ap.add_argument("--reputation-probation-weight", type=float,
                    default=None, metavar="W",
                    help="vote weight while on probation (default 0.5)")
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--checkpoint-every", type=int, default=None)
    ap.add_argument("--telemetry-dir", default=None, metavar="DIR",
                    help="event-stream directory (bcfl_tpu.telemetry, "
                         "OBSERVABILITY.md). Default: dist runs stream "
                         "into their run dir, local runs emit nothing; "
                         "naming a dir enables streaming on both")
    ap.add_argument("--no-telemetry", action="store_true",
                    help="disable event streaming everywhere (the "
                         "overhead-measurement setting)")
    ap.add_argument("--telemetry-sample", type=float, default=None,
                    metavar="P",
                    help="sampling rate in [0,1] for high-rate transport "
                         "events (per-attempt outcomes, chaos draws); "
                         "invariant-grade events are never sampled")
    ap.add_argument("--platform", default=None,
                    help="force a jax platform (e.g. 'cpu' for the virtual "
                         "host mesh). The JAX_PLATFORMS env var is NOT enough "
                         "on hosts whose site hooks pin a platform at "
                         "interpreter start; this flag wins because it sets "
                         "the config before any backend initializes")
    args = ap.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    cfg = get_preset(args.preset, hf=args.hf)
    simple = {
        "clients": "num_clients", "rounds": "num_rounds", "model": "model",
        "dataset": "dataset", "mode": "mode", "sync": "sync", "task": "task",
        "seq_len": "seq_len", "batch_size": "batch_size",
        "lr": "learning_rate", "lora_rank": "lora_rank",
        "lora_ranks": "lora_ranks",
        "max_local_batches": "max_local_batches", "seed": "seed",
        "registry_size": "registry_size", "sample_clients": "sample_clients",
        "cohort_size": "cohort_size",
        "rounds_per_dispatch": "rounds_per_dispatch", "tp": "tp", "sp": "sp",
        "eval_every": "eval_every",
        "checkpoint_dir": "checkpoint_dir", "checkpoint_every": "checkpoint_every",
        "compute_dtype": "compute_dtype", "param_dtype": "param_dtype",
        "prng_impl": "prng_impl",
    }
    overrides = {}
    for arg_name, cfg_name in simple.items():
        v = getattr(args, arg_name)
        if v is not None:
            overrides[cfg_name] = v
    if args.lora_ranks is not None and args.lora_rank is None:
        # a per-client spec supersedes a preset's uniform rank (FedConfig
        # rejects setting both and re-canonicalizes lora_rank to max(spec));
        # an EXPLICIT --lora-rank alongside --lora-ranks still reaches
        # FedConfig and fails there with its clear set-one-not-both message
        overrides["lora_rank"] = 0
    if args.model is not None and cfg.hf_checkpoint is not None:
        # keep checkpoint/tokenizer consistent with the overridden architecture
        if args.model not in _HF:
            raise SystemExit(
                f"--model {args.model!r} has no HF checkpoint mapping; "
                f"under --hf use one of {sorted(_HF)}")
        overrides["hf_checkpoint"] = _HF[args.model]
        overrides["tokenizer"] = _HF[args.model]
    if args.use_flash is not None:
        overrides["use_flash"] = args.use_flash == "on"
    if args.remat:
        overrides["remat"] = True
    if args.donate:
        overrides["donate"] = True
    if args.faithful:
        overrides["faithful"] = True
    if args.anomaly_filter is not None or args.gossip_steps is not None:
        topo_kw = {}
        if args.anomaly_filter is not None:
            topo_kw["anomaly_filter"] = (None if args.anomaly_filter == "none"
                                         else args.anomaly_filter)
        if args.gossip_steps is not None:
            topo_kw["gossip_steps"] = args.gossip_steps
        overrides["topology"] = dataclasses.replace(cfg.topology, **topo_kw)
    if args.ledger:
        overrides["ledger"] = dataclasses.replace(cfg.ledger, enabled=True)
    if args.pod:
        overrides["pod"] = True
    if args.aggregator is not None:
        overrides["aggregator"] = args.aggregator
    if args.aggregator_trim is not None:
        overrides["aggregator_trim"] = args.aggregator_trim
    if (args.compress is not None or args.compress_topk is not None
            or args.compress_chunk is not None or args.no_compress_ef):
        comp_kw = {"kind": args.compress if args.compress is not None
                   else cfg.compression.kind}
        if comp_kw["kind"] == "none" and args.compress != "none":
            # a codec sub-flag with no codec selected would silently ship
            # full-precision trees under a compression-tweak label — the
            # same fail-loudly stance as the shard_map/bench rejections
            raise SystemExit(
                "--compress-topk/--compress-chunk/--no-compress-ef have no "
                "effect without a codec: add --compress "
                "{int8,topk,int8+topk}")
        if args.compress_topk is not None:
            comp_kw["topk_frac"] = args.compress_topk
        if args.compress_chunk is not None:
            comp_kw["chunk"] = args.compress_chunk
        if args.no_compress_ef:
            comp_kw["error_feedback"] = False
        overrides["compression"] = dataclasses.replace(
            cfg.compression, **comp_kw)
    def _pair_schedule(entries, flag):
        if not entries:
            return None
        out = []
        for s in entries:
            try:
                c, r = s.split(":")
                out.append((int(c), int(r)))
            except ValueError:
                raise SystemExit(f"{flag} {s!r}: expected CLIENT:ROUND")
        return tuple(out)

    chaos_flags = (
        args.chaos_dropout is not None or args.chaos_straggler is not None
        or args.chaos_corrupt is not None
        or args.chaos_crash_round is not None
        or args.chaos_partition is not None
        or args.chaos_churn_leave or args.chaos_churn_join
        or args.chaos_flaky is not None or args.chaos_wire is not None
        or args.chaos_byz is not None
        # byz sub-flags enter the gate so "--chaos-byz-prob without
        # --chaos-byz" reaches the fail-loudly check below instead of
        # being silently ignored
        or args.chaos_byz_behaviors is not None
        or args.chaos_byz_prob is not None
        or args.chaos_byz_scale is not None
        or args.chaos_byz_rounds is not None)
    if chaos_flags:
        from bcfl_tpu.faults import FaultPlan

        plan_kw = dict(
            seed=args.chaos_seed,
            dropout_prob=args.chaos_dropout or 0.0,
            straggler_prob=args.chaos_straggler or 0.0,
            straggler_delay_s=args.chaos_straggler_delay,
            corrupt_prob=args.chaos_corrupt or 0.0,
            crash_at_round=args.chaos_crash_round,
            churn_leave=_pair_schedule(args.chaos_churn_leave,
                                       "--chaos-churn-leave"),
            churn_join=_pair_schedule(args.chaos_churn_join,
                                      "--chaos-churn-join"),
        )
        if args.chaos_partition is not None:
            if args.chaos_partition_rounds is None:
                raise SystemExit("--chaos-partition needs "
                                 "--chaos-partition-rounds START:END")
            try:
                lo, hi = (int(x) for x in
                          args.chaos_partition_rounds.split(":"))
            except ValueError:
                raise SystemExit(
                    f"--chaos-partition-rounds "
                    f"{args.chaos_partition_rounds!r}: expected START:END")
            if hi <= lo:
                # an empty span would make the partition silently never
                # fire (FaultPlan rejects it too; fail in CLI style here)
                raise SystemExit(
                    f"--chaos-partition-rounds "
                    f"{args.chaos_partition_rounds!r}: empty span "
                    "(END must be > START; the span is half-open)")
            plan_kw["partition_rounds"] = tuple(range(lo, hi))
            spec = args.chaos_partition
            if "/" in spec or "," in spec:
                try:
                    plan_kw["partition_groups"] = tuple(
                        tuple(int(c) for c in g.split(","))
                        for g in spec.split("/") if g)
                except ValueError:
                    raise SystemExit(f"--chaos-partition {spec!r}: expected "
                                     "groups like 0,1/2,3 or an integer N")
            else:
                try:
                    plan_kw["partition_count"] = int(spec)
                except ValueError:
                    raise SystemExit(f"--chaos-partition {spec!r}: expected "
                                     "groups like 0,1/2,3 or an integer N")
        if args.chaos_flaky is not None:
            try:
                plan_kw["flaky_clients"] = tuple(
                    int(c) for c in args.chaos_flaky.split(","))
            except ValueError:
                raise SystemExit(f"--chaos-flaky {args.chaos_flaky!r}: "
                                 "expected comma-separated client ids")
            if args.chaos_flaky_burst is not None:
                plan_kw["flaky_burst_len"] = args.chaos_flaky_burst
            if args.chaos_flaky_on_prob is not None:
                plan_kw["flaky_on_prob"] = args.chaos_flaky_on_prob
        if args.chaos_wire is not None:
            wire_keys = {"drop": "wire_drop_prob", "dup": "wire_dup_prob",
                         "reorder": "wire_reorder_prob",
                         "delay": "wire_delay_prob",
                         "corrupt": "wire_corrupt_prob",
                         "delay-s": "wire_delay_s",
                         "hold-s": "wire_reorder_hold_s"}
            for part in args.chaos_wire.split(","):
                try:
                    k, v = part.split("=")
                    plan_kw[wire_keys[k.strip()]] = float(v)
                except (ValueError, KeyError):
                    raise SystemExit(
                        f"--chaos-wire {part!r}: expected K=V with K in "
                        f"{sorted(wire_keys)}")
            if not any(plan_kw.get(wire_keys[k])
                       for k in ("drop", "dup", "reorder", "delay",
                                 "corrupt")):
                # delay-s/hold-s alone arm nothing: the lane fires off
                # probabilities — fail loudly instead of silently
                # injecting zero faults under a chaos-looking flag
                raise SystemExit(
                    f"--chaos-wire {args.chaos_wire!r} sets no "
                    "probability: add at least one of "
                    "drop/dup/reorder/delay/corrupt > 0")
        if args.chaos_byz is not None:
            try:
                plan_kw["byz_peers"] = tuple(
                    int(p) for p in args.chaos_byz.split(","))
            except ValueError:
                raise SystemExit(f"--chaos-byz {args.chaos_byz!r}: "
                                 "expected comma-separated peer ids")
            if args.chaos_byz_behaviors is not None:
                plan_kw["byz_behaviors"] = tuple(
                    b.strip() for b in args.chaos_byz_behaviors.split(",")
                    if b.strip())
            if args.chaos_byz_prob is not None:
                plan_kw["byz_prob"] = args.chaos_byz_prob
            if args.chaos_byz_scale is not None:
                plan_kw["byz_scale"] = args.chaos_byz_scale
            if args.chaos_byz_rounds is not None:
                try:
                    lo, hi = (int(x) for x in
                              args.chaos_byz_rounds.split(":"))
                except ValueError:
                    raise SystemExit(f"--chaos-byz-rounds "
                                     f"{args.chaos_byz_rounds!r}: "
                                     "expected START:END")
                if hi <= lo:
                    raise SystemExit(f"--chaos-byz-rounds "
                                     f"{args.chaos_byz_rounds!r}: empty "
                                     "span (END must be > START; the span "
                                     "is half-open)")
                plan_kw["byz_rounds"] = tuple(range(lo, hi))
        elif (args.chaos_byz_behaviors is not None
              or args.chaos_byz_prob is not None
              or args.chaos_byz_scale is not None
              or args.chaos_byz_rounds is not None):
            # same fail-loudly stance as the codec sub-flags
            raise SystemExit("--chaos-byz-* tuning flags have no effect "
                             "without --chaos-byz PEERS")
        if args.chaos_wire_rounds is not None:
            if args.chaos_wire is None:
                raise SystemExit("--chaos-wire-rounds has no effect "
                                 "without --chaos-wire")
            try:
                lo, hi = (int(x) for x in args.chaos_wire_rounds.split(":"))
            except ValueError:
                raise SystemExit(f"--chaos-wire-rounds "
                                 f"{args.chaos_wire_rounds!r}: expected "
                                 "START:END")
            if hi <= lo:
                raise SystemExit(f"--chaos-wire-rounds "
                                 f"{args.chaos_wire_rounds!r}: empty span "
                                 "(END must be > START; the span is "
                                 "half-open)")
            plan_kw["wire_rounds"] = tuple(range(lo, hi))
        overrides["faults"] = FaultPlan(**plan_kw)
    rep_tweaks = {
        "ewma_alpha": args.reputation_alpha,
        "suspect_below": args.reputation_suspect_below,
        "quarantine_below": args.reputation_quarantine_below,
        "quarantine_rounds": args.reputation_quarantine_rounds,
        "probation_rounds": args.reputation_probation_rounds,
        "probation_weight": args.reputation_probation_weight,
    }
    rep_tweaks = {k: v for k, v in rep_tweaks.items() if v is not None}
    if rep_tweaks and not args.reputation:
        # same fail-loudly stance as the codec sub-flags: a tuning flag
        # with the subsystem off would silently change nothing
        raise SystemExit("--reputation-* tuning flags have no effect "
                         "without --reputation")
    if args.reputation:
        overrides["reputation"] = dataclasses.replace(
            cfg.reputation, enabled=True, **rep_tweaks)
    if args.no_telemetry and args.telemetry_dir is not None:
        raise SystemExit("--no-telemetry contradicts --telemetry-dir")
    if args.no_telemetry:
        overrides["telemetry_dir"] = "off"
    elif args.telemetry_dir is not None:
        overrides["telemetry_dir"] = args.telemetry_dir
    if args.telemetry_sample is not None:
        overrides["telemetry_sample"] = args.telemetry_sample
    if args.peers is not None and args.runtime != "dist":
        raise SystemExit("--peers only applies to --runtime dist")
    if args.dist_quorum is not None and args.runtime != "dist":
        raise SystemExit("--dist-quorum only applies to --runtime dist")
    if args.dist_buffer is not None and args.runtime != "dist":
        raise SystemExit("--dist-buffer only applies to --runtime dist")
    if args.no_dist_pipeline and args.runtime != "dist":
        raise SystemExit("--no-dist-pipeline only applies to "
                         "--runtime dist")
    if args.dist_pipeline_depth is not None and args.runtime != "dist":
        raise SystemExit("--dist-pipeline-depth only applies to "
                         "--runtime dist")
    if args.runtime is not None:
        # runtime joins the ONE combined replace below: applying sync/mode/
        # faults first with runtime still "local" would run the local-
        # runtime validation on an intermediate config and reject legal
        # dist combinations (e.g. dist + --chaos-partition) with the wrong
        # error. Only fields the user did NOT set are defaulted — explicit
        # conflicting overrides still fail in the capability table.
        overrides["runtime"] = args.runtime
        if args.runtime == "dist":
            overrides.setdefault("sync", "async")
            overrides.setdefault("mode", "server")
            overrides.setdefault("eval_every", 0)
            dist_kw = dict(peers=args.peers or cfg.dist.peers,
                           peer_deadline_s=args.dist_deadline)
            if args.dist_quorum is not None:
                dist_kw["quorum_frac"] = args.dist_quorum
            if args.dist_buffer is not None:
                dist_kw["buffer"] = args.dist_buffer
            if args.no_dist_pipeline:
                dist_kw["pipeline"] = False
            if args.dist_pipeline_depth is not None:
                dist_kw["pipeline_depth"] = args.dist_pipeline_depth
            overrides["dist"] = dataclasses.replace(cfg.dist, **dist_kw)
    cfg = cfg.replace(**overrides)

    fused_tamper = None
    if args.fused_tamper:
        import numpy as np

        if not cfg.ledger.enabled:
            # without the ledger the engine runs the non-fp programs, which
            # have no transport stage — the corruption would be silently
            # dropped and the demo would pass vacuously
            raise SystemExit("--fused-tamper needs --ledger (the transport-"
                             "verification stage lives in the ledger's "
                             "fused fingerprint programs)")
        spec = {}
        for s in args.fused_tamper:
            try:
                r, c, scale = s.split(":")
                r, c, scale = int(r), int(c), float(scale)
            except ValueError:
                raise SystemExit(
                    f"--fused-tamper {s!r}: expected ROUND:CLIENT:SCALE")
            if not 0 <= c < cfg.num_clients:
                raise SystemExit(
                    f"--fused-tamper {s!r}: client out of range "
                    f"[0, {cfg.num_clients})")
            if not 0 <= r < cfg.num_rounds:
                # rounds are 0-indexed; a never-reached round would make the
                # demo pass vacuously (no corruption, all auth 1.0)
                raise SystemExit(
                    f"--fused-tamper {s!r}: round out of range "
                    f"[0, {cfg.num_rounds}) (rounds are 0-indexed)")
            spec.setdefault(r, []).append((c, scale))

        def fused_tamper(rnd, _spec=spec, _n=cfg.num_clients):
            rows = _spec.get(rnd)
            if not rows:
                return None
            row = np.zeros((_n,), np.float32)
            for c, scale in rows:
                row[c] = scale
            return row

    if cfg.runtime == "dist":
        if args.sweep or fused_tamper is not None or args.resume:
            raise SystemExit("--runtime dist composes with neither --sweep "
                             "nor --fused-tamper nor --resume (peer "
                             "crash/rejoin is driven by "
                             "scripts/dist_async.py --kill-peer)")
        import json as _json
        import os as _os

        from bcfl_tpu.dist.harness import run_dist

        run_dir = _os.path.join("/tmp", f"bcfl_dist_cli_{_os.getpid()}")
        result = run_dist(cfg, run_dir, platform=args.platform)
        summary = {
            "ok": result["ok"],
            "process_count": result["process_count"],
            "returncodes": result["returncodes"],
            "final_versions": {p: r.get("final_version")
                               for p, r in result["reports"].items()},
            "final_eval": result["reports"].get(0, {}).get("final_eval"),
            "run_dir": run_dir,
        }
        if result["event_streams"]:
            # collate the run's event streams right here: the timeline
            # block + invariant verdicts are the run's observability
            # surface (re-query any time: `bcfl-tpu trace <run_dir>`).
            # Collate the paths the harness found — with --telemetry-dir
            # the streams live outside run_dir
            from bcfl_tpu.telemetry import collate

            col = collate(result["event_streams"])
            summary["event_streams"] = result["event_streams"]
            summary["timeline"] = col["timeline"]
            summary["invariants"] = col["invariants"]
            summary["invariants_ok"] = col["ok"]
        print(_json.dumps(summary, indent=2), flush=True)
        if not result["ok"] or not summary.get("invariants_ok", True):
            raise SystemExit(1)
    elif args.sweep:
        if fused_tamper is not None:
            raise SystemExit("--fused-tamper does not compose with --sweep "
                             "(client indices change per sweep point)")
        run_sweep(cfg, resume=args.resume)
    else:
        run(cfg, resume=args.resume, fused_tamper=fused_tamper)


if __name__ == "__main__":
    main()
