"""The reference's 11 scripts as config presets.

The reference's configuration space is 11 near-copy files whose deltas are
module-level constants (the matrix in SURVEY.md §2.1). Each row becomes a
named :class:`~bcfl_tpu.config.FedConfig` preset over ONE engine; run any of
them with ``python -m bcfl_tpu.entrypoints --preset <name>``.

Reference citations per preset are in the individual docstring comments.
Notes on reference quirks preserved / fixed:

- ``server_noniid_imdb``: the reference defines ``load_data_count(count)`` but
  calls it once with ``count=0`` and never increments (``server_NonIID_IMDB.py
  :224-225``) so all Ray clients share one loader. We implement the *intended*
  per-client contiguous slices (the 300k/240 schedule).
- ``serverless_cancer_biobert_allclients``: the reference builds ``net`` with
  3 labels but ``global_model`` with 41 (``serverless_cancer_biobert_allclients
  .py:117`` vs ``:242``) — a latent shape bug. We hard-error on such mismatch
  by construction (one ``num_labels`` knob).
- HF checkpoints (``albert-base-v2``, ``dmis-lab/biobert-v1.1``) need hub
  access; presets default to the same-architecture registry config with fresh
  init, and ``hf=True`` switches on real weight import.
"""

from __future__ import annotations

from typing import Dict, List

from bcfl_tpu.config import FedConfig, LedgerConfig, PartitionConfig, TopologyConfig

_HF = {
    "albert-base": "albert-base-v2",
    "biobert-base": "dmis-lab/biobert-v1.1",
    "bert-base": "bert-base-uncased",
    "clinical-bert": "emilyalsentzer/Bio_ClinicalBERT",
}


def _mk(name: str, model: str, hf: bool, **kw) -> FedConfig:
    extra = dict(kw)
    if hf:
        extra["hf_checkpoint"] = _HF[model]
        extra["tokenizer"] = _HF[model]
    return FedConfig(name=name, model=model, **extra)


def build_presets(hf: bool = False) -> Dict[str, FedConfig]:
    """All presets; ``hf=True`` imports real HF weights/tokenizers (needs hub
    access — in air-gapped runs keep False for same-architecture fresh init)."""
    p: Dict[str, FedConfig] = {}

    # ---- Servercase (Flower-simulation scripts -> mode="server") ----
    # server_IID_IMDB.py: biobert (:48), 2 labels, 20 clients/20 rounds
    # (:49-50), IID 100 shared random indices (:79-84)
    p["server_iid_imdb"] = _mk(
        "server_iid_imdb", "biobert-base", hf,
        dataset="imdb", num_labels=2, mode="server",
        num_clients=20, num_rounds=20,
        partition=PartitionConfig(kind="iid", iid_samples=100),
    )
    # server_NonIID_IMDB.py: albert (:48), intended 300k/240 contiguous
    # schedule (:83-84)
    p["server_noniid_imdb"] = _mk(
        "server_noniid_imdb", "albert-base", hf,
        dataset="imdb", num_labels=2, mode="server",
        num_clients=20, num_rounds=20,
        partition=PartitionConfig(
            kind="contiguous", stride=300, train_span=240, test_span=60,
            test_mode="trailing"),
    )
    # server_iid_medical_transcirptions.py: biobert, 40 labels (:124),
    # 5 clients (:30), IID 500 (:59-60)
    p["server_iid_medical"] = _mk(
        "server_iid_medical", "biobert-base", hf,
        dataset="medical_transcriptions", num_labels=40, mode="server",
        num_clients=5, num_rounds=20,
        partition=PartitionConfig(kind="iid", iid_samples=500),
    )
    # server_noniid_medical_transcriptions.py: biobert, 10 clients (:30),
    # 500i/400 slices w/ fixed test [0,400) (:55-56)
    p["server_noniid_medical"] = _mk(
        "server_noniid_medical", "biobert-base", hf,
        dataset="medical_transcriptions", num_labels=40, mode="server",
        num_clients=10, num_rounds=20,
        partition=PartitionConfig(
            kind="contiguous", stride=500, train_span=400, test_span=400,
            test_mode="fixed"),
    )

    # ---- Serverlesscase (manual round loops -> mode="serverless") ----
    # serverless_IID_IMDB.py: albert (:31), 10 clients (:32), fresh
    # 100-random resample per client per round (:258)
    p["serverless_iid_imdb"] = _mk(
        "serverless_iid_imdb", "albert-base", hf,
        dataset="imdb", num_labels=2, mode="serverless", weighted_agg=False,
        num_clients=10, num_rounds=20,
        partition=PartitionConfig(
            kind="iid", iid_samples=100, resample_each_round=True),
    )
    # serverless_NonIID_IMDB.py: albert (:30), 300k/240 trailing slices
    # (:59-60), unweighted mean (:296)
    p["serverless_noniid_imdb"] = _mk(
        "serverless_noniid_imdb", "albert-base", hf,
        dataset="imdb", num_labels=2, mode="serverless", weighted_agg=False,
        num_clients=10, num_rounds=20,
        partition=PartitionConfig(
            kind="contiguous", stride=300, train_span=240, test_span=60,
            test_mode="trailing"),
    )
    # Serverless_iid_Medical_transcriptions.py: biobert (:28), 20 clients
    # (:30), IID 500 per round (:54-55, :238)
    p["serverless_iid_medical"] = _mk(
        "serverless_iid_medical", "biobert-base", hf,
        dataset="medical_transcriptions", num_labels=40, mode="serverless",
        weighted_agg=False, num_clients=20, num_rounds=20,
        partition=PartitionConfig(
            kind="iid", iid_samples=500, resample_each_round=True),
    )
    # Serverless_NonIID_Medical_transcriptions.py: biobert, 10 clients (:30),
    # 500i/400 slices, fixed test (:55-56)
    p["serverless_noniid_medical"] = _mk(
        "serverless_noniid_medical", "biobert-base", hf,
        dataset="medical_transcriptions", num_labels=40, mode="serverless",
        weighted_agg=False, num_clients=10, num_rounds=20,
        partition=PartitionConfig(
            kind="contiguous", stride=500, train_span=400, test_span=400,
            test_mode="fixed"),
    )
    # serverless_covid_iid.py: albert (:32), 41 labels (:122), 10 clients,
    # IID 500 per round (:253)
    p["serverless_covid_iid"] = _mk(
        "serverless_covid_iid", "albert-base", hf,
        dataset="covid", num_labels=41, mode="serverless", weighted_agg=False,
        num_clients=10, num_rounds=20,
        partition=PartitionConfig(
            kind="iid", iid_samples=500, resample_each_round=True),
    )
    # serverless_caner_classification_iid.py: albert (:32), 41 labels (:120),
    # IID 500 per round (:251)
    p["serverless_cancer_iid"] = _mk(
        "serverless_cancer_iid", "albert-base", hf,
        dataset="cancer", num_labels=41, mode="serverless", weighted_agg=False,
        num_clients=10, num_rounds=20,
        partition=PartitionConfig(
            kind="iid", iid_samples=500, resample_each_round=True),
    )
    # serverless_cancer_biobert_allclients.py: biobert (:39), sweep handled by
    # run_sweep(); single-config preset uses 10 clients. num_labels unified to
    # 41 (see module docstring on the reference's 3-vs-41 bug).
    p["serverless_cancer_biobert"] = _mk(
        "serverless_cancer_biobert", "biobert-base", hf,
        dataset="cancer", num_labels=41, mode="serverless", weighted_agg=False,
        num_clients=10, num_rounds=20,
        partition=PartitionConfig(
            kind="iid", iid_samples=500, resample_each_round=True),
    )

    # ---- extended capabilities the reference only describes ----
    # BC-FL: hash-chained ledger + PageRank gating + async gossip
    # (README.md:10; MT notebook cells 23-28)
    p["bcfl_async_pagerank"] = _mk(
        "bcfl_async_pagerank", "albert-base", hf,
        dataset="imdb", num_labels=2, mode="serverless", sync="async",
        weighted_agg=False, num_clients=10, num_rounds=20, async_buffer=4,
        partition=PartitionConfig(kind="iid", iid_samples=100,
                                  resample_each_round=True),
        topology=TopologyConfig(anomaly_filter="pagerank"),
        ledger=LedgerConfig(enabled=True),
    )
    # smoke: the reference's de-facto test = NUM_CLIENTS=2/NUM_ROUNDS=2
    # scale-down (serverless_cancer_classification_with_BioBERT.ipynb)
    p["smoke"] = FedConfig(
        name="smoke", model="tiny-bert", dataset="synthetic", num_labels=2,
        mode="serverless", weighted_agg=False, num_clients=2, num_rounds=2,
        seq_len=64, max_local_batches=2,
        partition=PartitionConfig(kind="iid", iid_samples=64),
    )
    return p


def get_preset(name: str, hf: bool = False) -> FedConfig:
    presets = build_presets(hf)
    if name not in presets:
        raise KeyError(f"unknown preset {name!r}; have {sorted(presets)}")
    return presets[name]


def list_presets() -> List[str]:
    return sorted(build_presets())


# the reference's worker sweep: ``for NUM_CLIENTS in [5, 10, 20]``
# (serverless_cancer_biobert_allclients.py:41)
SWEEP_CLIENTS = [5, 10, 20]
