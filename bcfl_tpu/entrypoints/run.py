"""``run(config)`` — the single entrypoint replacing all 11 reference scripts.

Prints the reference's metric set at the end (CPU overhead %, memory GB,
latency minutes, model size GB, per-round local/global accuracy — the prints
at ``serverless_NonIID_IMDB.py:320-334`` and ``server_IID_IMDB.py:221-233``),
plus the info-passing-time and ledger accounting the notebooks model offline.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

from bcfl_tpu.config import FedConfig
from bcfl_tpu.fed.engine import FedEngine, RunResult


def run(cfg: FedConfig, resume: bool = False, verbose: bool = True,
        fused_tamper=None) -> RunResult:
    """``fused_tamper``: optional ``(round) -> [num_clients] float scales or
    None`` — in-graph transport corruption for fused dispatches (the BC-FL
    tamper-resistance demo; see ``FedEngine.__init__``)."""
    if verbose:
        print("\n".join(_header(cfg)), flush=True)
    engine = FedEngine(cfg, fused_tamper=fused_tamper)
    result = engine.run(resume=resume,
                        on_round=_print_round if verbose else None)
    if verbose:
        print(format_report(cfg, result, rounds=False, header=False))
    return result


def _header(cfg: FedConfig) -> list:
    clients = f"clients={cfg.num_clients}"
    if cfg.registry_size:
        # cohort mode (SCALING.md): the stacked axis is the sampled cohort
        clients = (f"registry={cfg.registry_size} "
                   f"cohort={cfg.sample_clients or cfg.num_clients}/round")
    return [
        f"== {cfg.name} ==",
        f"mode={cfg.mode} sync={cfg.sync} {clients} "
        f"rounds={cfg.num_rounds} model={cfg.model} dataset={cfg.dataset}",
    ]


def _round_line(r) -> str:
    acc = f" global_acc={r.global_acc:.4f}" if r.global_acc is not None else ""
    anom = f" anomalies={r.anomalies}" if r.anomalies else ""
    # surface ledger rejections: a tampered/corrupted update failing auth is
    # the BC-FL flow's observable outcome and must not be silent
    rejected = ([i for i, a in enumerate(r.auth) if a == 0.0]
                if r.auth else [])
    rej = f" auth_failed={rejected}" if rejected else ""
    # chaos-harness observability (bcfl_tpu.faults): injected dropout and an
    # all-eliminated (model-kept) round must be visible in the stream
    drop = f" dropped={r.dropped}" if r.dropped else ""
    deg = " DEGRADED" if r.degraded else ""
    # peer-lifecycle observability (ROBUSTNESS.md §6): partition spans,
    # heals, churn absences, and quarantined/probation peers in the stream
    part = ""
    if r.partition is not None:
        comps = sorted(set(p for p in r.partition if p >= 0))
        part = f" PARTITIONED x{len(comps)}"
    if r.healed:
        part += " HEALED"
    gone = ([i for i, a in enumerate(r.churn_alive) if a == 0.0]
            if r.churn_alive else [])
    churn = f" churned_out={gone}" if gone else ""
    rep = ""
    if r.reputation_state is not None:
        q = [i for i, s in enumerate(r.reputation_state)
             if s == "quarantined"]
        p = [i for i, s in enumerate(r.reputation_state) if s == "probation"]
        if q:
            rep += f" quarantined={q}"
        if p:
            rep += f" probation={p}"
    return (f"round {r.round:3d}: train_loss={r.train_loss:.4f} "
            f"train_acc={r.train_acc:.4f}{acc}{anom}{rej}{drop}{part}"
            f"{churn}{rep}{deg} wall={r.wall_s:.2f}s")


def _print_round(r) -> None:
    print(_round_line(r), flush=True)


def format_report(cfg: FedConfig, result: RunResult, rounds: bool = True,
                  header: bool = True) -> str:
    """rounds=False / header=False omit the per-round lines / header (already
    streamed live by run(verbose=True) via the engine's on_round callback)."""
    m = result.metrics
    lines = _header(cfg) if header else []
    if rounds:
        lines.extend(_round_line(r) for r in m.rounds)
    # reference metric names (server_IID_IMDB.py:221-233, with the reversed
    # before/after memory naming fixed — SURVEY.md C11)
    lines.append(m.summary())
    if m.rounds and m.rounds[-1].info_passing_sync_s is not None:
        r = m.rounds[-1]
        lines.append(
            f"info passing time: sync={r.info_passing_sync_s:.3f}s "
            f"async={r.info_passing_async_s:.3f}s"
        )
    if m.ledger:
        lines.append("ledger: " + json.dumps(m.ledger))
    accs = m.global_accuracies
    lines.append(f"global_accuracies: {[round(a, 4) for a in accs]}")
    return "\n".join(lines)


def run_sweep(
    cfg: FedConfig,
    client_counts: Optional[List[int]] = None,
    resume: bool = False,
    verbose: bool = True,
    out_dir: Optional[str] = "results",
) -> Dict[int, RunResult]:
    """The reference's worker sweep (``for NUM_CLIENTS in [5,10,20]``,
    ``serverless_cancer_biobert_allclients.py:41``) over one config. Each
    client count checkpoints into its own subdirectory. ``out_dir`` gets
    the reference notebooks' sweep figure set (latency/accuracy/memory by
    client count — cells 15/18/21) plus ``<name>_sweep.json``; None skips
    recording."""
    import json
    import os

    from bcfl_tpu.entrypoints.presets import SWEEP_CLIENTS
    from bcfl_tpu.viz import sweep_report

    out: Dict[int, RunResult] = {}
    for n in client_counts or SWEEP_CLIENTS:
        ckpt = (os.path.join(cfg.checkpoint_dir, f"c{n}")
                if cfg.checkpoint_dir else None)
        out[n] = run(
            cfg.replace(name=f"{cfg.name}_c{n}", num_clients=n,
                        checkpoint_dir=ckpt),
            resume=resume, verbose=verbose)
    if out_dir:
        paths = sweep_report(out, out_dir, name=f"{cfg.name}_sweep")
        record = {
            str(n): {
                "final_acc": (r.metrics.global_accuracies[-1]
                              if r.metrics.global_accuracies else None),
                "latency_min": sum(x.wall_s for x in r.metrics.rounds) / 60.0,
                "memory_gb": r.metrics.resources.get("memory_gb"),
            } for n, r in out.items()
        }
        jpath = os.path.join(out_dir, f"{cfg.name}_sweep.json")
        with open(jpath, "w") as f:
            json.dump({"model": cfg.model, "dataset": cfg.dataset,
                       "rounds": cfg.num_rounds, "mode": cfg.mode,
                       "counts": sorted(out), "runs": record}, f, indent=2)
        if verbose:
            print(f"sweep artifacts: {jpath} + {len(paths)} figures",
                  flush=True)
    return out
