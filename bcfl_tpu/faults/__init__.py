from bcfl_tpu.faults.plan import (  # noqa: F401
    BYZ_BEHAVIORS,
    FaultInjector,
    FaultPlan,
    SimulatedCrash,
)
