from bcfl_tpu.faults.plan import (  # noqa: F401
    FaultInjector,
    FaultPlan,
    SimulatedCrash,
)
