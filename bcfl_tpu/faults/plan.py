"""Config-driven fault injection — the chaos layer.

The paper's pitch is decentralized FL that survives bad actors and bad
networks (the anomaly gating and the hash-chained ledger exist exactly for
that), yet until this module the engine could only be *attacked* through two
ad-hoc hooks (``tamper_hook`` host-tree tampering, ``fused_tamper`` in-graph
transport scales) and never *stressed*: no client dropout, no stragglers, no
host crashes. :class:`FaultPlan` turns those implicit failure assumptions
into one seeded, deterministic, config-level schedule:

- **dropout** — per-round Bernoulli client dropout, composed into the
  participation mask exactly like an anomaly-filter exclusion (the mesh
  shape never changes; dropped clients carry weight 0),
- **stragglers** — per-round simulated-clock delays, fed into
  :meth:`bcfl_tpu.topology.graph.LatencyGraph.info_passing_time` (sync
  accounting) and added to the async engine's per-client completion clock
  (so a straggler genuinely accumulates staleness),
- **corruption** — in-flight update corruption: per-round per-client
  additive scales applied to the *transported* copy of each update, the one
  API behind both legacy hooks (see :class:`FaultInjector`). With the ledger
  on, commit fingerprints are taken before transport and verification after,
  so corrupted clients fail authentication and are excluded; without the
  ledger, the robust aggregators (``FedConfig.aggregator``) are the defense.
  When communication compression is on (COMPRESSION.md) the transported
  quantity is the COMPRESSED payload, and the scales perturb its float
  parts (quantization scales / top-k values) — the chaos matrix exercises
  the actual wire format, not a tree the network never carried,
- **crash** — kill the round loop at a chosen round
  (:class:`SimulatedCrash`); a restart with ``resume=True`` must reproduce
  the uninterrupted run bit-for-bit (tests/test_faults.py pins this),
- **partition** — split the P2P mesh into isolated connected components for
  a span of rounds (``partition_rounds`` x ``partition_groups`` or a seeded
  ``partition_count``-way split). Each component aggregates independently
  with the configured aggregator during the span; on heal the components
  reconcile through the same rule (ROBUSTNESS.md §6). Expressed as
  per-round component ids — the device mesh never reshapes,
- **churn** — permanent client leave (``churn_leave``) and late join
  (``churn_join``), expressed as per-round alive masks composed into the
  participation mask exactly like dropout, except monotone: a departed
  client never comes back and a late joiner is absent before its join
  round. The mesh never reshapes; absent clients carry weight 0,
- **flaky** — per-client *intermittent* corruption bursts: a fixed flaky
  set (explicit ``flaky_clients`` or a seeded ``flaky_frac`` draw) corrupts
  transport during multi-round burst windows (``flaky_burst_len`` rounds
  per window, each window bad with ``flaky_on_prob``). This is the input
  that makes reputation-driven quarantine (bcfl_tpu.reputation)
  non-vacuous: the per-round Bernoulli ``corrupt_*`` lane has no repeat
  offenders to remember,
- **wire** — socket-level message faults for the dist runtime
  (``runtime="dist"`` only; RUNTIME.md "Delivery contract"): per-message
  drop / duplicate / reorder-hold / delay-jitter / byte-corruption, drawn
  per transmission attempt from ``(seed, lane, round, src, dst, msg_id,
  attempt)`` and injected at the socket boundary in
  :class:`bcfl_tpu.dist.transport.PeerTransport`. This is the failure mode
  real DCN actually exhibits — the lane the retry/dedup/CRC self-healing
  transport is validated against (``scripts/dist_chaos.py``). The local
  engine has no socket to inject at, so the capability table rejects the
  lane on ``runtime="local"``,
- **byzantine** — adversarial PEERS for the dist runtime
  (``runtime="dist"`` only; ROBUSTNESS.md §8 "Adversary model"): the
  ``byz_peers`` act maliciously per ``(peer, round)`` draw, injected
  *above* the wire in :class:`bcfl_tpu.dist.byzantine.ByzantineAdversary`
  — the frames are well-formed and correctly delivered (CRC passes, acks
  flow); it is their CONTENT that lies. Behaviors: scaled / sign-flipped /
  garbage update payloads (announced digests match, so ledger auth passes
  and only the robust merge + outlier evidence catch them), replayed stale
  updates (an old base version's payload resent verbatim), digest
  forgeries (announce one fingerprint, ship another — the leader's
  refingerprint-on-arrival catches it), and equivocation (different
  payload bytes to different destinations under one announced digest).
  Composable with the wire lane (a lying peer on a lossy network) and
  bounded by ``byz_rounds``. The local engine exchanges no forgeable wire
  headers, so the capability table rejects the lane on
  ``runtime="local"`` (use ``corrupt_prob``/``flaky_*`` for the simulated
  in-graph analogue),
- **storage** — durable-state damage for the dist runtime
  (``runtime="dist"`` only; ROBUSTNESS.md §10 "Durable-state adversary
  model"): each peer's freshly committed checkpoint is damaged at rest
  per ``(peer, version)`` draw — torn writes, payload/meta bit rot,
  truncation, deletion of the newest K rounds, ledger-chain tampering,
  and rollback to an older intact snapshot (see :data:`STORAGE_CLASSES`).
  Injected at the checkpoint write seam
  (:func:`bcfl_tpu.checkpoint.checkpoint.apply_storage_fault`), detected
  by the startup scrub, and recovered via the ledger-authenticated
  STATE_SYNC peer repair (RUNTIME.md "State-sync protocol").
  ``sync_tamper`` additionally corrupts the FIRST state-sync transfer a
  listed (server, requester) pair serves, proving the receiver-side
  refingerprint refuses unauthenticated state. The local engine has no
  per-peer durable state to damage, so the capability table rejects the
  lane on ``runtime="local"``,
- **limp** — gray failures for the dist runtime (``runtime="dist"``
  only; ROBUSTNESS.md §11 "Gray-failure adversary model"): peers that
  are SLOW BUT ALIVE, the failure mode binary crash detectors either
  miss or flap on. Per ``(peer, round)`` draw a limping peer stalls its
  train step (injected sleep at the train seam, beside the straggler
  sleep) and its links degrade to ``limp_throttle_bps`` — throttle
  draws are DIRECTION-keyed (A→B can limp while B→A stays healthy;
  ``limp_oneway`` restricts to the limp peer's outbound side).
  Supervisor-driven SIGSTOP/SIGCONT pauses ride the harness
  (``run_dist(limp=...)``), same split as churn. The proportional
  response — phi-accrual suspicion, adaptive deadlines, w_slow
  down-weighting that can never quarantine — is what this lane grades,
- **resource** — durable-write failures for the dist runtime
  (``runtime="dist"`` only; ROBUSTNESS.md §11): ENOSPC/EMFILE drawn per
  ``(seam, write-counter, peer)`` at the moment a durable write is
  attempted (checkpoint commit, ledger append, EventWriter flush — see
  :data:`RESOURCE_SEAMS`). The runtime's response ladder — emergency
  retention GC, then telemetry shed (sampled events first, never
  ledger/checkpoint bytes), then a distinct exit code when a round
  cannot be made durable — is what this lane grades. Unlike lane 8
  (storage) this lane never damages bytes at rest: the write FAILS
  cleanly and the process stays alive to respond.

Everything is derived from ``(seed, fault lane, round)`` via
``np.random.default_rng`` — two engines with equal plans draw identical
fault schedules, which is what makes crash/resume and A/B comparisons
meaningful. The plan is a frozen dataclass so it can live inside
:class:`bcfl_tpu.config.FedConfig` (hashable, comparable, replace()-able).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import numpy as np


class SimulatedCrash(RuntimeError):
    """Raised by the engine when a :class:`FaultPlan` schedules a host crash.

    Carries ``round`` so harnesses can assert where the run died before
    restarting it from the last checkpoint."""

    def __init__(self, round_idx: int):
        super().__init__(
            f"FaultPlan injected a host crash at round {round_idx}")
        self.round = round_idx


# fault lanes: each fault class draws from its own RNG stream so enabling
# one never perturbs another's schedule (a dropout sweep must not reshuffle
# which clients get corrupted)
_LANE_DROPOUT = 1
_LANE_STRAGGLER = 2
_LANE_CORRUPT = 3
_LANE_PARTITION = 4
_LANE_FLAKY = 5
_LANE_WIRE = 6
_LANE_BYZ = 7
_LANE_STORAGE = 8
_LANE_LIMP = 9
_LANE_RESOURCE = 10

# the byzantine lane's behavior vocabulary (ROBUSTNESS.md §8): every name a
# plan may draw, in the canonical order the seeded choice indexes into
BYZ_BEHAVIORS = ("scale", "sign_flip", "garbage", "replay", "digest_forge",
                 "equivocate")

# the storage lane's damage-class vocabulary (ROBUSTNESS.md §10): every
# class a plan may draw, in the canonical order the seeded choice indexes
# into. Each names one way a peer's DURABLE state (checkpoint payload, meta
# sidecar, ledger chain) gets damaged at rest:
#   torn         — the payload commit is interrupted mid-write (a staging
#                  dir left where the committed round dir should be),
#   payload_flip — one checkpoint payload byte flipped (silent bit rot),
#   meta_flip    — one meta-sidecar byte flipped (digest/chain JSON rot),
#   truncate     — the payload loses its tail (partial fsync loss),
#   delete       — the newest K checkpoints removed outright,
#   ledger       — one committed chain row tampered inside the newest meta
#                  (the chain no longer verifies against its own links),
#   rollback     — the whole checkpoint dir replaced by an older intact
#                  snapshot (the restored-from-stale-backup case; locally
#                  undetectable — only the chain high-water guard and peer
#                  repair catch it).
STORAGE_CLASSES = ("torn", "payload_flip", "meta_flip", "truncate",
                   "delete", "ledger", "rollback")

# the resource lane's failure-class vocabulary (ROBUSTNESS.md §11): every
# class a plan may draw, in the canonical order the seeded choice indexes
# into. Each names one way a durable write FAILS while the process stays
# alive (the lane never damages bytes at rest — lane 8 owns that):
#   enospc — the filesystem is full: the write raises ENOSPC with nothing
#            landed (all-or-nothing; the runtime's GC → shed → exit
#            ladder owns the response),
#   emfile — the fd table is exhausted: the open raises EMFILE before any
#            byte is written (same ladder; the GC step frees handles too).
RESOURCE_CLASSES = ("enospc", "emfile")

# the resource lane's seam vocabulary: every durable-write seam the dist
# runtime consults the lane at, in canonical index order (the index keys
# the seeded draw, so "checkpoint" draws never collide with "events"
# draws at equal counters)
RESOURCE_SEAMS = ("checkpoint", "ledger", "events")


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Deterministic per-round fault schedule. The all-defaults plan injects
    nothing (``enabled`` is False) — it is the no-op value every config
    carries.

    ``*_rounds`` fields restrict a fault class to an explicit round tuple
    (None = every round); probabilities are per-client Bernoulli draws from
    the seeded stream. ``dropout_prob=1.0`` with ``dropout_rounds=(k,)`` is
    the deterministic "every client vanishes in round k" scenario the
    degraded-round handling exists for."""

    seed: int = 0
    # client dropout: each client independently sits the round out
    dropout_prob: float = 0.0
    dropout_rounds: Optional[Tuple[int, ...]] = None
    # stragglers: affected clients finish `straggler_delay_s` late
    straggler_prob: float = 0.0
    straggler_delay_s: float = 30.0
    straggler_rounds: Optional[Tuple[int, ...]] = None
    # transport corruption: affected clients' shipped updates arrive with
    # `corrupt_scale` added to every parameter (the fused `_transport`
    # semantics — an exact float perturbation, never a silent no-op)
    corrupt_prob: float = 0.0
    corrupt_scale: float = 1e6
    corrupt_rounds: Optional[Tuple[int, ...]] = None
    # host crash: the engine raises SimulatedCrash at the START of this
    # round (anything checkpointed before it survives; nothing after runs).
    # Models ONE host failure: a resumed run (``engine.run(resume=True)``)
    # does not re-fire it — resume restarts at or before the crash round,
    # so re-firing would make the crash -> resume workflow unpassable
    crash_at_round: Optional[int] = None
    # network partition: during `partition_rounds` the mesh splits into
    # isolated components. `partition_groups` lists them explicitly (every
    # client in exactly one group — validated against the client count by
    # FaultInjector); alternatively `partition_count` >= 2 draws a stable
    # seeded `count`-way split (constant across the whole plan, so the
    # components never reshuffle mid-span).
    partition_rounds: Optional[Tuple[int, ...]] = None
    partition_groups: Optional[Tuple[Tuple[int, ...], ...]] = None
    partition_count: int = 0
    # churn: ((client, round), ...) schedules. A `churn_leave` client is
    # gone from its round onward (permanently); a `churn_join` client is
    # absent before its round (late join).
    churn_leave: Optional[Tuple[Tuple[int, int], ...]] = None
    churn_join: Optional[Tuple[Tuple[int, int], ...]] = None
    # flaky peers: intermittent corruption bursts. The flaky set is
    # `flaky_clients` plus a seeded `flaky_frac` draw; rounds are grouped
    # into `flaky_burst_len`-round windows and each (window) is bad with
    # `flaky_on_prob` (per-client draws), corrupting transport with
    # `flaky_scale` for the whole window.
    flaky_clients: Optional[Tuple[int, ...]] = None
    flaky_frac: float = 0.0
    flaky_burst_len: int = 3
    flaky_on_prob: float = 0.5
    flaky_scale: float = 1e6
    # wire lane (runtime="dist" only): per-message socket-level faults,
    # drawn per transmission attempt by `wire_actions`. `wire_drop_prob`
    # loses the frame (the sender learns only via the missing ack),
    # `wire_dup_prob` delivers a second copy (the receiver's dedup window
    # must absorb it), `wire_reorder_prob` holds the frame for
    # `wire_reorder_hold_s` at the receiver so later frames overtake it,
    # `wire_delay_prob` sleeps a uniform [0, wire_delay_s) jitter before
    # the send, and `wire_corrupt_prob` flips payload bytes in flight (the
    # frame CRC must catch it). `wire_rounds` bounds the lane to a span of
    # the sender's wire clock (None = every round) — the knob the
    # "recovers when the chaos clears" legs use.
    wire_drop_prob: float = 0.0
    wire_dup_prob: float = 0.0
    wire_reorder_prob: float = 0.0
    wire_reorder_hold_s: float = 0.25
    wire_delay_prob: float = 0.0
    wire_delay_s: float = 0.2
    wire_corrupt_prob: float = 0.0
    wire_rounds: Optional[Tuple[int, ...]] = None
    # byzantine lane (runtime="dist" only): `byz_peers` are adversarial —
    # each acts per (peer, round) with probability `byz_prob`, drawing one
    # behavior from `byz_behaviors` (a subset of BYZ_BEHAVIORS; see
    # `byz_action`). `byz_scale` is the payload perturbation magnitude for
    # scale/garbage; `byz_rounds` bounds the lane to a span of the
    # adversary's local-round clock (None = every round) — the knob the
    # "recovers after the adversary goes quiet" legs use.
    byz_peers: Optional[Tuple[int, ...]] = None
    byz_behaviors: Tuple[str, ...] = BYZ_BEHAVIORS
    byz_prob: float = 1.0
    byz_scale: float = 25.0
    byz_rounds: Optional[Tuple[int, ...]] = None
    # storage lane (runtime="dist" only): durable-state damage drawn per
    # (peer, version) at the checkpoint write seam. `storage_peers` bounds
    # the victims (None = every peer), each commit is damaged with
    # `storage_prob`, the class drawn from `storage_classes` (a subset of
    # STORAGE_CLASSES), `storage_delete_last` is K for the delete class,
    # and `storage_rounds` bounds the lane to a span of the peer's version
    # clock (None = every version). `sync_tamper` lists (server, requester)
    # pairs whose FIRST state-sync transfer is byte-tampered in flight —
    # the seeded needle proving the refingerprint refusal path fires.
    storage_peers: Optional[Tuple[int, ...]] = None
    storage_prob: float = 0.0
    storage_classes: Tuple[str, ...] = STORAGE_CLASSES
    storage_delete_last: int = 1
    storage_rounds: Optional[Tuple[int, ...]] = None
    sync_tamper: Optional[Tuple[Tuple[int, int], ...]] = None
    # limp lane (runtime="dist" only): gray failures — peers slow but
    # alive. `limp_peers` bounds the victims (None = every peer); each
    # limps per (peer, round) with `limp_prob`. A limp draw stalls the
    # peer's train step `limp_stall_s` seconds (the CPU-starved/swapping
    # case) and, when `limp_throttle_bps` > 0, degrades its links to that
    # byte rate for the round. Throttle draws are DIRECTION-keyed — the
    # (src, dst) and (dst, src) directions draw independently — and
    # `limp_oneway` restricts eligibility to the limp peer's OUTBOUND
    # direction (A→B limps while B→A stays healthy). `limp_rounds` bounds
    # the lane to a span of the peer's local-round clock (None = every
    # round). Supervisor-side SIGSTOP pauses are the harness's job
    # (run_dist(limp=...)), not a plan draw — the same split as churn.
    limp_peers: Optional[Tuple[int, ...]] = None
    limp_prob: float = 0.0
    limp_stall_s: float = 2.0
    limp_throttle_bps: float = 0.0
    limp_oneway: bool = False
    limp_rounds: Optional[Tuple[int, ...]] = None
    # resource lane (runtime="dist" only): durable-write failures drawn
    # per (seam, counter, peer) at the moment a durable write is attempted
    # (RESOURCE_SEAMS: checkpoint commit, ledger append, EventWriter
    # flush). `resource_peers` bounds the victims (None = every peer),
    # each write fails with `resource_prob`, the class drawn from
    # `resource_classes` (a subset of RESOURCE_CLASSES), and
    # `resource_rounds` bounds the lane to a span of the seam's own write
    # counter (None = every write).
    resource_peers: Optional[Tuple[int, ...]] = None
    resource_prob: float = 0.0
    resource_classes: Tuple[str, ...] = RESOURCE_CLASSES
    resource_rounds: Optional[Tuple[int, ...]] = None

    def __post_init__(self):
        for name in ("dropout_prob", "straggler_prob", "corrupt_prob"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {p}")
        if self.straggler_delay_s < 0:
            raise ValueError(
                f"straggler_delay_s must be >= 0, got {self.straggler_delay_s}")
        if not np.isfinite(self.corrupt_scale):
            raise ValueError("corrupt_scale must be finite (NaN/Inf would "
                             "poison the fingerprint comparison itself)")
        for name in ("dropout_rounds", "straggler_rounds", "corrupt_rounds"):
            r = getattr(self, name)
            if r is not None and not isinstance(r, tuple):
                raise ValueError(
                    f"{name} must be a tuple of round indices (hashable — "
                    f"the plan lives inside the frozen FedConfig), got "
                    f"{type(r).__name__}")
        if self.crash_at_round is not None and self.crash_at_round < 0:
            raise ValueError(
                f"crash_at_round must be >= 0, got {self.crash_at_round}")
        # --- partition lane ---
        if self.partition_groups is not None:
            if not (isinstance(self.partition_groups, tuple) and all(
                    isinstance(g, tuple) for g in self.partition_groups)):
                raise ValueError(
                    "partition_groups must be a tuple of client-index "
                    "tuples (hashable — the plan lives inside the frozen "
                    "FedConfig)")
            if not self.partition_groups:
                raise ValueError("partition_groups must name at least one "
                                 "component (unlisted clients form one "
                                 "extra component; the effective count is "
                                 "validated against the client count by "
                                 "FaultInjector)")
            flat = [c for g in self.partition_groups for c in g]
            if len(flat) != len(set(flat)) or any(c < 0 for c in flat):
                raise ValueError(
                    "partition_groups must be disjoint non-negative client "
                    f"indices, got {self.partition_groups}")
        if self.partition_count < 0 or self.partition_count == 1:
            raise ValueError(
                f"partition_count must be 0 (off) or >= 2, got "
                f"{self.partition_count}")
        if self.partition_groups is not None and self.partition_count:
            raise ValueError("give partition_groups OR partition_count, "
                             "not both")
        if self.partitions and self.partition_rounds is None:
            raise ValueError(
                "a partition plan needs partition_rounds (the span of "
                "rounds the mesh stays split)")
        if self.partition_rounds is not None:
            if not isinstance(self.partition_rounds, tuple):
                raise ValueError("partition_rounds must be a tuple of round "
                                 "indices")
            if not self.partition_rounds:
                # an empty span (e.g. a typo'd START:END with START >= END)
                # would make every chaos-matrix partition check pass
                # vacuously — the exact silent no-op this lane must not have
                raise ValueError(
                    "partition_rounds is empty: the partition would "
                    "silently never fire (check the span bounds)")
            if not self.partitions:
                raise ValueError(
                    "partition_rounds without partition_groups or "
                    "partition_count would silently never partition")
        # --- churn lane ---
        for name in ("churn_leave", "churn_join"):
            sched = getattr(self, name)
            if sched is None:
                continue
            if not (isinstance(sched, tuple)
                    and all(isinstance(e, tuple) and len(e) == 2
                            and e[0] >= 0 and e[1] >= 0 for e in sched)):
                raise ValueError(
                    f"{name} must be a tuple of (client, round) pairs of "
                    f"non-negative ints, got {sched!r}")
            if len({c for c, _ in sched}) != len(sched):
                raise ValueError(f"{name} lists a client twice: {sched!r}")
        if self.churn_leave and self.churn_join:
            j = dict((c, r) for c, r in self.churn_join)
            for c, r in self.churn_leave:
                if c in j and j[c] >= r:
                    raise ValueError(
                        f"client {c} would join at round {j[c]} after "
                        f"leaving at round {r}; churn is permanent")
        # --- flaky lane ---
        if self.flaky_clients is not None and not isinstance(
                self.flaky_clients, tuple):
            raise ValueError("flaky_clients must be a tuple of client "
                             "indices")
        if not 0.0 <= self.flaky_frac <= 1.0:
            raise ValueError(
                f"flaky_frac must be in [0, 1], got {self.flaky_frac}")
        if not 0.0 <= self.flaky_on_prob <= 1.0:
            raise ValueError(
                f"flaky_on_prob must be in [0, 1], got {self.flaky_on_prob}")
        if self.flaky_burst_len < 1:
            raise ValueError(
                f"flaky_burst_len must be >= 1, got {self.flaky_burst_len}")
        if not np.isfinite(self.flaky_scale):
            raise ValueError("flaky_scale must be finite (same fingerprint-"
                             "poisoning concern as corrupt_scale)")
        # --- wire lane ---
        for name in ("wire_drop_prob", "wire_dup_prob", "wire_reorder_prob",
                     "wire_delay_prob", "wire_corrupt_prob"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {p}")
        for name in ("wire_reorder_hold_s", "wire_delay_s"):
            v = getattr(self, name)
            if v < 0 or not np.isfinite(v):
                raise ValueError(f"{name} must be finite and >= 0, got {v}")
        if self.wire_rounds is not None:
            if not isinstance(self.wire_rounds, tuple):
                raise ValueError("wire_rounds must be a tuple of round "
                                 "indices (hashable — the plan lives inside "
                                 "the frozen FedConfig)")
            if not self.wire_rounds:
                raise ValueError(
                    "wire_rounds is empty: the wire lane would silently "
                    "never fire (check the span bounds)")
            if not self.wire_enabled:
                raise ValueError(
                    "wire_rounds without any wire_*_prob > 0 would "
                    "silently never inject a wire fault")
        # --- byzantine lane ---
        if self.byz_peers is not None:
            if not (isinstance(self.byz_peers, tuple)
                    and all(isinstance(p, int) and p >= 0
                            for p in self.byz_peers)):
                raise ValueError(
                    "byz_peers must be a tuple of non-negative peer ids "
                    "(hashable — the plan lives inside the frozen "
                    "FedConfig)")
            if len(set(self.byz_peers)) != len(self.byz_peers):
                raise ValueError(
                    f"byz_peers lists a peer twice: {self.byz_peers!r}")
        if not (isinstance(self.byz_behaviors, tuple) and self.byz_behaviors):
            raise ValueError("byz_behaviors must be a non-empty tuple")
        bad = [b for b in self.byz_behaviors if b not in BYZ_BEHAVIORS]
        if bad:
            raise ValueError(
                f"unknown byzantine behaviors {bad}; known: "
                f"{BYZ_BEHAVIORS}")
        if not 0.0 <= self.byz_prob <= 1.0:
            raise ValueError(
                f"byz_prob must be in [0, 1], got {self.byz_prob}")
        if not np.isfinite(self.byz_scale):
            raise ValueError("byz_scale must be finite (NaN/Inf would "
                             "poison the very aggregates the robust merge "
                             "is graded on tolerating)")
        if self.byz_rounds is not None:
            if not isinstance(self.byz_rounds, tuple):
                raise ValueError("byz_rounds must be a tuple of round "
                                 "indices (hashable — the plan lives "
                                 "inside the frozen FedConfig)")
            if not self.byz_rounds:
                raise ValueError(
                    "byz_rounds is empty: the byzantine lane would "
                    "silently never fire (check the span bounds)")
            if not self.byz_enabled:
                raise ValueError(
                    "byz_rounds without byz_peers would silently never "
                    "inject an adversarial behavior")
        if self.byz_peers is not None and self.byz_prob <= 0.0:
            raise ValueError(
                "byz_peers with byz_prob=0 would silently never act — "
                "the exact vacuous-pass this lane must not have")
        # --- storage lane ---
        if not 0.0 <= self.storage_prob <= 1.0:
            raise ValueError(
                f"storage_prob must be in [0, 1], got {self.storage_prob}")
        if self.storage_peers is not None:
            if not (isinstance(self.storage_peers, tuple)
                    and all(isinstance(p, int) and p >= 0
                            for p in self.storage_peers)):
                raise ValueError(
                    "storage_peers must be a tuple of non-negative peer ids "
                    "(hashable — the plan lives inside the frozen "
                    "FedConfig)")
            if len(set(self.storage_peers)) != len(self.storage_peers):
                raise ValueError(
                    f"storage_peers lists a peer twice: {self.storage_peers!r}")
            if self.storage_prob <= 0.0:
                raise ValueError(
                    "storage_peers with storage_prob=0 would silently never "
                    "damage anything — the exact vacuous-pass this lane "
                    "must not have")
        if not (isinstance(self.storage_classes, tuple)
                and self.storage_classes):
            raise ValueError("storage_classes must be a non-empty tuple")
        bad = [c for c in self.storage_classes if c not in STORAGE_CLASSES]
        if bad:
            raise ValueError(
                f"unknown storage damage classes {bad}; known: "
                f"{STORAGE_CLASSES}")
        if self.storage_delete_last < 1:
            raise ValueError(
                f"storage_delete_last must be >= 1, got "
                f"{self.storage_delete_last}")
        if self.storage_rounds is not None:
            if not isinstance(self.storage_rounds, tuple):
                raise ValueError("storage_rounds must be a tuple of version "
                                 "indices (hashable — the plan lives inside "
                                 "the frozen FedConfig)")
            if not self.storage_rounds:
                raise ValueError(
                    "storage_rounds is empty: the storage lane would "
                    "silently never fire (check the span bounds)")
            if self.storage_prob <= 0.0:
                raise ValueError(
                    "storage_rounds without storage_prob > 0 would "
                    "silently never damage a checkpoint")
        if self.sync_tamper is not None:
            if not (isinstance(self.sync_tamper, tuple)
                    and all(isinstance(e, tuple) and len(e) == 2
                            and isinstance(e[0], int) and isinstance(e[1], int)
                            and e[0] >= 0 and e[1] >= 0 and e[0] != e[1]
                            for e in self.sync_tamper)):
                raise ValueError(
                    "sync_tamper must be a tuple of distinct-id (server, "
                    f"requester) peer pairs, got {self.sync_tamper!r}")
            if len(set(self.sync_tamper)) != len(self.sync_tamper):
                raise ValueError(
                    f"sync_tamper lists a pair twice: {self.sync_tamper!r}")
        # --- limp lane ---
        if not 0.0 <= self.limp_prob <= 1.0:
            raise ValueError(
                f"limp_prob must be in [0, 1], got {self.limp_prob}")
        if self.limp_peers is not None:
            if not (isinstance(self.limp_peers, tuple)
                    and all(isinstance(p, int) and p >= 0
                            for p in self.limp_peers)):
                raise ValueError(
                    "limp_peers must be a tuple of non-negative peer ids "
                    "(hashable — the plan lives inside the frozen "
                    "FedConfig)")
            if len(set(self.limp_peers)) != len(self.limp_peers):
                raise ValueError(
                    f"limp_peers lists a peer twice: {self.limp_peers!r}")
            if self.limp_prob <= 0.0:
                raise ValueError(
                    "limp_peers with limp_prob=0 would silently never limp "
                    "— the exact vacuous-pass this lane must not have")
        for name in ("limp_stall_s", "limp_throttle_bps"):
            v = getattr(self, name)
            if v < 0 or not np.isfinite(v):
                raise ValueError(f"{name} must be finite and >= 0, got {v}")
        if (self.limp_prob > 0 and self.limp_stall_s <= 0
                and self.limp_throttle_bps <= 0):
            raise ValueError(
                "limp_prob > 0 with limp_stall_s=0 and limp_throttle_bps=0 "
                "injects nothing — the exact silent no-op this lane must "
                "not have")
        if self.limp_rounds is not None:
            if not isinstance(self.limp_rounds, tuple):
                raise ValueError("limp_rounds must be a tuple of round "
                                 "indices (hashable — the plan lives inside "
                                 "the frozen FedConfig)")
            if not self.limp_rounds:
                raise ValueError(
                    "limp_rounds is empty: the limp lane would silently "
                    "never fire (check the span bounds)")
            if self.limp_prob <= 0.0:
                raise ValueError(
                    "limp_rounds without limp_prob > 0 would silently "
                    "never limp a peer")
        # --- resource lane ---
        if not 0.0 <= self.resource_prob <= 1.0:
            raise ValueError(
                f"resource_prob must be in [0, 1], got {self.resource_prob}")
        if self.resource_peers is not None:
            if not (isinstance(self.resource_peers, tuple)
                    and all(isinstance(p, int) and p >= 0
                            for p in self.resource_peers)):
                raise ValueError(
                    "resource_peers must be a tuple of non-negative peer "
                    "ids (hashable — the plan lives inside the frozen "
                    "FedConfig)")
            if len(set(self.resource_peers)) != len(self.resource_peers):
                raise ValueError(
                    f"resource_peers lists a peer twice: "
                    f"{self.resource_peers!r}")
            if self.resource_prob <= 0.0:
                raise ValueError(
                    "resource_peers with resource_prob=0 would silently "
                    "never fail a write — the exact vacuous-pass this lane "
                    "must not have")
        if not (isinstance(self.resource_classes, tuple)
                and self.resource_classes):
            raise ValueError("resource_classes must be a non-empty tuple")
        bad = [c for c in self.resource_classes if c not in RESOURCE_CLASSES]
        if bad:
            raise ValueError(
                f"unknown resource failure classes {bad}; known: "
                f"{RESOURCE_CLASSES}")
        if self.resource_rounds is not None:
            if not isinstance(self.resource_rounds, tuple):
                raise ValueError("resource_rounds must be a tuple of write-"
                                 "counter indices (hashable — the plan "
                                 "lives inside the frozen FedConfig)")
            if not self.resource_rounds:
                raise ValueError(
                    "resource_rounds is empty: the resource lane would "
                    "silently never fire (check the span bounds)")
            if self.resource_prob <= 0.0:
                raise ValueError(
                    "resource_rounds without resource_prob > 0 would "
                    "silently never fail a durable write")

    # ------------------------------------------------------------------ query

    @property
    def enabled(self) -> bool:
        return (self.dropout_prob > 0 or self.straggler_prob > 0
                or self.corrupt_prob > 0 or self.crash_at_round is not None
                or self.partitions or self.churns or self.flaky_enabled
                or self.wire_enabled or self.byz_enabled
                or self.storage_enabled or self.limp_enabled
                or self.resource_enabled)

    @property
    def wire_enabled(self) -> bool:
        return (self.wire_drop_prob > 0 or self.wire_dup_prob > 0
                or self.wire_reorder_prob > 0 or self.wire_delay_prob > 0
                or self.wire_corrupt_prob > 0)

    @property
    def byz_enabled(self) -> bool:
        return bool(self.byz_peers)

    @property
    def storage_enabled(self) -> bool:
        return self.storage_prob > 0 or bool(self.sync_tamper)

    @property
    def limp_enabled(self) -> bool:
        return self.limp_prob > 0

    @property
    def resource_enabled(self) -> bool:
        return self.resource_prob > 0

    @property
    def partitions(self) -> bool:
        return (self.partition_groups is not None
                or self.partition_count >= 2)

    @property
    def churns(self) -> bool:
        return bool(self.churn_leave) or bool(self.churn_join)

    @property
    def flaky_enabled(self) -> bool:
        return bool(self.flaky_clients) or self.flaky_frac > 0

    @property
    def corrupts(self) -> bool:
        # flaky IS transport corruption (burst-scheduled), so every
        # corruption-path requirement (mix_recv, faithful-mode rejection,
        # tamper_hook exclusivity) applies to it identically
        return self.corrupt_prob > 0 or self.flaky_enabled

    def _rng(self, lane: int, rnd: int) -> np.random.Generator:
        return np.random.default_rng((self.seed, lane, rnd))

    def _due(self, rounds: Optional[Tuple[int, ...]], rnd: int) -> bool:
        return rounds is None or rnd in rounds

    # ------------------------------------------------------------- per-round

    def dropout_keep(self, rnd: int, num_clients: int) -> Optional[np.ndarray]:
        """[C] float 0/1 keep-mask (0 = client sits this round out), or None
        when dropout is not scheduled for ``rnd``."""
        if self.dropout_prob <= 0 or not self._due(self.dropout_rounds, rnd):
            return None
        draw = self._rng(_LANE_DROPOUT, rnd).random(num_clients)
        return (draw >= self.dropout_prob).astype(np.float32)

    def straggler_delays(self, rnd: int,
                         num_clients: int) -> Optional[np.ndarray]:
        """[C] float seconds of extra completion delay, or None when no
        straggler is scheduled for ``rnd``."""
        if self.straggler_prob <= 0 or not self._due(self.straggler_rounds,
                                                     rnd):
            return None
        draw = self._rng(_LANE_STRAGGLER, rnd).random(num_clients)
        delays = np.where(draw < self.straggler_prob,
                          self.straggler_delay_s, 0.0)
        return delays.astype(np.float64) if delays.any() else None

    def transport_scales(self, rnd: int,
                         num_clients: int) -> Optional[np.ndarray]:
        """[C] float32 additive transport-corruption scales (0 = clean), or
        None when no corruption is scheduled for ``rnd``."""
        if self.corrupt_prob <= 0 or not self._due(self.corrupt_rounds, rnd):
            return None
        draw = self._rng(_LANE_CORRUPT, rnd).random(num_clients)
        row = np.where(draw < self.corrupt_prob, self.corrupt_scale, 0.0)
        return row.astype(np.float32) if row.any() else None

    def should_crash(self, rnd: int) -> bool:
        return self.crash_at_round is not None and rnd == self.crash_at_round

    def partition_components(
            self, rnd: int,
            num_clients: int) -> Optional[Tuple[Tuple[int, ...], ...]]:
        """The round's connected components as client-index tuples, or None
        when the mesh is whole this round. The assignment is constant for
        the whole plan (seeded once, not per round), so a multi-round span
        keeps stable components and A/B seeds compare like the other
        lanes. Under the dist runtime ``rnd`` is each peer's OWN local
        round (the PartitionGate's autonomous clock): peers evaluate this
        at different wall instants, and the constant assignment is what
        guarantees they still agree on component membership — under
        gossip dispatch there is no shared clock at all."""
        if not self.partitions or self.partition_rounds is None:
            return None
        if rnd not in self.partition_rounds:
            return None
        if self.partition_groups is not None:
            groups = [list(g) for g in self.partition_groups]
            covered = {c for g in groups for c in g}
            rest = [c for c in range(num_clients) if c not in covered]
            if rest:
                # clients the spec doesn't mention form their own component
                # (an explicit 2-group spec over 10 clients partitions the
                # other 8 together, not out of existence)
                groups.append(rest)
            return tuple(tuple(g) for g in groups if g)
        count = min(self.partition_count, num_clients)
        perm = self._rng(_LANE_PARTITION, 0).permutation(num_clients)
        groups = [sorted(int(c) for c in perm[i::count])
                  for i in range(count)]
        return tuple(tuple(g) for g in groups)

    def churn_alive(self, rnd: int,
                    num_clients: int) -> Optional[np.ndarray]:
        """[C] float 0/1 alive-mask (0 = permanently left, or not yet
        joined), or None when no churn is scheduled. Monotone per client:
        once 0 by leave it stays 0; once 1 by join it stays 1 until (if
        ever) its leave round."""
        if not self.churns:
            return None
        alive = np.ones((num_clients,), np.float32)
        for c, r in (self.churn_join or ()):
            if c < num_clients and rnd < r:
                alive[c] = 0.0
        for c, r in (self.churn_leave or ()):
            if c < num_clients and rnd >= r:
                alive[c] = 0.0
        return alive

    def flaky_set(self, num_clients: int) -> np.ndarray:
        """[C] bool: which clients are flaky (explicit list + seeded
        fraction draw; constant for the whole plan)."""
        flaky = np.zeros((num_clients,), bool)
        for c in (self.flaky_clients or ()):
            if c < num_clients:
                flaky[c] = True
        if self.flaky_frac > 0:
            draw = self._rng(_LANE_FLAKY, 0).random(num_clients)
            flaky |= draw < self.flaky_frac
        return flaky

    def flaky_scales(self, rnd: int,
                     num_clients: int) -> Optional[np.ndarray]:
        """[C] float32 additive transport-corruption scales from the flaky
        lane (0 = clean), or None when no flaky client bursts this round.
        Rounds are grouped into ``flaky_burst_len`` windows; each window is
        independently bad per flaky client, so an offending client corrupts
        for ``burst_len`` CONSECUTIVE rounds — the repeat-offender signature
        reputation quarantine exists for."""
        if not self.flaky_enabled:
            return None
        flaky = self.flaky_set(num_clients)
        if not flaky.any():
            return None
        window = rnd // self.flaky_burst_len
        # window draws come from lane (seed, FLAKY, 1 + window): offset by 1
        # so they never collide with the flaky-set draw at (seed, FLAKY, 0)
        draw = self._rng(_LANE_FLAKY, 1 + window).random(num_clients)
        row = np.where(flaky & (draw < self.flaky_on_prob),
                       self.flaky_scale, 0.0)
        return row.astype(np.float32) if row.any() else None

    def wire_actions(self, rnd: int, src: int, dst: int, msg_id: int,
                     attempt: int = 0) -> Optional[dict]:
        """Socket-level fault draw for ONE transmission attempt of message
        ``(src, dst, msg_id)`` while the sender's wire clock reads ``rnd``
        (the peer's local round, the same clock the partition gate uses).
        Returns None when the lane is off or not due this round, else a
        dict of actions:

        - ``drop``: lose this attempt's frame (no delivery, no ack),
        - ``dup``: after a successful delivery, send a second copy,
        - ``reorder_s``: > 0 — the receiver holds the frame this long
          before enqueueing, letting later frames overtake it,
        - ``delay_s``: pre-send jitter sleep,
        - ``corrupt``: flip payload bytes after the CRC is computed,
        - ``corrupt_pos``: fractions in [0, 1) choosing which bytes flip.

        The draw includes ``attempt`` so a retried frame re-rolls its fate
        — a ``wire_drop_prob < 1`` lane cannot black-hole a message forever
        — while identical (clock, ids, attempt) coordinates always replay
        the identical fault."""
        if not self.wire_enabled or not self._due(self.wire_rounds, rnd):
            return None
        rng = self._wire_rng(rnd, src, dst, msg_id, attempt)
        draw = rng.random(5)
        delay = 0.0
        if self.wire_delay_prob > 0 and draw[3] < self.wire_delay_prob:
            delay = float(rng.random() * self.wire_delay_s)
        return {
            "drop": bool(draw[0] < self.wire_drop_prob),
            "dup": bool(draw[1] < self.wire_dup_prob),
            "reorder_s": (self.wire_reorder_hold_s
                          if draw[2] < self.wire_reorder_prob else 0.0),
            "delay_s": delay,
            "corrupt": bool(draw[4] < self.wire_corrupt_prob),
            "corrupt_pos": tuple(float(x) for x in rng.random(4)),
        }

    def _wire_rng(self, rnd: int, src: int, dst: int, msg_id: int,
                  attempt: int) -> np.random.Generator:
        return np.random.default_rng(
            (self.seed, _LANE_WIRE, rnd, src, dst, msg_id, attempt))

    def byz_action(self, rnd: int, peer: int) -> Optional[dict]:
        """Adversarial-behavior draw for ONE update of ``peer`` while its
        local-round clock reads ``rnd`` (the same autonomous clock the
        partition and wire lanes use). Returns None when the peer is
        honest, the lane is off, the span is not due, or the ``byz_prob``
        draw says "behave this round"; else::

            {"behavior": <one of this plan's byz_behaviors>,
             "scale": byz_scale}

        Identical ``(seed, rnd, peer)`` coordinates always draw the
        identical behavior — the injection is replayable, which is what
        lets the unit tests pin per-behavior determinism and the chaos
        legs assert exact evidence trails. Payload mutations draw their
        noise separately via :meth:`byz_rng` keyed by the same coordinates
        plus the destination (equivocation differs per destination BY
        construction)."""
        if not self.byz_enabled or peer not in self.byz_peers:
            return None
        if not self._due(self.byz_rounds, rnd):
            return None
        rng = np.random.default_rng((self.seed, _LANE_BYZ, rnd, peer))
        if rng.random() >= self.byz_prob:
            return None
        pick = int(rng.integers(len(self.byz_behaviors)))
        return {"behavior": self.byz_behaviors[pick],
                "scale": float(self.byz_scale)}

    def byz_rng(self, rnd: int, peer: int, dst: int) -> np.random.Generator:
        """Noise stream for one (adversary, round, destination) payload
        mutation — destination-keyed, so equivocation ships DIFFERENT
        deterministic bytes to different receivers while the same
        coordinates always replay the same bytes."""
        return np.random.default_rng(
            (self.seed, _LANE_BYZ, rnd, peer, dst, 1))

    def storage_action(self, version: int, peer: int) -> Optional[dict]:
        """Durable-state damage draw for ONE freshly committed checkpoint
        of ``peer`` at ``version`` (the peer's global-version clock — the
        round index its ``round_XXXXXX`` dir carries). Returns None when
        the peer keeps its state intact, else::

            {"cls": <one of this plan's storage_classes>,
             "frac": <float in [0, 1) — the byte-offset fraction the flip/
                      truncate classes damage at>,
             "delete_last": storage_delete_last}

        Identical ``(seed, version, peer)`` coordinates always draw the
        identical damage — the injection is replayable, which is what lets
        the unit tests pin per-class determinism and the soak assert every
        class actually fired. The draw is consumed by
        :func:`bcfl_tpu.checkpoint.checkpoint.apply_storage_fault` AFTER
        the commit+fsync completes: the lane models media failure of
        durable state, never an interrupted writer (the ``torn`` class
        fabricates the leftover staging dir itself)."""
        if self.storage_prob <= 0:
            return None
        if self.storage_peers is not None and peer not in self.storage_peers:
            return None
        if not self._due(self.storage_rounds, version):
            return None
        rng = np.random.default_rng(
            (self.seed, _LANE_STORAGE, version, peer))
        if rng.random() >= self.storage_prob:
            return None
        pick = int(rng.integers(len(self.storage_classes)))
        return {"cls": self.storage_classes[pick],
                "frac": float(rng.random()),
                "delete_last": int(self.storage_delete_last)}

    def sync_tamper_action(self, server: int, requester: int,
                           serial: int) -> Optional[dict]:
        """In-flight tamper draw for ONE state-sync transfer ``server`` is
        about to serve ``requester`` (``serial`` counts that pair's serves,
        0-based). Only the FIRST serve of a pair listed in ``sync_tamper``
        is tampered — the requester refuses it (refingerprint mismatch),
        re-requests, and the clean retry proves recovery; tampering every
        serve would wedge the repair loop instead of needling it. Returns
        ``{"frac": <byte-offset fraction to flip>}`` or None."""
        if not self.sync_tamper or serial != 0:
            return None
        if (server, requester) not in self.sync_tamper:
            return None
        rng = np.random.default_rng(
            (self.seed, _LANE_STORAGE, server, requester, 1))
        return {"frac": float(rng.random())}

    def limp_action(self, rnd: int, peer: int) -> Optional[dict]:
        """Gray-failure draw for ONE round of ``peer`` while its
        local-round clock reads ``rnd`` (the same autonomous clock the
        straggler and byzantine lanes use). Returns None when the peer
        runs at full speed, else::

            {"stall_s": <train-seam sleep, seconds>,
             "throttle_bps": <link byte rate this round; 0 = unthrottled>}

        Identical ``(seed, rnd, peer)`` coordinates always draw the
        identical limp — replayable, so the unit tests pin determinism
        and the soak can assert exactly which rounds limped. The stall is
        injected at the train seam (beside the straggler sleep); the
        throttle component is consumed per-direction via
        :meth:`limp_throttle` (a round-level draw here, direction-level
        draws there — a peer can limp without every link limping)."""
        if not self.limp_enabled:
            return None
        if self.limp_peers is not None and peer not in self.limp_peers:
            return None
        if not self._due(self.limp_rounds, rnd):
            return None
        rng = np.random.default_rng((self.seed, _LANE_LIMP, rnd, peer))
        if rng.random() >= self.limp_prob:
            return None
        return {"stall_s": float(self.limp_stall_s),
                "throttle_bps": float(self.limp_throttle_bps)}

    def limp_throttle(self, rnd: int, src: int, dst: int) -> Optional[float]:
        """Direction-keyed link throttle for transmissions ``src -> dst``
        while the sender's wire clock reads ``rnd``. Returns the byte
        rate (bytes/s) the direction is degraded to, or None when it is
        healthy. The draw is keyed by the ORDERED pair — (src, dst) and
        (dst, src) draw independently, so A→B can limp while B→A stays
        healthy — and with ``limp_oneway`` only the limp peer's OUTBOUND
        direction is ever eligible (the asymmetric-link case one-way
        gray failures exhibit)."""
        if not self.limp_enabled or self.limp_throttle_bps <= 0:
            return None
        if not self._due(self.limp_rounds, rnd):
            return None
        if not (self._is_limp_peer(src)
                or (not self.limp_oneway and self._is_limp_peer(dst))):
            return None
        rng = np.random.default_rng(
            (self.seed, _LANE_LIMP, rnd, src, dst, 1))
        if rng.random() >= self.limp_prob:
            return None
        return float(self.limp_throttle_bps)

    def _is_limp_peer(self, peer: int) -> bool:
        return self.limp_peers is None or peer in self.limp_peers

    def resource_action(self, seam: str, counter: int,
                        peer: int) -> Optional[dict]:
        """Durable-write failure draw for ONE write attempt at ``seam``
        (a :data:`RESOURCE_SEAMS` name) while that seam's write counter
        reads ``counter`` (checkpoint: the version being committed;
        ledger: the append index; events: the flush sequence). Returns
        None when the write proceeds, else::

            {"cls": <one of this plan's resource_classes>,
             "depth": 1 | 2 | 3}

        ``depth`` is how far up the response ladder the fault persists:
        a depth-1 fault clears after emergency retention GC (the freed
        space was enough), depth 2 clears only after telemetry shed, and
        depth 3 survives every remedy — the peer must exit with the
        durability code rather than silently commit un-durable state.

        Identical ``(seed, seam, counter, peer)`` coordinates always draw
        the identical failure — replayable, which is what lets the unit
        tests pin the GC → shed → exit ladder against exact injection
        points. The draw is consumed by the dist runtime BEFORE the write
        lands: the lane models the write call failing cleanly (ENOSPC /
        EMFILE), never bytes damaged at rest (lane 8 owns that)."""
        if not self.resource_enabled:
            return None
        if (self.resource_peers is not None
                and peer not in self.resource_peers):
            return None
        if not self._due(self.resource_rounds, counter):
            return None
        seam_idx = RESOURCE_SEAMS.index(seam)   # unknown seam fails loud
        rng = np.random.default_rng(
            (self.seed, _LANE_RESOURCE, seam_idx, counter, peer))
        if rng.random() >= self.resource_prob:
            return None
        pick = int(rng.integers(len(self.resource_classes)))
        depth = 1 + int(rng.integers(3))
        return {"cls": self.resource_classes[pick], "depth": depth}


class FaultInjector:
    """Binds a :class:`FaultPlan` to one engine run (fixed client count) and
    hosts the two legacy corruption hooks as deprecated shims:

    - ``host_tamper`` (né ``tamper_hook``): ``(rnd, host_stacked) -> tree``
      byte-level tampering of HOST trees — forces the faithful full
      byte-hash ledger flow and the per-round path,
    - ``fused_tamper``: ``(rnd) -> [C] scales or None`` — in-graph transport
      corruption for FUSED dispatches only (a request landing on a
      per-round-path round still fails loudly, engine semantics unchanged).

    New code expresses corruption through ``FaultPlan.corrupt_*``, which
    works on BOTH the per-round path (split-phase commit -> transport ->
    verify) and composes with every aggregator. The engine consults only
    this adapter, so the three corruption sources cannot drift apart.
    """

    def __init__(self, plan: Optional[FaultPlan], num_clients: int,
                 host_tamper: Optional[Callable] = None,
                 fused_tamper: Optional[Callable] = None):
        self.plan = plan if plan is not None else FaultPlan()
        self.num_clients = int(num_clients)
        self.host_tamper = host_tamper
        self.fused_tamper = fused_tamper
        if self.plan.corrupts and host_tamper is not None:
            raise ValueError(
                "FaultPlan corruption and the legacy tamper_hook are two "
                "transport models for the same updates — pick one (the "
                "tamper_hook shim exists only for byte-level host tampering)")
        p = self.plan
        if p.partition_groups is not None:
            bad = [c for g in p.partition_groups for c in g
                   if c >= self.num_clients]
            if bad:
                raise ValueError(
                    f"partition_groups name clients {bad} but the run has "
                    f"only {self.num_clients} clients")
            covered = {c for g in p.partition_groups for c in g}
            rest = self.num_clients - len(covered)
            if len(p.partition_groups) + (1 if rest else 0) < 2:
                raise ValueError(
                    "partition_groups split nothing: the spec covers every "
                    f"client in {len(p.partition_groups)} component(s) and "
                    "leaves no unlisted clients to form another — a "
                    "partition needs >= 2 effective components")
        if p.partition_count > self.num_clients:
            raise ValueError(
                f"partition_count {p.partition_count} > num_clients "
                f"{self.num_clients}: components would be empty")
        for name in ("churn_leave", "churn_join", "flaky_clients"):
            sched = getattr(p, name) or ()
            ids = [e[0] if isinstance(e, tuple) else e for e in sched]
            bad = [c for c in ids if c >= self.num_clients]
            if bad:
                raise ValueError(
                    f"{name} names clients {bad} but the run has only "
                    f"{self.num_clients} clients")

    # thin per-round delegates (client count already bound)
    def dropout_keep(self, rnd: int) -> Optional[np.ndarray]:
        return self.plan.dropout_keep(rnd, self.num_clients)

    def straggler_delays(self, rnd: int) -> Optional[np.ndarray]:
        return self.plan.straggler_delays(rnd, self.num_clients)

    def transport_scales(self, rnd: int) -> Optional[np.ndarray]:
        """Per-round Bernoulli corruption + flaky burst corruption, summed:
        both lanes are additive transport perturbations and ONE call site
        decides 'is corruption scheduled' for the round."""
        base = self.plan.transport_scales(rnd, self.num_clients)
        flaky = self.plan.flaky_scales(rnd, self.num_clients)
        if flaky is None:
            return base
        if base is None:
            return flaky
        return (base + flaky).astype(np.float32)

    def partition_components(self, rnd: int):
        return self.plan.partition_components(rnd, self.num_clients)

    def churn_alive(self, rnd: int) -> Optional[np.ndarray]:
        return self.plan.churn_alive(rnd, self.num_clients)

    def should_crash(self, rnd: int) -> bool:
        return self.plan.should_crash(rnd)

    def blocks_fusion(self) -> bool:
        """Any scheduled plan fault forces the per-round path: dropout,
        churn, and partition perturb the mask/topology, stragglers and
        crashes need the host clock/loop between rounds, and plan
        corruption (incl. flaky bursts) runs the split-phase transport
        stage (the fused in-graph stage remains reachable via the
        ``fused_tamper`` shim, which does not block fusion)."""
        return self.plan.enabled
