"""Config-driven fault injection — the chaos layer.

The paper's pitch is decentralized FL that survives bad actors and bad
networks (the anomaly gating and the hash-chained ledger exist exactly for
that), yet until this module the engine could only be *attacked* through two
ad-hoc hooks (``tamper_hook`` host-tree tampering, ``fused_tamper`` in-graph
transport scales) and never *stressed*: no client dropout, no stragglers, no
host crashes. :class:`FaultPlan` turns those implicit failure assumptions
into one seeded, deterministic, config-level schedule:

- **dropout** — per-round Bernoulli client dropout, composed into the
  participation mask exactly like an anomaly-filter exclusion (the mesh
  shape never changes; dropped clients carry weight 0),
- **stragglers** — per-round simulated-clock delays, fed into
  :meth:`bcfl_tpu.topology.graph.LatencyGraph.info_passing_time` (sync
  accounting) and added to the async engine's per-client completion clock
  (so a straggler genuinely accumulates staleness),
- **corruption** — in-flight update corruption: per-round per-client
  additive scales applied to the *transported* copy of each update, the one
  API behind both legacy hooks (see :class:`FaultInjector`). With the ledger
  on, commit fingerprints are taken before transport and verification after,
  so corrupted clients fail authentication and are excluded; without the
  ledger, the robust aggregators (``FedConfig.aggregator``) are the defense.
  When communication compression is on (COMPRESSION.md) the transported
  quantity is the COMPRESSED payload, and the scales perturb its float
  parts (quantization scales / top-k values) — the chaos matrix exercises
  the actual wire format, not a tree the network never carried,
- **crash** — kill the round loop at a chosen round
  (:class:`SimulatedCrash`); a restart with ``resume=True`` must reproduce
  the uninterrupted run bit-for-bit (tests/test_faults.py pins this).

Everything is derived from ``(seed, fault lane, round)`` via
``np.random.default_rng`` — two engines with equal plans draw identical
fault schedules, which is what makes crash/resume and A/B comparisons
meaningful. The plan is a frozen dataclass so it can live inside
:class:`bcfl_tpu.config.FedConfig` (hashable, comparable, replace()-able).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

import numpy as np


class SimulatedCrash(RuntimeError):
    """Raised by the engine when a :class:`FaultPlan` schedules a host crash.

    Carries ``round`` so harnesses can assert where the run died before
    restarting it from the last checkpoint."""

    def __init__(self, round_idx: int):
        super().__init__(
            f"FaultPlan injected a host crash at round {round_idx}")
        self.round = round_idx


# fault lanes: each fault class draws from its own RNG stream so enabling
# one never perturbs another's schedule (a dropout sweep must not reshuffle
# which clients get corrupted)
_LANE_DROPOUT = 1
_LANE_STRAGGLER = 2
_LANE_CORRUPT = 3


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Deterministic per-round fault schedule. The all-defaults plan injects
    nothing (``enabled`` is False) — it is the no-op value every config
    carries.

    ``*_rounds`` fields restrict a fault class to an explicit round tuple
    (None = every round); probabilities are per-client Bernoulli draws from
    the seeded stream. ``dropout_prob=1.0`` with ``dropout_rounds=(k,)`` is
    the deterministic "every client vanishes in round k" scenario the
    degraded-round handling exists for."""

    seed: int = 0
    # client dropout: each client independently sits the round out
    dropout_prob: float = 0.0
    dropout_rounds: Optional[Tuple[int, ...]] = None
    # stragglers: affected clients finish `straggler_delay_s` late
    straggler_prob: float = 0.0
    straggler_delay_s: float = 30.0
    straggler_rounds: Optional[Tuple[int, ...]] = None
    # transport corruption: affected clients' shipped updates arrive with
    # `corrupt_scale` added to every parameter (the fused `_transport`
    # semantics — an exact float perturbation, never a silent no-op)
    corrupt_prob: float = 0.0
    corrupt_scale: float = 1e6
    corrupt_rounds: Optional[Tuple[int, ...]] = None
    # host crash: the engine raises SimulatedCrash at the START of this
    # round (anything checkpointed before it survives; nothing after runs).
    # Models ONE host failure: a resumed run (``engine.run(resume=True)``)
    # does not re-fire it — resume restarts at or before the crash round,
    # so re-firing would make the crash -> resume workflow unpassable
    crash_at_round: Optional[int] = None

    def __post_init__(self):
        for name in ("dropout_prob", "straggler_prob", "corrupt_prob"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {p}")
        if self.straggler_delay_s < 0:
            raise ValueError(
                f"straggler_delay_s must be >= 0, got {self.straggler_delay_s}")
        if not np.isfinite(self.corrupt_scale):
            raise ValueError("corrupt_scale must be finite (NaN/Inf would "
                             "poison the fingerprint comparison itself)")
        for name in ("dropout_rounds", "straggler_rounds", "corrupt_rounds"):
            r = getattr(self, name)
            if r is not None and not isinstance(r, tuple):
                raise ValueError(
                    f"{name} must be a tuple of round indices (hashable — "
                    f"the plan lives inside the frozen FedConfig), got "
                    f"{type(r).__name__}")
        if self.crash_at_round is not None and self.crash_at_round < 0:
            raise ValueError(
                f"crash_at_round must be >= 0, got {self.crash_at_round}")

    # ------------------------------------------------------------------ query

    @property
    def enabled(self) -> bool:
        return (self.dropout_prob > 0 or self.straggler_prob > 0
                or self.corrupt_prob > 0 or self.crash_at_round is not None)

    @property
    def corrupts(self) -> bool:
        return self.corrupt_prob > 0

    def _rng(self, lane: int, rnd: int) -> np.random.Generator:
        return np.random.default_rng((self.seed, lane, rnd))

    def _due(self, rounds: Optional[Tuple[int, ...]], rnd: int) -> bool:
        return rounds is None or rnd in rounds

    # ------------------------------------------------------------- per-round

    def dropout_keep(self, rnd: int, num_clients: int) -> Optional[np.ndarray]:
        """[C] float 0/1 keep-mask (0 = client sits this round out), or None
        when dropout is not scheduled for ``rnd``."""
        if self.dropout_prob <= 0 or not self._due(self.dropout_rounds, rnd):
            return None
        draw = self._rng(_LANE_DROPOUT, rnd).random(num_clients)
        return (draw >= self.dropout_prob).astype(np.float32)

    def straggler_delays(self, rnd: int,
                         num_clients: int) -> Optional[np.ndarray]:
        """[C] float seconds of extra completion delay, or None when no
        straggler is scheduled for ``rnd``."""
        if self.straggler_prob <= 0 or not self._due(self.straggler_rounds,
                                                     rnd):
            return None
        draw = self._rng(_LANE_STRAGGLER, rnd).random(num_clients)
        delays = np.where(draw < self.straggler_prob,
                          self.straggler_delay_s, 0.0)
        return delays.astype(np.float64) if delays.any() else None

    def transport_scales(self, rnd: int,
                         num_clients: int) -> Optional[np.ndarray]:
        """[C] float32 additive transport-corruption scales (0 = clean), or
        None when no corruption is scheduled for ``rnd``."""
        if self.corrupt_prob <= 0 or not self._due(self.corrupt_rounds, rnd):
            return None
        draw = self._rng(_LANE_CORRUPT, rnd).random(num_clients)
        row = np.where(draw < self.corrupt_prob, self.corrupt_scale, 0.0)
        return row.astype(np.float32) if row.any() else None

    def should_crash(self, rnd: int) -> bool:
        return self.crash_at_round is not None and rnd == self.crash_at_round


class FaultInjector:
    """Binds a :class:`FaultPlan` to one engine run (fixed client count) and
    hosts the two legacy corruption hooks as deprecated shims:

    - ``host_tamper`` (né ``tamper_hook``): ``(rnd, host_stacked) -> tree``
      byte-level tampering of HOST trees — forces the faithful full
      byte-hash ledger flow and the per-round path,
    - ``fused_tamper``: ``(rnd) -> [C] scales or None`` — in-graph transport
      corruption for FUSED dispatches only (a request landing on a
      per-round-path round still fails loudly, engine semantics unchanged).

    New code expresses corruption through ``FaultPlan.corrupt_*``, which
    works on BOTH the per-round path (split-phase commit -> transport ->
    verify) and composes with every aggregator. The engine consults only
    this adapter, so the three corruption sources cannot drift apart.
    """

    def __init__(self, plan: Optional[FaultPlan], num_clients: int,
                 host_tamper: Optional[Callable] = None,
                 fused_tamper: Optional[Callable] = None):
        self.plan = plan if plan is not None else FaultPlan()
        self.num_clients = int(num_clients)
        self.host_tamper = host_tamper
        self.fused_tamper = fused_tamper
        if self.plan.corrupts and host_tamper is not None:
            raise ValueError(
                "FaultPlan corruption and the legacy tamper_hook are two "
                "transport models for the same updates — pick one (the "
                "tamper_hook shim exists only for byte-level host tampering)")

    # thin per-round delegates (client count already bound)
    def dropout_keep(self, rnd: int) -> Optional[np.ndarray]:
        return self.plan.dropout_keep(rnd, self.num_clients)

    def straggler_delays(self, rnd: int) -> Optional[np.ndarray]:
        return self.plan.straggler_delays(rnd, self.num_clients)

    def transport_scales(self, rnd: int) -> Optional[np.ndarray]:
        return self.plan.transport_scales(rnd, self.num_clients)

    def should_crash(self, rnd: int) -> bool:
        return self.plan.should_crash(rnd)

    def blocks_fusion(self) -> bool:
        """Any scheduled plan fault forces the per-round path: dropout
        perturbs the mask, stragglers and crashes need the host clock/loop
        between rounds, and plan corruption runs the split-phase transport
        stage (the fused in-graph stage remains reachable via the
        ``fused_tamper`` shim, which does not block fusion)."""
        return self.plan.enabled
