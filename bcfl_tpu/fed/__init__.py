from bcfl_tpu.fed.client_step import FedPrograms, build_programs  # noqa: F401
