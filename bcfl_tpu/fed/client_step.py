"""The compiled federated round: every client's local fine-tune + the
aggregation collective in ONE XLA program.

Reference equivalents (SURVEY.md §3):

- local step (hot loop): 1-epoch AdamW lr=5e-5 full fine-tune, fresh optimizer
  per round — ``train``, ``src/Servercase/server_IID_IMDB.py:108-118`` and
  ``IMDBClient.train_model``, ``serverless_NonIID_IMDB.py:188-199``. Here it is
  a ``lax.scan`` over static-shape batches, vmapped over the stacked clients of
  each device, ``shard_map``-ped over the mesh.
- server aggregation: Flower FedAvg (``server_IID_IMDB.py:205-218``) ->
  :func:`bcfl_tpu.parallel.masked_weighted_mean` (psum).
- serverless aggregation: all-client unweighted mean
  (``serverless_NonIID_IMDB.py:296``) -> masked ring gossip
  (:func:`bcfl_tpu.parallel.gossip_mix`, ppermute) or exact mean when
  ``gossip_steps == 0``.

Trainable tree is either the full param tree (reference behaviour) or a LoRA
adapter tree over a frozen base (``frozen``), chosen by the engine; the round
program is identical.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from bcfl_tpu.compression import CompressionConfig, codecs as cc
from bcfl_tpu.core.compat import shard_map
from bcfl_tpu.core.mesh import ClientMesh
from bcfl_tpu.ledger.fingerprint import client_fingerprint, tree_fingerprint
from bcfl_tpu.models import lora as lora_lib
from bcfl_tpu.parallel import gspmd
from bcfl_tpu.parallel.collectives import gossip_mix, masked_weighted_mean

Tree = Any


def make_optimizer(name: str, lr: float, max_grad_norm: float = 0.0):
    """Reference: fresh ``AdamW(lr=5e-5)`` torch defaults each round
    (``server_IID_IMDB.py:109``); torch AdamW weight_decay default is 0.01."""
    if name == "adamw":
        tx = optax.adamw(lr, b1=0.9, b2=0.999, eps=1e-8, weight_decay=0.01)
    elif name == "sgd":
        tx = optax.sgd(lr)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if max_grad_norm and max_grad_norm > 0:
        tx = optax.chain(optax.clip_by_global_norm(max_grad_norm), tx)
    return tx


def _merge(trainable: Tree, frozen: Optional[Tree]) -> Tree:
    """Full fine-tune: trainable IS the param tree. LoRA: merge adapters into
    the frozen base."""
    if frozen is None:
        return trainable
    return lora_lib.apply_lora(frozen, trainable)


def make_loss_fn(model, task: str = "classification") -> Callable:
    """Per-batch loss + (correct, n) stats, shared by train and eval.

    ``classification``: softmax CE over the label column (reference task).
    ``causal_lm``: next-token CE — targets are ``ids`` shifted left, token
    positions weighted by the padding mask x example mask; ``n`` counts
    TOKENS, so the engine's loss/acc normalization is per-token.
    """

    def _forward(trainable, frozen, batch, rng):
        params = _merge(trainable, frozen)
        return model.apply(
            {"params": params}, batch["ids"], batch["mask"],
            deterministic=rng is None,
            rngs=None if rng is None else {"dropout": rng},
        )

    def loss_cls(trainable, frozen, batch, rng):
        logits = _forward(trainable, frozen, batch, rng)
        labels = batch["labels"]
        ex = batch["example_mask"].astype(jnp.float32)
        per_ex = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
        n = jnp.maximum(ex.sum(), 1.0)
        loss = (per_ex * ex).sum() / n
        correct = ((jnp.argmax(logits, -1) == labels).astype(jnp.float32) * ex).sum()
        return loss, (correct, ex.sum())

    def loss_lm(trainable, frozen, batch, rng):
        logits = _forward(trainable, frozen, batch, rng)  # [B, S, V]
        targets = batch["ids"][:, 1:]
        logits = logits[:, :-1]
        w = (batch["mask"][:, 1:].astype(jnp.float32)
             * batch["example_mask"].astype(jnp.float32)[:, None])
        per_tok = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets)
        n = jnp.maximum(w.sum(), 1.0)
        loss = (per_tok * w).sum() / n
        correct = ((jnp.argmax(logits, -1) == targets).astype(jnp.float32)
                   * w).sum()
        return loss, (correct, w.sum())

    if task == "classification":
        return loss_cls
    if task == "causal_lm":
        return loss_lm
    raise ValueError(f"unknown task {task!r}")


def _unstack_rng(r, impl=None):
    # rngs arrive as stacked key-data uint32 [..., K] (threefry K=2,
    # rbg K=4); rebuild typed keys. impl=None follows jax's default —
    # passing an explicit impl makes the programs independent of the
    # process-global config (FedConfig.prng_impl).
    return jax.random.wrap_key_data(r, impl=impl)


def make_eval_one(loss_fn) -> Callable:
    """(trainable, frozen, batches) -> summed [loss*n, correct, n] over the
    scanned eval batches. Shared by both program implementations."""

    def eval_one(trainable, frozen, batches):
        def step(carry, batch):
            loss, (correct, n) = loss_fn(trainable, frozen, batch, None)
            return carry, jnp.stack([loss * n, correct, n])

        _, stats = lax.scan(step, 0.0, batches)
        return stats.sum(axis=0)

    return eval_one


def make_broadcast(mesh: ClientMesh) -> Callable:
    """global tree -> stacked per-client tree [C, ...] on the clients axis."""

    def broadcast(global_t):
        return jax.device_put(
            jax.tree.map(
                lambda x: jnp.broadcast_to(x[None], (mesh.num_clients,) + x.shape), global_t
            ),
            mesh.client_sharding(),
        )

    return broadcast


def _adopt_pull(client_t: Tree, global_t: Tree, pull: jnp.ndarray) -> Tree:
    """Pull-masked clients adopt the replicated ``global_t`` (broadcast
    fused into the select); everyone else keeps their stacked row. THE
    definition of the ``adopt`` program body — both impl builders wrap
    exactly this, so the select semantics cannot drift between them."""
    return jax.tree.map(
        lambda x, g: jnp.where(
            pull.reshape((-1,) + (1,) * (x.ndim - 1)) > 0,
            jnp.broadcast_to(g, x.shape).astype(x.dtype), x),
        client_t, global_t)


def _exact_mean_spread(avg: Tree, new_t: Tree, mask: jnp.ndarray) -> Tree:
    """Serverless exact-mean aggregation: every unmasked client adopts the
    (mask-weighted) average, masked clients keep their own state. Shared by
    both implementations' ``gossip_steps == 0`` path."""
    return jax.tree.map(
        lambda a, x: jnp.where(
            mask.reshape((-1,) + (1,) * (x.ndim - 1)) > 0,
            jnp.broadcast_to(a, x.shape), x),
        avg, new_t,
    )


def make_local_train(tx, loss_fn) -> Callable:
    """One client's local round: fresh optimizer state (reference semantics,
    ``server_IID_IMDB.py:109``), ``lax.scan`` over static-shape batches.
    ``(trainable, frozen, batches, rng) -> (trainable, [loss*n, correct, n])``.
    Shared by the 1-D clients mesh programs and the clients x tp composition
    (:mod:`bcfl_tpu.parallel.fed_tp`)."""

    def local_train(trainable, frozen, batches, rng):
        opt_state = tx.init(trainable)
        steps = batches["ids"].shape[0]
        step_rngs = jax.random.split(rng, steps)

        def step(carry, xs):
            t, opt = carry
            batch, r = xs
            (loss, (correct, n)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(t, frozen, batch, r)
            updates, opt = tx.update(grads, opt, t)
            t = optax.apply_updates(t, updates)
            return (t, opt), jnp.stack([loss * n, correct, n])

        (trainable, _), stats = lax.scan(
            step, (trainable, opt_state), (batches, step_rngs))
        return trainable, stats.sum(axis=0)

    return local_train


@dataclasses.dataclass
class FedPrograms:
    """Compiled round/eval programs bound to one (model, mesh, optimizer)."""

    mesh: ClientMesh
    server_round: Callable  # (global_t, frozen, batches, weights, rngs) -> (global_t, metrics)
    server_rounds: Callable  # R rounds in one program; batches/weights/rngs leaves [R, C, ...]
    server_rounds_static: Callable  # same, ONE batch tree [C, ...] reused every round
    gossip_round: Callable  # (client_t, frozen, batches, mask, rngs) -> (client_t, metrics)
    gossip_rounds: Callable  # R gossip rounds in one program; batches/masks/rngs leaves [R, C, ...]
    gossip_rounds_static: Callable  # same, ONE batch tree [C, ...] reused every round
    eval_clients: Callable  # (client_t, frozen, batches) -> per-client [C, 3] stats
    eval_clients_global: Callable  # (global_t, frozen, batches) -> per-client [C, 3] stats
    eval_global: Callable  # (trainable, frozen, batches) -> [loss*n, correct, n]
    broadcast: Callable  # global_t -> stacked client_t [C, ...]
    collapse: Callable  # (stacked client_t, weights, fallback) -> global mean
    # split-phase programs for the ledger flow (commit -> verify -> aggregate)
    # and the async engine:
    client_updates: Callable  # (global_t, frozen, batches, rngs) -> (stacked_t, metrics)
    local_updates: Callable  # (client_t, frozen, batches, rngs) -> (stacked_t, metrics)
    mix_only: Callable  # (client_t, mask, start_t) -> client_t (gossip mix / full mean)
    single_update: Callable  # (trainable, frozen, batches, rng) -> (trainable, stats);
    # un-shard_mapped single client, used by the reference-faithful sequential
    # serverless mode (SURVEY.md §3.2)
    # device-side ledger digests (bcfl_tpu.ledger.fingerprint) — [C, K] / [K]
    # content fingerprints so the ledger never pulls the full tree to host:
    fingerprint: Optional[Callable] = None  # stacked client_t -> [C, K]
    fingerprint_one: Optional[Callable] = None  # trainable -> [K]
    # transport-aware serverless mix for the split-phase corruption flow
    # (faults.FaultPlan): (self_t, recv_t, mask, start_t) -> client_t —
    # neighbor/aggregate terms from the TRANSPORTED tree, self-terms from
    # the honest local tree (gspmd impl only)
    mix_recv: Optional[Callable] = None
    # (client_t, global_t, pull) -> client_t: pull-masked clients adopt the
    # replicated global (broadcast fused into the select — ONE dispatch, no
    # materialized [C, ...] broadcast buffer). Used by the async engine's
    # post-merge pull and the chaos-partition scatter/heal (component
    # members adopt their component aggregate / the reconciled global);
    # both impls compile it.
    adopt: Optional[Callable] = None
    # --- communication-compression programs (COMPRESSION.md; gspmd impl
    # only, present iff the builder's CompressionConfig is enabled). When
    # compression is on, the round/fused programs above change signature:
    # their first argument and first result become the carry tuple
    # ``(params_tree, ef_residual)`` — the error-feedback residual rides the
    # round state so compression error never accumulates. Split-phase twins:
    # (new_t, ref_t, resid, rngs) -> (payload, recon, resid'); ref is the
    # REPLICATED global (server) or the stacked round-start params
    # (serverless/async). ``recon`` is the clean-transport reconstruction
    # (ref + decoded delta) computed inside the encode program — the
    # roundtrip already decodes to derive the residual, so returning it
    # saves the engine a redundant full-tree decode on every uncorrupted
    # ledger round (corrupted rounds re-decode the TRANSPORTED payload via
    # decode_recon)
    encode_deltas: Optional[Callable] = None
    encode_deltas_local: Optional[Callable] = None
    # async twin WITHOUT the recon output: the async merge decodes the
    # (possibly corrupted) transported payload itself via decode_delta, so
    # a returned recon would be computed and thrown away every round
    encode_deltas_async: Optional[Callable] = None
    # (payload, ref_t, like_t) -> stacked recon tree (ref + decoded delta,
    # cast back to the param dtype) — what the receivers aggregate/mix
    decode_recon: Optional[Callable] = None
    # (payload, like_t) -> stacked decoded delta (param dtype) — async merge
    decode_delta: Optional[Callable] = None
    # (payload, [C] scales) -> transport-corrupted payload (float parts only)
    corrupt_payload: Optional[Callable] = None
    # (trainable_like) -> [C, ...] f32 zero error-feedback state
    ef_init: Optional[Callable] = None
    # fused-round twins that ALSO emit each round's per-client update
    # fingerprints [R, C, K] (gspmd impl only — the ledger can then fuse):
    server_rounds_fp: Optional[Callable] = None
    server_rounds_static_fp: Optional[Callable] = None
    gossip_rounds_fp: Optional[Callable] = None
    gossip_rounds_static_fp: Optional[Callable] = None


def build_programs(
    model,
    mesh: ClientMesh,
    optimizer: str = "adamw",
    learning_rate: float = 5e-5,
    max_grad_norm: float = 0.0,
    gossip_alpha: float = 0.5,
    gossip_steps: int = 1,
    task: str = "classification",
    # Byzantine-robust aggregation rule (parallel.gspmd.AGGREGATORS,
    # ROBUSTNESS.md). A build-time static: each choice is its own compiled
    # program, so switching it never retraces inside a run. gspmd impl only;
    # shard_map supports "mean".
    aggregator: str = "mean",
    aggregator_trim: float = 0.2,
    # typed-key impl for the stacked per-client rngs: None follows jax's
    # process default; "rbg" opts into the TPU hardware generator
    # (dropout RNG is +38% of step time under threefry, PERF.md)
    prng_impl: Optional[str] = None,
    # communication compression for the update exchange (COMPRESSION.md).
    # A build-time static like the aggregator: every CompressionConfig is
    # its own compiled program set (the config is part of the program-cache
    # key below), so switching codecs never retraces inside a run. None or
    # kind='none' builds EXACTLY today's uncompressed programs — that path
    # is untouched, bit-for-bit. gspmd impl only.
    compression: Optional[CompressionConfig] = None,
    # donate=True deletes the caller's input param/opt buffers after each call
    # (halves peak HBM for the round-chained engine); leave False if you reuse
    # the input tree afterwards.
    donate: bool = False,
    # hierarchical=True compiles the explicit two-level device -> global
    # aggregation (gspmd.hierarchical_weighted_mean) into every mean
    # aggregation point — cohort mode's within-cohort-then-cross-device
    # reduction (SCALING.md). Only meaningful for aggregator='mean' (the
    # robust order statistics are global by definition) and only the gspmd
    # impl compiles it; normalized away otherwise so equal program sets
    # share one cache entry.
    hierarchical: bool = False,
    # Two numerically-identical implementations of the same programs:
    #   "gspmd"     (default) — global stacked-client arrays under plain jit
    #               with sharding annotations; XLA's SPMD partitioner inserts
    #               the collectives. Measured ~200x faster than shard_map on
    #               the tunnelled single-chip TPU platform (PERF.md).
    #   "shard_map" — explicit psum/ppermute manual SPMD
    #               (bcfl_tpu.parallel.collectives).
    # Parity between them is pinned by tests/test_gspmd_impl.py. Override the
    # default with BCFL_FED_IMPL.
    impl: str = "auto",
    # per-client LoRA rank tuple (FedConfig.client_lora_ranks) for
    # HETEROGENEOUS fleets: every client is materialized zero-padded at
    # max(lora_ranks), the [C, R] padding mask compiles in as a closure
    # constant (static in this tuple — part of the cache key below, zero
    # per-round retraces), locals are clipped to their own rank at
    # train entry, and every 'mean' aggregation point becomes the
    # rank-aware RBLA rule (gspmd.rank_aware_weighted_mean). None or a
    # uniform tuple builds EXACTLY the plain programs.
    lora_ranks: Optional[tuple] = None,
) -> FedPrograms:
    if impl == "auto":
        impl = os.environ.get("BCFL_FED_IMPL", "gspmd")
    if lora_ranks is not None and len(set(lora_ranks)) <= 1:
        # uniform spec == plain build: the all-ones clip would be a
        # different (wastefully retraced) program computing the identity
        lora_ranks = None
    if compression is not None and not compression.enabled:
        # normalize so compress='none' and no-compression callers share ONE
        # cache entry — they are the same programs by construction (the
        # builders never branch on a disabled config), and the shared entry
        # makes that identity observable: build_programs(compression=none)
        # IS build_programs() (tests/test_compression.py pins it)
        compression = None
    # same normalization for the hierarchical flag: it only changes the
    # 'mean' aggregation body, so a hierarchical trimmed_mean/median/krum
    # build IS the plain build — sharing the entry keeps cohort-mode robust
    # runs on the exact programs the chaos matrix already compiled
    hierarchical = bool(hierarchical) and aggregator == "mean"
    # Program memoization: flax modules and jax Meshes hash/compare by VALUE
    # (module config dataclasses, mesh devices + axis names), so two engines
    # over equal configs get the SAME jitted program objects — and with them
    # XLA's compile cache. Sweeps (run_results, scaling ladders) and the test
    # suite re-create engines constantly; without this every one recompiles
    # every program (~half the r04 suite's 36 minutes). Unhashable inputs
    # (e.g. an sp-injected attention closure compares by identity) just skip
    # the cache — never wrong, only cold.
    try:
        # ClientMesh is a frozen dataclass: hashing the instance covers every
        # mesh field, including any added later that changes program layout
        key = (model, mesh, optimizer, learning_rate, max_grad_norm,
               gossip_alpha, gossip_steps, task, aggregator, aggregator_trim,
               prng_impl, donate, impl, compression, hierarchical, lora_ranks)
        hash(key)
    except TypeError:
        key = None
    if os.environ.get("BCFL_PROGRAM_CACHE", "1") == "0":  # debug kill-switch
        key = None
    if key is not None and key in _PROGRAM_CACHE:
        return _PROGRAM_CACHE[key]
    progs = _build_programs_dispatch(
        model, mesh, optimizer=optimizer, learning_rate=learning_rate,
        max_grad_norm=max_grad_norm, gossip_alpha=gossip_alpha,
        gossip_steps=gossip_steps, donate=donate, task=task,
        aggregator=aggregator, aggregator_trim=aggregator_trim,
        prng_impl=prng_impl, compression=compression,
        hierarchical=hierarchical, impl=impl, lora_ranks=lora_ranks)
    if key is not None:
        while len(_PROGRAM_CACHE) >= _PROGRAM_CACHE_MAX:
            # FIFO eviction bounds the compiled-executable footprint over a
            # long sweep; live engines keep their own references, so an
            # evicted entry frees only once no engine uses it
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
        _PROGRAM_CACHE[key] = progs
    return progs


_PROGRAM_CACHE: dict = {}
_PROGRAM_CACHE_MAX = 32


def clear_program_cache() -> None:
    """Drop all memoized program sets (their compiled executables free once
    no live engine references them)."""
    _PROGRAM_CACHE.clear()


def _build_programs_dispatch(
    model,
    mesh: ClientMesh,
    optimizer: str,
    learning_rate: float,
    max_grad_norm: float,
    gossip_alpha: float,
    gossip_steps: int,
    task: str,
    aggregator: str,
    aggregator_trim: float,
    prng_impl: Optional[str],
    compression: Optional[CompressionConfig],
    donate: bool,
    hierarchical: bool,
    impl: str,
    lora_ranks: Optional[tuple] = None,
) -> FedPrograms:
    if impl == "gspmd":
        return _build_programs_gspmd(
            model, mesh, optimizer=optimizer, learning_rate=learning_rate,
            max_grad_norm=max_grad_norm, gossip_alpha=gossip_alpha,
            gossip_steps=gossip_steps, donate=donate, task=task,
            aggregator=aggregator, aggregator_trim=aggregator_trim,
            prng_impl=prng_impl, compression=compression,
            hierarchical=hierarchical, lora_ranks=lora_ranks)
    if impl != "shard_map":
        raise ValueError(f"unknown fed impl {impl!r}")
    if lora_ranks is not None:
        # the rank-aware RBLA aggregation is global-array math over the full
        # stacked client dim (per-rank-dim normalization needs every
        # client's mask row at once); the manual-SPMD twin has no form of it
        raise ValueError(
            "heterogeneous lora_ranks require impl='gspmd' (unset "
            "BCFL_FED_IMPL or set it to 'gspmd'); the shard_map twin has no "
            "rank-aware aggregation and would dilute low-rank clients")
    if hierarchical:
        # the explicit two-level reduction is global-array math over the
        # full stacked client dim — the manual-SPMD twin would need its own
        # psum-within-psum form; only the GSPMD programs compile it
        raise ValueError(
            "hierarchical aggregation (cohort mode) requires impl='gspmd' "
            "(unset BCFL_FED_IMPL or set it to 'gspmd')")
    if compression is not None and compression.enabled:
        # same gap class as the robust aggregators below (both documented in
        # ROBUSTNESS.md §5): the codecs are global-array math over the full
        # stacked client dim, and the shard_map twin would need its own
        # manual-SPMD encode/decode + an error-feedback carry threaded
        # through every program signature — only the GSPMD programs compile
        # them today. Failing loudly beats silently shipping full-precision
        # trees under a compress=... label.
        raise ValueError(
            f"compress={compression.kind!r} requires impl='gspmd' (unset "
            "BCFL_FED_IMPL or set it to 'gspmd'); the shard_map twin has no "
            "codec path and would silently exchange uncompressed updates")
    if aggregator != "mean":
        # the robust rules are order statistics over the GLOBAL client dim;
        # inside a shard_map body each device sees only its local stack, so
        # a faithful manual-SPMD form needs an all-gather the twin deliberately
        # avoids — only the GSPMD programs compile them today
        raise ValueError(
            f"aggregator={aggregator!r} requires impl='gspmd' (unset "
            "BCFL_FED_IMPL or set it to 'gspmd'); the shard_map twin "
            "implements 'mean' only")
    if getattr(mesh, "tp", 1) > 1:
        # the manual-SPMD twin would replicate each client's compute over the
        # tp axis instead of sharding it; only GSPMD composes clients x tp
        raise ValueError(
            "clients x tp meshes require impl='gspmd' (unset BCFL_FED_IMPL "
            "or set it to 'gspmd' when tp > 1)")
    if getattr(mesh, "sp", 1) > 1:
        # same story for the (clients, seq) mesh: these specs only name the
        # clients axis, and the model's ring-attention override constrains on
        # the full mesh — inside a shard_map body that either errors or
        # silently replicates the sequence dimension
        raise ValueError(
            "clients x seq meshes require impl='gspmd' (unset BCFL_FED_IMPL "
            "or set it to 'gspmd' when sp > 1)")
    tx = make_optimizer(optimizer, learning_rate, max_grad_norm)
    loss_fn = make_loss_fn(model, task)
    unstack = lambda r: _unstack_rng(r, prng_impl)  # noqa: E731
    axis = mesh.axis
    jmesh = mesh.mesh
    repl = P()
    shard = P("clients")

    # ---- one client's local round: fresh opt state, scan over batches ----
    local_train = make_local_train(tx, loss_fn)

    # ---- server mode: everyone trains from the SAME global trainable ----
    # single source of truth for one FedAvg round; the per-round program and
    # the scanned multi-round fast path below both apply exactly this body
    def server_shard(global_t, frozen, batches, weights, rngs):
        def per_client(b, r):
            return local_train(global_t, frozen, b, unstack(r))

        new_t, stats = jax.vmap(per_client)(batches, rngs)
        # all-masked round -> keep the round's starting params, don't zero them
        avg = masked_weighted_mean(new_t, weights, axis, fallback=global_t)
        return avg, stats

    server_round = jax.jit(
        shard_map(
            server_shard, mesh=jmesh,
            in_specs=(repl, repl, shard, shard, shard),
            out_specs=(repl, shard),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    # ---- serverless mode: per-client params persist, ring gossip after ----
    def _mix(new_t, mask, fallback):
        """Post-train serverless aggregation. gossip_steps == 0 -> exact
        mask-weighted all-client mean, the reference-faithful serverless
        aggregation (serverless_NonIID_IMDB.py:296): every participating
        client ends the round with the same average; ``fallback`` (the
        round's STARTING per-client params) is what an all-masked round keeps.
        gossip_steps > 0 -> masked ring diffusion."""
        if gossip_steps == 0:
            avg = masked_weighted_mean(new_t, mask, axis, fallback=fallback)
            return _exact_mean_spread(avg, new_t, mask)
        return gossip_mix(new_t, mask, gossip_alpha, axis, steps=gossip_steps)

    def gossip_shard(client_t, frozen, batches, mask, rngs):
        def per_client(t, b, r):
            return local_train(t, frozen, b, unstack(r))

        new_t, stats = jax.vmap(per_client)(client_t, batches, rngs)
        return _mix(new_t, mask, fallback=client_t), stats

    gossip_round = jax.jit(
        shard_map(
            gossip_shard, mesh=jmesh,
            in_specs=(shard, repl, shard, shard, shard),
            out_specs=(shard, shard),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    # ---- multi-round fast path: R whole federated rounds in ONE program ----
    # For sync FedAvg with static participation/data the per-round host
    # round-trip is pure overhead (and on a tunnelled TPU it dominates: the
    # replicated result tree re-crosses the link every call). Scanning the
    # rounds on-device keeps params in HBM for the whole block. The engine
    # keeps the per-round program (masks/ledger need the host between
    # rounds); this is the bench/static-config path.
    def server_rounds_shard(global_t, frozen, batches, weights, rngs):
        def one_round(t, xs):
            b, w, r = xs
            return server_shard(t, frozen, b, w, r)

        # batches/weights/rngs leaves are [R, Cl, ...] (round-leading, client
        # dim sharded); scan consumes the leading round axis
        return lax.scan(one_round, global_t, (batches, weights, rngs))

    rshard = P(None, "clients")
    server_rounds = jax.jit(
        shard_map(
            server_rounds_shard, mesh=jmesh,
            in_specs=(repl, repl, rshard, rshard, rshard),
            out_specs=(repl, rshard),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    # static-partition variant: every round reuses ONE batch tree [Cl, ...]
    # (round-static partitions would otherwise stack R identical copies of
    # the batches on device — an R-fold HBM blowup for no information)
    def server_rounds_static_shard(global_t, frozen, batches, weights, rngs):
        def one_round(t, xs):
            w, r = xs
            return server_shard(t, frozen, batches, w, r)

        return lax.scan(one_round, global_t, (weights, rngs))

    server_rounds_static = jax.jit(
        shard_map(
            server_rounds_static_shard, mesh=jmesh,
            in_specs=(repl, repl, shard, rshard, rshard),
            out_specs=(repl, rshard),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    # serverless twin of the multi-round fast path: R gossip rounds scanned
    # on-device, per-client params carried in HBM across the whole block
    def gossip_rounds_shard(client_t, frozen, batches, masks, rngs):
        def one_round(t, xs):
            b, m, r = xs
            return gossip_shard(t, frozen, b, m, r)

        return lax.scan(one_round, client_t, (batches, masks, rngs))

    gossip_rounds = jax.jit(
        shard_map(
            gossip_rounds_shard, mesh=jmesh,
            in_specs=(shard, repl, rshard, rshard, rshard),
            out_specs=(shard, rshard),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    def gossip_rounds_static_shard(client_t, frozen, batches, masks, rngs):
        def one_round(t, xs):
            m, r = xs
            return gossip_shard(t, frozen, batches, m, r)

        return lax.scan(one_round, client_t, (masks, rngs))

    gossip_rounds_static = jax.jit(
        shard_map(
            gossip_rounds_static_shard, mesh=jmesh,
            in_specs=(shard, repl, shard, rshard, rshard),
            out_specs=(shard, rshard),
            check_vma=False,
        ),
        donate_argnums=(0,) if donate else (),
    )

    # ---- split-phase programs (ledger commit/verify flow, async engine) ----
    def client_updates_shard(global_t, frozen, batches, rngs):
        new_t, stats = jax.vmap(
            lambda b, r: local_train(global_t, frozen, b, unstack(r))
        )(batches, rngs)
        return new_t, stats

    client_updates = jax.jit(
        shard_map(
            client_updates_shard, mesh=jmesh,
            in_specs=(repl, repl, shard, shard),
            out_specs=(shard, shard),
            check_vma=False,
        ),
    )

    def local_updates_shard(client_t, frozen, batches, rngs):
        return jax.vmap(
            lambda t, b, r: local_train(t, frozen, b, unstack(r))
        )(client_t, batches, rngs)

    local_updates = jax.jit(
        shard_map(
            local_updates_shard, mesh=jmesh,
            in_specs=(shard, repl, shard, shard),
            out_specs=(shard, shard),
            check_vma=False,
        ),
    )

    # split-phase serverless aggregation: ``fallback`` must be the round's
    # STARTING stacked params (the engine keeps them across the
    # local_updates -> ledger-verify -> mix_only sequence)
    mix_only = jax.jit(
        shard_map(
            lambda client_t, mask, fallback: _mix(client_t, mask, fallback),
            mesh=jmesh,
            in_specs=(shard, shard, shard), out_specs=shard, check_vma=False,
        ),
    )

    single_update = jax.jit(local_train)

    # ---- evaluation ----
    eval_one = make_eval_one(loss_fn)

    def eval_clients_shard(client_t, frozen, batches):
        return jax.vmap(lambda t, b: eval_one(t, frozen, b))(client_t, batches)

    eval_clients = jax.jit(
        shard_map(
            eval_clients_shard, mesh=jmesh,
            in_specs=(shard, repl, shard),
            out_specs=shard,
            check_vma=False,
        ),
    )

    # Flower-style client evaluate: the ONE (global) model scored on each
    # client's local test set (server_IID_IMDB.py:176-179)
    eval_clients_global = jax.jit(
        shard_map(
            lambda g, f, b: jax.vmap(lambda bb: eval_one(g, f, bb))(b),
            mesh=jmesh,
            in_specs=(repl, repl, shard),
            out_specs=shard,
            check_vma=False,
        ),
    )

    eval_global = jax.jit(eval_one)

    # ---- layout helpers ----
    broadcast = make_broadcast(mesh)

    # ``fallback`` (replicated) is returned when every weight is zero — e.g. a
    # round where all clients fail ledger authentication must NOT aggregate
    # the rejected updates.
    collapse = jax.jit(
        shard_map(
            lambda t, w, fallback: masked_weighted_mean(t, w, axis, fallback=fallback),
            mesh=jmesh,
            in_specs=(shard, shard, repl), out_specs=repl, check_vma=False,
        )
    )

    adopt = jax.jit(
        shard_map(
            _adopt_pull, mesh=jmesh,
            in_specs=(shard, repl, shard), out_specs=shard, check_vma=False,
        )
    )

    return FedPrograms(
        mesh=mesh,
        server_round=server_round,
        server_rounds=server_rounds,
        server_rounds_static=server_rounds_static,
        gossip_round=gossip_round,
        gossip_rounds=gossip_rounds,
        gossip_rounds_static=gossip_rounds_static,
        eval_clients=eval_clients,
        eval_clients_global=eval_clients_global,
        eval_global=eval_global,
        broadcast=broadcast,
        collapse=collapse,
        client_updates=client_updates,
        local_updates=local_updates,
        mix_only=mix_only,
        single_update=single_update,
        adopt=adopt,
        # impl-agnostic (plain global-array math); the fused *_fp twins are
        # gspmd-only, so a ledger run under shard_map falls back per-round
        fingerprint=jax.jit(lambda t: client_fingerprint(t)),
        fingerprint_one=jax.jit(lambda t: tree_fingerprint(t)),
    )


def _build_programs_gspmd(
    model,
    mesh: ClientMesh,
    optimizer: str = "adamw",
    learning_rate: float = 5e-5,
    max_grad_norm: float = 0.0,
    gossip_alpha: float = 0.5,
    gossip_steps: int = 1,
    donate: bool = False,
    task: str = "classification",
    aggregator: str = "mean",
    aggregator_trim: float = 0.2,
    prng_impl: Optional[str] = None,
    compression: Optional[CompressionConfig] = None,
    hierarchical: bool = False,
    lora_ranks: Optional[tuple] = None,
) -> FedPrograms:
    """GSPMD twin of the shard_map builder: identical program signatures and
    semantics (global stacked-client arrays in, global arrays out), but the
    bodies are plain global-array math under ``jit`` with sharding
    annotations — reductions/rolls over the sharded client dim become XLA
    all-reduce / collective-permute (:mod:`bcfl_tpu.parallel.gspmd`).

    ``aggregator`` swaps the masked weighted mean for a Byzantine-robust
    rule at every aggregation point that consumes a full stacked-client
    view: server FedAvg (per-round and fused), the consensus ``collapse``,
    and the serverless exact-mean (``gossip_steps == 0``). Ring-gossip
    diffusion (``gossip_steps > 0``) keeps its pairwise mixing rule — a
    two-neighbour exchange has no order statistics to harden.

    ``compression`` (enabled) compiles the update-exchange codecs
    (:mod:`bcfl_tpu.compression`, COMPRESSION.md) into every aggregation
    path: each client's post-train DELTA vs the round's reference params is
    error-feedback-compensated, encoded, and only the DECODED (lossy)
    reconstruction reaches the aggregator / gossip mix — the sender's own
    carried state stays its honest local tree (the existing ``mix_recv``
    transport split). The round/fused programs then carry
    ``(params, ef_residual)`` tuples instead of a bare tree; the fused
    ``*_fp`` twins fingerprint the COMPRESSED payload before and after the
    simulated transport stage, so ledger auth covers exactly the bytes on
    the wire. ``None``/'none' leaves every body below byte-identical to the
    uncompressed build."""
    comp = (compression
            if compression is not None and compression.enabled else None)
    # hierarchical (cohort mode): every 'mean' aggregation point — server
    # FedAvg, collapse, the serverless exact-mean — becomes the explicit
    # within-device-stack then cross-device reduction; groups = the mesh's
    # clients-axis extent, so each inner group IS one device's cohort slice
    groups = int(mesh.mesh.shape[mesh.axis]) if hierarchical else 0
    # heterogeneous LoRA ranks: the [C, R] padding mask is a CLOSURE
    # CONSTANT derived from the static rank tuple — it compiles into every
    # program below (clipped train entry, RBLA aggregation, clipped codec
    # deltas), so which client trains at which rank never retraces
    rmask = (None if lora_ranks is None
             else lora_lib.rank_mask(lora_ranks))
    agg = gspmd.make_aggregator(aggregator, aggregator_trim,
                                hierarchical_groups=groups,
                                rank_mask=rmask)
    tx = make_optimizer(optimizer, learning_rate, max_grad_norm)
    loss_fn = make_loss_fn(model, task)
    unstack = lambda r: _unstack_rng(r, prng_impl)  # noqa: E731
    local_train = make_local_train(tx, loss_fn)
    jmesh = mesh.mesh
    cl = NamedSharding(jmesh, P(mesh.axis))
    rcl = NamedSharding(jmesh, P(None, mesh.axis))
    repl = NamedSharding(jmesh, P())

    def _c(tree, sh):
        return jax.tree.map(lambda x: lax.with_sharding_constraint(x, sh), tree)

    def _don(*idx):
        return idx if donate else ()

    # every client trains from the same replicated trainable. Heterogeneous
    # ranks clip the replicated global to EACH client's own rank at train
    # entry (a low-rank client never sees the fleet's higher-rank
    # components); both factors of a padded dim enter at exactly 0, so
    # grads there are 0 and AdamW keeps them exactly 0 through the round —
    # no post-aggregation re-clip is needed on any path.
    def train_clients(global_t, frozen, batches, rngs):
        if rmask is None:
            new_t, stats = jax.vmap(
                lambda b, r: local_train(global_t, frozen, b, unstack(r))
            )(batches, rngs)
        else:
            new_t, stats = jax.vmap(
                lambda mrow, b, r: local_train(
                    lora_lib.clip_adapters(global_t, mrow), frozen, b,
                    unstack(r))
            )(rmask, batches, rngs)
        return _c(new_t, cl), _c(stats, cl)

    def server_body(global_t, frozen, batches, weights, rngs):
        new_t, stats = train_clients(global_t, frozen, batches, rngs)
        avg = agg(new_t, weights, global_t)
        return _c(avg, repl), stats

    def server_body_comp(carry, frozen, batches, weights, rngs):
        # compressed FedAvg: the server aggregates each client's
        # RECONSTRUCTION from the compressed delta — what actually arrived —
        # never the honest full-precision update
        global_t, resid = carry
        new_t, stats = train_clients(global_t, frozen, batches, rngs)
        payload, dec, resid = _compress_stage(new_t, global_t, resid, rngs)
        del payload  # clean path: ledger/corruption rounds run split-phase
        avg = agg(_recon(global_t, dec, new_t), weights, global_t)
        return (_c(avg, repl), resid), stats

    if comp is None:
        server_round = jax.jit(server_body, donate_argnums=_don(0),
                               out_shardings=(repl, cl))
    else:
        server_round = jax.jit(server_body_comp, donate_argnums=_don(0),
                               out_shardings=((repl, cl), cl))

    def _transport(new_t, c_row):
        """Simulated transport of a client-stacked update tree: the buffer
        that reaches aggregation is ``new_t + c_row`` (per-client scalar,
        0 = clean — an exact float identity, so an honest round's post-
        transport fingerprints match the committed ones bit-for-bit). The
        corruption input is what makes fused-mode ledger auth a real check
        rather than an identity: commit fingerprints are taken BEFORE this
        point, verification fingerprints AFTER."""
        return jax.tree.map(
            lambda x: x + c_row.reshape((-1,) + (1,) * (x.ndim - 1))
            .astype(x.dtype), new_t)

    def _fp_auth(new_t, c_row):
        """(sent_t, fp_commit, fp_recv, auth): fingerprint the update before
        and after simulated transport and compare in-graph. ``auth`` [C] is
        1.0 iff every fingerprint lane survived transport unchanged."""
        fp_commit = _c(client_fingerprint(new_t), cl)
        sent_t = _transport(new_t, c_row)
        fp_recv = _c(client_fingerprint(sent_t), cl)
        auth = jnp.all(fp_recv == fp_commit, axis=-1).astype(jnp.float32)
        return sent_t, fp_commit, fp_recv, _c(auth, cl)

    # ---- communication-compression stages (comp is not None only) ----
    def _ckey(rngs):
        # codec stochastic-rounding key: derived from the same per-round
        # stacked key rows the training consumes, on a lane the training
        # stream never touches — identical on the per-round and fused paths
        return cc.codec_key(unstack(rngs))

    def _compress_stage(new_t, ref_t, resid, rngs):
        """Sender side of one wire exchange: ``(payload, decoded, resid')``
        for ``delta = new_t - ref_t`` (+ the carried error-feedback
        residual). ``ref_t`` may be the replicated global (server) or the
        stacked round-start params (serverless) — the subtract broadcasts."""
        delta = jax.tree.map(
            lambda n, g: n.astype(jnp.float32) - g.astype(jnp.float32),
            new_t, ref_t)
        if rmask is not None:
            # a client's delta on its PADDING dims is -ref there (its local
            # is structurally 0, the global needn't be): those dims aren't
            # the client's to ship — clip them so the codec budget (top-k
            # slots, quantization range) is spent on real coordinates and
            # the EF residual stays exactly 0 on padding
            delta = jax.vmap(lora_lib.clip_adapters)(delta, rmask)
        payload, dec, resid = cc.roundtrip(comp, delta, resid, _ckey(rngs))
        return _c(payload, cl), dec, _c(resid, cl)

    def _recon(ref_t, dec, like_t):
        """Receiver-side reconstruction ``ref + decoded delta``, cast back to
        the param dtype — the stacked tree the aggregator/mix consumes."""
        return _c(jax.tree.map(
            lambda g, d, n: (g.astype(jnp.float32) + d).astype(n.dtype),
            ref_t, dec, like_t), cl)

    def _fp_auth_payload(payload, c_row):
        """Compressed twin of ``_fp_auth``: fingerprints are taken over the
        COMPRESSED payload (the bytes actually on the wire), transport
        corrupts the payload's float parts, and auth is the in-graph
        comparison. c_row == 0 keeps the payload bit-identical (exact float
        identity), so clean rounds authenticate bit-for-bit."""
        fp_commit = _c(client_fingerprint(payload), cl)
        sent = cc.corrupt_payload(payload, c_row)
        fp_recv = _c(client_fingerprint(sent), cl)
        auth = jnp.all(fp_recv == fp_commit, axis=-1).astype(jnp.float32)
        return sent, fp_commit, fp_recv, _c(auth, cl)

    def _make_server_rounds(static: bool, with_fp: bool):
        """Fused R-round server program; ``with_fp=True`` additionally takes
        a per-round per-client transport-corruption input [R, C] and emits
        ``(stats, fp_commit, fp_recv, auth)`` with fingerprints [R, C, K]:
        ``fp_commit`` digests the pre-transport update (what each client
        commits to the ledger), ``fp_recv`` the post-transport buffer that
        is actually aggregated, and the round's mean is gated by the
        in-graph comparison — a corrupted update is EXCLUDED from the
        aggregate, not just flagged. This keeps the fused fast path a real
        verification (VERDICT r04 weak #2), not an accounting identity."""

        def body(global_t, frozen, batches, weights, rngs, corrupts=None):
            def one_round(t, xs):
                if static:
                    b = batches
                    (w, r), rest = xs[:2], xs[2:]
                else:
                    (b, w, r), rest = xs[:3], xs[3:]
                if comp is not None:
                    # compressed carry: (global params, EF residual). The
                    # residual is per-client sender state riding the scan —
                    # compression error re-enters the next round's encode
                    # instead of accumulating (COMPRESSION.md).
                    g, resid = t
                    new_t, stats = train_clients(g, frozen, b, r)
                    payload, dec, resid = _compress_stage(new_t, g, resid, r)
                    if with_fp:
                        sent, fpc, fpr, auth = _fp_auth_payload(
                            payload, rest[0])
                        # decode the TRANSPORTED payload: a corrupted wire
                        # yields a corrupted reconstruction, which auth
                        # already excluded from the aggregate
                        dec = cc.decode_tree(comp, sent, new_t)
                        avg = _c(agg(_recon(g, dec, new_t), w * auth, g),
                                 repl)
                        return (avg, resid), (stats, fpc, fpr, auth)
                    avg = _c(agg(_recon(g, dec, new_t), w, g), repl)
                    return (avg, resid), stats
                new_t, stats = train_clients(t, frozen, b, r)
                if with_fp:
                    sent_t, fpc, fpr, auth = _fp_auth(new_t, rest[0])
                    avg = _c(agg(sent_t, w * auth, t), repl)
                    return avg, (stats, fpc, fpr, auth)
                avg = _c(agg(new_t, w, t), repl)
                return avg, stats

            xs = (weights, rngs) if static else (batches, weights, rngs)
            if with_fp:
                xs = xs + (corrupts,)
            return lax.scan(one_round, global_t, xs)

        carry_sh = repl if comp is None else (repl, cl)
        out_sh = ((carry_sh, (rcl, rcl, rcl, rcl)) if with_fp
                  else (carry_sh, rcl))
        return jax.jit(body, donate_argnums=_don(0), out_shardings=out_sh)

    server_rounds = _make_server_rounds(static=False, with_fp=False)
    server_rounds_static = _make_server_rounds(static=True, with_fp=False)
    server_rounds_fp = _make_server_rounds(static=False, with_fp=True)
    server_rounds_static_fp = _make_server_rounds(static=True, with_fp=True)

    def _mix_g(new_t, mask, fallback):
        # same semantics as the shard_map _mix (see its docstring); the
        # exact-mean path rides the configured aggregator
        if gossip_steps == 0:
            avg = agg(new_t, mask, fallback)
            return _exact_mean_spread(avg, new_t, mask)
        return gspmd.gossip_mix(new_t, mask, gossip_alpha, steps=gossip_steps)

    def _mix_g_recv(self_t, recv_t, mask, fallback):
        # transport-aware twin of _mix_g: neighbor/aggregate terms come from
        # the TRANSPORTED tree, the self-term (and a masked client's kept
        # state) from the client's own honest post-train tree — in-flight
        # corruption must not rewrite the sender's local copy
        if gossip_steps == 0:
            avg = agg(recv_t, mask, fallback)
            return _exact_mean_spread(avg, self_t, mask)
        return gspmd.gossip_mix_recv(self_t, recv_t, mask, gossip_alpha,
                                     steps=gossip_steps)

    # each client trains from its OWN stacked params (same per-client rank
    # clip at entry as train_clients — an adopted global's higher-rank
    # components are chopped before a low-rank client optimizes)
    def local_updates_body(client_t, frozen, batches, rngs):
        if rmask is None:
            new_t, stats = jax.vmap(
                lambda t, b, r: local_train(t, frozen, b, unstack(r))
            )(client_t, batches, rngs)
        else:
            new_t, stats = jax.vmap(
                lambda mrow, t, b, r: local_train(
                    lora_lib.clip_adapters(t, mrow), frozen, b, unstack(r))
            )(rmask, client_t, batches, rngs)
        return _c(new_t, cl), _c(stats, cl)

    def gossip_body(client_t, frozen, batches, mask, rngs):
        new_t, stats = local_updates_body(client_t, frozen, batches, rngs)
        return _c(_mix_g(new_t, mask, client_t), cl), stats

    def gossip_body_comp(carry, frozen, batches, mask, rngs):
        # compressed gossip: the DELTA each peer ships is vs its own
        # round-start params (which its neighbours hold from the previous
        # exchange — the standard delta-compression gossip assumption);
        # neighbour/aggregate terms come from the lossy reconstruction, each
        # sender's self-term stays its honest post-train tree (mix_recv's
        # transport split, reused as the codec split)
        client_t, resid = carry
        new_t, stats = local_updates_body(client_t, frozen, batches, rngs)
        payload, dec, resid = _compress_stage(new_t, client_t, resid, rngs)
        del payload
        recon = _recon(client_t, dec, new_t)
        mixed = _c(_mix_g_recv(new_t, recon, mask, client_t), cl)
        return (mixed, resid), stats

    if comp is None:
        gossip_round = jax.jit(gossip_body, donate_argnums=_don(0),
                               out_shardings=(cl, cl))
    else:
        gossip_round = jax.jit(gossip_body_comp, donate_argnums=_don(0),
                               out_shardings=((cl, cl), cl))

    def _make_gossip_rounds(static: bool, with_fp: bool):
        """Fused R-round gossip program; ``with_fp`` adds the same
        simulated-transport verification as ``_make_server_rounds``: commit
        fingerprints on the post-train pre-transport update (the tree the
        split-phase ledger flow commits via ``local_updates``), verification
        fingerprints + in-graph auth on the transported buffer, and the
        gossip mix consumes the transported buffer gated by auth."""

        def body(client_t, frozen, batches, masks, rngs, corrupts=None):
            def one_round(t, xs):
                if static:
                    b = batches
                    (m, r), rest = xs[:2], xs[2:]
                else:
                    (b, m, r), rest = xs[:3], xs[3:]
                if comp is not None:
                    # compressed carry (client params, EF residual); see
                    # gossip_body_comp for the delta-reference semantics
                    ct, resid = t
                    new_t, stats = local_updates_body(ct, frozen, b, r)
                    payload, dec, resid = _compress_stage(new_t, ct, resid, r)
                    if with_fp:
                        sent, fpc, fpr, auth = _fp_auth_payload(
                            payload, rest[0])
                        dec = cc.decode_tree(comp, sent, new_t)
                        mixed = _c(_mix_g_recv(
                            new_t, _recon(ct, dec, new_t), m * auth, ct), cl)
                        return (mixed, resid), (stats, fpc, fpr, auth)
                    mixed = _c(_mix_g_recv(
                        new_t, _recon(ct, dec, new_t), m, ct), cl)
                    return (mixed, resid), stats
                new_t, stats = local_updates_body(t, frozen, b, r)
                if with_fp:
                    sent_t, fpc, fpr, auth = _fp_auth(new_t, rest[0])
                    mixed = _c(_mix_g_recv(new_t, sent_t, m * auth, t), cl)
                    return mixed, (stats, fpc, fpr, auth)
                mixed = _c(_mix_g(new_t, m, t), cl)
                return mixed, stats

            xs = (masks, rngs) if static else (batches, masks, rngs)
            if with_fp:
                xs = xs + (corrupts,)
            return lax.scan(one_round, client_t, xs)

        carry_sh = cl if comp is None else (cl, cl)
        out_sh = ((carry_sh, (rcl, rcl, rcl, rcl)) if with_fp
                  else (carry_sh, rcl))
        return jax.jit(body, donate_argnums=_don(0), out_shardings=out_sh)

    gossip_rounds = _make_gossip_rounds(static=False, with_fp=False)
    gossip_rounds_static = _make_gossip_rounds(static=True, with_fp=False)
    gossip_rounds_fp = _make_gossip_rounds(static=False, with_fp=True)
    gossip_rounds_static_fp = _make_gossip_rounds(static=True, with_fp=True)

    client_updates = jax.jit(train_clients, out_shardings=(cl, cl))

    local_updates = jax.jit(local_updates_body, out_shardings=(cl, cl))

    mix_only = jax.jit(
        lambda client_t, mask, fallback: _c(_mix_g(client_t, mask, fallback), cl),
        out_shardings=cl)

    mix_recv = jax.jit(
        lambda self_t, recv_t, mask, fallback: _c(
            _mix_g_recv(self_t, recv_t, mask, fallback), cl),
        out_shardings=cl)

    single_update = jax.jit(local_train)

    eval_one = make_eval_one(loss_fn)

    eval_clients = jax.jit(
        lambda client_t, frozen, b: _c(
            jax.vmap(lambda t, bb: eval_one(t, frozen, bb))(client_t, b), cl),
        out_shardings=cl)

    eval_clients_global = jax.jit(
        lambda g, f, b: _c(jax.vmap(lambda bb: eval_one(g, f, bb))(b), cl),
        out_shardings=cl)

    eval_global = jax.jit(eval_one)

    broadcast = make_broadcast(mesh)

    collapse = jax.jit(
        lambda t, w, fallback: _c(agg(t, w, fallback), repl),
        out_shardings=repl)

    adopt = jax.jit(
        lambda client_t, global_t, pull: _c(
            _adopt_pull(client_t, global_t, pull), cl),
        out_shardings=cl)

    # ---- split-phase codec programs (per-round ledger/corruption flow) ----
    # The engine composes these exactly like the uncompressed split-phase
    # sequence (client_updates -> commit -> transport -> verify ->
    # aggregate), except the quantity that is fingerprinted, corrupted, and
    # shipped is the compressed payload. Same codec math as the in-graph
    # stages above, so fused and per-round rounds commit identical digests
    # for identical content.
    encode_deltas = encode_deltas_local = decode_recon = decode_delta = None
    encode_deltas_async = corrupt_payload_p = ef_init = None
    if comp is not None:
        def _enc(new_t, ref_t, resid, rngs):
            payload, dec, resid = _compress_stage(new_t, ref_t, resid, rngs)
            return payload, _recon(ref_t, dec, new_t), resid

        def _enc_delta(new_t, ref_t, resid, rngs):
            payload, _, resid = _compress_stage(new_t, ref_t, resid, rngs)
            return payload, resid

        # separate jit objects so the replicated-ref (server/global) and
        # stacked-ref (serverless) traces each own one cache entry
        encode_deltas = jax.jit(_enc)
        encode_deltas_local = jax.jit(_enc)
        encode_deltas_async = jax.jit(_enc_delta)
        decode_recon = jax.jit(
            lambda payload, ref_t, like_t: _recon(
                ref_t, cc.decode_tree(comp, payload, like_t), like_t))
        decode_delta = jax.jit(
            lambda payload, like_t: _c(jax.tree.map(
                lambda d, n: d.astype(n.dtype),
                cc.decode_tree(comp, payload, like_t), like_t), cl))
        corrupt_payload_p = jax.jit(
            lambda payload, scales: _c(cc.corrupt_payload(payload, scales),
                                       cl))
        ef_init = jax.jit(
            lambda t: cc.zero_residual(t, mesh.num_clients),
            out_shardings=cl)

    return FedPrograms(
        mesh=mesh,
        server_round=server_round,
        server_rounds=server_rounds,
        server_rounds_static=server_rounds_static,
        gossip_round=gossip_round,
        gossip_rounds=gossip_rounds,
        gossip_rounds_static=gossip_rounds_static,
        eval_clients=eval_clients,
        eval_clients_global=eval_clients_global,
        eval_global=eval_global,
        broadcast=broadcast,
        collapse=collapse,
        client_updates=client_updates,
        local_updates=local_updates,
        mix_only=mix_only,
        single_update=single_update,
        adopt=adopt,
        fingerprint=jax.jit(lambda t: _c(client_fingerprint(t), cl),
                            out_shardings=cl),
        fingerprint_one=jax.jit(lambda t: tree_fingerprint(t)),
        server_rounds_fp=server_rounds_fp,
        server_rounds_static_fp=server_rounds_static_fp,
        gossip_rounds_fp=gossip_rounds_fp,
        gossip_rounds_static_fp=gossip_rounds_static_fp,
        mix_recv=mix_recv,
        encode_deltas=encode_deltas,
        encode_deltas_local=encode_deltas_local,
        encode_deltas_async=encode_deltas_async,
        decode_recon=decode_recon,
        decode_delta=decode_delta,
        corrupt_payload=corrupt_payload_p,
        ef_init=ef_init,
    )
