"""Cohort-batched client scale-out: seeded registry sampling + per-registry
host state (SCALING.md "Cohort mode").

The engine's unit of execution has always been a stacked client axis — a
``(num_clients, ...)`` leading dim vmapped per device and sharded over the
mesh. What capped the simulator at tens of clients was the IDENTITY between
that axis and the client population: every registered client occupied a mesh
slot every round. Cohort mode splits the two:

- a **registry** of ``registry_size`` clients exists only as host state
  (data-partition identity, PRNG stream, fault schedules, reputation arrays,
  error-feedback residuals — everything keyed by registry id),
- each round a seeded :class:`ClientSampler` draws a ``cohort`` of
  ``sample_clients`` registry ids, and ONLY that cohort occupies the stacked
  axis: same compiled programs, same shapes, zero per-round retraces —
  the cohort ids are runtime *values*, never trace-time shapes,
- device/HBM cost is bounded by the cohort (``sample_clients``), not the
  registry; per-round host cost is O(registry) only in trivially cheap
  lanes (one RNG draw per fault lane, the reputation EWMA pass).

Design constraints (the :mod:`bcfl_tpu.faults` contract):

- **Deterministic.** The sampler is a pure function of
  ``(seed, round)`` via ``np.random.default_rng`` — no sequential RNG
  state, so a resumed run reproduces the remaining rounds' cohorts
  bit-for-bit from the config seed alone (the checkpoint still records
  registry/cohort sizes and refuses a mismatch: changing either changes
  the cohort stream).
- **Checkpointable.** :class:`EFRegistry` (the per-registry-client
  error-feedback residual store compression carries across rounds)
  round-trips through the engine checkpoint as a stacked tree + id vector.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

import jax
import numpy as np

# sampler RNG lane: the tuple seed (cfg.seed, _SAMPLER_LANE, round) keeps the
# cohort draw on its own stream — enabling any fault lane (which draws from
# (faults.seed, lane, round)) can never reshuffle which clients are sampled
_SAMPLER_LANE = 77_003


@dataclasses.dataclass(frozen=True)
class ClientSampler:
    """Seeded per-round cohort draw over a client registry.

    ``cohort_ids(rnd)`` is a pure function: uniform without replacement,
    sorted ascending (a stable presentation order for records/ledger
    entries; the stacked-slot order carries no semantics — aggregation is
    permutation-invariant up to FP summation order, which the sort pins).
    """

    seed: int
    registry_size: int
    cohort: int

    def __post_init__(self):
        if not 1 <= self.cohort <= self.registry_size:
            raise ValueError(
                f"cohort {self.cohort} must be in [1, registry_size="
                f"{self.registry_size}]")

    def cohort_ids(self, round_idx: int) -> np.ndarray:
        """[cohort] int64 registry ids sampled for ``round_idx``."""
        rng = np.random.default_rng((self.seed, _SAMPLER_LANE, round_idx))
        ids = rng.choice(self.registry_size, size=self.cohort, replace=False)
        return np.sort(ids).astype(np.int64)


class EFRegistry:
    """Host-side per-registry-client error-feedback residual store.

    The compiled codec programs carry a stacked ``[cohort, ...]`` f32
    residual; with sampling on, that buffer belongs to a DIFFERENT set of
    clients each round, so the engine gathers the cohort's residuals from here
    before the round and scatters the updated rows back after it. Unseen
    clients read as zeros (the fresh-residual semantics of ``ef_init``), so
    the store grows O(unique sampled clients x params) on the host — the
    device never holds more than the cohort's rows.
    """

    def __init__(self, template_tree):
        # per-client zero template, shaped like one client's residual row
        self._zero = jax.tree.map(
            lambda x: np.zeros(x.shape, np.float32), template_tree)
        self._store: Dict[int, object] = {}

    def __len__(self) -> int:
        return len(self._store)

    def gather(self, ids: np.ndarray):
        """Stacked host tree ``[len(ids), ...]`` of the ids' residuals."""
        rows = [self._store.get(int(i), self._zero) for i in ids]
        return jax.tree.map(lambda *xs: np.stack(xs), *rows)

    def scatter(self, ids: np.ndarray, host_stacked) -> None:
        """Write the round's updated residual rows back by registry id.

        Rows are COPIED out of the stacked buffer: ``x[pos]`` is a numpy
        view whose base is the whole ``[C, ...]`` leaf, and storing views
        would keep every round's full cohort buffer alive for as long as
        any one of its rows is some client's current residual."""
        for pos, i in enumerate(ids):
            self._store[int(i)] = jax.tree.map(
                lambda x: np.array(x[pos], copy=True), host_stacked)

    # ------------------------------------------------------------ checkpoint

    def checkpoint_state(self) -> Dict[str, object]:
        """``ef_ids`` ([K] int64) + ``ef_registry`` (stacked tree) for the
        engine checkpoint; empty dict when nothing has been scattered yet
        (restore treats absence as an empty store)."""
        if not self._store:
            return {}
        ids = np.asarray(sorted(self._store), np.int64)
        return {"ef_ids": ids, "ef_registry": self.gather(ids)}

    def restore(self, state: Dict) -> None:
        self._store.clear()
        ids = state.get("ef_ids")
        if ids is None:
            return
        self.scatter(np.asarray(ids, np.int64).reshape(-1),
                     state["ef_registry"])


def cohort_view(arr: Optional[np.ndarray],
                ids: Optional[np.ndarray]) -> Optional[np.ndarray]:
    """Slice a registry-sized host array down to the round's cohort rows
    (identity when sampling is off — ``ids is None`` — or ``arr`` is None)."""
    if arr is None or ids is None:
        return arr
    return np.asarray(arr)[ids]
