"""Federated round engines — the orchestration layer.

Replaces both reference orchestrators with one config-driven loop
(SURVEY.md §1 L3a/L3b):

- ``mode="server"``  — centralized FedAvg (reference: Flower
  ``start_simulation`` + ``FedAvg`` strategy, ``server_IID_IMDB.py:205-218``),
- ``mode="serverless"`` — P2P gossip (reference: hand-rolled round loop +
  all-client mean, ``serverless_NonIID_IMDB.py:284-318``), with
  ``faithful=True`` reproducing the reference's sequential shared-model quirk
  exactly (clients mutate ONE model within a round — ``:288``, SURVEY.md §3.2),
- ``sync="async"`` — buffered asynchronous aggregation (FedBuff-style) under a
  simulated network clock derived from the latency graph; the reference only
  *models* asynchrony as max-instead-of-sum info-passing time (MT nb cell 23).

Per round the host control plane:
1. runs the anomaly filter over the latency graph -> participation mask
   (reference: offline notebook cells, never wired in — here it gates psum),
   composed with the fault plan's injected client dropout,
2. (ledger mode) commits each client's update digest to the hash chain,
   simulates transport (the fault plan's corruption stage), re-verifies
   digests, and zeroes the mask of any client whose shipped update fails
   authentication,
3. launches the compiled round program on the mesh (aggregation rule =
   ``cfg.aggregator``: mean or a Byzantine-robust statistic, ROBUSTNESS.md),
4. records the reference metric set + info-passing times (straggler delays
   from the fault plan included).

Fault injection (dropout / stragglers / corruption / host crash) is driven
by ``cfg.faults`` (:class:`bcfl_tpu.faults.FaultPlan`); an all-eliminated
round keeps the previous global model and is recorded ``degraded`` instead
of emitting a 0/0 NaN mean.

Peer lifecycle (ROBUSTNESS.md §6): ``cfg.reputation`` enables the
HEALTHY -> SUSPECT -> QUARANTINED -> PROBATION state machine
(:mod:`bcfl_tpu.reputation`) — per-round evidence (ledger-auth failures,
anomaly flags, corruption hits, staleness) drives an EWMA trust score whose
gate multiplier folds into the participation mask: quarantined peers carry
weight 0 for a configurable window, probation peers a reduced vote weight.
The chaos plan's **partition** lane routes the affected rounds through
:meth:`_partitioned_round` (per-component aggregation over the stacked
client view, robust reconciliation on heal); **churn** composes permanent
leave / late join into the mask; **flaky** bursts ride the corruption
transport stage. All of it is host-side mask/weight arithmetic feeding the
already-compiled programs — no per-round retraces.

Cohort-batched scale-out (SCALING.md "Cohort mode"): with
``cfg.registry_size > 0`` the run simulates a REGISTRY of clients far larger
than the mesh — per-client identity (data partition, PRNG stream, fault
schedule, reputation, EF residuals) is keyed by registry id in host state,
and each round a seeded sampler (:mod:`bcfl_tpu.fed.cohort`) draws
``sample_clients`` of them onto the stacked axis. The compiled programs and
their shapes never change (cohort ids are runtime values), aggregation runs
the explicit hierarchical within-device-stack -> cross-device reduction, and
device memory is bounded by the cohort, not the registry.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
import warnings
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bcfl_tpu.checkpoint import restore_latest, save_checkpoint
from bcfl_tpu.compression import codecs as cc
from bcfl_tpu.config import FedConfig
from bcfl_tpu.core import client_mesh, client_round_keys, pod_devices
from bcfl_tpu.core.fence import fence
from bcfl_tpu.data import (
    Partitioner,
    TokenCache,
    client_batches,
    get_tokenizer,
    load_dataset,
)
from bcfl_tpu.data.pipeline import central_eval_batches
from bcfl_tpu.faults import FaultInjector, SimulatedCrash
from bcfl_tpu.fed.client_step import FedPrograms, build_programs, _merge
from bcfl_tpu.fed.cohort import ClientSampler, EFRegistry, cohort_view
from bcfl_tpu.ledger import Ledger
from bcfl_tpu.ledger import fingerprint as fp_lib
from bcfl_tpu.metrics import (
    ResourceMonitor,
    RoundRecord,
    RunMetrics,
    StepClock,
    model_size_gb,
    trace,
)
from bcfl_tpu.models import TextClassifier, lora as lora_lib
from bcfl_tpu.reputation import ReputationTracker
from bcfl_tpu import telemetry
from bcfl_tpu.topology import (
    anomaly_filter,
    partitioned_anomaly_filter,
    random_graph,
    reference_graph,
)
from bcfl_tpu.topology.graph import LatencyGraph


@dataclasses.dataclass
class RunResult:
    metrics: RunMetrics
    trainable: object  # final global trainable (params or adapters)
    params: object  # final merged full params
    ledger: Optional[Ledger]


@dataclasses.dataclass
class ExchangeResult:
    """One update exchange through the engine's wire seam
    (:meth:`FedEngine._exchange_updates`) — the single code path every
    consumer of 'what crossed the wire' shares: the per-round split-phase
    bodies (server/serverless/partitioned/async) and the dist runtime's
    real TCP transport (bcfl_tpu.dist, which serializes ``sent`` and ships
    ``fp``-derived digests alongside it)."""

    # what arrived at the aggregation point: the transported stacked tree
    # (uncompressed) or the codec payload dict (compressed). Identity with
    # the input tree when nothing touched transport (clean, uncompressed).
    sent: object
    # receiver-side reconstruction to aggregate/mix: decoded ref+delta for
    # the compressed global/local modes, ``sent`` itself uncompressed,
    # None for mode="async" (the async merge decodes deltas itself)
    recon: object
    # ledger 0/1 auth mask over the stacked slots (None: ledger off or
    # commit=False)
    auth: Optional[np.ndarray]
    # [C, K] fingerprint rows of ``sent`` when commit=False (the dist wire
    # path: commit/verify happens at the remote leader, so the sender only
    # announces digests); None on the inline-commit path
    fp: Optional[np.ndarray]
    # the ledger struct-digest kind binding ``fp``/auth entries:
    # "stacked" (raw trees) or "payload" (codec payloads)
    wire_kind: str


# Cached jitted tree helpers. Defined once at module level so they compile
# once per shape signature — an inline ``jax.jit(lambda ...)`` built inside a
# round body would retrace EVERY round, and an unjitted ``jax.tree.map`` of
# arithmetic dispatches one op per leaf (hundreds of tiny device round-trips
# on a tunnelled TPU).
_tree_sub = jax.jit(lambda a, b: jax.tree.map(jnp.subtract, a, b))
_tree_axpy = jax.jit(
    lambda y, x, a: jax.tree.map(lambda yy, xx: yy + a * xx, y, x))
_tree_select = jax.jit(
    lambda s, b, p: jax.tree.map(
        lambda x, y: jnp.where(p.reshape((-1,) + (1,) * (x.ndim - 1)) > 0, y, x),
        s, b))
_tree_wsum = jax.jit(
    lambda ws, trees: jax.tree.map(
        lambda *xs: sum(w * x for w, x in zip(ws, xs)), *trees))
# simulated transport of a stacked update tree on the per-round path: the
# buffer that "arrives" is new_t + scale per client (0 = clean, an exact
# float identity) — the same corruption model the fused *_fp programs apply
# in-graph (client_step._transport), so per-round and fused chaos runs are
# comparable
_tree_corrupt = jax.jit(
    lambda t, s: jax.tree.map(
        lambda x: x + s.reshape((-1,) + (1,) * (x.ndim - 1)).astype(x.dtype),
        t))

logger = logging.getLogger(__name__)


class FedEngine:
    def __init__(
        self,
        cfg: FedConfig,
        tamper_hook: Optional[Callable] = None,
        info_source: int = 1,
        fused_tamper: Optional[Callable] = None,
    ):
        self.cfg = cfg
        # ``tamper_hook`` (host-tree byte tampering, forces the per-round
        # path) and ``fused_tamper`` ((rnd) -> [C] scales, in-graph transport
        # corruption for fused dispatches) are DEPRECATED shims over the
        # FaultPlan corruption API (bcfl_tpu.faults): new code schedules
        # corruption via cfg.faults, which works on both paths and composes
        # with every aggregator. The shims stay so existing tests/scripts
        # keep their exact semantics.
        if tamper_hook is not None or fused_tamper is not None:
            warnings.warn(
                "tamper_hook/fused_tamper are deprecated shims — schedule "
                "corruption via FedConfig.faults (bcfl_tpu.faults.FaultPlan)",
                DeprecationWarning, stacklevel=2)
        # --- cohort-batched scale-out (SCALING.md "Cohort mode") ---
        # self.C = the stacked client-axis width (the per-round cohort);
        # self.R = the client registry size. Sampling off: R == C ==
        # num_clients and every per-round id is an identity — the classic
        # layout, bit-identical to the pre-cohort engine. Sampling on:
        # registry-sized HOST arrays (faults, reputation, EF residuals)
        # carry per-client identity; only the sampled cohort's rows ever
        # reach the mesh.
        self.sampling = cfg.registry_size > 0
        self.C = ((cfg.sample_clients or cfg.num_clients) if self.sampling
                  else cfg.num_clients)
        self.R = cfg.registry_size if self.sampling else cfg.num_clients
        self.sampler = (ClientSampler(cfg.seed, self.R, self.C)
                        if self.sampling else None)
        self._cohort_cache = (-1, None)
        if self.sampling and (tamper_hook is not None
                              or fused_tamper is not None):
            raise ValueError(
                "the legacy tamper_hook/fused_tamper shims are positional "
                "over a fixed client set; with registry sampling schedule "
                "corruption via FedConfig.faults (its schedules are keyed "
                "by registry id)")
        self.faults = FaultInjector(
            cfg.faults, self.R,
            host_tamper=tamper_hook, fused_tamper=fused_tamper)
        # peer-lifecycle reputation (bcfl_tpu.reputation): host-side state
        # machine whose gate multiplier folds into each round's mask —
        # None when disabled; state rides the checkpoint. Sized by the
        # REGISTRY: a flaky peer keeps its record whether or not this
        # round's sampler drew it.
        self.reputation = (ReputationTracker(cfg.reputation, self.R)
                           if cfg.reputation.enabled else None)
        self.root_key = jax.random.key(cfg.seed,
                                       impl=cfg.resolved_prng_impl)
        # RESOLVED key impl: with prng_impl=None the run follows jax's
        # process default, which env vars can change — checkpoints must
        # record what actually ran, not the config field. The NAME is the
        # real identity (two different impls can share a key-data width,
        # e.g. rbg vs unsafe_rbg are both 4); the width stays recorded for
        # checkpoints written before the name existed
        self._prng_code = int(jax.random.key_data(self.root_key).shape[-1])
        self._prng_name = str(jax.random.key_impl(self.root_key))

        # --- data (tokenize once; SURVEY.md §3.2 fixes the 200x re-tokenize) ---
        self.dataset = load_dataset(
            cfg.dataset, num_labels=cfg.num_labels,
            text_col=cfg.text_col, label_col=cfg.label_col)
        self.tokenizer = get_tokenizer(cfg.tokenizer, cfg.vocab_size)
        self.cache = TokenCache.build(self.dataset, self.tokenizer, cfg.seq_len)
        self.num_labels = max(cfg.num_labels, self.cache.num_labels)
        self.partitioner = Partitioner(
            cfg.partition, self.dataset.n_train, self.dataset.n_test,
            jax.random.fold_in(self.root_key, 1),
        )

        # --- mesh (before the model: sp injects the mesh into attention) ---
        # pod=True spans every host's devices (hosts-major, DCN-outermost);
        # tp>1 makes the mesh 2-D (clients, tp) and megatron-shards the
        # frozen base; sp>1 makes it (clients, seq) and rides ring attention
        devices = pod_devices() if cfg.pod else None
        if self.sampling and cfg.cohort_size:
            # pin the per-device stack: exactly C/cohort_size CLIENT shards
            # (config validated the divisibility), each vmapping a
            # cohort_size-client slab. With an inner tp/sp axis the mesh
            # reserves `inner` devices per client shard, so the device
            # budget scales by it — without this, client_mesh would quietly
            # fold the shortfall back into a bigger per-device stack,
            # breaking the documented pin.
            devices = list(devices if devices is not None
                           else jax.devices())
            inner = max(cfg.tp, cfg.sp)
            need = (self.C // cfg.cohort_size) * inner
            if need > len(devices):
                raise ValueError(
                    f"cohort_size {cfg.cohort_size} needs {need} devices "
                    f"for a {self.C}-client cohort"
                    + (f" x {inner} inner (tp/sp) shards" if inner > 1
                       else "")
                    + f", have {len(devices)}")
            devices = devices[:need]
        self.mesh = client_mesh(self.C, devices=devices,
                                tp=cfg.tp, sp=cfg.sp)

        # --- model ---
        # dtype/attention knobs flow from the config into EVERY build path:
        # a config that says float32 compute must not silently train bf16
        dtype_overrides = {"dtype": jnp.dtype(cfg.compute_dtype),
                           "param_dtype": jnp.dtype(cfg.param_dtype)}
        if cfg.remat:
            dtype_overrides["remat"] = True
        if cfg.use_flash is not None:
            dtype_overrides["use_flash"] = cfg.use_flash
            if cfg.use_flash:
                # an explicit "on" FORCES the blockwise path at every
                # length (both families otherwise gate on flash_min_seq,
                # which would silently run dense attention below 512)
                dtype_overrides["flash_min_seq"] = 0
        if cfg.sp > 1:
            from bcfl_tpu.parallel.sp import SEQ_AXIS, ring_override

            # each client's attention becomes exact ring attention over the
            # mesh's seq axis (activations shard O(S/sp) per device); both
            # model families expose the hook — llama rides the causal ring,
            # encoders the non-causal one
            assert SEQ_AXIS in self.mesh.mesh.shape
            dtype_overrides["attention_override"] = ring_override(
                self.mesh.mesh)
            dtype_overrides["use_flash"] = False
        if cfg.hf_checkpoint is not None:
            if cfg.task == "causal_lm":
                raise ValueError(
                    "task='causal_lm' needs a decoder; the HF import path "
                    "builds encoder classifiers")
            from bcfl_tpu.models.hf_import import import_pretrained

            model_cfg, variables = import_pretrained(
                cfg.hf_checkpoint, num_labels=self.num_labels,
                reinit_classifier=True,
            )
            model_cfg = dataclasses.replace(model_cfg, **dtype_overrides)
            self.model = TextClassifier(model_cfg)
            # the importer materializes float32; the configured param dtype
            # must apply to the ARRAYS, not just the config record
            params = jax.tree.map(
                lambda x: x.astype(model_cfg.param_dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                variables["params"])
        else:
            from bcfl_tpu.models import build as build_model

            self.model = build_model(
                cfg.model, num_labels=self.num_labels,
                vocab_size=self.tokenizer.vocab_size,
                head="lm" if cfg.task == "causal_lm" else "classifier",
                **dtype_overrides,
            )
            ids = jnp.ones((2, cfg.seq_len), jnp.int32)
            params = self.model.init(
                jax.random.fold_in(self.root_key, 2), ids, ids)["params"]

        if cfg.lora_rank > 0:
            from bcfl_tpu.models import lora_targets

            self.frozen = params
            ranks = cfg.client_lora_ranks
            if ranks is not None and len(set(ranks)) > 1:
                # heterogeneous fleet: each client's adapters initialize AT
                # ITS OWN rank (own gaussian/sqrt(r_c) scale), zero-padded
                # to the cohort max; the round-0 global is their RBLA mean
                # (b starts at zeros everywhere, so the collapse only
                # blends the per-rank-normalized 'a' factors)
                from bcfl_tpu.parallel import gspmd

                stacked0 = lora_lib.init_lora_ranks(
                    jax.random.fold_in(self.root_key, 3), params, ranks,
                    targets=lora_targets(cfg.model))
                self.trainable0 = gspmd.rank_aware_weighted_mean(
                    stacked0, jnp.ones((len(ranks),), jnp.float32),
                    lora_lib.rank_mask(ranks))
            else:
                self.trainable0 = lora_lib.init_lora(
                    jax.random.fold_in(self.root_key, 3), params,
                    cfg.lora_rank, targets=lora_targets(cfg.model))
        else:
            self.frozen = None
            self.trainable0 = params

        # --- programs ---
        if cfg.tp > 1:
            from jax.sharding import NamedSharding

            from bcfl_tpu.models import tp_param_specs

            # tp_param_specs dispatches on the BUILT model's family (an
            # hf_checkpoint always builds an encoder, even when cfg.model
            # names a llama config)
            specs = tp_param_specs(self.model, self.frozen)
            if not any("tp" in str(s) for s in jax.tree.leaves(specs)):
                raise ValueError(
                    "tp > 1 but no parameter matched the tensor-parallel "
                    "layout — model family unsupported for tp")
            self.frozen = jax.device_put(
                self.frozen,
                jax.tree.map(lambda s: NamedSharding(self.mesh.mesh, s),
                             specs))
        self.progs: FedPrograms = build_programs(
            self.model, self.mesh,
            optimizer=cfg.optimizer, learning_rate=cfg.learning_rate,
            max_grad_norm=cfg.max_grad_norm,
            gossip_alpha=cfg.topology.gossip_alpha,
            gossip_steps=cfg.topology.gossip_steps,
            task=cfg.task,
            aggregator=cfg.aggregator,
            aggregator_trim=cfg.aggregator_trim,
            prng_impl=cfg.resolved_prng_impl,
            compression=cfg.compression,
            donate=cfg.donate,
            # cohort mode compiles the explicit hierarchical (within-device
            # stack, then cross-device) reduction into every mean
            # aggregation point (SCALING.md); normalized away for robust
            # aggregators, whose order statistics stay global
            hierarchical=self.sampling,
            # heterogeneous LoRA ranks: the per-client tuple is part of the
            # program-cache key; build_programs normalizes a uniform tuple
            # (or None) to the plain programs
            lora_ranks=cfg.client_lora_ranks,
        )
        # per-round rank-collapse guard (arXiv 2602.13486): mean effective
        # rank of the global adapter tree, one tiny separate jit (compiles
        # once — the round programs stay untouched); None when LoRA is off
        self._eff_rank = (jax.jit(lora_lib.effective_rank)
                          if cfg.lora_rank > 0 else None)
        # communication compression (COMPRESSION.md): None when disabled.
        # The error-feedback residual (stacked [C, ...] f32) is engine round
        # state, lazily initialized in _run and checkpointed — crash/resume
        # must reproduce compressed runs bit-for-bit too.
        self._comp = cfg.compression if cfg.compression.enabled else None
        self._ef = None
        self._ef_reg = None  # cohort-mode per-registry EF store, set below
        if self._comp is not None and tamper_hook is not None:
            # the legacy host-tamper shim byte-hashes FULL host trees; with
            # compression the wire carries payloads, so the two transport
            # models cannot compose (same exclusivity as FaultPlan corruption
            # vs tamper_hook)
            raise ValueError(
                "tamper_hook models byte-tampering of full host update "
                "trees; with compression enabled the wire carries encoded "
                "payloads — schedule corruption via FedConfig.faults "
                "(it corrupts the compressed representation)")
        if (self.faults.plan.corrupts and cfg.mode == "serverless"
                and cfg.sync != "async" and self.progs.mix_recv is None):
            # async is exempt: _async_round never mixes — `sent` feeds only
            # the delta merge, and each sender's carried state stays honest
            # without the transport-aware mix the corrupted copy would
            # REPLACE the sender's own carried state — the next round it
            # would honestly commit (and pass authentication for) garbage
            # params, diverging through a path the fault model says cannot
            # exist. Only the gspmd programs compile mix_recv today.
            raise ValueError(
                "serverless FaultPlan corruption requires the gspmd fed "
                "impl (mix_recv): the shard_map twin has no transport-aware "
                "mix, so in-flight corruption would poison the sender's own "
                "carried state (unset BCFL_FED_IMPL or set it to 'gspmd')")
        if cfg.donate and (cfg.sync == "async" or cfg.faithful):
            warnings.warn(
                "donate=True has no effect on the async/faithful paths — "
                "they run only undonated split-phase programs, so peak HBM "
                "is unchanged", stacklevel=2)
        # Pin the global trees to their steady-state shardings NOW: the round
        # programs return replicated trees, so a single-device-committed
        # trainable0 would make round 2's input sharding differ from round
        # 1's — a full recompile of the round program on the second round
        # (measured as the r04 bench's 87.5 s/dispatch artifact,
        # results/dispatch_bisect.json). frozen keeps its tp layout when
        # tp > 1 (placed above).
        self.trainable0 = self.mesh.replicate(self.trainable0)
        if self.frozen is not None and cfg.tp == 1:
            self.frozen = self.mesh.replicate(self.frozen)
        if self.sampling and self._comp is not None:
            # cohort-mode error-feedback store: residuals live per REGISTRY
            # client on the host; each round the sampled cohort's rows are
            # gathered onto the device and scattered back after (fed.cohort)
            self._ef_reg = EFRegistry(self.trainable0)

        # --- topology graph (positional over the round's stacked slots:
        # in cohort mode the network model applies to whoever is sampled) ---
        if cfg.topology.bandwidth == "reference" and self.C == 10:
            self.graph: LatencyGraph = reference_graph()
        else:
            self.graph = random_graph(
                self.C, cfg.topology.bw_low, cfg.topology.bw_high,
                seed=cfg.seed,
            )
        self.info_source = info_source % self.C

        self.ledger = Ledger(cfg.ledger.use_native) if cfg.ledger.enabled else None
        # bytes-on-wire accounting (COMPRESSION.md): what ONE client ships
        # per round, raw vs through the configured codec — host-side shape
        # arithmetic, no device transfer. Equal when compression is off.
        # Feeds RoundRecord.bytes_*, the topology comms model (_payload_gb),
        # and the ledger's per-entry payload accounting: the chain covers
        # (and bills for) what is actually transmitted.
        self._raw_bytes_per_client = cc.payload_nbytes(None, self.trainable0)
        self._wire_bytes_per_client = cc.payload_nbytes(
            self._comp, self.trainable0)
        self._client_payload_bytes = int(self._wire_bytes_per_client)
        self._struct_cache: Dict[str, bytes] = {}
        if self._comp is not None and self.ledger is not None:
            # ledger entries digest the COMPRESSED payload: precompute its
            # structure digest from an eval_shape of the encoder (no device
            # work), so split-phase and fused rounds bind identical digests
            C = self.C

            def _payload_shape(t):
                stacked = jax.tree.map(
                    lambda x: jnp.zeros((C,) + x.shape, jnp.float32), t)
                return cc.encode_tree(self._comp, stacked, jax.random.key(0))

            self._struct_cache["payload"] = fp_lib.struct_digest(
                jax.eval_shape(_payload_shape, self.trainable0),
                cfg.ledger.use_native)
        self.eval_batches = jax.tree.map(
            jnp.asarray, central_eval_batches(self.cache, cfg.batch_size,
                                              max_batches=cfg.max_eval_batches))
        self._static_batches = None  # cache when the partition is round-static

    # ------------------------------------------------------------------ utils

    def _cohort_ids(self, rnd: int) -> Optional[np.ndarray]:
        """The round's sampled registry ids ([C] int64), or None when
        sampling is off (stacked slot == client id). Cached per round —
        the sampler is a pure function of (seed, round), so the cache only
        saves the re-draw, never changes the value."""
        if self.sampler is None:
            return None
        if self._cohort_cache[0] != rnd:
            self._cohort_cache = (rnd, self.sampler.cohort_ids(rnd))
        return self._cohort_cache[1]

    def _client_id(self, rnd: int, pos: int) -> int:
        """Registry client id occupying stacked slot ``pos`` this round."""
        ids = self._cohort_ids(rnd)
        return int(ids[pos]) if ids is not None else pos

    def _transport_scales(self, rnd: int) -> Optional[np.ndarray]:
        """The round's transport-corruption scales for the STACKED slots:
        the plan draws per registry client; cohort mode slices the sampled
        rows (and an all-clean slice collapses to None, keeping the clean
        fast path). The one call-site rule of the FaultInjector still
        holds — every consumer (round bodies, reputation evidence) goes
        through here, so 'is corruption on the wire this round' can never
        disagree between them."""
        row = self.faults.transport_scales(rnd)
        ids = self._cohort_ids(rnd)
        if row is None or ids is None:
            return row
        row = row[ids]
        return row if row.any() else None

    def _round_batches(self, rnd: int):
        cfg = self.cfg
        # cohort mode: batches depend on WHO was sampled, so the
        # round-static cache only applies with sampling off
        static = (not (cfg.partition.kind == "iid"
                       and cfg.partition.resample_each_round)
                  and not self.sampling)
        if static and self._static_batches is not None:
            return self._static_batches
        ids = self._cohort_ids(rnd)
        tree, n_ex = client_batches(
            self.cache, self.partitioner,
            ids if ids is not None else self.C, rnd, cfg.batch_size,
            max_batches=cfg.max_local_batches,
        )
        out = (self.mesh.shard_clients(jax.tree.map(jnp.asarray, tree)),
               np.asarray(n_ex))
        if static:
            self._static_batches = out
        return out

    def _test_batches(self, rnd: int):
        cfg = self.cfg
        ids = self._cohort_ids(rnd)
        tree, _ = client_batches(
            self.cache, self.partitioner,
            ids if ids is not None else self.C, rnd, cfg.batch_size,
            max_batches=cfg.max_local_batches, split="test",
        )
        return self.mesh.shard_clients(jax.tree.map(jnp.asarray, tree))

    def _rngs(self, rnd: int):
        # keyed by REGISTRY id in cohort mode: a client's dropout/codec
        # stream depends on (seed, id, round), never on its cohort slot
        ids = self._cohort_ids(rnd)
        keys = client_round_keys(
            jax.random.fold_in(self.root_key, 4),
            ids if ids is not None else self.C, rnd)
        return self.mesh.shard_clients(jax.random.key_data(keys))

    def _participation(self, rnd: int, components=None) -> Dict:
        if components is not None:
            # under a chaos partition the filter sees each component's own
            # subgraph — cross-component links don't exist for the span
            return partitioned_anomaly_filter(
                self.cfg.topology.anomaly_filter, self.graph, components,
                protect=(self.info_source,),
            )
        return anomaly_filter(
            self.cfg.topology.anomaly_filter, self.graph,
            protect=(self.info_source,),
        )

    def _payload_gb(self) -> float:
        # the comms model scales by what actually crosses a link: the codec
        # payload when compression is on, the raw tree otherwise (for
        # compress=none this equals model_size_gb(trainable0) exactly —
        # both are sum(size * itemsize) / 1e9)
        return self._wire_bytes_per_client / 1e9

    def _comms_payload_bytes(self) -> int:
        """What one update exchange ships, for the info-passing model.

        Compression wins over the ledger constant: with a codec on, the
        update payload on the wire IS the compressed encoding (and the
        ledger's own accounting already bills those same bytes per entry —
        using the reference's fixed 0.043 GB blockchain figure here would
        make the two accountings disagree). Uncompressed ledger runs keep
        the reference's modeled ledger-entry payload (MT nb cell 27);
        everything else ships the raw tree."""
        if self._comp is not None:
            return int(self._wire_bytes_per_client)
        if self.ledger is not None:
            return int(self.cfg.ledger.entry_payload_bytes)
        return int(self._raw_bytes_per_client)

    def _global_eval(self, trainable) -> tuple:
        s = np.asarray(self.progs.eval_global(trainable, self.frozen, self.eval_batches))
        return float(s[0] / max(s[2], 1)), float(s[1] / max(s[2], 1))

    def _ledger_authenticate(self, rnd: int, host) -> np.ndarray:
        """Authenticate what 'arrived' against the already-committed chain
        (tamper_hook simulates in-flight modification). Returns 0/1 auth mask."""
        C = self.C
        tamper = self.faults.host_tamper
        shipped = tamper(rnd, host) if tamper else host
        auth = np.ones((C,), np.float32)
        for c in range(C):
            ok = self.ledger.authenticate(
                rnd, self._client_id(rnd, c),
                jax.tree.map(lambda x: x[c], shipped))
            auth[c] = 1.0 if ok else 0.0
        return auth

    def _entry_digest(self, kind: str, fp_row: np.ndarray) -> bytes:
        """Digest a device-computed fingerprint row, bound to the update
        tree's structure (names/dtypes/shapes). The structure template comes
        from ``jax.eval_shape`` over ``trainable0`` — no device transfer, and
        the fused and split-phase paths commit identical digests for the
        same content."""
        struct = self._struct_cache.get(kind)
        if struct is None:
            if kind == "payload":
                # precomputed in __init__ whenever ledger + compression are
                # both on; reaching here means a payload digest was requested
                # on an uncompressed run — a caller bug, not a cache miss
                raise RuntimeError(
                    "payload struct digest requested without compression")
            tmpl = self.trainable0
            if kind == "stacked":
                C = self.C
                tmpl = jax.eval_shape(
                    lambda t: jax.tree.map(
                        lambda x: jnp.broadcast_to(x[None], (C,) + x.shape),
                        t),
                    tmpl)
            struct = self._struct_cache[kind] = fp_lib.struct_digest(
                tmpl, self.cfg.ledger.use_native)
        return fp_lib.entry_digest(struct, fp_row,
                                   self.cfg.ledger.use_native)

    def _ledger_commit_rows(self, rnd: int, kind: str, fps) -> None:
        """Chain one entry per client for the given fingerprint rows [C, K].
        Entries are keyed by REGISTRY client id (slot id when sampling is
        off), so a client's chain history survives cohort reshuffles."""
        for c in range(self.C):
            self.ledger.append_digest(
                rnd, self._client_id(rnd, c),
                self._entry_digest(kind, fps[c]),
                self._client_payload_bytes)
        telemetry.emit("ledger", op="commit", round=int(rnd), n=self.C,
                       chain_len=len(self.ledger), rewrite=False,
                       head8=self.ledger.head.hex()[:16])

    def _ledger_auth_rows(self, rnd: int, kind: str, fps) -> np.ndarray:
        """0/1 auth mask: do the fingerprint rows match the committed chain
        entries for this round? Shared by the split-phase, fused, and
        faithful ledger paths so the digest binding cannot diverge."""
        return np.asarray([
            1.0 if self.ledger.authenticate_digest(
                rnd, self._client_id(rnd, c),
                self._entry_digest(kind, fps[c]))
            else 0.0
            for c in range(self.C)], np.float32)

    def _ledger_verify(self, rnd: int, stacked, sent=None,
                       kind: str = "stacked") -> np.ndarray:
        """Commit every client's update, then authenticate what arrived.
        Returns the 0/1 auth mask.

        ``stacked`` is the honest tree each client COMMITS; ``sent``
        (default: the same buffer) is the tree that survived the simulated
        transport stage and is about to be aggregated. When the fault plan
        corrupts transport the two differ, and authentication genuinely
        fails for exactly the corrupted clients — the per-round twin of the
        fused ``*_fp`` programs' in-graph commit/verify split.

        With compression on, callers pass the COMPRESSED payload trees and
        ``kind='payload'``: the chain then authenticates exactly the bytes
        on the wire, not a tree the network never carried.

        Default path: the content digest is a device-side fingerprint
        (:mod:`bcfl_tpu.ledger.fingerprint`) — only ``[C, K]`` floats cross
        the link instead of the full stacked tree (~4.4 GB/round for
        BERT-base x 10 clients over the r03 host path). A ``tamper_hook``
        simulates in-flight modification of HOST trees, so that path keeps
        the faithful full byte-hash flow."""
        C = self.C
        # dispatch is async: without this, the TRAINING compute of the
        # just-dispatched client_updates/local_updates program completes
        # inside this phase's first blocking transfer and gets billed to
        # the ledger (observed: a "90% ledger" reading that was ~95%
        # training wait). Must be core.fence — on the tunnelled backend
        # block_until_ready returns before the device finishes
        fence(stacked if sent is None else sent)
        with self.clock.phase("ledger"):
            if self.faults.host_tamper is not None:
                host = jax.device_get(stacked)
                for c in range(C):
                    self.ledger.append(rnd, c,
                                       jax.tree.map(lambda x: x[c], host))
                return self._ledger_authenticate(rnd, host)
            fp = np.asarray(self.progs.fingerprint(stacked))
            self._ledger_commit_rows(rnd, kind, fp)
            if sent is None or sent is stacked:
                # the committed HBM buffer IS the aggregated one: re-running
                # the fingerprint program would reproduce `fp` bit-for-bit
                # (device arrays are immutable), so auth re-derives digests
                # from it directly
                return self._ledger_auth_rows(rnd, kind, fp)
            fp_recv = np.asarray(self.progs.fingerprint(sent))
            return self._ledger_auth_rows(rnd, kind, fp_recv)

    # ------------------------------------------------------- fault utilities

    def _exchange_updates(self, rnd, new_t, ref_t, rngs, scales, mode,
                          commit: bool = True) -> ExchangeResult:
        """The update-exchange seam: one wire exchange of the round's
        stacked updates, shared by EVERY consumer — the per-round
        split-phase bodies (server/serverless/partitioned/async) and the
        dist runtime's real TCP transport (bcfl_tpu.dist) — so the codec
        encode, corruption sharding, transported-payload decode, and
        ledger digest binding can never drift apart (the fused ``*_fp``
        programs apply the same sequence in-graph).

        ``mode`` picks the compressed encoder: "global" (delta vs the
        replicated global), "local" (vs the stacked round-start params), or
        "async" (recon-free — the async/dist merges decode deltas
        themselves). Uncompressed runs ignore ``ref_t``/``mode``: the wire
        quantity is the stacked tree itself.

        ``commit=True`` (the local engine) chains+verifies inline via
        :meth:`_ledger_verify`. ``commit=False`` (the dist wire) skips the
        inline chain and instead returns the fingerprint rows of ``sent``
        so the caller can announce digests to a REMOTE leader, which
        commits and re-verifies what actually arrived."""
        if self._comp is None:
            sent = self._transport(new_t, scales)
            auth = fp = None
            if self.ledger is not None:
                if commit:
                    auth = self._ledger_verify(rnd, new_t, sent)
                else:
                    fence(sent)
                    fp = np.asarray(self.progs.fingerprint(sent))
            return ExchangeResult(sent=sent, recon=sent, auth=auth, fp=fp,
                                  wire_kind="stacked")
        if mode == "async":
            payload, self._ef = self.progs.encode_deltas_async(
                new_t, ref_t, self._ef, rngs)
            recon = None
        else:
            enc = (self.progs.encode_deltas if mode == "global"
                   else self.progs.encode_deltas_local)
            payload, recon, self._ef = enc(new_t, ref_t, self._ef, rngs)
        if scales is None:
            sent_p = payload
        else:
            sent_p = self.progs.corrupt_payload(
                payload, self.mesh.shard_clients(jnp.asarray(scales)))
            if recon is not None:
                # a corrupted wire yields a corrupted reconstruction —
                # re-decode the TRANSPORTED payload (the clean-path recon
                # came fused with the encode)
                recon = self.progs.decode_recon(sent_p, ref_t, new_t)
        auth = fp = None
        if self.ledger is not None:
            if commit:
                auth = self._ledger_verify(rnd, payload, sent_p,
                                           kind="payload")
            else:
                fence(sent_p)
                fp = np.asarray(self.progs.fingerprint(sent_p))
        return ExchangeResult(sent=sent_p, recon=recon, auth=auth, fp=fp,
                              wire_kind="payload")

    def _transport(self, stacked, scales):
        """Simulated transport of the round's stacked updates: returns the
        tree that 'arrives' at aggregation. Identity (the same buffer) when
        ``scales`` is None — callers draw the round's schedule ONCE via
        ``faults.transport_scales(rnd)`` and thread it here, so the
        'is corruption scheduled' decision and the scales actually applied
        can never come from different draws."""
        if scales is None:
            return stacked
        return _tree_corrupt(stacked,
                             self.mesh.shard_clients(jnp.asarray(scales)))

    def _note_degraded(self, rec, participation: np.ndarray) -> None:
        """Mark (and warn about) a round whose every client was eliminated
        by the anomaly gate x dropout x churn x reputation x ledger auth —
        the aggregation programs keep the previous params via their
        fallback input, so the run continues NaN-free but made no progress
        this round."""
        if float(np.asarray(participation).sum()) > 0.0:
            return
        rec.degraded = True
        logger.warning(
            "round %d: every client eliminated from the aggregate "
            "(mask/auth all zero) — keeping the previous global model",
            rec.round)

    # --------------------------------------------------- partition round body

    def _partitioned_round(self, rnd, trainable, stacked, mask, comps):
        """One round under a chaos network partition (ROBUSTNESS.md §6).

        The mesh never reshapes: every client still trains in the same
        compiled ``local_updates`` dispatch, but aggregation runs PER
        CONNECTED COMPONENT — each component's participants collapse through
        the configured aggregator (robust rules included) and only the
        component's members adopt its aggregate, so the components evolve as
        genuinely independent federations for the span. ``trainable``
        becomes the robust cross-component consensus (collapse over the
        per-client component models, weighted by participation): the
        eval/checkpoint view during the span and the reconciliation the
        heal round adopts — never a silent global average of divergent
        components, and a fully-eliminated component keeps its previous
        model instead of NaN-ing out.

        Composes with the ledger (split-phase commit/verify on what each
        client shipped), compression (the wire quantity is the encoded
        delta vs the client's round-start params — ``mode='local'``), and
        transport corruption/flaky bursts. Everything here is pre-compiled
        programs fed runtime masks/weights: zero per-round retraces."""
        cfg = self.cfg
        C = self.C
        batches, n_ex = self._round_batches(rnd)
        rngs = self._rngs(rnd)
        if stacked is None:
            # span entry from server mode: every client starts the span
            # from the last whole-mesh global
            stacked = self.progs.broadcast(trainable)
        start = stacked
        stacked, stats = self.progs.local_updates(
            stacked, self.frozen, batches, rngs)
        rec = self._stats_to_rec(rnd, stats)
        scales = self._transport_scales(rnd)
        # wire exchange through the shared seam: the wire quantity is the
        # encoded delta vs the client's round-start params (mode="local")
        # when compression is on, the stacked tree itself otherwise
        ex = self._exchange_updates(rnd, stacked, start, rngs, scales,
                                    mode="local")
        agg_src, auth = ex.recon, ex.auth
        if auth is not None:
            rec.auth = auth.tolist()
            mask = mask * auth
        w = np.asarray(mask, np.float32) * (
            np.asarray(n_ex, np.float32) if cfg.weighted_agg else 1.0)
        part_id = np.full((C,), -1, np.int64)
        out = stacked
        for ci, comp in enumerate(comps):
            cm = np.zeros((C,), np.float32)
            cm[list(comp)] = 1.0
            part_id[list(comp)] = ci
            wc = w * cm
            if float(wc.sum()) <= 0.0:
                # fully-eliminated component: in server mode its members
                # keep the component's round-start model (identical rows by
                # construction); serverless members keep their own
                # post-train state, the existing all-masked semantics
                if cfg.mode == "server":
                    out = _tree_select(
                        out, start, self.mesh.shard_clients(jnp.asarray(cm)))
                logger.warning(
                    "round %d: partition component %d fully eliminated — "
                    "keeping its previous model", rnd, ci)
                continue
            comp_mean = self.progs.collapse(
                agg_src, self.mesh.shard_clients(jnp.asarray(wc)), trainable)
            if cfg.mode == "server":
                pull = cm  # every member receives the component model
            else:
                # serverless: masked clients keep their own carried state
                pull = cm * (np.asarray(mask) > 0)
            out = self.progs.adopt(
                out, comp_mean, self.mesh.shard_clients(jnp.asarray(
                    pull, jnp.float32)))
        # robust consensus ACROSS components (participation-weighted
        # collapse over the per-client component models): the span's
        # eval/checkpoint view and what the heal round reconciles onto
        consensus = self.progs.collapse(
            out, self.mesh.shard_clients(jnp.asarray(w)), trainable)
        rec.partition = part_id.tolist()
        self._note_degraded(rec, mask)
        return consensus, out, rec

    def _heal_partition(self, trainable, stacked, mask):
        """First whole-mesh round after a partition span: the reconciled
        global — the robust cross-component consensus the last partitioned
        round computed — becomes the starting point. Server mode resumes
        from it directly (the stacked per-component view is dropped);
        serverless participants adopt it into their carried state. Either
        way the components reconcile through the configured aggregator,
        deterministically, rather than silently averaging divergent models
        inside the next round's mix."""
        if self.cfg.mode == "server":
            return trainable, None
        pull = self.mesh.shard_clients(jnp.asarray(
            (np.asarray(mask) > 0).astype(np.float32)))
        return trainable, self.progs.adopt(stacked, trainable, pull)

    # ------------------------------------------------------ reputation bridge

    def _reputation_observe(self, rnd: int, rec, gate: Dict) -> None:
        """Fold this round's evidence into the peer-lifecycle tracker and
        record the post-round states on the RoundRecord. Evidence sources
        (combined per client by max, each weighted by the config):

        - ledger-auth failure — the update that arrived failed chain
          authentication (the hard, protocol-level evidence),
        - anomaly-filter flag — the topology heuristics singled the peer out,
        - injected corruption hit — the chaos plan corrupted this peer's
          transport this round (the simulation's stand-in for a local
          detector; coincides with auth failure when the ledger is on;
          disable via reputation.observe_injected=False),
        - async staleness beyond ``staleness_limit``.

        Quarantined peers accrue nothing (they were excluded); the tracker
        just ticks their sentence. Every input derives from seeded draws
        and recorded round outputs, so the trajectory is deterministic and
        crash/resume-stable."""
        rcfg = self.cfg.reputation
        C = self.C
        fault = np.zeros((C,), np.float64)
        if rec.auth is not None:
            failed = (np.asarray(rec.auth, np.float64) == 0.0)
            fault = np.maximum(fault, rcfg.w_auth * failed)
        if gate["anomalies"]:
            flag = np.zeros((C,), np.float64)
            flag[list(gate["anomalies"])] = 1.0
            fault = np.maximum(fault, rcfg.w_anomaly * flag)
        if rcfg.observe_injected:
            scales = self._transport_scales(rnd)  # deterministic redraw
            if scales is not None:
                hit = (np.asarray(scales, np.float64) != 0.0)
                fault = np.maximum(fault, rcfg.w_corrupt * hit)
        if rec.staleness is not None and rcfg.staleness_limit > 0:
            stale = (np.asarray(rec.staleness, np.float64)
                     > rcfg.staleness_limit)
            fault = np.maximum(fault, rcfg.w_staleness * stale)
        ids = self._cohort_ids(rnd)
        if ids is None:
            self.reputation.observe(fault)
            rec.reputation_state = self.reputation.state_names()
            rec.reputation_trust = [float(t) for t in self.reputation.trust]
            return
        # cohort mode: scatter the cohort's evidence into the
        # registry-sized tracker. Only sampled peers are 'active' — their
        # EWMA and probation clocks advance; a non-sampled peer's trust
        # must not drift on rounds it never participated in (quarantine
        # sentences still tick: wall rounds pass either way). The record
        # carries the cohort's post-round view, slot-aligned with
        # mask/auth.
        fault_r = np.zeros((self.R,), np.float64)
        fault_r[ids] = fault
        active = np.zeros((self.R,), bool)
        active[ids] = True
        self.reputation.observe(fault_r, active=active)
        names = self.reputation.state_names()
        rec.reputation_state = [names[int(i)] for i in ids]
        rec.reputation_trust = [float(self.reputation.trust[int(i)])
                                for i in ids]

    # ------------------------------------------------------------------- run

    def run(self, resume: bool = False, on_round=None) -> RunResult:
        """on_round: optional callable(RoundRecord), invoked after each round
        record is finalized (long runs are otherwise silent until the end)."""
        # event telemetry (OBSERVABILITY.md): the local engine streams only
        # when a directory is named (the dist runtime defaults ON instead —
        # its run dir is the natural home). Installed around the whole run
        # so StepClock phases, ledger commits, reputation transitions, and
        # checkpoint events all land in one stream; a SimulatedCrash still
        # closes it with its status.
        cfg = self.cfg
        installed = None
        if cfg.telemetry_dir and cfg.telemetry_dir != "off":
            installed = telemetry.install(telemetry.EventWriter(
                os.path.join(cfg.telemetry_dir, "events_engine.jsonl"),
                peer=None, run=cfg.name, sample=cfg.telemetry_sample))
            telemetry.emit("run.start", role="engine", resume=resume,
                           clients=self.C, rounds=cfg.num_rounds)
        status = "crashed"
        try:
            with trace(self.cfg.profile_dir):
                out = self._run(resume, on_round)
            status = "ok"
            return out
        finally:
            if installed is not None:
                telemetry.emit("run.end", status=status)
                telemetry.uninstall()

    def _run(self, resume: bool = False, on_round=None) -> RunResult:
        cfg = self.cfg
        monitor = ResourceMonitor()
        metrics = RunMetrics()
        clock = self.clock = StepClock()
        start_round = 0
        trainable = self.trainable0
        stacked = None

        resumed_from_checkpoint = False
        if resume and cfg.checkpoint_dir:
            restored = restore_latest(cfg.checkpoint_dir)
            if restored is not None:
                resumed_from_checkpoint = True
                start_round, state, ledger_json = restored
                start_round += 1
                ck_name = state.get("prng_impl_name")
                if ck_name is not None:
                    ck_name = bytes(np.asarray(ck_name, np.uint8)).decode()
                    if ck_name != self._prng_name:
                        raise ValueError(
                            f"checkpoint prng impl {ck_name!r} != this run's "
                            f"{self._prng_name!r} "
                            f"(prng_impl={cfg.prng_impl!r}): resuming would "
                            "change the RNG stream")
                # width-only fallback for checkpoints that predate the name
                # field (cannot distinguish same-width impls, e.g. rbg vs
                # unsafe_rbg — the name check above exists for exactly that)
                ck_impl = state.get("prng_impl_code")
                if ck_impl is not None and int(ck_impl) != self._prng_code:
                    raise ValueError(
                        f"checkpoint prng key width {int(ck_impl)} != this "
                        f"run's {self._prng_code} "
                        f"(prng_impl={cfg.prng_impl!r}): resuming would "
                        "change the RNG stream")
                ck_comp = state.get("compress_format")
                if ck_comp is not None:
                    ck_comp = bytes(np.asarray(ck_comp, np.uint8)).decode()
                    here = cc.wire_format(self._comp)
                    if ck_comp != here:
                        # a codec change across resume would re-inject the
                        # checkpointed error-feedback residual into a
                        # different encode (or drop it) silently — same
                        # guard class as the prng-impl check above
                        raise ValueError(
                            f"checkpoint was written with compress="
                            f"{ck_comp!r} but this run has {here!r}: "
                            "resuming would change the wire format under "
                            "the carried error-feedback state")
                ck_lora = state.get("lora_format")
                if ck_lora is not None:
                    ck_lora = bytes(np.asarray(ck_lora, np.uint8)).decode()
                    here = self._lora_format()
                    if ck_lora != here:
                        raise ValueError(
                            f"checkpoint was written with LoRA layout "
                            f"{ck_lora!r} but this run has {here!r}: "
                            "resuming would reinterpret the checkpointed "
                            "adapter (and error-feedback) trees under a "
                            "different rank layout")
                ck_seed = state.get("seed")
                if ck_seed is not None and int(ck_seed) != cfg.seed:
                    raise ValueError(
                        f"checkpoint was written with seed {int(ck_seed)} but "
                        f"config has seed {cfg.seed}: resuming would break the "
                        "per-(client, round) RNG stream")
                # cohort identity: the sampler is a pure function of
                # (seed, registry_size, sample_clients, round) — the seed
                # check above plus these two pin the remaining rounds'
                # cohorts bit-for-bit; a change would silently re-deal
                # every future cohort
                ck_reg = state.get("registry_size")
                want_sc = self.C if self.sampling else 0
                if ck_reg is not None:
                    ck_sc = int(state.get("sample_clients") or 0)
                    if (int(ck_reg) != int(cfg.registry_size)
                            or ck_sc != want_sc):
                        raise ValueError(
                            "checkpoint was written with registry_size="
                            f"{int(ck_reg)}/sample_clients={ck_sc} but this "
                            f"run has {cfg.registry_size}/{want_sc}: "
                            "resuming would change the per-round cohort "
                            "stream")
                elif self.sampling:
                    raise ValueError(
                        "checkpoint predates cohort mode (no registry_size "
                        "recorded) but this run samples a registry: "
                        "resuming would change every remaining round's "
                        "cohort")
                # checkpoints written under a different param_dtype must not
                # silently override the configured one on resume
                pd = jnp.dtype(cfg.param_dtype)

                def _cast(t):
                    return jax.tree.map(
                        lambda x: jnp.asarray(x, pd)
                        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)
                        else jnp.asarray(x), t)

                if state.get("stacked") is not None:
                    stacked = self.mesh.shard_clients(_cast(state["stacked"]))
                if (state.get("ef_residual") is not None
                        and self._comp is not None):
                    # error-feedback state travels with the checkpoint: a
                    # compressed crash/resume must re-inject exactly the
                    # residual the uninterrupted run would have carried
                    self._ef = self.mesh.shard_clients(jax.tree.map(
                        lambda x: jnp.asarray(x, jnp.float32),
                        state["ef_residual"]))
                if self._ef_reg is not None:
                    # cohort mode carries residuals per REGISTRY client
                    # instead (ef_ids + ef_registry); the round loop
                    # re-gathers each cohort's rows from the restored store
                    self._ef_reg.restore(state)
                # replicate: a resumed tree left on the default device would
                # re-trigger the round-2 recompile (tests/test_recompile.py)
                trainable = self.mesh.replicate(_cast(state["trainable"]))
                if (self.reputation is not None
                        and state.get("rep_trust") is not None):
                    # peer-lifecycle state travels with the checkpoint: a
                    # resumed run must pick up every trust score, lifecycle
                    # state, and quarantine timer exactly where the crash
                    # left them (tests/test_reputation.py pins bit-equality)
                    self.reputation.restore(state)
                if ledger_json and self.ledger is not None:
                    self.ledger = Ledger.from_json(
                        ledger_json, cfg.ledger.use_native)

        # single-shot guard AFTER the restore branch: resume supplies a
        # fresh trainable, so a donated-away trainable0 only matters when
        # it is actually the tree this run will consume
        if (cfg.donate and trainable is self.trainable0
                and any(getattr(x, "is_deleted", lambda: False)()
                        for x in jax.tree.leaves(self.trainable0))):
            raise RuntimeError(
                "engine.run() is single-shot with donate=True: round 1 "
                "donated the initial trainable buffers to the round "
                "program. Build a fresh FedEngine (or resume from a "
                "checkpoint, or set donate=False) to run again.")

        if (self._comp is not None and self._ef is None
                and self._ef_reg is None):
            # fresh error-feedback state (zeros): round 1's encode sees the
            # pure delta, later rounds re-inject what compression dropped.
            # Cohort mode skips this — each round gathers its cohort's
            # residual rows from the registry store instead.
            self._ef = self.progs.ef_init(trainable)

        if cfg.mode == "serverless" and not cfg.faithful and stacked is None:
            stacked = self.progs.broadcast(trainable)

        async_state = self._init_async_state() if cfg.sync == "async" else None

        rnd = start_round
        while rnd < cfg.num_rounds:
            if not resumed_from_checkpoint and self.faults.should_crash(rnd):
                # chaos-plan host crash: nothing of round `rnd` runs; the
                # newest checkpoint is the only state that survives. Raised
                # BEFORE any dispatch so a resumed run reproduces the
                # uninterrupted one bit-for-bit (tests/test_faults.py).
                # The crash models ONE host failure, so a run that actually
                # restored a checkpoint does not re-fire it — otherwise the
                # documented crash -> --resume workflow could never get
                # past the crash round (resume restarts at or before it).
                # Gated on the RESTORE, not the resume flag: a standing
                # --resume on a fresh checkpoint dir must still crash, or
                # the chaos experiment silently never happens
                raise SimulatedCrash(rnd)
            chunk = self._chunk_rounds(rnd)
            if chunk > 1:
                t0 = time.time()
                with clock.phase("round_program"):
                    if cfg.mode == "server":
                        trainable, recs = self._server_chunk(
                            rnd, trainable, chunk)
                    else:
                        stacked, trainable, recs = self._serverless_chunk(
                            rnd, stacked, trainable, chunk)
                self._annotate_chunk(recs, time.time() - t0)
                if self._eff_rank is not None and recs:
                    # fused dispatch: only the chunk's FINAL global exists
                    # host-side; the guard statistic lands on its record
                    recs[-1].effective_rank = float(self._eff_rank(trainable))
                last_rnd = rnd + chunk - 1
                self._maybe_eval(last_rnd, recs[-1], trainable, stacked, clock)
                metrics.rounds.extend(recs)
                self._maybe_checkpoint(last_rnd, trainable, stacked)
                for r in recs:
                    telemetry.emit("round", round=r.round, wall_s=r.wall_s,
                                   fused=True, degraded=r.degraded)
                if on_round is not None:
                    for r in recs:
                        on_round(r)
                rnd += chunk
                continue

            if (self.faults.fused_tamper is not None
                    and self.faults.fused_tamper(rnd) is not None):
                # the transport-corruption stage only exists inside the fused
                # *_fp programs: silently dropping a requested corruption on
                # a per-round-path round would let a verification test pass
                # vacuously (auth all-ones because nothing was corrupted)
                raise ValueError(
                    f"fused_tamper requests corruption for round {rnd}, but "
                    "this round runs the per-round path (chunk=1: check "
                    "rounds_per_dispatch, eval/checkpoint boundaries, and "
                    "_chunk_rounds eligibility) — the corruption would be "
                    "silently ignored; use tamper_hook for per-round "
                    "tampering")

            t0 = time.time()
            ids = self._cohort_ids(rnd)
            comps = self.faults.partition_components(rnd)
            with clock.phase("control_plane"):
                gate = self._participation(rnd, comps)
                mask = gate["mask"].astype(np.float32)
                # chaos dropout composes with the anomaly gate exactly like
                # a second filter: the mesh never reshapes, dropped clients
                # carry weight 0 for the round. All chaos lanes draw per
                # REGISTRY client; cohort_view slices the sampled rows
                # (identity when sampling is off).
                keep = cohort_view(self.faults.dropout_keep(rnd), ids)
                dropped = None
                if keep is not None:
                    # SLOT indices, like every other per-client index list
                    # on the record (anomalies, mask positions); cohort
                    # mode recovers registry identity via rec.cohort[slot]
                    dropped = [c for c in range(self.C) if keep[c] == 0.0]
                    mask = mask * keep
                # churn: permanently-departed / not-yet-joined clients carry
                # weight 0 — the monotone twin of dropout
                alive = cohort_view(self.faults.churn_alive(rnd), ids)
                if alive is not None:
                    mask = mask * alive
                # reputation gate: quarantined peers 0, probation peers a
                # reduced vote weight (bcfl_tpu.reputation; registry-sized,
                # cohort-sliced)
                if self.reputation is not None:
                    mask = mask * cohort_view(self.reputation.gate(), ids)
                healed = False
                if (comps is None and stacked is not None and rnd > 0
                        and self.faults.partition_components(rnd - 1)
                        is not None):
                    # partition span just ended: reconcile (derived from the
                    # PLAN, not carried flags, so a resumed run heals at
                    # exactly the same round as the uninterrupted one)
                    trainable, stacked = self._heal_partition(
                        trainable, stacked, mask)
                    healed = True

            delays = cohort_view(self.faults.straggler_delays(rnd), ids)
            if delays is not None and not delays.any():
                delays = None  # no sampled client straggles this round
            if self._ef_reg is not None:
                # gather the cohort's error-feedback residual rows from the
                # per-registry store (zeros for never-sampled clients) —
                # the compiled codec programs see the usual [C, ...] carry
                self._ef = self.mesh.shard_clients(jax.tree.map(
                    jnp.asarray, self._ef_reg.gather(ids)))
            with clock.phase("round_program"):
                if comps is not None:
                    trainable, stacked, rec = self._partitioned_round(
                        rnd, trainable, stacked, mask, comps)
                elif cfg.sync == "async":
                    trainable, stacked, rec = self._async_round(
                        rnd, trainable, stacked, mask, async_state,
                        delays=delays)
                elif cfg.mode == "server":
                    trainable, rec = self._server_round(rnd, trainable, mask)
                elif cfg.faithful:
                    trainable, rec = self._faithful_round(rnd, trainable, mask)
                else:
                    stacked, trainable, rec = self._serverless_round(
                        rnd, stacked, trainable, mask)
            if self._ef_reg is not None:
                # scatter the updated residual rows back by registry id
                # BEFORE eval/checkpoint, so the checkpointed store matches
                # the uninterrupted run's at every boundary
                self._ef_reg.scatter(ids, jax.device_get(self._ef))

            rec.mask = mask.tolist()
            if ids is not None:
                rec.cohort = ids.tolist()
            rec.anomalies = list(gate["anomalies"])
            rec.healed = healed
            if dropped is not None:
                rec.dropped = dropped
            if alive is not None:
                rec.churn_alive = alive.tolist()
            if delays is not None:
                rec.straggler_s = delays.tolist()
            # info passing: during a partition the source informs only its
            # own component; churned-out clients are not targets either
            # (the source itself always stays in the restricted set — a
            # departed source degenerates to informing whoever remains,
            # which with everyone else gone is (0, 0), not a crash)
            restrict = None
            if comps is not None:
                restrict = list(next(
                    c for c in comps if self.info_source in c))
            if alive is not None:
                base = (restrict if restrict is not None
                        else range(self.C))
                restrict = [c for c in base
                            if alive[c] > 0 or c == self.info_source]
            sync_t, async_t = self.graph.info_passing_time(
                0.0, source=self.info_source, anomalies=gate["anomalies"],
                extra_delay=delays,
                payload_bytes=self._comms_payload_bytes(),
                restrict=restrict,
            )
            rec.info_passing_sync_s = sync_t
            rec.info_passing_async_s = async_t
            rec.wall_s = time.time() - t0
            if self._eff_rank is not None:
                rec.effective_rank = float(self._eff_rank(trainable))

            if self.reputation is not None:
                # evidence folds in BEFORE eval/checkpoint so the
                # checkpointed tracker state matches the uninterrupted
                # run's at every checkpoint boundary
                self._reputation_observe(rnd, rec, gate)
            self._maybe_eval(rnd, rec, trainable, stacked, clock)
            metrics.rounds.append(rec)
            self._maybe_checkpoint(rnd, trainable, stacked)
            telemetry.emit("round", round=rnd, wall_s=rec.wall_s,
                           degraded=rec.degraded, healed=rec.healed,
                           partitioned=rec.partition is not None)
            if on_round is not None:
                on_round(rec)
            rnd += 1

        params = _merge(trainable, self.frozen)
        metrics.model_size_gb = model_size_gb(params)
        metrics.resources = monitor.snapshot()
        metrics.phases = clock.summary()
        # run-level bytes-on-wire accounting (COMPRESSION.md): per-round
        # totals are on every RoundRecord; this is the headline rollup
        # (per-cohort in sampling mode: only sampled clients ship updates)
        C = self.C
        metrics.comms = {
            "compress": cfg.compression.kind,
            "bytes_raw_per_round": float(self._raw_bytes_per_client * C),
            "bytes_on_wire_per_round": float(
                self._wire_bytes_per_client * C),
            "compression_ratio": float(
                self._raw_bytes_per_client
                / max(self._wire_bytes_per_client, 1)),
        }
        if self.ledger is not None and len(self.ledger):
            metrics.ledger = self.ledger.payload_accounting()
            metrics.ledger["chain_ok"] = float(self.ledger.verify_chain() == -1)
        if self.reputation is not None:
            metrics.reputation = self.reputation.summary()
        return RunResult(metrics=metrics, trainable=trainable, params=params,
                         ledger=self.ledger)

    # ------------------------------------------------- eval/checkpoint cadence

    def _maybe_eval(self, rnd: int, rec: RoundRecord, trainable, stacked,
                    clock) -> None:
        cfg = self.cfg
        # the FINAL round always evaluates (when eval is on at all): with
        # eval_every=N and rounds % N != 0 the run would otherwise end
        # without a final-round number, and callers report accs[-1] as the
        # final accuracy
        due = ((rnd + 1) % cfg.eval_every == 0
               or rnd == cfg.num_rounds - 1) if cfg.eval_every else False
        if not due:
            return
        with clock.phase("eval"):
            loss, acc = self._global_eval(trainable)
            rec.global_loss, rec.global_acc = loss, acc
            # reference-style per-client local accuracy on each client's
            # LOCAL TEST split (serverless_NonIID_IMDB.py:291-292; Flower
            # client.evaluate server_IID_IMDB.py:176-179)
            tb = self._test_batches(rnd)
            if stacked is not None:
                s = self.progs.eval_clients(stacked, self.frozen, tb)
            else:
                s = self.progs.eval_clients_global(trainable, self.frozen, tb)
            s = np.asarray(s)
            rec.local_acc = (s[:, 1] / np.maximum(s[:, 2], 1)).tolist()

    def _lora_format(self) -> str:
        """Checkpoint identity of the LoRA layout: ``full`` (no adapters),
        ``r<k>`` uniform, or the per-client spec ``ranks:2,4,8,...``. Like
        ``compress_format``, a change across resume would silently
        reinterpret the restored trainable/EF trees — resume refuses it."""
        cfg = self.cfg
        if cfg.lora_rank <= 0:
            return "full"
        ranks = cfg.client_lora_ranks
        if ranks is None or len(set(ranks)) <= 1:
            return f"r{cfg.lora_rank}"
        return "ranks:" + ",".join(str(r) for r in ranks)

    def _maybe_checkpoint(self, rnd: int, trainable, stacked) -> None:
        cfg = self.cfg
        if not (cfg.checkpoint_dir and cfg.checkpoint_every
                and (rnd + 1) % cfg.checkpoint_every == 0):
            return
        state = {
            "trainable": jax.device_get(trainable),
            "stacked": jax.device_get(stacked) if stacked is not None else None,
            # compression error-feedback residual (None when compression is
            # off); required for bit-identical compressed crash/resume.
            # Cohort mode stores the per-REGISTRY store (ef_ids/ef_registry
            # below) instead — the stacked device buffer is just the last
            # cohort's gathered view.
            "ef_residual": (jax.device_get(self._ef)
                            if self._ef is not None and self._ef_reg is None
                            else None),
            # cohort identity: with cfg.seed these pin the sampler's entire
            # cohort stream; resume refuses a change (above)
            "registry_size": np.int64(cfg.registry_size),
            "sample_clients": np.int64(self.C if self.sampling else 0),
            # codec identity, uint8-encoded (orbax trees hold arrays):
            # resume refuses a wire-format change under the carried residual
            "compress_format": np.frombuffer(
                cc.wire_format(self._comp).encode(), np.uint8).copy(),
            # the RNG stream is derived deterministically from the seed +
            # round + key impl; storing both lets resume verify them
            "seed": np.int64(cfg.seed),
            # resolved key-data width (orbax trees hold arrays): threefry=2,
            # rbg=4 — see __init__._prng_code
            "prng_impl_code": np.int64(self._prng_code),
            # resolved impl NAME, uint8-encoded (orbax trees hold arrays):
            # distinguishes same-width impls (rbg vs unsafe_rbg)
            "prng_impl_name": np.frombuffer(
                self._prng_name.encode(), np.uint8).copy(),
            # LoRA rank identity, uint8-encoded ("r<uniform>" or the
            # per-client spec): resuming under a different rank layout
            # would reinterpret the checkpointed adapter (and EF) trees —
            # resume refuses a mismatch (below)
            "lora_format": np.frombuffer(
                self._lora_format().encode(), np.uint8).copy(),
        }
        if self.reputation is not None:
            # rep_trust / rep_state / rep_timer / counters: the peer
            # lifecycle must resume exactly where the crash left it
            state.update(self.reputation.checkpoint_state())
        if self._ef_reg is not None and len(self._ef_reg):
            # per-registry-client EF residuals (fed.cohort.EFRegistry)
            state.update(self._ef_reg.checkpoint_state())
        save_checkpoint(
            cfg.checkpoint_dir, rnd, state,
            self.ledger.to_json() if self.ledger else None,
        )

    # -------------------------------------------------- multi-round fast path

    def _chunk_rounds(self, rnd: int) -> int:
        """How many rounds starting at ``rnd`` can fuse into one dispatch.

        Eligible only when the host has nothing to do between rounds: sync
        server FedAvg or sync parallel serverless gossip (NOT the faithful
        host-sequential mode), no anomaly filter (the mask is all-ones), no
        host tamper hook. The LEDGER no longer blocks fusion: the fused
        ``*_fp`` programs commit each round's per-client fingerprints
        in-graph BEFORE a simulated-transport stage, re-fingerprint the
        transported buffer AFTER it, gate the aggregation by the in-graph
        comparison, and the host chain authenticates the post-transport
        fingerprints — so fused-mode auth genuinely fails for a corrupted
        update (``fused_tamper``) instead of being an identity. A host
        tamper hook (or the shard_map impl, which has no fp programs) falls
        back to per-round. Chunks never cross an eval or checkpoint
        boundary, so the observable cadence is identical to the per-round
        path."""
        cfg = self.cfg
        k = cfg.rounds_per_dispatch
        ledger_blocks = (self.ledger is not None
                         and self.progs.server_rounds_fp is None)
        if (k <= 1 or cfg.sync != "sync"
                or (cfg.mode != "server" and cfg.faithful)
                or ledger_blocks or self.faults.host_tamper is not None
                or self.faults.blocks_fusion()
                or self.reputation is not None
                or self.sampling
                or cfg.topology.anomaly_filter is not None):
            # reputation needs the host between rounds: the lifecycle state
            # machine consumes each round's evidence before gating the next.
            # Cohort sampling does too: each round's batches/rngs/ledger ids
            # belong to a different sampled cohort, and the EF-residual
            # gather/scatter is host work between rounds by construction.
            return 1
        k = min(k, cfg.num_rounds - rnd)
        if cfg.eval_every:
            k = min(k, cfg.eval_every - rnd % cfg.eval_every)
        if cfg.checkpoint_dir and cfg.checkpoint_every:
            k = min(k, cfg.checkpoint_every - rnd % cfg.checkpoint_every)
        return max(k, 1)

    def _chunk_inputs(self, rnd: int, k: int):
        """Stage batches/rngs/example-counts for rounds [rnd, rnd+k).

        Returns ``(static, batches, rrngs, n_ex_list)``: ``static=True``
        means ONE batch tree [C, ...] reused every round (round-static
        partition cache hit — stacking k identical copies would be a k-fold
        HBM blowup for no information), else ``batches`` is the stacked
        [k, C, ...] tree."""
        batch_list, rng_list, n_ex_list = [], [], []
        for r in range(rnd, rnd + k):
            b, n_ex = self._round_batches(r)
            batch_list.append(b)
            n_ex_list.append(n_ex)
            rng_list.append(self._rngs(r))
        rrngs = self.mesh.shard_round_clients(
            jnp.stack([jnp.asarray(r) for r in rng_list]))
        if all(b is batch_list[0] for b in batch_list):
            return True, batch_list[0], rrngs, n_ex_list
        rbatches = self.mesh.shard_round_clients(
            jax.tree.map(lambda *xs: jnp.stack(xs), *batch_list))
        return False, rbatches, rrngs, n_ex_list

    def _commit_chunk_fps(self, rnd: int, k: int, fps_commit, fps_recv,
                          recs) -> None:
        """Fused-mode ledger flow: chain each round's PRE-transport commit
        fingerprints ([k, C, K], computed in-graph before the simulated
        transport stage), then authenticate the POST-transport fingerprints
        against the chain. The two trees differ whenever transport corrupted
        an update (``fused_tamper``), so this auth can genuinely fail — and
        the in-graph aggregation already excluded exactly those clients."""
        fps_commit = np.asarray(fps_commit)  # blocks on the fused dispatch
        fps_recv = np.asarray(fps_recv)
        # compressed fused rounds fingerprint the PAYLOAD (client_step
        # _fp_auth_payload), so the chain entry binds the payload structure
        kind = "stacked" if self._comp is None else "payload"
        with self.clock.phase("ledger"):
            for i in range(k):
                self._ledger_commit_rows(rnd + i, kind, fps_commit[i])
            for i, rec in enumerate(recs):
                rec.auth = self._ledger_auth_rows(
                    rnd + i, kind, fps_recv[i]).tolist()

    def _chunk_corrupts(self, rnd: int, k: int):
        """[k, C] transport-corruption scales for the fused fp programs
        (zeros = clean; see ``fused_tamper`` in ``__init__``)."""
        corr = np.zeros((k, self.C), np.float32)
        if self.faults.fused_tamper is not None:
            for i in range(k):
                row = self.faults.fused_tamper(rnd + i)
                if row is not None:
                    corr[i] = np.asarray(row, np.float32)
        return self.mesh.shard_round_clients(jnp.asarray(corr))

    def _server_chunk(self, rnd: int, trainable, k: int):
        """Run rounds [rnd, rnd+k) in ONE XLA dispatch via server_rounds."""
        cfg = self.cfg
        static, batches, rrngs, n_ex_list = self._chunk_inputs(rnd, k)
        rweights = self.mesh.shard_round_clients(jnp.asarray(np.stack([
            np.full((self.C,),
                    n_ex if cfg.weighted_agg else 1.0, np.float32)
            for n_ex in n_ex_list])))
        # compressed programs carry (params, error-feedback residual)
        carry = trainable if self._comp is None else (trainable, self._ef)
        if self.ledger is not None:
            prog = (self.progs.server_rounds_static_fp if static
                    else self.progs.server_rounds_fp)
            carry, (stats, fpc, fpr, _auth) = prog(
                carry, self.frozen, batches, rweights, rrngs,
                self._chunk_corrupts(rnd, k))
            if self._comp is not None:
                carry, self._ef = carry
            stats = np.asarray(stats)
            recs = [self._stats_to_rec(rnd + i, stats[i]) for i in range(k)]
            self._commit_chunk_fps(rnd, k, fpc, fpr, recs)
            return carry, recs
        prog = (self.progs.server_rounds_static if static
                else self.progs.server_rounds)
        carry, stats = prog(carry, self.frozen, batches, rweights, rrngs)
        if self._comp is not None:
            carry, self._ef = carry
        stats = np.asarray(stats)  # [k, C, 3]
        return carry, [self._stats_to_rec(rnd + i, stats[i])
                       for i in range(k)]

    def _serverless_chunk(self, rnd, stacked, prev_consensus, k):
        """Run gossip rounds [rnd, rnd+k) in ONE dispatch via gossip_rounds.

        Only reached with an all-ones participation mask (``_chunk_rounds``
        rejects filters/ledger/tamper), so the consensus view for eval/
        checkpoint is computed once at the chunk end — the per-round
        consensus values it skips are unobservable (no eval inside a
        chunk)."""
        cfg = self.cfg
        static, batches, rrngs, _ = self._chunk_inputs(rnd, k)
        masks = self.mesh.shard_round_clients(
            jnp.ones((k, self.C), jnp.float32))
        fps = None
        carry = stacked if self._comp is None else (stacked, self._ef)
        if self.ledger is not None:
            prog = (self.progs.gossip_rounds_static_fp if static
                    else self.progs.gossip_rounds_fp)
            carry, (stats, fpc, fpr, _auth) = prog(
                carry, self.frozen, batches, masks, rrngs,
                self._chunk_corrupts(rnd, k))
            fps = (fpc, fpr)
        else:
            prog = (self.progs.gossip_rounds_static if static
                    else self.progs.gossip_rounds)
            carry, stats = prog(carry, self.frozen, batches, masks, rrngs)
        if self._comp is None:
            stacked = carry
        else:
            stacked, self._ef = carry
        # collapse (a full-tree consensus all-reduce + host round-trip) only
        # when this chunk's end is observable — an eval round, a checkpoint
        # round, or the end of the run; otherwise the value would be
        # discarded, re-paying the dispatch overhead fusing exists to avoid
        last = rnd + k - 1
        observed = (
            last == cfg.num_rounds - 1
            or (cfg.eval_every and (last + 1) % cfg.eval_every == 0)
            or (cfg.checkpoint_dir and cfg.checkpoint_every
                and (last + 1) % cfg.checkpoint_every == 0))
        consensus = prev_consensus
        if observed:
            m = self.mesh.shard_clients(
                jnp.ones((self.C,), jnp.float32))
            consensus = self.progs.collapse(stacked, m, prev_consensus)
        stats = np.asarray(stats)  # [k, C, 3]
        recs = [self._stats_to_rec(rnd + i, stats[i]) for i in range(k)]
        if fps is not None:
            self._commit_chunk_fps(rnd, k, fps[0], fps[1], recs)
        return stacked, consensus, recs

    def _annotate_chunk(self, recs, wall: float) -> None:
        """Participation/info-passing fields for fused rounds (all-ones mask
        by construction). The measured unit is the CHUNK: ``wall_chunk_s``
        carries the real dispatch wall time, ``wall_s`` its even split
        across the chunk's rounds, and ``fused=True`` marks both as
        chunk-derived so consumers can tell interpolated from measured."""
        C = self.C
        sync_t, async_t = self.graph.info_passing_time(
            0.0, source=self.info_source, anomalies=(),
            payload_bytes=self._comms_payload_bytes())
        for rec in recs:
            rec.mask = [1.0] * C
            rec.anomalies = []
            rec.info_passing_sync_s = sync_t
            rec.info_passing_async_s = async_t
            rec.fused = True
            rec.wall_chunk_s = wall
            rec.wall_s = wall / max(len(recs), 1)

    # ----------------------------------------------------------- round bodies

    def _stats_to_rec(self, rnd: int, stats) -> RoundRecord:
        s = np.asarray(stats)  # [C, 3]
        n = np.maximum(s[:, 2], 1)
        total = s.sum(0)
        C = self.C
        raw = float(self._raw_bytes_per_client * C)
        wire = float(self._wire_bytes_per_client * C)
        return RoundRecord(
            round=rnd,
            train_loss=float(total[0] / max(total[2], 1)),
            train_acc=float(total[1] / max(total[2], 1)),
            local_acc=(s[:, 1] / n).tolist(),
            # bytes-on-wire accounting: one shipped update per client per
            # round, raw vs through the configured codec (equal at
            # compress=none)
            bytes_raw=raw,
            bytes_on_wire=wire,
            compression_ratio=raw / max(wire, 1.0),
        )

    def _weights(self, mask: np.ndarray, n_ex: np.ndarray) -> jnp.ndarray:
        w = np.asarray(mask, np.float32) * (
            np.asarray(n_ex, np.float32) if self.cfg.weighted_agg else 1.0)
        if not np.isfinite(w).all():
            # a NaN/Inf weight would silently poison every aggregation
            # fallback comparison downstream (NaN > 0 is False but NaN * x
            # propagates); an all-MASKED round is fine — the aggregators'
            # fallback keeps the params and the round is recorded degraded
            raise ValueError(
                f"non-finite aggregation weights at round mask={mask!r} "
                f"n_ex={n_ex!r}")
        return self.mesh.shard_clients(jnp.asarray(w, jnp.float32))

    def _server_round(self, rnd, trainable, mask):
        batches, n_ex = self._round_batches(rnd)
        rngs = self._rngs(rnd)
        scales = self._transport_scales(rnd)
        if self.ledger is None and scales is None:
            w = self._weights(mask, n_ex)
            if self._comp is None:
                trainable, stats = self.progs.server_round(
                    trainable, self.frozen, batches, w, rngs)
            else:
                # compressed carry: (params, error-feedback residual)
                (trainable, self._ef), stats = self.progs.server_round(
                    (trainable, self._ef), self.frozen, batches, w, rngs)
            rec = self._stats_to_rec(rnd, stats)
            self._note_degraded(rec, mask)
            return trainable, rec
        # split-phase flow: train -> (ledger commit) -> transport ->
        # (ledger verify) -> aggregate; if every update is eliminated the
        # round keeps its starting params (collapse fallback). Without the
        # ledger a corrupted update reaches the aggregation rule — the
        # robust aggregators (cfg.aggregator) are the defense there.
        stacked, stats = self.progs.client_updates(
            trainable, self.frozen, batches, rngs)
        # the wire quantity is the compressed payload when a codec is on
        # (the ledger commits/authenticates ITS fingerprints, transport
        # corruption perturbs IT) and the stacked tree otherwise; either
        # way the server aggregates what ARRIVED (ex.recon)
        ex = self._exchange_updates(rnd, stacked, trainable, rngs, scales,
                                    mode="global")
        auth = ex.auth
        if auth is not None:
            mask = mask * auth
        w = self._weights(mask, n_ex)
        trainable = self.progs.collapse(ex.recon, w, trainable)
        rec = self._stats_to_rec(rnd, stats)
        if auth is not None:
            rec.auth = auth.tolist()
        self._note_degraded(rec, mask)
        return trainable, rec

    def _serverless_round(self, rnd, stacked, prev_consensus, mask):
        batches, n_ex = self._round_batches(rnd)
        rngs = self._rngs(rnd)
        m = self.mesh.shard_clients(jnp.asarray(mask, jnp.float32))
        auth = None
        scales = self._transport_scales(rnd)
        if self.ledger is None and scales is None:
            if self._comp is None:
                stacked, stats = self.progs.gossip_round(
                    stacked, self.frozen, batches, m, rngs)
            else:
                (stacked, self._ef), stats = self.progs.gossip_round(
                    (stacked, self._ef), self.frozen, batches, m, rngs)
        else:
            # split-phase: peers ship their update (the encoded delta vs
            # their own round-start params under a codec, the stacked tree
            # otherwise) through the shared wire seam; the mix consumes
            # what ARRIVED (ex.recon) while each sender's self-term stays
            # its honest post-train tree (mix_recv). An untouched wire
            # (clean, uncompressed) keeps the one-buffer mix_only path.
            start = stacked  # pre-train params: what an all-rejected round keeps
            stacked, stats = self.progs.local_updates(
                stacked, self.frozen, batches, rngs)
            ex = self._exchange_updates(rnd, stacked, start, rngs, scales,
                                        mode="local")
            auth = ex.auth
            if auth is not None:
                mask = mask * auth
                m = self.mesh.shard_clients(jnp.asarray(mask, jnp.float32))
            if ex.recon is not stacked:
                # corruption/codec reconstruction poisons only the RECEIVED
                # copies: neighbor and aggregate terms come from the
                # transported tree, each sender's own carry stays its honest
                # local state (__init__ rejects corrupting serverless
                # configs whose impl has no mix_recv, so this cannot
                # silently fall through to a mix that rewrites the sender's
                # state with the corruption)
                stacked = self.progs.mix_recv(stacked, ex.recon, m, start)
            else:
                stacked = self.progs.mix_only(stacked, m, start)
        # consensus view for eval/checkpoint (mask-weighted aggregation)
        consensus = self.progs.collapse(stacked, m, prev_consensus)
        rec = self._stats_to_rec(rnd, stats)
        if auth is not None:
            rec.auth = auth.tolist()
        self._note_degraded(rec, mask)
        return stacked, consensus, rec

    def _faithful_round(self, rnd, trainable, mask):
        """Reference-exact serverless semantics: clients sequentially mutate a
        shared model within the round, snapshots are averaged unweighted
        (``serverless_NonIID_IMDB.py:284-297``). Host-sequential by nature.

        With the ledger on, each snapshot is committed as it is produced and
        re-authenticated before aggregation — a tampered snapshot is excluded
        exactly as in the parallel paths. An all-excluded round keeps the
        round's starting params instead of zeroing the model."""
        cfg = self.cfg
        batches, n_ex = self._round_batches(rnd)
        keys = client_round_keys(
            jax.random.fold_in(self.root_key, 4), self.C, rnd)
        snapshots, host_snaps, snap_fps, all_stats = [], [], [], []
        fp_mode = self.ledger is not None and self.faults.host_tamper is None
        # Pin the sequential path to ONE device when the model fits on one.
        # The engine holds trainable replicated over the mesh (the r04
        # steady-state-sharding fix), and jitting the per-client program on
        # replicated-committed inputs executes EVERY replica — pure
        # redundant FLOPs on a pod, and an 8x wall-clock multiplier on the
        # serialized virtual CPU mesh (measured: small-bert x 10 clients,
        # round 0 went 536 s pinned vs >60 min replicated). The result is
        # put back into the caller's sharding so the parallel eval/round
        # programs see their layout. With tp/sp > 1 the model is sharded
        # BECAUSE it exceeds one device — there the GSPMD path stands.
        pin = cfg.tp == 1 and cfg.sp == 1
        if pin:
            out_sharding = jax.tree.map(lambda x: x.sharding, trainable)
            dev = jax.local_devices()[0]
            shared = jax.device_put(trainable, dev)
            frozen = getattr(self, "_frozen_dev0", None)
            if frozen is None:
                frozen = self._frozen_dev0 = jax.device_put(self.frozen, dev)
            keys = jax.device_put(keys, dev)
            # one bulk transfer, sliced on-device per client — not a
            # device_get + per-client re-upload round trip
            dev_b = jax.device_put(batches, dev)
        else:
            shared, frozen = trainable, self.frozen
            host_b = jax.device_get(batches)
        for c in range(self.C):
            cb = (jax.tree.map(lambda x: x[c], dev_b) if pin
                  else jax.tree.map(lambda x: jnp.asarray(x[c]), host_b))
            shared, stats = self.progs.single_update(shared, frozen, cb,
                                                     keys[c])
            if fp_mode:
                # device-side digest: K floats cross the link, not the tree
                fence(shared)  # single_update is async; see _ledger_verify
                with self.clock.phase("ledger"):
                    fp = np.asarray(self.progs.fingerprint_one(shared))
                    snap_fps.append(fp)
                    self.ledger.append_digest(
                        rnd, c, self._entry_digest("one", fp),
                        self._client_payload_bytes)
            elif self.ledger is not None:
                with self.clock.phase("ledger"):
                    snap = jax.device_get(shared)
                    self.ledger.append(rnd, c, snap)
                    host_snaps.append(snap)
            snapshots.append(shared)
            all_stats.append(np.asarray(stats))
        rec = self._stats_to_rec(rnd, np.stack(all_stats))
        w = np.asarray(mask, np.float32)
        if fp_mode:
            with self.clock.phase("ledger"):
                # reuse the commit-time fingerprints: the snapshots are
                # immutable device buffers, so recomputing would reproduce
                # them bit-for-bit at 2x the fingerprint cost
                auth = self._ledger_auth_rows(rnd, "one", snap_fps)
            rec.auth = auth.tolist()
            w = w * auth
        elif self.ledger is not None:
            with self.clock.phase("ledger"):
                stacked_host = jax.tree.map(
                    lambda *xs: np.stack(xs), *host_snaps)
                auth = self._ledger_authenticate(rnd, stacked_host)
            rec.auth = auth.tolist()
            w = w * auth
        total = float(w.sum())
        if total <= 0.0:
            self._note_degraded(rec, w)
            return trainable, rec
        avg = _tree_wsum(jnp.asarray(w / total), snapshots)
        return (jax.device_put(avg, out_sharding) if pin else avg), rec

    # ------------------------------------------------------------------ async

    def _init_async_state(self) -> Dict:
        """Simulated network clock: per-client round duration = local compute
        (proportional to the client's example count, mean-normalized to 1) +
        transfer time to the aggregation point over the latency graph (the
        quantity the notebooks call information passing time)."""
        cfg = self.cfg
        times = self.graph.shortest_path_times(self._payload_gb())
        src = self.info_source
        transfer = np.array([
            times[c, src] if c != src else 0.0 for c in range(self.C)])
        _, n_ex = self._round_batches(0)
        n_ex = np.asarray(n_ex, np.float64)
        compute = n_ex / max(n_ex.mean(), 1e-9)  # relative local-compute cost
        duration = compute + transfer
        return {
            "duration": duration,
            "next_done": duration.copy(),
            "version": np.zeros((self.C,), np.int64),
            "global_version": 0,
            "clock": 0.0,
        }

    def _async_merge_scale(self, alpha, arrived, n_ex) -> float:
        """sum(decayed weights) / sum(un-decayed weights) over the arrived
        buffer — the factor that survives collapse's normalization, in (0, 1]:
        1.0 when every arrival is fresh, ``staleness_decay ** s`` when a lone
        arrival is ``s`` versions stale."""
        if self.cfg.weighted_agg:
            base = float(np.asarray(n_ex)[arrived].sum())
        else:
            base = float(len(arrived))
        return float(alpha[arrived].sum() / max(base, 1e-9))

    def _async_round(self, rnd, trainable, stacked, mask, st, delays=None):
        """One buffered-async aggregation event (FedBuff-style): the K
        earliest-finishing clients merge their local DELTAS, each decayed by
        ``staleness_decay ** staleness``; the global takes an
        ``async_server_lr`` step along the weighted-mean delta. Clients that
        haven't arrived keep training on their stale base."""
        cfg = self.cfg
        K = cfg.async_buffer or self.C
        if stacked is None:
            stacked = self.progs.broadcast(trainable)
        base = stacked  # each client's round-start params (delta reference)
        batches, n_ex = self._round_batches(rnd)
        rngs = self._rngs(rnd)
        stacked, stats = self.progs.local_updates(
            stacked, self.frozen, batches, rngs)
        rec = self._stats_to_rec(rnd, stats)

        # chaos stragglers: an affected client's completion slips by the
        # injected delay, so it arrives later and accumulates staleness —
        # the fault plan feeding the simulated network clock directly.
        # ``delays`` is threaded from the run loop's single per-round draw
        # (None from direct callers, who draw here instead)
        if delays is None:
            delays = self.faults.straggler_delays(rnd)
        if delays is not None:
            st["next_done"] = st["next_done"] + delays
            rec.straggler_s = delays.tolist()

        # transport corruption: the transmitted copies (deltas) may be
        # perturbed; each client's own carried state stays honest. With
        # compression the transmitted quantity IS the encoded delta payload
        # (async is delta-exchange by construction, so the codec slots in
        # exactly where _tree_sub used to run). EF semantics under partial
        # arrival: the residual advances for EVERY client each round, but a
        # non-arrived client's base is its OWN carried post-train state, so
        # its next delta stays incremental — the kept mass of an unmerged
        # payload is dropped exactly like the uncompressed path drops
        # unmerged deltas, and the residual re-delivers only compression
        # error (no update mass is ever applied twice).
        scales = self.faults.transport_scales(rnd)
        ex = self._exchange_updates(rnd, stacked, base, rngs, scales,
                                    mode="async")
        auth = ex.auth
        if auth is not None:
            rec.auth = auth.tolist()
            mask = mask * auth

        self._note_degraded(rec, mask)
        # pick the K earliest arrivals among participating clients
        order = np.argsort(st["next_done"])
        arrived = [c for c in order if mask[c] > 0][:K]
        st["clock"] = float(st["next_done"][arrived].max()) if arrived else st["clock"]

        staleness = st["global_version"] - st["version"]
        # staleness is reputation evidence (a chronically stale peer is a
        # flaky peer) and run observability either way
        rec.staleness = [max(int(s), 0) for s in staleness]
        alpha = np.zeros((self.C,), np.float32)
        for c in arrived:
            # mask[c] folds in the reputation gate: a probation peer's
            # merge weight is scaled down exactly like its sync vote
            alpha[c] = (float(mask[c])
                        * cfg.staleness_decay ** max(int(staleness[c]), 0))
        rec.async_alpha = alpha.tolist()
        if self.cfg.weighted_agg:
            alpha = alpha * n_ex

        if arrived:
            deltas = (_tree_sub(ex.sent, base) if self._comp is None
                      else self.progs.decode_delta(ex.sent, stacked))
            zero = jax.tree.map(jnp.zeros_like, trainable)
            # collapse is a weight-NORMALIZED mean (divides by sum(alpha)), so
            # on its own the staleness decay would cancel out of the update
            # magnitude; rescale by sum(alpha)/sum(un-decayed weights) so a
            # stale delta really is applied smaller, FedBuff-style.
            merged_delta = self.progs.collapse(
                deltas, self.mesh.shard_clients(jnp.asarray(alpha)), zero)
            scale = self._async_merge_scale(alpha, arrived, n_ex)
            trainable = _tree_axpy(
                trainable, merged_delta, cfg.async_server_lr * scale)
            # arrived clients pull the fresh global and restart (adopt
            # fuses the broadcast into the select: one dispatch, no
            # materialized [C, ...] broadcast buffer)
            pull = np.zeros((self.C,), np.float32)
            pull[arrived] = 1.0
            pull_d = self.mesh.shard_clients(jnp.asarray(pull))
            stacked = self.progs.adopt(stacked, trainable, pull_d)
            st["global_version"] += 1
            for c in arrived:
                st["version"][c] = st["global_version"]
                st["next_done"][c] = st["clock"] + st["duration"][c]

        return trainable, stacked, rec
