"""Synthetic round inputs for benches/dry-runs: the stacked per-client batch
tree :func:`bcfl_tpu.data.pipeline.client_batches` produces, filled with
random tokens, plus uniform weights and per-client RNGs, all device-put onto
the client mesh."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bcfl_tpu.core.mesh import ClientMesh


def synthetic_round_inputs(
    mesh: ClientMesh,
    steps: int,
    batch: int,
    seq: int,
    vocab_size: int = 8192,
    num_labels: int = 2,
    seed: int = 0,
):
    """Returns ``(batches, weights, rngs)`` ready for any FedPrograms round."""
    C = mesh.num_clients
    rng = np.random.default_rng(seed)
    batches = mesh.shard_clients({
        "ids": jnp.asarray(
            rng.integers(0, vocab_size, (C, steps, batch, seq)), jnp.int32),
        "mask": jnp.ones((C, steps, batch, seq), jnp.int32),
        "labels": jnp.asarray(
            rng.integers(0, num_labels, (C, steps, batch)), jnp.int32),
        "example_mask": jnp.ones((C, steps, batch), jnp.float32),
    })
    weights = mesh.shard_clients(jnp.ones((C,), jnp.float32))
    keys = jax.random.split(jax.random.key(seed + 1), C)
    rngs = mesh.shard_clients(jax.random.key_data(keys))
    return batches, weights, rngs
