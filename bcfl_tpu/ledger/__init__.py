from bcfl_tpu.ledger.ledger import Ledger, LedgerEntry, params_digest  # noqa: F401
from bcfl_tpu.ledger.fingerprint import (  # noqa: F401
    client_fingerprint,
    entry_digest,
    struct_digest,
    tree_fingerprint,
)
