from bcfl_tpu.ledger.ledger import (  # noqa: F401
    GENESIS,
    Ledger,
    LedgerEntry,
    chain_extend,
    params_digest,
)
from bcfl_tpu.ledger.fingerprint import (  # noqa: F401
    client_fingerprint,
    entry_digest,
    struct_digest,
    tree_fingerprint,
)
