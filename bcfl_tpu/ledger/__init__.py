from bcfl_tpu.ledger.ledger import Ledger, LedgerEntry, params_digest  # noqa: F401
