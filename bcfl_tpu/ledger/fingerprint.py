"""Device-side parameter fingerprints for the ledger.

Round 3's ledger flow pulled the FULL stacked param tree to host every round
(``jax.device_get(stacked)`` + per-client SHA-256 over the raw bytes): for
BERT-base x 10 clients that is ~4.4 GB across the TPU tunnel per round, and
it also forced round fusion off. Here the content digest is computed ON
DEVICE as a compact weighted fold and only ``[C, K]`` floats cross the
link; the SHA-256 chain then hashes those fingerprint bytes (plus a
structure digest over leaf names/dtypes/shapes, which needs no data
transfer).

Fingerprint construction (cheap by design — an earlier draft generated an
``O(params x K)`` random projection per call, whose threefry cost alone was
~90% of a small round's wall on CPU; this one is ~2 streaming passes over
the params and no per-element PRNG):

1. each leaf ``x`` is viewed as ``[C, M, LANES]`` (zero-padded to
   LANES=128, the TPU lane width),
2. folded over ``M`` with per-leaf cos/sin position weights
   ``cos(a*m + b), sin(a*m + b)``, where ``(a, b)`` derive from the SHA-256
   of the leaf's path name -> ``[C, 2*LANES]``,
3. all leaves' folds are summed and passed through ONE small fixed
   standard-normal projection ``[2*LANES, K]`` (generated once at trace
   time from a constant key) -> ``[C, K]``.

Any single element change moves the fingerprint (its lane picks up a
nonzero ``delta * w_m`` contribution that the dense projection spreads over
all K outputs); the position weights make value *moves* within a lane
detectable too. The construction is dtype-generic (every leaf is cast to
f32 before folding), which is what lets the engine fingerprint COMPRESSED
payload trees — int8 codes, f32 scales/values, int32 indices — so chain
auth covers the bytes actually on the wire (COMPRESSION.md §3; int32
indices above 2^24 can alias in the f32 cast, a cooperative-audit caveat of
the same class as the note below). Deterministic across calls and processes. This is a
*content* fingerprint for tamper-evidence in a cooperative audit chain, not
a cryptographic MAC over the raw bytes: an adversary who knows the
construction could craft a colliding tree, so faithful byte-hashing
(:func:`bcfl_tpu.ledger.ledger.params_digest`) remains available and is
what the engine uses when a tamper hook simulates in-flight modification of
host trees.

Cost: ~``3 * params`` flops per client per round, memory-bandwidth bound —
measured as a small fraction of round wall (``scripts/ledger_overhead.py``
-> ``results/ledger_overhead.json``).
"""

from __future__ import annotations

import hashlib
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Tree = Any

K = 4  # fingerprint floats per client; 16 bytes of content evidence/entry
LANES = 128  # fold width (TPU lane count)


def _path_name(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "name", p)))
                    for p in path)


def _leaf_phase(name: str) -> tuple:
    """Per-leaf position-weight parameters (a, b), derived from the leaf's
    path name so sibling leaves fold differently."""
    h = hashlib.sha256(name.encode()).digest()
    a = 0.5 + int.from_bytes(h[:4], "little") % 100_000 / 100_000.0
    b = int.from_bytes(h[4:8], "little") % 628_318 / 100_000.0
    return a, b


def _projection(k: int) -> jnp.ndarray:
    """The one fixed [2*LANES, k] projection — tiny, constant key, generated
    at trace time (constant-folded by XLA)."""
    return jax.random.normal(jax.random.key(0xBCF1), (2 * LANES, k),
                             jnp.float32)


def client_fingerprint(stacked: Tree, k: int = K) -> jnp.ndarray:
    """``[C, k]`` float32 fingerprint of a client-stacked tree (leaves
    ``[C, ...]``). Traceable — jit it once per structure; inside a scanned
    round body it adds a streaming fold per leaf."""
    flat = jax.tree_util.tree_flatten_with_path(stacked)[0]
    if not flat:
        raise ValueError("cannot fingerprint an empty tree")
    C = flat[0][1].shape[0]
    folds = jnp.zeros((C, 2 * LANES), jnp.float32)
    for path, leaf in flat:
        x = leaf.reshape(C, -1).astype(jnp.float32)
        n = x.shape[1]
        pad = (-n) % LANES
        if pad:
            x = jnp.pad(x, ((0, 0), (0, pad)))
        m = (n + pad) // LANES
        x = x.reshape(C, m, LANES)
        a, b = _leaf_phase(_path_name(path))
        idx = jnp.arange(m, dtype=jnp.float32)
        w = jnp.stack([jnp.cos(a * idx + b), jnp.sin(a * idx + b)])  # [2, M]
        # tensordot, not einsum: measured 5x faster on the single-core CPU
        # lowering (2.4s vs 12.6s per 640M elements), same values
        y = jnp.tensordot(w, x, axes=((1,), (1,)))  # [2, C, LANES]
        folds = folds + y.transpose(1, 0, 2).reshape(C, 2 * LANES)
    return folds @ _projection(k)


def tree_fingerprint(tree: Tree, k: int = K) -> jnp.ndarray:
    """``[k]`` fingerprint of ONE client's (unstacked) tree — the faithful
    sequential mode's per-snapshot commit."""
    return client_fingerprint(
        jax.tree.map(lambda x: x[None], tree), k=k)[0]


def struct_digest(tree: Tree, use_native: bool = True) -> bytes:
    """SHA-256 over the tree's leaf names + dtypes + shapes — binds the
    fingerprint to the parameter STRUCTURE without touching leaf data (no
    device transfer; works on avals)."""
    from bcfl_tpu.ledger.ledger import _sha256_chunks

    chunks = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = _path_name(path)
        dt = jnp.dtype(leaf.dtype).str
        chunks.append(f"{name}:{dt}:{tuple(leaf.shape)}".encode())
    return _sha256_chunks(chunks, use_native)


def entry_digest(struct: bytes, fp_row: np.ndarray,
                 use_native: bool = True) -> bytes:
    """The 32-byte digest a fingerprint-mode ledger entry commits:
    ``SHA-256(struct_digest || fingerprint_bytes)``."""
    from bcfl_tpu.ledger.ledger import _sha256_chunks

    row = np.ascontiguousarray(np.asarray(fp_row, np.float32))
    return _sha256_chunks([struct, row.tobytes()], use_native)
