"""Hash-chained weight ledger — the real implementation of the reference's
"BC-FL" blockchain layer.

The reference only *describes* this component: README.md:10 claims a
blockchain mitigates node anomalies and cuts communication, and the MT
notebook (cells 26-28) models its payload as 0.043 GB vs the 0.4036 GB full
model — there is no blockchain code anywhere in the repo (SURVEY.md §2.2 C18,
verified). Here it exists:

- every accepted client update appends a :class:`LedgerEntry`
  ``{round, client, params_digest, payload_bytes}``; the entry hash extends a
  SHA-256 chain ``head_i = H(head_{i-1} || entry_i)`` (genesis = 32 zero
  bytes),
- verification walks the chain and recomputes every link — any tampered
  entry (or reordered history) is located by index,
- update authentication: before aggregation the engine recomputes each
  client's parameter digest and compares it to the announced entry; a
  mismatch zeroes that client's participation mask (tamper -> excluded, the
  "mitigating node anomalies" behaviour the README claims),
- communication accounting: entries are ~100 bytes vs multi-100MB weight
  trees; :meth:`Ledger.payload_accounting` reports both, reproducing the
  0.043-vs-0.4036 GB-class comparison the notebooks plot.

Hashing runs in the C++ core (:mod:`bcfl_tpu.native`) when a toolchain is
present, hashlib otherwise — identical digests either way.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import struct
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from bcfl_tpu.native.build import load_ledger_lib

GENESIS = b"\x00" * 32

# reserved ledger-row client ids for STATE_SYNC commitments (RUNTIME.md
# "State-sync protocol"): real clients are >= 0 everywhere and reputation
# rows live at REP_CLIENT_BASE(-1000) - peer, so rows at or below this base
# can never collide with either. Peer p's state commitments use
# SYNC_CLIENT_BASE - p; the row's digest slot carries the params digest of
# the FULL global state p serves — the chain link that makes a transferred
# state verifiable against committed history instead of merely plausible.
SYNC_CLIENT_BASE = -2000


def sync_row_client(peer: int) -> int:
    return SYNC_CLIENT_BASE - int(peer)


def chain_extend(prev: bytes, payload: bytes, use_native: bool = True) -> bytes:
    """One chain link: ``H(prev || payload)`` (C++ core when built)."""
    lib = load_ledger_lib() if use_native else None
    if lib is not None:
        import ctypes

        out = ctypes.create_string_buffer(32)
        lib.bcfl_chain_extend(prev, payload, len(payload), out)
        return out.raw
    return hashlib.sha256(prev + payload).digest()


def _leaf_bytes(path, leaf) -> Tuple[bytes, bytes]:
    name = "/".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path)
    arr = np.asarray(leaf)
    header = f"{name}:{arr.dtype.str}:{arr.shape}".encode()
    return header, np.ascontiguousarray(arr).tobytes()


def _sha256_chunks(chunks: List[bytes], use_native: bool = True) -> bytes:
    """SHA-256 over concatenated chunks — C++ core when built, hashlib
    otherwise (identical digests either way)."""
    lib = load_ledger_lib() if use_native else None
    if lib is not None:
        import ctypes

        n = len(chunks)
        bufs = (ctypes.c_char_p * n)(*chunks)
        lens = (ctypes.c_uint64 * n)(*[len(c) for c in chunks])
        out = ctypes.create_string_buffer(32)
        lib.bcfl_sha256_multi(bufs, lens, n, out)
        return out.raw
    h = hashlib.sha256()
    for c in chunks:
        h.update(c)
    return h.digest()


def params_digest(tree, use_native: bool = True) -> bytes:
    """Canonical SHA-256 of a parameter tree (leaf names + dtypes + shapes +
    raw bytes, in tree order) — what a client announces to the ledger.
    Requires the full tree on host; the engine's default commit path instead
    hashes a device-computed fingerprint
    (:mod:`bcfl_tpu.ledger.fingerprint`) so the tree never leaves HBM."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    chunks: List[bytes] = []
    for path, leaf in flat:
        header, body = _leaf_bytes(path, leaf)
        chunks.append(header)
        chunks.append(body)
    return _sha256_chunks(chunks, use_native)


@dataclasses.dataclass(frozen=True)
class LedgerEntry:
    round: int
    client: int
    params_digest: bytes  # 32 bytes
    payload_bytes: int  # size of the update this entry stands in for

    def serialize(self) -> bytes:
        return struct.pack("<qq32sq", self.round, self.client,
                          self.params_digest, self.payload_bytes)

    @property
    def size_bytes(self) -> int:
        return len(self.serialize()) + 32  # + chain head stored alongside


class Ledger:
    """Append-only hash chain over accepted client updates."""

    def __init__(self, use_native: bool = True):
        self.use_native = use_native
        self.entries: List[LedgerEntry] = []
        self.heads: List[bytes] = []

    @property
    def head(self) -> bytes:
        return self.heads[-1] if self.heads else GENESIS

    def __len__(self) -> int:
        return len(self.entries)

    def _extend(self, prev: bytes, payload: bytes) -> bytes:
        return chain_extend(prev, payload, self.use_native)

    def append(self, round_idx: int, client: int, tree,
               payload_bytes: Optional[int] = None) -> LedgerEntry:
        """Digest ``tree`` (the client's update) and chain an entry for it."""
        digest = params_digest(tree, self.use_native)
        if payload_bytes is None:
            payload_bytes = int(
                sum(np.asarray(x).nbytes for x in jax.tree.leaves(tree))
            )
        return self.append_digest(round_idx, client, digest, payload_bytes)

    def append_digest(self, round_idx: int, client: int, digest: bytes,
                      payload_bytes: int) -> LedgerEntry:
        """Chain an entry for an already-computed 32-byte digest (the
        device-side fingerprint path — the tree never reaches the host)."""
        entry = LedgerEntry(round_idx, client, digest, payload_bytes)
        self.heads.append(self._extend(self.head, entry.serialize()))
        self.entries.append(entry)
        return entry

    def verify_chain(self) -> int:
        """-1 if every link checks out, else the index of the first bad link
        (runs in C++ when available)."""
        payloads = [e.serialize() for e in self.entries]
        lib = load_ledger_lib() if self.use_native else None
        if lib is not None and payloads:
            import ctypes

            n = len(payloads)
            bufs = (ctypes.c_char_p * n)(*payloads)
            lens = (ctypes.c_uint64 * n)(*[len(p) for p in payloads])
            heads = b"".join(self.heads)
            return int(lib.bcfl_chain_verify(bufs, lens, heads, n))
        prev = GENESIS
        for i, p in enumerate(payloads):
            h = hashlib.sha256(prev + p).digest()
            if h != self.heads[i]:
                return i
            prev = h
        return -1

    def authenticate(self, round_idx: int, client: int, tree) -> bool:
        """Does ``tree`` match what ``client`` committed for ``round_idx``?
        The engine masks out clients whose shipped update fails this check."""
        return self.authenticate_digest(
            round_idx, client, params_digest(tree, self.use_native))

    def authenticate_digest(self, round_idx: int, client: int,
                            digest: bytes) -> bool:
        """Digest-level authenticate (fingerprint path twin)."""
        for e in reversed(self.entries):
            if e.round == round_idx and e.client == client:
                return e.params_digest == digest
        return False

    # ------------------------------------------------------------ fork/merge
    # A real network partition (RUNTIME.md) leaves each connected component
    # extending its own copy of the chain from a common prefix — a genuine
    # fork. The heal protocol is: exchange heads -> locate the fork point
    # (longest common prefix) -> exchange the divergent SEGMENTS -> verify
    # each received segment link by link against the fork-point head ->
    # adopt the deterministic merge (both sides re-chain the union in one
    # canonical order, so the merged chain is identical on every peer and
    # verifies end to end).

    def head_at(self, n: int) -> bytes:
        """Chain head after the first ``n`` entries (GENESIS at 0)."""
        if n < 0 or n > len(self.heads):
            raise ValueError(f"head_at({n}) out of range [0, {len(self.heads)}]")
        return GENESIS if n == 0 else self.heads[n - 1]

    def fork_point(self, other_heads: List[bytes]) -> int:
        """Length of the longest common prefix with another chain's head
        list — the index both chains agree up to (0 = they share only
        genesis)."""
        n = 0
        for mine, theirs in zip(self.heads, other_heads):
            if mine != theirs:
                break
            n += 1
        return n

    def segment(self, start: int) -> List[Dict]:
        """JSON-able rows for entries ``[start:]`` (entry fields + the head
        after each link) — what one side of a fork ships to the other."""
        return [
            {"round": e.round, "client": e.client,
             "digest": e.params_digest.hex(),
             "payload_bytes": e.payload_bytes,
             "head": self.heads[start + i].hex()}
            for i, e in enumerate(self.entries[start:])
        ]

    @staticmethod
    def verify_segment(prev_head: bytes, rows: List[Dict],
                       use_native: bool = True) -> int:
        """Recompute every link of a received segment against the shared
        fork-point head: -1 if the segment's claimed heads all check out,
        else the index (within the segment) of the first bad link. A
        tampered entry OR a tampered claimed head both fail here — the
        receiving component never adopts an unverifiable fork."""
        prev = prev_head
        for i, row in enumerate(rows):
            entry = LedgerEntry(int(row["round"]), int(row["client"]),
                                bytes.fromhex(row["digest"]),
                                int(row["payload_bytes"]))
            h = chain_extend(prev, entry.serialize(), use_native)
            if h != bytes.fromhex(row["head"]):
                return i
            prev = h
        return -1

    @staticmethod
    def merge_rows(*segments: List[Dict]) -> List[Dict]:
        """Deterministic union of divergent fork segments: rows sorted by
        ``(round, client, digest)`` with exact duplicates dropped. Every
        peer computes the same order from the same segments, so re-chaining
        the merge yields identical heads everywhere — the consensus head."""
        seen = set()
        out = []
        # the sort key is the FULL row identity (incl. payload_bytes):
        # rows tied on (round, client, digest) but differing in
        # payload_bytes would otherwise keep input-dependent stable-sort
        # order and the two sides would re-chain different heads
        for row in sorted(
                (r for seg in segments for r in seg),
                key=lambda r: (int(r["round"]), int(r["client"]),
                               r["digest"], int(r["payload_bytes"]))):
            key = (int(row["round"]), int(row["client"]), row["digest"],
                   int(row["payload_bytes"]))
            if key in seen:
                continue
            seen.add(key)
            out.append(row)
        return out

    def adopt_merge(self, fork_base: int, merged_rows: List[Dict]) -> None:
        """Replace everything after ``fork_base`` with the merged segment,
        re-chaining from the fork-point head. After this, both sides of the
        heal hold byte-identical chains (``verify_chain() == -1``)."""
        if fork_base > len(self.entries):
            raise ValueError(
                f"fork_base {fork_base} beyond chain length "
                f"{len(self.entries)}")
        del self.entries[fork_base:]
        del self.heads[fork_base:]
        for row in merged_rows:
            self.append_digest(int(row["round"]), int(row["client"]),
                               bytes.fromhex(row["digest"]),
                               int(row["payload_bytes"]))

    def append_rows(self, rows: List[Dict]) -> int:
        """Append already-chained rows (a replica catching up from its
        leader), verifying each link as it lands: returns -1 on success or
        the index of the first row whose claimed head does not extend this
        chain."""
        for i, row in enumerate(rows):
            entry = LedgerEntry(int(row["round"]), int(row["client"]),
                                bytes.fromhex(row["digest"]),
                                int(row["payload_bytes"]))
            h = self._extend(self.head, entry.serialize())
            if h != bytes.fromhex(row["head"]):
                return i
            self.heads.append(h)
            self.entries.append(entry)
        return -1

    def commit_state(self, version: int, peer: int,
                     state_digest: bytes) -> LedgerEntry:
        """Append a reserved state-commitment row: ``peer`` attests that at
        ``version`` its full global state hashes to ``state_digest``. Served
        alongside a STATE_SYNC transfer, this is the receiving side's root
        of trust — the transferred tree is refingerprinted and compared to
        this row AFTER the chain segment carrying it verifies link-by-link
        against the receiver's surviving prefix (a tampered state, a
        tampered row, or a forked history all fail one of the two
        checks)."""
        if len(state_digest) != 32:
            raise ValueError(
                f"state commitment digest must be 32 bytes, got "
                f"{len(state_digest)}")
        return self.append_digest(int(version), sync_row_client(peer),
                                  state_digest, 0)

    @staticmethod
    def find_state_commitment(rows: List[Dict], version: int,
                              peer: int) -> Optional[bytes]:
        """The state digest ``peer`` committed for ``version`` in a row
        segment (newest match wins), or None. Rows are the JSON-able shape
        :meth:`segment`/:meth:`to_json` produce — callers verify the
        segment FIRST; an unverified row proves nothing."""
        want = sync_row_client(peer)
        for row in reversed(rows):
            if int(row["client"]) == want and int(row["round"]) == int(version):
                return bytes.fromhex(row["digest"])
        return None

    def payload_accounting(self) -> Dict[str, float]:
        """Ledger-vs-full-weights communication sizes (GB), the quantity the
        reference's BC-FL analysis models (MT nb cell 27: 0.043 GB entries vs
        cell 23: 0.4036 GB full model)."""
        full = sum(e.payload_bytes for e in self.entries)
        ledger = sum(e.size_bytes for e in self.entries)
        return {
            "full_weights_gb": full / 1e9,
            "ledger_gb": ledger / 1e9,
            "reduction": 1.0 - (ledger / full if full else 0.0),
        }

    def to_json(self) -> str:
        return json.dumps([
            {"round": e.round, "client": e.client,
             "digest": e.params_digest.hex(), "payload_bytes": e.payload_bytes,
             "head": self.heads[i].hex()}
            for i, e in enumerate(self.entries)
        ])

    @classmethod
    def from_json(cls, s: str, use_native: bool = True) -> "Ledger":
        led = cls(use_native)
        for row in json.loads(s):
            led.entries.append(LedgerEntry(
                row["round"], row["client"], bytes.fromhex(row["digest"]),
                row["payload_bytes"]))
            led.heads.append(bytes.fromhex(row["head"]))
        return led
