"""Hash-chained weight ledger — the real implementation of the reference's
"BC-FL" blockchain layer.

The reference only *describes* this component: README.md:10 claims a
blockchain mitigates node anomalies and cuts communication, and the MT
notebook (cells 26-28) models its payload as 0.043 GB vs the 0.4036 GB full
model — there is no blockchain code anywhere in the repo (SURVEY.md §2.2 C18,
verified). Here it exists:

- every accepted client update appends a :class:`LedgerEntry`
  ``{round, client, params_digest, payload_bytes}``; the entry hash extends a
  SHA-256 chain ``head_i = H(head_{i-1} || entry_i)`` (genesis = 32 zero
  bytes),
- verification walks the chain and recomputes every link — any tampered
  entry (or reordered history) is located by index,
- update authentication: before aggregation the engine recomputes each
  client's parameter digest and compares it to the announced entry; a
  mismatch zeroes that client's participation mask (tamper -> excluded, the
  "mitigating node anomalies" behaviour the README claims),
- communication accounting: entries are ~100 bytes vs multi-100MB weight
  trees; :meth:`Ledger.payload_accounting` reports both, reproducing the
  0.043-vs-0.4036 GB-class comparison the notebooks plot.

Hashing runs in the C++ core (:mod:`bcfl_tpu.native`) when a toolchain is
present, hashlib otherwise — identical digests either way.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import struct
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from bcfl_tpu.native.build import load_ledger_lib

GENESIS = b"\x00" * 32


def _leaf_bytes(path, leaf) -> Tuple[bytes, bytes]:
    name = "/".join(str(getattr(p, "key", getattr(p, "name", p))) for p in path)
    arr = np.asarray(leaf)
    header = f"{name}:{arr.dtype.str}:{arr.shape}".encode()
    return header, np.ascontiguousarray(arr).tobytes()


def _sha256_chunks(chunks: List[bytes], use_native: bool = True) -> bytes:
    """SHA-256 over concatenated chunks — C++ core when built, hashlib
    otherwise (identical digests either way)."""
    lib = load_ledger_lib() if use_native else None
    if lib is not None:
        import ctypes

        n = len(chunks)
        bufs = (ctypes.c_char_p * n)(*chunks)
        lens = (ctypes.c_uint64 * n)(*[len(c) for c in chunks])
        out = ctypes.create_string_buffer(32)
        lib.bcfl_sha256_multi(bufs, lens, n, out)
        return out.raw
    h = hashlib.sha256()
    for c in chunks:
        h.update(c)
    return h.digest()


def params_digest(tree, use_native: bool = True) -> bytes:
    """Canonical SHA-256 of a parameter tree (leaf names + dtypes + shapes +
    raw bytes, in tree order) — what a client announces to the ledger.
    Requires the full tree on host; the engine's default commit path instead
    hashes a device-computed fingerprint
    (:mod:`bcfl_tpu.ledger.fingerprint`) so the tree never leaves HBM."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    chunks: List[bytes] = []
    for path, leaf in flat:
        header, body = _leaf_bytes(path, leaf)
        chunks.append(header)
        chunks.append(body)
    return _sha256_chunks(chunks, use_native)


@dataclasses.dataclass(frozen=True)
class LedgerEntry:
    round: int
    client: int
    params_digest: bytes  # 32 bytes
    payload_bytes: int  # size of the update this entry stands in for

    def serialize(self) -> bytes:
        return struct.pack("<qq32sq", self.round, self.client,
                          self.params_digest, self.payload_bytes)

    @property
    def size_bytes(self) -> int:
        return len(self.serialize()) + 32  # + chain head stored alongside


class Ledger:
    """Append-only hash chain over accepted client updates."""

    def __init__(self, use_native: bool = True):
        self.use_native = use_native
        self.entries: List[LedgerEntry] = []
        self.heads: List[bytes] = []

    @property
    def head(self) -> bytes:
        return self.heads[-1] if self.heads else GENESIS

    def __len__(self) -> int:
        return len(self.entries)

    def _extend(self, prev: bytes, payload: bytes) -> bytes:
        lib = load_ledger_lib() if self.use_native else None
        if lib is not None:
            import ctypes

            out = ctypes.create_string_buffer(32)
            lib.bcfl_chain_extend(prev, payload, len(payload), out)
            return out.raw
        return hashlib.sha256(prev + payload).digest()

    def append(self, round_idx: int, client: int, tree,
               payload_bytes: Optional[int] = None) -> LedgerEntry:
        """Digest ``tree`` (the client's update) and chain an entry for it."""
        digest = params_digest(tree, self.use_native)
        if payload_bytes is None:
            payload_bytes = int(
                sum(np.asarray(x).nbytes for x in jax.tree.leaves(tree))
            )
        return self.append_digest(round_idx, client, digest, payload_bytes)

    def append_digest(self, round_idx: int, client: int, digest: bytes,
                      payload_bytes: int) -> LedgerEntry:
        """Chain an entry for an already-computed 32-byte digest (the
        device-side fingerprint path — the tree never reaches the host)."""
        entry = LedgerEntry(round_idx, client, digest, payload_bytes)
        self.heads.append(self._extend(self.head, entry.serialize()))
        self.entries.append(entry)
        return entry

    def verify_chain(self) -> int:
        """-1 if every link checks out, else the index of the first bad link
        (runs in C++ when available)."""
        payloads = [e.serialize() for e in self.entries]
        lib = load_ledger_lib() if self.use_native else None
        if lib is not None and payloads:
            import ctypes

            n = len(payloads)
            bufs = (ctypes.c_char_p * n)(*payloads)
            lens = (ctypes.c_uint64 * n)(*[len(p) for p in payloads])
            heads = b"".join(self.heads)
            return int(lib.bcfl_chain_verify(bufs, lens, heads, n))
        prev = GENESIS
        for i, p in enumerate(payloads):
            h = hashlib.sha256(prev + p).digest()
            if h != self.heads[i]:
                return i
            prev = h
        return -1

    def authenticate(self, round_idx: int, client: int, tree) -> bool:
        """Does ``tree`` match what ``client`` committed for ``round_idx``?
        The engine masks out clients whose shipped update fails this check."""
        return self.authenticate_digest(
            round_idx, client, params_digest(tree, self.use_native))

    def authenticate_digest(self, round_idx: int, client: int,
                            digest: bytes) -> bool:
        """Digest-level authenticate (fingerprint path twin)."""
        for e in reversed(self.entries):
            if e.round == round_idx and e.client == client:
                return e.params_digest == digest
        return False

    def payload_accounting(self) -> Dict[str, float]:
        """Ledger-vs-full-weights communication sizes (GB), the quantity the
        reference's BC-FL analysis models (MT nb cell 27: 0.043 GB entries vs
        cell 23: 0.4036 GB full model)."""
        full = sum(e.payload_bytes for e in self.entries)
        ledger = sum(e.size_bytes for e in self.entries)
        return {
            "full_weights_gb": full / 1e9,
            "ledger_gb": ledger / 1e9,
            "reduction": 1.0 - (ledger / full if full else 0.0),
        }

    def to_json(self) -> str:
        return json.dumps([
            {"round": e.round, "client": e.client,
             "digest": e.params_digest.hex(), "payload_bytes": e.payload_bytes,
             "head": self.heads[i].hex()}
            for i, e in enumerate(self.entries)
        ])

    @classmethod
    def from_json(cls, s: str, use_native: bool = True) -> "Ledger":
        led = cls(use_native)
        for row in json.loads(s):
            led.entries.append(LedgerEntry(
                row["round"], row["client"], bytes.fromhex(row["digest"]),
                row["payload_bytes"]))
            led.heads.append(bytes.fromhex(row["head"]))
        return led
