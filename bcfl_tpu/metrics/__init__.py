from bcfl_tpu.metrics.metrics import (  # noqa: F401
    ResourceMonitor,
    RoundRecord,
    RunMetrics,
    model_size_gb,
)
from bcfl_tpu.metrics.tracing import StepClock, annotate, trace  # noqa: F401
