"""Run metrics — the reference's observability surface (SURVEY.md §3.5), with
its bugs fixed but its metric set preserved for comparability:

- wall-clock latency in minutes (``server_IID_IMDB.py:221-224`` prints
  "Latency : X mins"),
- CPU overhead percent via psutil (``:59-63, 226-229``),
- memory overhead in GB — the reference captures ``memory_info_after``
  BEFORE training and ``memory_info_before`` after, so it usually prints a
  negative number (C11); here before is before and after is after,
- model size in GB (reference: ``save_pretrained`` + ``os.path.getsize``,
  ``serverless_IID_IMDB.py:280-284``; here computed from the param tree
  directly — no disk round-trip needed),
- per-client local accuracy per round and global accuracy per round
  (``serverless_NonIID_IMDB.py:292, 304, 334``).
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional

import jax
import numpy as np


def model_size_gb(tree) -> float:
    # metadata-only on array leaves: np.asarray would pull every leaf to host
    # (a full-tree device transfer per call) and crashes on donated-away
    # buffers. Non-array leaves (plain ints/floats in a host-side state dict)
    # fall back to np.asarray — those are already on host, so the transfer
    # concern doesn't apply.
    def leaf_bytes(x):
        if hasattr(x, "size") and hasattr(x, "dtype"):
            return x.size * x.dtype.itemsize
        return np.asarray(x).nbytes

    return sum(leaf_bytes(x) for x in jax.tree.leaves(tree)) / 1e9


class ResourceMonitor:
    """before/after psutil capture, with before actually before.

    psutil interval semantics (the part the reference gets wrong twice):
    ``Process.cpu_percent(None)`` is a *windowed* measurement — each call
    reports the average CPU utilization since the PREVIOUS call, and the
    very first call has no previous window, so it always returns a
    meaningless ``0.0`` and merely arms the baseline. ``__init__``
    therefore makes a priming call whose result is *discarded* (the old
    code stored that 0.0 as ``cpu_before``, a number that could never mean
    anything); ``snapshot()``'s reading then covers exactly the
    init -> snapshot window. Calling :meth:`snapshot` more than once is
    supported, but each later reading covers only the window since the
    previous snapshot — not the whole run."""

    def __init__(self, run_dir: Optional[str] = None):
        import psutil

        self._proc = psutil.Process()
        self._psutil = psutil
        self._proc.cpu_percent(None)  # prime: first call is always 0.0
        self.rss_before = self._proc.memory_info().rss
        self.t_before = time.time()
        # when set, sampling also reports free bytes on the filesystem
        # holding the run directory — the resource fault lane's ENOSPC
        # ladder (RUNTIME.md) is exactly the failure this series predicts
        self._run_dir = run_dir

    def disk_free_bytes(self) -> Optional[int]:
        """Free bytes on the filesystem holding ``run_dir``, or None when
        no run_dir was given or the statvfs fails (observer never raises)."""
        if self._run_dir is None:
            return None
        try:
            import shutil

            return int(shutil.disk_usage(self._run_dir).free)
        except OSError:
            return None

    def snapshot(self) -> Dict[str, float]:
        return {
            # average CPU% over the window since __init__ (or the previous
            # snapshot) — see the interval semantics above
            "cpu_percent": self._proc.cpu_percent(None),
            "memory_gb": (self._proc.memory_info().rss - self.rss_before) / 1e9,
            "latency_min": (time.time() - self.t_before) / 60.0,
        }

    # -------------------------------------------------- periodic sampling
    # Before/after snapshots bound a run; a hundreds-of-rounds soak needs
    # the drift BETWEEN them. The sampling thread emits one catalogued
    # `resource` event per interval through the process telemetry seam
    # (absolute RSS, not the delta — the health series plots a level, and
    # windowed CPU% per psutil's interval semantics above), so the live
    # monitor's health.jsonl can track host memory/CPU across the soak.
    # A daemon thread with a waitable stop event: never blocks exit, and
    # the emit seam is a no-op when telemetry is off.

    def start_sampling(self, interval_s: float) -> bool:
        """Begin emitting `resource` telemetry events every ``interval_s``
        seconds (idempotent; returns False when already running or the
        interval is non-positive)."""
        import threading

        if interval_s <= 0 or getattr(self, "_sample_thread", None):
            return False
        from bcfl_tpu.telemetry import events as _telemetry

        self._sample_stop = threading.Event()

        def _loop():
            # a dedicated windowed-CPU baseline for the sampler: sharing
            # snapshot()'s window would make both readings meaningless
            while not self._sample_stop.wait(interval_s):
                try:
                    free = self.disk_free_bytes()
                    extra = ({} if free is None
                             else {"disk_free_bytes": free,
                                   "disk_free_gb": free / 1e9})
                    _telemetry.emit(
                        "resource",
                        rss_gb=self._proc.memory_info().rss / 1e9,
                        cpu_percent=self._proc.cpu_percent(None),
                        interval_s=interval_s, **extra)
                except Exception:  # noqa: BLE001 — observer never crashes the run
                    pass

        self._sample_thread = threading.Thread(
            target=_loop, daemon=True, name="bcfl-resource-sampler")
        self._sample_thread.start()
        return True

    def stop_sampling(self) -> None:
        """Stop the sampling thread (idempotent, joins briefly)."""
        t = getattr(self, "_sample_thread", None)
        if t is None:
            return
        self._sample_stop.set()
        t.join(timeout=5.0)
        self._sample_thread = None


@dataclasses.dataclass
class RoundRecord:
    round: int
    train_loss: float
    train_acc: float
    local_acc: List[float]  # per client
    global_acc: Optional[float] = None
    global_loss: Optional[float] = None
    mask: Optional[List[float]] = None
    anomalies: Optional[List[int]] = None
    # ledger-authentication outcome per client (1 = update verified against
    # the hash chain, 0 = rejected); None when the ledger is off
    auth: Optional[List[float]] = None
    # staleness-decayed merge weight per client for this aggregation event
    # (async mode only)
    async_alpha: Optional[List[float]] = None
    # True when every client was eliminated from this round's aggregate
    # (anomaly filter x fault-injected dropout x ledger auth): the engine
    # kept the previous global model instead of emitting a 0/0 mean
    degraded: bool = False
    # fault-injection observability (bcfl_tpu.faults): clients dropped by the
    # chaos plan this round / per-client injected straggler delay (seconds)
    dropped: Optional[List[int]] = None
    straggler_s: Optional[List[float]] = None
    # chaos partition (ROBUSTNESS.md §6): per-client connected-component id
    # this round (None = mesh whole); healed marks the first whole round
    # after a span, where the components reconciled through the configured
    # aggregator
    partition: Optional[List[int]] = None
    healed: bool = False
    # chaos churn: per-client alive mask (0 = permanently left / not yet
    # joined); None when no churn is scheduled
    churn_alive: Optional[List[float]] = None
    # peer lifecycle (bcfl_tpu.reputation): per-client state name and EWMA
    # trust AFTER this round's evidence was folded in; None = reputation off
    reputation_state: Optional[List[str]] = None
    reputation_trust: Optional[List[float]] = None
    # async staleness (global version - client version) at this aggregation
    # event, for each client (async mode only)
    staleness: Optional[List[int]] = None
    # cohort mode (SCALING.md): the round's sampled REGISTRY client ids, in
    # stacked-slot order. Every other per-client field on this record stays
    # in the SLOT domain — value lists (mask/auth/local_acc/reputation_*)
    # are slot-aligned and index lists (anomalies/dropped) hold slot
    # indices — so `cohort[slot]` is the one mapping back to registry
    # identity. None when registry sampling is off (slot == client id).
    cohort: Optional[List[int]] = None
    info_passing_sync_s: Optional[float] = None
    info_passing_async_s: Optional[float] = None
    # bytes-on-wire accounting (COMPRESSION.md): what this round's update
    # exchange shipped across all clients — raw full-precision size vs the
    # configured codec's payload (equal, ratio 1.0, at compress=none)
    bytes_raw: Optional[float] = None
    bytes_on_wire: Optional[float] = None
    compression_ratio: Optional[float] = None
    # LoRA adapter exchange: mean Shannon effective rank of the global
    # adapter tree after this round's aggregation — the rank-collapse guard
    # for heterogeneous-rank fleets (a healthy RBLA aggregate keeps energy
    # spread across rank dims; a collapsing one trends toward 1.0). None
    # when lora_rank == 0.
    effective_rank: Optional[float] = None
    wall_s: float = 0.0
    # True when this round ran inside a fused multi-round dispatch: wall_s
    # is then the chunk total split EVENLY across its rounds (an
    # interpolation, not a per-round measurement — the real measured unit is
    # wall_chunk_s) and info-passing values are chunk-constant
    fused: bool = False
    wall_chunk_s: Optional[float] = None


@dataclasses.dataclass
class RunMetrics:
    rounds: List[RoundRecord] = dataclasses.field(default_factory=list)
    model_size_gb: float = 0.0
    resources: Dict[str, float] = dataclasses.field(default_factory=dict)
    ledger: Dict[str, float] = dataclasses.field(default_factory=dict)
    # per-phase step timings from metrics.tracing.StepClock
    phases: Dict[str, Dict[str, float]] = dataclasses.field(default_factory=dict)
    # communication accounting rollup: codec kind, per-round raw vs
    # bytes-on-wire, and the compression ratio (COMPRESSION.md)
    comms: Dict[str, float] = dataclasses.field(default_factory=dict)
    # peer-lifecycle rollup (bcfl_tpu.reputation.ReputationTracker.summary):
    # final state/trust per client, quarantine event + round counts
    reputation: Dict = dataclasses.field(default_factory=dict)

    @property
    def global_accuracies(self) -> List[float]:
        """The reference's ``global_accuracies`` list
        (``serverless_NonIID_IMDB.py:334``)."""
        return [r.global_acc for r in self.rounds if r.global_acc is not None]

    def to_json(self) -> str:
        return json.dumps({
            "rounds": [dataclasses.asdict(r) for r in self.rounds],
            "model_size_gb": self.model_size_gb,
            "resources": self.resources,
            "ledger": self.ledger,
            "phases": self.phases,
            "comms": self.comms,
            "reputation": self.reputation,
            "global_accuracies": self.global_accuracies,
        }, indent=2)

    def summary(self) -> str:
        accs = self.global_accuracies
        lines = [
            f"rounds: {len(self.rounds)}",
            f"model size: {self.model_size_gb:.4f} GB",
            f"final global accuracy: {accs[-1]:.4f}" if accs else "no global eval",
        ]
        for k, v in self.resources.items():
            lines.append(f"{k}: {v:.3f}")
        return "\n".join(lines)
