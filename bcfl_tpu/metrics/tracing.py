"""Tracing / profiling (SURVEY.md §5: the reference has none beyond coarse
psutil+wall-clock — this subsystem is the rebuild's upgrade, kept optional).

Three layers:

- :class:`StepClock` — cheap host-side phase timing (data, train, aggregate,
  eval per round) with mean/p50/p95 summaries; always on, no deps.
- :func:`trace` — context manager around ``jax.profiler`` emitting a
  TensorBoard-loadable trace directory for the wrapped region.
- :func:`annotate` — ``jax.profiler.TraceAnnotation`` wrapper so engine
  phases show up as named spans inside device traces.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Dict, List, Optional

from bcfl_tpu.telemetry import events as _telemetry


class StepClock:
    """Named phase timers: ``with clock.phase("train"): ...`` per round.

    Every completed phase also feeds the run's event stream as a typed
    ``phase`` span (bcfl_tpu.telemetry, OBSERVABILITY.md) — a no-op unless
    the run installed an event writer, so the pre-telemetry cost model is
    unchanged."""

    def __init__(self):
        self._times: Dict[str, List[float]] = defaultdict(list)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self._times[name].append(dt)
            _telemetry.emit("phase", name=name, wall_s=dt)

    def record(self, name: str, seconds: float):
        self._times[name].append(seconds)
        _telemetry.emit("phase", name=name, wall_s=seconds)

    def summary(self) -> Dict[str, Dict[str, float]]:
        import numpy as np

        out = {}
        for name, xs in self._times.items():
            a = np.asarray(xs)
            out[name] = {
                "count": int(a.size),
                "total_s": float(a.sum()),
                "mean_s": float(a.mean()),
                "p50_s": float(np.percentile(a, 50)),
                "p95_s": float(np.percentile(a, 95)),
            }
        return out


@contextlib.contextmanager
def trace(log_dir: Optional[str]):
    """``jax.profiler`` trace of the wrapped region (no-op if ``log_dir`` is
    falsy). View with TensorBoard's profile plugin or Perfetto."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span inside a device trace (safe no-op if profiling is off)."""
    import jax

    return jax.profiler.TraceAnnotation(name)
