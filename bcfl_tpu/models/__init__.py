"""Model registry.

The reference's model space is three HF checkpoints (SURVEY.md §2.1):
``albert-base-v2``, ``dmis-lab/biobert-v1.1`` (cased BERT-base), used via
``AutoModelForSequenceClassification``. Registry names map to
:class:`~bcfl_tpu.models.bert.EncoderConfig` instances; ``tiny-*`` variants are
the scale-down smoke models (the reference's de-facto test method is a
NUM_CLIENTS=2/NUM_ROUNDS=2 scale-down of the same script — SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from bcfl_tpu.models.bert import EncoderConfig, TextClassifier  # noqa: F401
from bcfl_tpu.models import lora  # noqa: F401

_CONFIGS: Dict[str, EncoderConfig] = {
    # test/bench scale-downs
    "tiny-bert": EncoderConfig(vocab_size=8192, hidden_size=128, num_layers=2,
                               num_heads=2, intermediate_size=512),
    "tiny-albert": EncoderConfig(vocab_size=8192, hidden_size=128, num_layers=2,
                                 num_heads=2, intermediate_size=512,
                                 share_layers=True, embedding_size=64),
    # mid-size encoder: real-data experiments on hosts without an
    # accelerator (a BERT-base run is TPU-sized); same family, 4 layers
    "small-bert": EncoderConfig(vocab_size=30522, hidden_size=512,
                                num_layers=4, num_heads=8,
                                intermediate_size=2048),
    # BERT-base family (BASELINE.json north-star model; biobert-v1.1 is a
    # cased BERT-base, vocab 28996 — reference server_IID_IMDB.py:48)
    "bert-base": EncoderConfig(vocab_size=30522, hidden_size=768, num_layers=12,
                               num_heads=12, intermediate_size=3072),
    "biobert-base": EncoderConfig(vocab_size=28996, hidden_size=768, num_layers=12,
                                  num_heads=12, intermediate_size=3072),
    # albert-base-v2 (reference serverless_NonIID_IMDB.py:30)
    "albert-base": EncoderConfig(vocab_size=30000, hidden_size=768, num_layers=12,
                                 num_heads=12, intermediate_size=3072,
                                 share_layers=True, embedding_size=128),
    # emilyalsentzer/Bio_ClinicalBERT — cased BERT-base init'd from BioBERT
    # (BASELINE.json configs[3] "ClinicalBERT Medical-Transcriptions")
    "clinical-bert": EncoderConfig(vocab_size=28996, hidden_size=768,
                                   num_layers=12, num_heads=12,
                                   intermediate_size=3072),
}

_LLAMA_CONFIGS: Dict[str, "LlamaConfig"] = {}


def _llama_configs():
    global _LLAMA_CONFIGS
    if not _LLAMA_CONFIGS:
        from bcfl_tpu.models.llama import LlamaConfig

        _LLAMA_CONFIGS = {
            # test/bench scale-down (GQA exercised: 4 heads / 2 kv heads)
            "tiny-llama": LlamaConfig(vocab_size=8192, hidden_size=128,
                                      num_layers=2, num_heads=4, num_kv_heads=2,
                                      intermediate_size=384, max_position=512),
            # Llama-2-7B (BASELINE.json configs[4]: LoRA fed fine-tune)
            "llama2-7b": LlamaConfig(vocab_size=32000, hidden_size=4096,
                                     num_layers=32, num_heads=32,
                                     intermediate_size=11008,
                                     max_position=4096),
        }
    return _LLAMA_CONFIGS


def get_config(name: str, **overrides):
    # encoder registry first: llama.py is only imported on an encoder miss,
    # so encoder-only runs never depend on the llama module importing
    if name in _CONFIGS:
        return dataclasses.replace(_CONFIGS[name], **overrides)
    if name in _llama_configs():
        return dataclasses.replace(_llama_configs()[name], **overrides)
    raise KeyError(
        f"unknown model {name!r}; have "
        f"{sorted(_CONFIGS) + sorted(_llama_configs())}")


def list_models():
    return sorted(_CONFIGS) + sorted(_llama_configs())


def build(name: str, head: str = "classifier", **overrides):
    """Build the named model; encoder and llama families share the forward
    signature ``apply(vars, ids, mask, deterministic=...) -> logits``.
    ``head="lm"`` builds the causal-LM variant ([B, S, vocab] logits —
    llama family only; encoders are bidirectional, so next-token training
    would leak the target)."""
    cfg = get_config(name, **overrides)
    if name not in _CONFIGS:
        from bcfl_tpu.models.llama import LlamaClassifier, LlamaLM

        return LlamaLM(cfg) if head == "lm" else LlamaClassifier(cfg)
    if head == "lm":
        raise ValueError(
            f"model {name!r} is an encoder: causal-LM training needs a "
            "decoder (llama family)")
    return TextClassifier(cfg)


def lora_targets(name: str):
    """Module names whose kernels get LoRA adapters, per model family."""
    if name not in _CONFIGS and name in _llama_configs():
        from bcfl_tpu.models.llama import LORA_TARGETS

        return LORA_TARGETS
    return lora.DEFAULT_TARGETS


def tp_param_specs(model, params, axis: str = "tp"):
    """Megatron tensor-parallel PartitionSpecs for ``params``, dispatched on
    the BUILT model's family. Pass the model INSTANCE (what :func:`build`
    returned), not a registry name: an ``hf_checkpoint`` run always builds an
    encoder even when the config names a llama model, and name-based specs
    would then match nothing and silently replicate the base onto every tp
    shard. This is the single dispatch point (the engine calls it too)."""
    if isinstance(model, str):
        raise TypeError(
            "tp_param_specs takes the built model instance, not a name: "
            "a name cannot see through hf_checkpoint overrides")
    if isinstance(model, TextClassifier):
        from bcfl_tpu.models.bert import tp_specs

        return tp_specs(params, axis=axis)
    from bcfl_tpu.models.llama import tp_specs

    return tp_specs(params, axis=axis)
