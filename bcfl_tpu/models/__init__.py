"""Model registry.

The reference's model space is three HF checkpoints (SURVEY.md §2.1):
``albert-base-v2``, ``dmis-lab/biobert-v1.1`` (cased BERT-base), used via
``AutoModelForSequenceClassification``. Registry names map to
:class:`~bcfl_tpu.models.bert.EncoderConfig` instances; ``tiny-*`` variants are
the scale-down smoke models (the reference's de-facto test method is a
NUM_CLIENTS=2/NUM_ROUNDS=2 scale-down of the same script — SURVEY.md §4).
"""

from __future__ import annotations

import dataclasses
from typing import Dict

from bcfl_tpu.models.bert import EncoderConfig, TextClassifier  # noqa: F401
from bcfl_tpu.models import lora  # noqa: F401

_CONFIGS: Dict[str, EncoderConfig] = {
    # test/bench scale-downs
    "tiny-bert": EncoderConfig(vocab_size=8192, hidden_size=128, num_layers=2,
                               num_heads=2, intermediate_size=512),
    "tiny-albert": EncoderConfig(vocab_size=8192, hidden_size=128, num_layers=2,
                                 num_heads=2, intermediate_size=512,
                                 share_layers=True, embedding_size=64),
    # BERT-base family (BASELINE.json north-star model; biobert-v1.1 is a
    # cased BERT-base, vocab 28996 — reference server_IID_IMDB.py:48)
    "bert-base": EncoderConfig(vocab_size=30522, hidden_size=768, num_layers=12,
                               num_heads=12, intermediate_size=3072),
    "biobert-base": EncoderConfig(vocab_size=28996, hidden_size=768, num_layers=12,
                                  num_heads=12, intermediate_size=3072),
    # albert-base-v2 (reference serverless_NonIID_IMDB.py:30)
    "albert-base": EncoderConfig(vocab_size=30000, hidden_size=768, num_layers=12,
                                 num_heads=12, intermediate_size=3072,
                                 share_layers=True, embedding_size=128),
}


def get_config(name: str, **overrides) -> EncoderConfig:
    if name not in _CONFIGS:
        raise KeyError(f"unknown model {name!r}; have {sorted(_CONFIGS)}")
    return dataclasses.replace(_CONFIGS[name], **overrides)


def list_models():
    return sorted(_CONFIGS)


def build(name: str, **overrides) -> TextClassifier:
    return TextClassifier(get_config(name, **overrides))
