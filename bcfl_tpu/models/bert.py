"""Flax BERT/ALBERT-family encoder + sequence-classification head.

The reference's L1 model layer is ``AutoModelForSequenceClassification
.from_pretrained(CHECKPOINT, num_labels=N)`` over three checkpoints:
``albert-base-v2``, ``dmis-lab/biobert-v1.1`` (a cased BERT-base) — SURVEY.md
§2.1. This module implements both architectures as ONE configurable Flax
model, TPU-first:

- post-LayerNorm transformer encoder (BERT formulation),
- ALBERT = the same encoder with ``share_layers=True`` (one parameter set
  applied ``num_layers`` times) + factorized embeddings
  (``embedding_size < hidden_size``),
- bf16 compute / f32 params by default (MXU-friendly),
- static shapes everywhere; padding handled by an additive attention bias and
  masked loss downstream,
- attention via :func:`bcfl_tpu.ops.dot_product_attention` (einsum -> MXU) or
  the Pallas flash kernel for long sequences.

HF checkpoint weights import via :mod:`bcfl_tpu.models.hf_import`.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp

from bcfl_tpu.ops.attention import attention_bias_from_mask, dot_product_attention


@dataclasses.dataclass(frozen=True)
class EncoderConfig:
    vocab_size: int = 8192
    hidden_size: int = 128
    num_layers: int = 2
    num_heads: int = 2
    intermediate_size: int = 512
    max_position: int = 512
    type_vocab_size: int = 2
    num_labels: int = 2
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12
    share_layers: bool = False  # ALBERT-style cross-layer parameter sharing
    embedding_size: Optional[int] = None  # ALBERT factorized embeddings; None = hidden
    use_flash: bool = False  # Pallas blockwise attention for long sequences
    flash_min_seq: int = 512  # below this, dense attention is faster
    # sequence-parallelism hook (same contract as LlamaConfig's): a callable
    # (q, k, v, key_bias, causal=False) -> out replacing the attention op,
    # e.g. ring attention over a 'seq' mesh axis (bcfl_tpu.parallel.sp).
    # Long-document ENCODER classification — the reference's medical
    # transcriptions are exactly this shape of input.
    attention_override: Optional[Callable] = None
    # per-layer activation rematerialization (jax.checkpoint via nn.remat):
    # recompute layer activations in the backward instead of storing them —
    # O(num_layers) less activation HBM for ~1/3 more FLOPs. The lever that
    # lets MORE full-fine-tune clients stack per chip.
    remat: bool = False
    dtype: jnp.dtype = jnp.bfloat16  # compute dtype
    param_dtype: jnp.dtype = jnp.float32

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


class SelfAttention(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x, bias, deterministic: bool, key_bias=None):
        c = self.cfg
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            features=(c.num_heads, c.head_dim),
            dtype=c.dtype,
            param_dtype=c.param_dtype,
            name=name,
        )
        # [B, S, H, D] -> [B, H, S, D]
        q = dense("query")(x).transpose(0, 2, 1, 3)
        k = dense("key")(x).transpose(0, 2, 1, 3)
        v = dense("value")(x).transpose(0, 2, 1, 3)
        if c.attention_override is not None:
            out = c.attention_override(q, k, v, key_bias, causal=False)
        elif c.use_flash and x.shape[1] >= c.flash_min_seq:
            from bcfl_tpu.ops.flash import flash_attention

            out = flash_attention(q, k, v, bias)
        else:
            out = dot_product_attention(q, k, v, bias)
        out = out.transpose(0, 2, 1, 3)  # [B, S, H, D]
        out = nn.DenseGeneral(
            features=self.cfg.hidden_size,
            axis=(-2, -1),
            dtype=c.dtype,
            param_dtype=c.param_dtype,
            name="out",
        )(out)
        return nn.Dropout(c.dropout_rate)(out, deterministic=deterministic)


class EncoderLayer(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, x, bias, key_bias, deterministic: bool):
        # deterministic is LAST and static — nn.remat static_argnums counts
        # self as index 0, so this arg is static_argnums=(4,) at the wrap
        # site in Encoder below
        c = self.cfg
        a = SelfAttention(c, name="attention")(x, bias, deterministic,
                                               key_bias)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         param_dtype=c.param_dtype, name="attention_norm")(x + a)
        h = nn.Dense(c.intermediate_size, dtype=c.dtype, param_dtype=c.param_dtype,
                     name="mlp_in")(x)
        h = nn.gelu(h, approximate=False)
        h = nn.Dense(c.hidden_size, dtype=c.dtype, param_dtype=c.param_dtype,
                     name="mlp_out")(h)
        h = nn.Dropout(c.dropout_rate)(h, deterministic=deterministic)
        return nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                            param_dtype=c.param_dtype, name="mlp_norm")(x + h)


class Embeddings(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids, type_ids, deterministic: bool):
        c = self.cfg
        e = c.embedding_size or c.hidden_size
        emb = nn.Embed(c.vocab_size, e, param_dtype=c.param_dtype, name="word")(ids)
        pos = nn.Embed(c.max_position, e, param_dtype=c.param_dtype, name="position")(
            jnp.arange(ids.shape[1])[None, :]
        )
        typ = nn.Embed(c.type_vocab_size, e, param_dtype=c.param_dtype, name="type")(type_ids)
        x = (emb + pos + typ).astype(c.dtype)
        x = nn.LayerNorm(epsilon=c.layer_norm_eps, dtype=c.dtype,
                         param_dtype=c.param_dtype, name="norm")(x)
        x = nn.Dropout(c.dropout_rate)(x, deterministic=deterministic)
        if e != c.hidden_size:  # ALBERT factorized projection
            x = nn.Dense(c.hidden_size, dtype=c.dtype, param_dtype=c.param_dtype,
                         name="projection")(x)
        return x


class Encoder(nn.Module):
    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids, mask, type_ids=None, deterministic: bool = True):
        c = self.cfg
        if type_ids is None:
            type_ids = jnp.zeros_like(ids)
        x = Embeddings(c, name="embeddings")(ids, type_ids, deterministic)
        # override (ring/SP) path: padding rides the [B, S] key bias, so the
        # dense O(S^2) bias tensor is never materialized
        bias = (None if c.attention_override is not None
                else attention_bias_from_mask(mask, dtype=jnp.float32))
        key_bias = jnp.where(mask > 0, 0.0, -1e30).astype(jnp.float32)
        # static_argnums counts self as 0: (x=1, bias=2, key_bias=3,
        # deterministic=4) — the bool drives python control flow (Dropout)
        layer_cls = (nn.remat(EncoderLayer, static_argnums=(4,))
                     if c.remat else EncoderLayer)
        if c.share_layers:
            layer = layer_cls(c, name="layer_shared")
            for _ in range(c.num_layers):
                x = layer(x, bias, key_bias, deterministic)
        else:
            for i in range(c.num_layers):
                x = layer_cls(c, name=f"layer_{i}")(x, bias, key_bias,
                                                    deterministic)
        return x


class TextClassifier(nn.Module):
    """Encoder + BERT-style pooler (tanh over [CLS]) + classification head.

    Forward signature matches what the federated client step needs:
    ``apply(params, ids, mask) -> [B, num_labels] float32 logits``.
    """

    cfg: EncoderConfig

    @nn.compact
    def __call__(self, ids, mask, type_ids=None, deterministic: bool = True):
        c = self.cfg
        x = Encoder(c, name="encoder")(ids, mask, type_ids, deterministic)
        cls = x[:, 0]
        pooled = nn.tanh(
            nn.Dense(c.hidden_size, dtype=c.dtype, param_dtype=c.param_dtype,
                     name="pooler")(cls)
        )
        pooled = nn.Dropout(c.dropout_rate)(pooled, deterministic=deterministic)
        logits = nn.Dense(c.num_labels, dtype=jnp.float32, param_dtype=c.param_dtype,
                          name="classifier")(pooled)
        return logits


def tp_specs(params, axis: str = "tp"):
    """PartitionSpecs for megatron-style tensor parallelism of the encoder
    family over ``axis``: column-parallel query/key/value (shard heads) and
    mlp_in (shard the intermediate dim), row-parallel out/mlp_out (shard the
    input side), everything else — embeddings, norms, pooler, classifier —
    replicated. Column-parallel biases shard with their outputs; row-parallel
    biases are replicated (added after the tp all-reduce).

    ``tp`` must divide ``num_heads`` and ``intermediate_size``. The twin of
    :func:`bcfl_tpu.models.llama.tp_specs` for the BERT/ALBERT family, so a
    clients x tp mesh works for every registry model.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    COL = {"query", "key", "value", "mlp_in"}
    ROW = {"out", "mlp_out"}

    def spec(path, leaf):
        names = tuple(getattr(p, "key", str(p)) for p in path)
        mod = names[-2] if len(names) >= 2 else ""
        is_bias = names[-1] == "bias"
        if mod in COL:
            if is_bias:  # q/k/v bias [heads, head_dim]; mlp_in bias [ffn]
                return P(axis) if leaf.ndim == 1 else P(axis, None)
            # q/k/v kernel [hidden, heads, head_dim] -> shard heads;
            # mlp_in kernel [hidden, ffn] -> shard ffn
            return P(None, axis) if leaf.ndim == 2 else P(None, axis, None)
        if mod in ROW and not is_bias:
            # out kernel [heads, head_dim, hidden] -> shard heads (input
            # side); mlp_out kernel [ffn, hidden] -> shard ffn (input side)
            return P(axis, None) if leaf.ndim == 2 else P(axis, None, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
