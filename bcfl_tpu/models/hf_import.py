"""HF checkpoint -> bcfl_tpu param-tree import.

The reference loads pretrained torch checkpoints with
``AutoModelForSequenceClassification.from_pretrained`` (``albert-base-v2``,
``dmis-lab/biobert-v1.1`` — ``src/Serverlesscase/serverless_NonIID_IMDB.py:155-157``,
``src/Servercase/server_IID_IMDB.py:48``). This module maps a HF torch
``state_dict`` onto :class:`bcfl_tpu.models.bert.EncoderConfig` param trees so
the same checkpoints seed federated fine-tuning here.

num_labels mismatches: the reference papers over them with
``ignore_mismatched_sizes=True`` (``server_noniid_medical_transcriptions.py:146-148``)
and even ships a silent 3-vs-41 head mismatch
(``serverless_cancer_biobert_allclients.py:117`` vs ``:242``). We hard-error
unless ``reinit_classifier=True`` is passed explicitly (SURVEY.md §7).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from bcfl_tpu.models.bert import EncoderConfig


def config_from_hf(hf_config, num_labels: Optional[int] = None) -> EncoderConfig:
    """Derive an :class:`EncoderConfig` from a HF Bert/Albert config object."""
    is_albert = hf_config.model_type == "albert"
    emb = getattr(hf_config, "embedding_size", None)
    return EncoderConfig(
        vocab_size=hf_config.vocab_size,
        hidden_size=hf_config.hidden_size,
        num_layers=hf_config.num_hidden_layers,
        num_heads=hf_config.num_attention_heads,
        intermediate_size=hf_config.intermediate_size,
        max_position=hf_config.max_position_embeddings,
        type_vocab_size=hf_config.type_vocab_size,
        num_labels=num_labels or getattr(hf_config, "num_labels", 2),
        layer_norm_eps=hf_config.layer_norm_eps,
        share_layers=is_albert,
        embedding_size=emb if (emb and emb != hf_config.hidden_size) else None,
    )


def _t(x) -> np.ndarray:
    return np.asarray(x.detach().cpu().numpy() if hasattr(x, "detach") else x)


def _dense(sd: Dict, prefix: str):
    """torch Linear [out, in] -> flax kernel [in, out] + bias [out]."""
    return {"kernel": _t(sd[prefix + ".weight"]).T, "bias": _t(sd[prefix + ".bias"])}


def _qkv(sd: Dict, prefix: str, heads: int, head_dim: int):
    w = _t(sd[prefix + ".weight"]).T  # [in, out]
    b = _t(sd[prefix + ".bias"])
    return {
        "kernel": w.reshape(w.shape[0], heads, head_dim),
        "bias": b.reshape(heads, head_dim),
    }


def _outproj(sd: Dict, prefix: str, heads: int, head_dim: int):
    w = _t(sd[prefix + ".weight"]).T  # [in(=h*d), out]
    return {
        "kernel": w.reshape(heads, head_dim, w.shape[1]),
        "bias": _t(sd[prefix + ".bias"]),
    }


def _ln(sd: Dict, prefix: str):
    return {"scale": _t(sd[prefix + ".weight"]), "bias": _t(sd[prefix + ".bias"])}


def _layer_from_bert(sd, p, h, d):
    return {
        "attention": {
            "query": _qkv(sd, f"{p}.attention.self.query", h, d),
            "key": _qkv(sd, f"{p}.attention.self.key", h, d),
            "value": _qkv(sd, f"{p}.attention.self.value", h, d),
            "out": _outproj(sd, f"{p}.attention.output.dense", h, d),
        },
        "attention_norm": _ln(sd, f"{p}.attention.output.LayerNorm"),
        "mlp_in": _dense(sd, f"{p}.intermediate.dense"),
        "mlp_out": _dense(sd, f"{p}.output.dense"),
        "mlp_norm": _ln(sd, f"{p}.output.LayerNorm"),
    }


def _layer_from_albert(sd, p, h, d):
    return {
        "attention": {
            "query": _qkv(sd, f"{p}.attention.query", h, d),
            "key": _qkv(sd, f"{p}.attention.key", h, d),
            "value": _qkv(sd, f"{p}.attention.value", h, d),
            "out": _outproj(sd, f"{p}.attention.dense", h, d),
        },
        "attention_norm": _ln(sd, f"{p}.attention.LayerNorm"),
        "mlp_in": _dense(sd, f"{p}.ffn"),
        "mlp_out": _dense(sd, f"{p}.ffn_output"),
        "mlp_norm": _ln(sd, f"{p}.full_layer_layer_norm"),
    }


def import_state_dict(
    sd: Dict,
    cfg: EncoderConfig,
    num_labels: Optional[int] = None,
    reinit_classifier: bool = False,
    rng: Optional[jax.Array] = None,
) -> Dict:
    """Build the full ``{'params': ...}`` tree from a HF torch state_dict.

    Works for ``BertForSequenceClassification`` / ``BertModel`` /
    ``AlbertForSequenceClassification`` / ``AlbertModel`` state dicts.
    """
    sd = {k.removeprefix("bert.").removeprefix("albert."): v for k, v in sd.items()}
    is_albert = cfg.share_layers
    h, d = cfg.num_heads, cfg.head_dim

    emb = {
        "word": {"embedding": _t(sd["embeddings.word_embeddings.weight"])},
        "position": {"embedding": _t(sd["embeddings.position_embeddings.weight"])},
        "type": {"embedding": _t(sd["embeddings.token_type_embeddings.weight"])},
        "norm": _ln(sd, "embeddings.LayerNorm"),
    }
    if is_albert:
        emb["projection"] = _dense(sd, "encoder.embedding_hidden_mapping_in")

    encoder = {"embeddings": emb}
    if is_albert:
        encoder["layer_shared"] = _layer_from_albert(
            sd, "encoder.albert_layer_groups.0.albert_layers.0", h, d
        )
    else:
        for i in range(cfg.num_layers):
            encoder[f"layer_{i}"] = _layer_from_bert(sd, f"encoder.layer.{i}", h, d)

    params = {"encoder": encoder}
    if "pooler.dense.weight" in sd:
        params["pooler"] = _dense(sd, "pooler.dense")
    elif "pooler.weight" in sd:  # ALBERT names the pooler directly
        params["pooler"] = _dense(sd, "pooler")
    else:
        raise KeyError("no pooler weights in state_dict")

    want_labels = num_labels or cfg.num_labels
    if "classifier.weight" in sd and not reinit_classifier:
        have = _t(sd["classifier.weight"]).shape[0]
        if have != want_labels:
            raise ValueError(
                f"checkpoint has {have} labels, config wants {want_labels}; pass "
                "reinit_classifier=True to keep the encoder and re-init the head "
                "(the reference silently ignores this with ignore_mismatched_sizes)"
            )
        params["classifier"] = _dense(sd, "classifier")
    else:
        if rng is None:
            rng = jax.random.key(0)
        scale = 1.0 / np.sqrt(cfg.hidden_size)
        params["classifier"] = {
            "kernel": jax.random.normal(rng, (cfg.hidden_size, want_labels),
                                        jnp.float32) * scale,
            "bias": jnp.zeros((want_labels,), jnp.float32),
        }

    return {"params": jax.tree.map(jnp.asarray, params)}


def import_pretrained(name_or_model, num_labels: Optional[int] = None,
                      reinit_classifier: bool = False):
    """Load a HF model (by hub name or an instantiated torch model) and return
    ``(EncoderConfig, params)``."""
    if isinstance(name_or_model, str):
        from transformers import AutoModelForSequenceClassification

        model = AutoModelForSequenceClassification.from_pretrained(name_or_model)
    else:
        model = name_or_model
    cfg = config_from_hf(model.config, num_labels=num_labels)
    params = import_state_dict(
        model.state_dict(), cfg, num_labels=num_labels,
        reinit_classifier=reinit_classifier,
    )
    return cfg, params
