"""Llama-family decoder (RMSNorm / RoPE / SwiGLU / GQA) for federated LoRA
fine-tuning.

The reference never runs a decoder LLM — its models are encoder classifiers
(SURVEY.md §2.1) — but the BASELINE.json north-star configs include
"Llama-2-7B LoRA federated fine-tune, 64 clients on v5e-64" (configs[4]).
This module provides that model family TPU-first:

- bf16 compute / f32 params, static shapes, additive causal+padding bias,
- classification head pools the LAST non-pad token (decoder convention,
  mirroring HF ``LlamaForSequenceClassification``) so the same federated
  client step / loss (:mod:`bcfl_tpu.fed.client_step`) trains it unchanged,
- an LM head for causal-LM local objectives,
- tensor-parallel PartitionSpecs via :func:`tp_specs` — attention heads and
  MLP hidden dim sharded over a ``tp`` mesh axis (the scaling-book megatron
  layout: column-parallel in, row-parallel out),
- LoRA targets (:data:`LORA_TARGETS`) for :mod:`bcfl_tpu.models.lora`.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import flax.linen as nn
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from bcfl_tpu.ops.attention import dot_product_attention

# lm_head is a LoRA target (not a full-trained head): on llama2-7b it is
# ~131M params, so full training would defeat the adapter-only
# communication win; the small classifier head full-trains via
# bcfl_tpu.models.lora.HEAD_MODULES
LORA_TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj",
                "gate_proj", "up_proj", "down_proj", "lm_head")


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # None = MHA; < num_heads = GQA
    intermediate_size: int = 11008
    max_position: int = 4096
    num_labels: int = 2
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    use_flash: bool = True  # blockwise causal attention (no dense [S,S] bias)
    flash_min_seq: int = 512  # below this, dense attention is faster
    dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    # sequence parallelism hook: a callable (q, k, v, key_bias, causal=...)
    # -> out that replaces the attention op — e.g. ring attention over a
    # 'seq' mesh axis (bcfl_tpu.parallel.sp.ring_config). Static module
    # config; None = the flash/dense selection above.
    attention_override: Optional[Callable] = None
    # per-layer activation rematerialization (nn.remat): O(num_layers) less
    # activation HBM for ~1/3 more FLOPs (see EncoderConfig.remat)
    remat: bool = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads


class RMSNorm(nn.Module):
    eps: float
    param_dtype: jnp.dtype

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones,
                           (x.shape[-1],), self.param_dtype)
        var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
        y = x.astype(jnp.float32) * jnp.reciprocal(jnp.sqrt(var + self.eps))
        return (y * scale.astype(jnp.float32)).astype(x.dtype)


def rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """Rotary embedding over [B, H, S, D] with positions [B, S] or [S]."""
    D = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
    if positions.ndim == 1:
        positions = positions[None, :]
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]
    cos = jnp.cos(angles)[:, None, :, :]  # [B, 1, S, D/2]
    sin = jnp.sin(angles)[:, None, :, :]
    x1, x2 = x[..., ::2].astype(jnp.float32), x[..., 1::2].astype(jnp.float32)
    r1 = x1 * cos - x2 * sin
    r2 = x1 * sin + x2 * cos
    out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    return out.astype(x.dtype)


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, bias, key_bias, positions):
        """``bias`` is the dense [B,1,S,S] path (None when flash is active);
        ``key_bias`` [B,S] is the padding mask for the flash path."""
        c = self.cfg
        dense = lambda name, heads: nn.DenseGeneral(  # noqa: E731
            features=(heads, c.head_dim), use_bias=False,
            dtype=c.dtype, param_dtype=c.param_dtype, name=name)
        q = dense("q_proj", c.num_heads)(x).transpose(0, 2, 1, 3)
        k = dense("k_proj", c.kv_heads)(x).transpose(0, 2, 1, 3)
        v = dense("v_proj", c.kv_heads)(x).transpose(0, 2, 1, 3)
        q = rope(q, positions, c.rope_theta)
        k = rope(k, positions, c.rope_theta)
        if c.kv_heads != c.num_heads:  # GQA: repeat KV groups
            rep = c.num_heads // c.kv_heads
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        if c.attention_override is not None:
            out = c.attention_override(q, k, v, key_bias, causal=True)
        elif bias is None:
            from bcfl_tpu.ops.flash import flash_attention

            out = flash_attention(q, k, v, key_bias, causal=True)
        else:
            out = dot_product_attention(q, k, v, bias)
        out = out.transpose(0, 2, 1, 3)
        return nn.DenseGeneral(
            features=c.hidden_size, axis=(-2, -1), use_bias=False,
            dtype=c.dtype, param_dtype=c.param_dtype, name="o_proj")(out)


class LlamaMLP(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x):
        c = self.cfg
        d = lambda f, name: nn.Dense(  # noqa: E731
            f, use_bias=False, dtype=c.dtype, param_dtype=c.param_dtype,
            name=name)
        return d(c.hidden_size, "down_proj")(
            nn.silu(d(c.intermediate_size, "gate_proj")(x))
            * d(c.intermediate_size, "up_proj")(x))


class LlamaLayer(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, x, bias, key_bias, positions):
        c = self.cfg
        h = RMSNorm(c.rms_eps, c.param_dtype, name="input_norm")(x)
        x = x + LlamaAttention(c, name="attention")(h, bias, key_bias, positions)
        h = RMSNorm(c.rms_eps, c.param_dtype, name="post_attention_norm")(x)
        return x + LlamaMLP(c, name="mlp")(h)


def causal_bias(mask: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """Causal + key-padding additive bias [B, 1, S, S] from mask [B, S]."""
    S = mask.shape[-1]
    causal = jnp.tril(jnp.ones((S, S), bool))
    ok = causal[None, :, :] & (mask[:, None, :] > 0)
    return jnp.where(ok, 0.0, -1e30).astype(dtype)[:, None, :, :]


class LlamaModel(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, ids, mask, deterministic: bool = True):
        c = self.cfg
        x = nn.Embed(c.vocab_size, c.hidden_size, param_dtype=c.param_dtype,
                     name="embed")(ids).astype(c.dtype)
        use_flash = c.use_flash and ids.shape[1] >= c.flash_min_seq
        # flash/ring paths: causal triangle + padding handled blockwise; the
        # dense [B,1,S,S] bias (O(S^2) memory) only exists for short
        # sequences where it is cheaper than the blockwise recurrence
        bias = (None if use_flash or c.attention_override is not None
                else causal_bias(mask))
        key_bias = jnp.where(mask > 0, 0.0, -1e30).astype(jnp.float32)
        positions = jnp.arange(ids.shape[1])
        # no static args: every LlamaLayer input is an array (or None bias)
        layer_cls = nn.remat(LlamaLayer) if c.remat else LlamaLayer
        for i in range(c.num_layers):
            x = layer_cls(c, name=f"layer_{i}")(x, bias, key_bias, positions)
        return RMSNorm(c.rms_eps, c.param_dtype, name="final_norm")(x)


class LlamaClassifier(nn.Module):
    """Decoder + last-non-pad-token classification head. Same forward
    signature as :class:`bcfl_tpu.models.bert.TextClassifier`, so the
    federated client step is model-agnostic."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, ids, mask, type_ids=None, deterministic: bool = True):
        c = self.cfg
        x = LlamaModel(c, name="model")(ids, mask, deterministic)
        last = jnp.maximum(mask.sum(axis=-1) - 1, 0)  # index of last real token
        pooled = jnp.take_along_axis(x, last[:, None, None].astype(jnp.int32), 1)[:, 0]
        return nn.Dense(c.num_labels, use_bias=False, dtype=jnp.float32,
                        param_dtype=c.param_dtype, name="classifier")(pooled)


class LlamaLM(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(self, ids, mask, deterministic: bool = True):
        c = self.cfg
        x = LlamaModel(c, name="model")(ids, mask, deterministic)
        return nn.Dense(c.vocab_size, use_bias=False, dtype=jnp.float32,
                        param_dtype=c.param_dtype, name="lm_head")(x)


def tp_specs(params, axis: str = "tp"):
    """PartitionSpecs for megatron-style tensor parallelism over ``axis``:
    column-parallel Q/K/V/gate/up (shard output heads/features), row-parallel
    o_proj/down (shard input), everything else replicated. Compose with the
    ``clients`` axis for clients x tp meshes (a client spanning several
    chips)."""
    import jax

    COL = {"q_proj", "k_proj", "v_proj", "gate_proj", "up_proj"}
    ROW = {"o_proj", "down_proj"}

    def spec(path, leaf):
        names = tuple(getattr(p, "key", str(p)) for p in path)
        mod = names[-2] if len(names) >= 2 else ""
        if mod in COL:
            # q/k/v kernel [in, heads, dim] -> shard heads;
            # gate/up kernel [in, out] -> shard out
            return P(None, axis) if leaf.ndim == 2 else P(None, axis, None)
        if mod in ROW:
            # o_proj kernel [heads, dim, out] -> shard heads (input side);
            # down kernel [in, out] -> shard in
            return P(axis, None) if leaf.ndim == 2 else P(axis, None, None)
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
