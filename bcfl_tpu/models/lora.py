"""LoRA adapters as a pure param-tree transform.

The reference does full fine-tuning only (1-epoch AdamW over all params,
``src/Servercase/server_IID_IMDB.py:108-118``); LoRA is required by the
BASELINE.json Llama-2-7B federated config and is the practical answer to the
per-client-state memory cost of stacking clients on a mesh (SURVEY.md §7
"hard parts"). Implementation is model-agnostic: it targets 2D(-reshapeable)
``kernel`` leaves of the frozen base tree, so the SAME federated client step
trains either full params or adapters — only the optimized tree changes.

Communication win: in federated mode only the adapter tree is aggregated /
gossiped, which is the real mechanism behind the reference's "0.043 GB instead
of 0.4036 GB" blockchain-payload claim (MT notebook cell 27).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

DEFAULT_TARGETS = ("query", "key", "value", "out", "mlp_in", "mlp_out")
# task heads are TRAINED IN FULL under LoRA (HF modules_to_save convention):
# a LoRA-only run would otherwise optimize against a frozen randomly-
# initialized head and plateau. They are small (hidden x labels / hidden x
# hidden); the vocab-sized lm_head instead gets a LoRA adapter (llama
# LORA_TARGETS) — full-training it would be ~131M params/client on
# llama2-7b, defeating the adapter-only communication win.
HEAD_MODULES = ("classifier", "pooler")


def _is_target(path: Tuple[str, ...], targets: Sequence[str]) -> bool:
    return len(path) >= 2 and path[-1] == "kernel" and path[-2] in targets


def init_lora(key: jax.Array, params, rank: int,
              targets: Sequence[str] = DEFAULT_TARGETS,
              head_modules: Sequence[str] = HEAD_MODULES):
    """Create the adapter tree: for each targeted kernel W (viewed 2D as
    [fan_in, fan_out]) an ``a`` [fan_in, rank] (gaussian/sqrt(rank)) and
    ``b`` [rank, fan_out] (zeros — adapters start as identity). Leaves of
    ``head_modules`` are copied into the tree whole and substituted (not
    low-rank-added) at merge time, so task heads fine-tune in full."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    adapters = {}
    for path, leaf in flat:
        names = tuple(getattr(p, "key", getattr(p, "name", str(p))) for p in path)
        if len(names) >= 2 and names[-2] in head_modules:
            adapters["/".join(names)] = {"full": leaf}
            continue
        if not _is_target(names, targets):
            continue
        shape = leaf.shape
        if len(shape) == 2:
            fan_in, fan_out = shape
        elif len(shape) == 3:
            # row-parallel output projections (DenseGeneral axis=(-2,-1))
            # have kernel [heads, head_dim, out]; column-parallel qkv
            # (features=(heads, head_dim)) have kernel [in, heads, head_dim]
            if names[-2] in ("out", "o_proj"):
                fan_in, fan_out = shape[0] * shape[1], shape[2]
            else:
                fan_in, fan_out = shape[0], shape[1] * shape[2]
        else:
            continue
        key, k1 = jax.random.split(key)
        adapters["/".join(names[:-1])] = {
            "a": (jax.random.normal(k1, (fan_in, rank), leaf.dtype)
                  / jnp.sqrt(jnp.asarray(rank, leaf.dtype))),
            "b": jnp.zeros((rank, fan_out), leaf.dtype),
        }
    return adapters


def apply_lora(params, adapters, scale: float = 1.0):
    """Return params with ``W + scale * (a @ b)`` merged into each targeted
    kernel (reshaped back to the kernel's native rank); head leaves stored
    whole in the adapter tree (``init_lora`` ``head_modules``) substitute
    the frozen value outright."""

    def merge(path, leaf):
        names = tuple(getattr(p, "key", getattr(p, "name", str(p))) for p in path)
        k_leaf = "/".join(names)
        entry = adapters.get(k_leaf)
        if isinstance(entry, dict) and "full" in entry:
            return entry["full"].astype(leaf.dtype)
        k = "/".join(names[:-1])
        if names and names[-1] == "kernel" and k in adapters:
            ab = adapters[k]["a"] @ adapters[k]["b"]
            return leaf + scale * ab.reshape(leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(merge, params)


def num_params(tree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))
