"""LoRA adapters as a pure param-tree transform.

The reference does full fine-tuning only (1-epoch AdamW over all params,
``src/Servercase/server_IID_IMDB.py:108-118``); LoRA is required by the
BASELINE.json Llama-2-7B federated config and is the practical answer to the
per-client-state memory cost of stacking clients on a mesh (SURVEY.md §7
"hard parts"). Implementation is model-agnostic: it targets 2D(-reshapeable)
``kernel`` leaves of the frozen base tree, so the SAME federated client step
trains either full params or adapters — only the optimized tree changes.

Communication win: in federated mode only the adapter tree is aggregated /
gossiped, which is the real mechanism behind the reference's "0.043 GB instead
of 0.4036 GB" blockchain-payload claim (MT notebook cell 27).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

DEFAULT_TARGETS = ("query", "key", "value", "out", "mlp_in", "mlp_out")
# task heads are TRAINED IN FULL under LoRA (HF modules_to_save convention):
# a LoRA-only run would otherwise optimize against a frozen randomly-
# initialized head and plateau. They are small (hidden x labels / hidden x
# hidden); the vocab-sized lm_head instead gets a LoRA adapter (llama
# LORA_TARGETS) — full-training it would be ~131M params/client on
# llama2-7b, defeating the adapter-only communication win.
HEAD_MODULES = ("classifier", "pooler")


def _is_target(path: Tuple[str, ...], targets: Sequence[str]) -> bool:
    return len(path) >= 2 and path[-1] == "kernel" and path[-2] in targets


def init_lora(key: jax.Array, params, rank: int,
              targets: Sequence[str] = DEFAULT_TARGETS,
              head_modules: Sequence[str] = HEAD_MODULES):
    """Create the adapter tree: for each targeted kernel W (viewed 2D as
    [fan_in, fan_out]) an ``a`` [fan_in, rank] (gaussian/sqrt(rank)) and
    ``b`` [rank, fan_out] (zeros — adapters start as identity). Leaves of
    ``head_modules`` are copied into the tree whole and substituted (not
    low-rank-added) at merge time, so task heads fine-tune in full."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    adapters = {}
    for path, leaf in flat:
        names = tuple(getattr(p, "key", getattr(p, "name", str(p))) for p in path)
        if len(names) >= 2 and names[-2] in head_modules:
            adapters["/".join(names)] = {"full": leaf}
            continue
        if not _is_target(names, targets):
            continue
        shape = leaf.shape
        if len(shape) == 2:
            fan_in, fan_out = shape
        elif len(shape) == 3:
            # row-parallel output projections (DenseGeneral axis=(-2,-1))
            # have kernel [heads, head_dim, out]; column-parallel qkv
            # (features=(heads, head_dim)) have kernel [in, heads, head_dim]
            if names[-2] in ("out", "o_proj"):
                fan_in, fan_out = shape[0] * shape[1], shape[2]
            else:
                fan_in, fan_out = shape[0], shape[1] * shape[2]
        else:
            continue
        key, k1 = jax.random.split(key)
        adapters["/".join(names[:-1])] = {
            "a": (jax.random.normal(k1, (fan_in, rank), leaf.dtype)
                  / jnp.sqrt(jnp.asarray(rank, leaf.dtype))),
            "b": jnp.zeros((rank, fan_out), leaf.dtype),
        }
    return adapters


def apply_lora(params, adapters, scale: float = 1.0):
    """Return params with ``W + scale * (a @ b)`` merged into each targeted
    kernel (reshaped back to the kernel's native rank); head leaves stored
    whole in the adapter tree (``init_lora`` ``head_modules``) substitute
    the frozen value outright."""

    def merge(path, leaf):
        names = tuple(getattr(p, "key", getattr(p, "name", str(p))) for p in path)
        k_leaf = "/".join(names)
        entry = adapters.get(k_leaf)
        if isinstance(entry, dict) and "full" in entry:
            return entry["full"].astype(leaf.dtype)
        k = "/".join(names[:-1])
        if names and names[-1] == "kernel" and k in adapters:
            ab = adapters[k]["a"] @ adapters[k]["b"]
            return leaf + scale * ab.reshape(leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(merge, params)


def num_params(tree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


# ---------------------------------------------------------------------------
# Heterogeneous per-client ranks (RBLA, arXiv 2408.08699).
#
# A fleet where client c trains at rank r_c is materialized at the COHORT MAX
# rank R = max(r_c): every 'a' is [fan_in, R], every 'b' is [R, fan_out], and
# client c's columns/rows >= r_c are structural zero padding. The padding is
# described by a [C, R] mask that is a pure function of the (static) rank
# spec — it compiles into the round programs as a closure constant, so
# heterogeneous fleets add ZERO per-round retraces. Padding stays exactly
# zero through training without re-clipping after aggregation: both factors
# start at 0 there, so gradients are 0, and AdamW (m=0, v=0, decay of a 0
# param) produces an exactly-0 update — clipping the global tree once at
# local-train entry covers every path (server, serverless, async, gossip).
# ---------------------------------------------------------------------------


def rank_mask(ranks: Sequence[int]) -> jnp.ndarray:
    """``[C, max(ranks)]`` float mask: ``mask[c, j] = 1`` iff ``j < ranks[c]``.
    Static in the rank spec — built once at program-build time."""
    r = jnp.asarray([int(x) for x in ranks], jnp.int32)
    rmax = int(max(int(x) for x in ranks))
    return (jnp.arange(rmax)[None, :] < r[:, None]).astype(jnp.float32)


def clip_adapters(adapters, mask_row: jnp.ndarray):
    """Zero one client's padding dims: ``a * row[None, :]``,
    ``b * row[:, None]``; ``full`` head leaves pass through. Applied to the
    replicated global tree at local-train entry (vmapped over mask rows)."""

    def clip(path, leaf):
        names = tuple(getattr(p, "key", getattr(p, "name", str(p)))
                      for p in path)
        last = names[-1] if names else ""
        if last == "a":
            return leaf * mask_row[None, :].astype(leaf.dtype)
        if last == "b":
            return leaf * mask_row[:, None].astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(clip, adapters)


def init_lora_ranks(key: jax.Array, params, ranks: Sequence[int],
                    targets: Sequence[str] = DEFAULT_TARGETS,
                    head_modules: Sequence[str] = HEAD_MODULES):
    """Stacked ``[C, ...]`` adapter tree for a heterogeneous fleet: client
    ``c`` is initialized AT ITS OWN rank (gaussian/sqrt(r_c) — the init
    scale a homogeneous rank-r_c client would get), then zero-padded to the
    cohort max rank so all clients share one stacked structure."""
    ranks = tuple(int(r) for r in ranks)
    rmax = max(ranks)
    per_client = []
    for c, r in enumerate(ranks):
        adp = init_lora(jax.random.fold_in(key, c), params, r,
                        targets=targets, head_modules=head_modules)
        padded = {}
        for k, entry in adp.items():
            if "full" in entry:
                padded[k] = entry
            else:
                padded[k] = {
                    "a": jnp.pad(entry["a"], ((0, 0), (0, rmax - r))),
                    "b": jnp.pad(entry["b"], ((0, rmax - r), (0, 0))),
                }
        per_client.append(padded)
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *per_client)


def effective_rank(adapters) -> jnp.ndarray:
    """Mean Shannon effective rank over the adapter factor pairs of one
    (unstacked) adapter tree — the rank-collapse guard of arXiv 2602.13486,
    without an SVD: per rank dim ``e_j = ||a[:, j]||^2 * ||b[j, :]||^2`` is
    the squared Frobenius energy of the j-th rank-1 component, and
    ``exp(entropy(e / sum e))`` counts how many components carry it. 0.0
    when the adapters carry no energy at all (b starts at zeros)."""
    tiny = jnp.float32(1e-30)
    effs = []
    flat = jax.tree_util.tree_flatten_with_path(adapters)[0]
    pairs = {}
    for path, leaf in flat:
        names = tuple(getattr(p, "key", getattr(p, "name", str(p)))
                      for p in path)
        if names and names[-1] in ("a", "b"):
            pairs.setdefault("/".join(names[:-1]), {})[names[-1]] = leaf
    for entry in pairs.values():
        if "a" not in entry or "b" not in entry:
            continue
        a = entry["a"].astype(jnp.float32)
        b = entry["b"].astype(jnp.float32)
        e = (a * a).sum(axis=0) * (b * b).sum(axis=1)
        tot = e.sum()
        p = e / jnp.maximum(tot, tiny)
        ent = -(p * jnp.log(jnp.maximum(p, tiny))).sum()
        effs.append(jnp.where(tot > tiny, jnp.exp(ent), 0.0))
    if not effs:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.stack(effs).mean()
