"""Native (C++) runtime components, bound via ctypes.

Build is on-demand and cached next to the sources; everything here has a pure
Python fallback so the framework never hard-requires a toolchain.
"""

from bcfl_tpu.native.build import load_ledger_lib  # noqa: F401
