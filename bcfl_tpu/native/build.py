"""On-demand g++ build + ctypes binding for the native cores.

One shared loader (lock, cache, mtime-based rebuild, graceful fallback)
serves every native component; each public ``load_*_lib`` passes only its
source/library paths and an argtypes-configuration callback. Everything here
has a pure Python fallback, so the framework never hard-requires a
toolchain: any failure — no g++, missing source, unloadable .so — returns
None and the caller takes the Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, Optional, Tuple

_DIR = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
# src path -> (lib or None); None is cached too so a broken toolchain is
# probed once per process, not once per call
_cache: Dict[str, Tuple[bool, Optional[ctypes.CDLL]]] = {}


def _compile(src: str, lib: str) -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", lib, src],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:
        return False


def _load_lib(src_name: str, lib_name: str,
              configure: Callable[[ctypes.CDLL], None]) -> Optional[ctypes.CDLL]:
    src = os.path.join(_DIR, src_name)
    lib_path = os.path.join(_DIR, lib_name)
    with _lock:
        hit = _cache.get(src)
        if hit is not None:
            return hit[1]
        lib = None
        try:
            # a shipped .so without its source is fine (no rebuild check);
            # neither file existing is the no-toolchain fallback
            if os.path.exists(src) and (
                    not os.path.exists(lib_path)
                    or os.path.getmtime(lib_path) < os.path.getmtime(src)):
                if not _compile(src, lib_path):
                    _cache[src] = (True, None)
                    return None
            if os.path.exists(lib_path):
                lib = ctypes.CDLL(lib_path)
                configure(lib)
        except OSError:
            lib = None
        _cache[src] = (True, lib)
        return lib


def _configure_ledger(lib: ctypes.CDLL) -> None:
    lib.bcfl_sha256.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.bcfl_sha256_multi.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64, ctypes.c_char_p]
    lib.bcfl_chain_extend.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
    lib.bcfl_chain_verify.argtypes = [
        ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_char_p, ctypes.c_uint64]
    lib.bcfl_chain_verify.restype = ctypes.c_int64


def _configure_tokenizer(lib: ctypes.CDLL) -> None:
    lib.bcfl_hash_tokenize.argtypes = [
        ctypes.c_char_p,                  # concatenated lowered UTF-8
        ctypes.POINTER(ctypes.c_int64),   # offsets [n+1]
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),   # ids [n, seq_len]
        ctypes.POINTER(ctypes.c_int32),   # mask [n, seq_len]
    ]


def load_ledger_lib() -> Optional[ctypes.CDLL]:
    """The compiled ledger library, building it on first use; None if no
    toolchain is available (callers fall back to hashlib)."""
    return _load_lib("sha256.cc", "libbcfl_ledger.so", _configure_ledger)


def load_tokenizer_lib() -> Optional[ctypes.CDLL]:
    """The compiled hash-tokenizer core, building it on first use; None if
    no toolchain is available (callers fall back to the Python loop)."""
    return _load_lib("tokenizer.cc", "libbcfl_tok.so", _configure_tokenizer)
