"""On-demand g++ build + ctypes binding for the native ledger core."""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "sha256.cc")
_LIB = os.path.join(_DIR, "libbcfl_ledger.so")
_lock = threading.Lock()
_cached: Optional[ctypes.CDLL] = None
_failed = False


def _compile() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", _LIB, _SRC],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except Exception:
        return False


def load_ledger_lib() -> Optional[ctypes.CDLL]:
    """The compiled ledger library, building it on first use; None if no
    toolchain is available (callers fall back to hashlib)."""
    global _cached, _failed
    with _lock:
        if _cached is not None:
            return _cached
        if _failed:
            return None
        if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _compile():
                _failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _failed = True
            return None
        lib.bcfl_sha256.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
        lib.bcfl_sha256_multi.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_uint64, ctypes.c_char_p]
        lib.bcfl_chain_extend.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p]
        lib.bcfl_chain_verify.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_char_p, ctypes.c_uint64]
        lib.bcfl_chain_verify.restype = ctypes.c_int64
        _cached = lib
        return lib
