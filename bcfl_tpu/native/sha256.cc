// SHA-256 (FIPS 180-4) + hash-chain primitives for the BC-FL weight ledger.
//
// The reference describes its blockchain layer only in prose (README.md:10;
// MT notebook cells 26-28 model a 0.043 GB ledger payload) — there is no
// blockchain code to port (SURVEY.md §2.2 C18). This is the native core of
// the real implementation: digesting per-client parameter buffers and
// extending the chain head runs in C++ on the TPU-VM host, off the Python
// hot path. Exposed as a plain C ABI for ctypes.
//
// Build: g++ -O3 -shared -fPIC -o libbcfl_ledger.so sha256.cc

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr uint32_t K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

inline uint32_t rotr(uint32_t x, unsigned n) { return (x >> n) | (x << (32 - n)); }

struct Sha256Ctx {
  uint32_t h[8];
  uint64_t len;      // total bytes seen
  uint8_t buf[64];   // pending block
  size_t buflen;
};

void sha256_init(Sha256Ctx* c) {
  static const uint32_t H0[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                                 0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
  std::memcpy(c->h, H0, sizeof(H0));
  c->len = 0;
  c->buflen = 0;
}

void sha256_block(Sha256Ctx* c, const uint8_t* p) {
  uint32_t w[64];
  for (int i = 0; i < 16; ++i)
    w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
           (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
  for (int i = 16; i < 64; ++i) {
    uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
    uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
    w[i] = w[i - 16] + s0 + w[i - 7] + s1;
  }
  uint32_t a = c->h[0], b = c->h[1], cc = c->h[2], d = c->h[3];
  uint32_t e = c->h[4], f = c->h[5], g = c->h[6], h = c->h[7];
  for (int i = 0; i < 64; ++i) {
    uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
    uint32_t ch = (e & f) ^ (~e & g);
    uint32_t t1 = h + S1 + ch + K[i] + w[i];
    uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
    uint32_t maj = (a & b) ^ (a & cc) ^ (b & cc);
    uint32_t t2 = S0 + maj;
    h = g; g = f; f = e; e = d + t1;
    d = cc; cc = b; b = a; a = t1 + t2;
  }
  c->h[0] += a; c->h[1] += b; c->h[2] += cc; c->h[3] += d;
  c->h[4] += e; c->h[5] += f; c->h[6] += g; c->h[7] += h;
}

void sha256_update(Sha256Ctx* c, const uint8_t* data, size_t len) {
  c->len += len;
  if (c->buflen) {
    size_t need = 64 - c->buflen;
    size_t take = len < need ? len : need;
    std::memcpy(c->buf + c->buflen, data, take);
    c->buflen += take;
    data += take;
    len -= take;
    if (c->buflen == 64) {
      sha256_block(c, c->buf);
      c->buflen = 0;
    }
  }
  while (len >= 64) {
    sha256_block(c, data);
    data += 64;
    len -= 64;
  }
  if (len) {
    std::memcpy(c->buf, data, len);
    c->buflen = len;
  }
}

void sha256_final(Sha256Ctx* c, uint8_t out[32]) {
  uint64_t bitlen = c->len * 8;
  uint8_t pad = 0x80;
  sha256_update(c, &pad, 1);
  uint8_t zero = 0;
  while (c->buflen != 56) sha256_update(c, &zero, 1);
  uint8_t lenb[8];
  for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bitlen >> (56 - 8 * i));
  sha256_update(c, lenb, 8);
  for (int i = 0; i < 8; ++i) {
    out[4 * i] = uint8_t(c->h[i] >> 24);
    out[4 * i + 1] = uint8_t(c->h[i] >> 16);
    out[4 * i + 2] = uint8_t(c->h[i] >> 8);
    out[4 * i + 3] = uint8_t(c->h[i]);
  }
}

}  // namespace

extern "C" {

// One-shot digest.
void bcfl_sha256(const uint8_t* data, uint64_t len, uint8_t out[32]) {
  Sha256Ctx c;
  sha256_init(&c);
  sha256_update(&c, data, size_t(len));
  sha256_final(&c, out);
}

// Digest of a list of buffers (a parameter tree's leaves, in canonical
// order) without concatenating on the Python side.
void bcfl_sha256_multi(const uint8_t* const* bufs, const uint64_t* lens,
                       uint64_t n, uint8_t out[32]) {
  Sha256Ctx c;
  sha256_init(&c);
  for (uint64_t i = 0; i < n; ++i)
    sha256_update(&c, bufs[i], size_t(lens[i]));
  sha256_final(&c, out);
}

// Chain extension: H(prev_hash[32] || payload). The ledger's entry hash.
void bcfl_chain_extend(const uint8_t prev[32], const uint8_t* payload,
                       uint64_t len, uint8_t out[32]) {
  Sha256Ctx c;
  sha256_init(&c);
  sha256_update(&c, prev, 32);
  sha256_update(&c, payload, size_t(len));
  sha256_final(&c, out);
}

// Verify a stored chain: heads[i] == H(heads[i-1] || payloads[i]) for all i
// (heads[-1] = genesis zeros). Returns the index of the first bad link or -1.
int64_t bcfl_chain_verify(const uint8_t* const* payloads, const uint64_t* lens,
                          const uint8_t* heads /* n x 32 */, uint64_t n) {
  uint8_t prev[32];
  std::memset(prev, 0, 32);
  uint8_t h[32];
  for (uint64_t i = 0; i < n; ++i) {
    bcfl_chain_extend(prev, payloads[i], lens[i], h);
    if (std::memcmp(h, heads + 32 * i, 32) != 0) return int64_t(i);
    std::memcpy(prev, h, 32);
  }
  return -1;
}

}  // extern "C"
