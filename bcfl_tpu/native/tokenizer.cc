// Native core for bcfl_tpu.data.tokenizer.HashTokenizer.encode_batch.
//
// The reference's data loader re-tokenizes the full dataset hundreds of
// times per run (SURVEY.md §3.2); this rebuild tokenizes ONCE into a static
// [N, seq_len] cache — and this file is that cache-build's hot loop in C++.
// Bit-for-bit parity with the Python path (tests/test_native_tokenizer.py):
//
//   words = re.findall(r"[a-z0-9']+|[^\sa-z0-9']", text.lower())
//   ids   = ([CLS] + [crc32(w)%(V-4)+4 for w in words[:seq_len-2]] + [SEP])[:seq_len]
//
// The caller lowercases in Python (full Unicode case rules stay there); this
// core consumes the lowered UTF-8 bytes and needs only: UTF-8 codepoint
// iteration, Python's \s whitespace set, the ASCII word classes, and
// zlib-compatible CRC-32. No libc beyond <cstdint>/<cstring>.

#include <cstdint>
#include <cstring>

namespace {

constexpr int PAD_ID = 0;
constexpr int CLS_ID = 2;
constexpr int SEP_ID = 3;
constexpr int N_SPECIAL = 4;

// CRC-32/ISO-HDLC (zlib.crc32): reflected, poly 0xEDB88320, init/xorout ~0.
// Table is built at load time (static initializer): ctypes releases the GIL
// during the call, so a lazy runtime init would be a data race between
// concurrently-tokenizing threads.
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const CrcTable crc;

inline uint32_t crc32_bytes(const uint8_t* p, size_t n) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) c = crc.t[(c ^ p[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

// Python re \s for str patterns == str.isspace() set
inline bool is_space_cp(uint32_t cp) {
  switch (cp) {
    case 0x09: case 0x0A: case 0x0B: case 0x0C: case 0x0D:
    case 0x1C: case 0x1D: case 0x1E: case 0x1F:
    case 0x20: case 0x85: case 0xA0: case 0x1680:
    case 0x2028: case 0x2029: case 0x202F: case 0x205F: case 0x3000:
      return true;
    default:
      return cp >= 0x2000 && cp <= 0x200A;
  }
}

inline bool is_word_byte(uint8_t b) {
  return (b >= 'a' && b <= 'z') || (b >= '0' && b <= '9') || b == '\'';
}

// decode one UTF-8 codepoint at p (valid input: produced by Python .encode)
inline uint32_t decode_cp(const uint8_t* p, int* len) {
  uint8_t b = p[0];
  if (b < 0x80) { *len = 1; return b; }
  if (b < 0xE0) { *len = 2; return ((b & 0x1Fu) << 6) | (p[1] & 0x3Fu); }
  if (b < 0xF0) {
    *len = 3;
    return ((b & 0x0Fu) << 12) | ((p[1] & 0x3Fu) << 6) | (p[2] & 0x3Fu);
  }
  *len = 4;
  return ((b & 0x07u) << 18) | ((p[1] & 0x3Fu) << 12) |
         ((p[2] & 0x3Fu) << 6) | (p[3] & 0x3Fu);
}

}  // namespace

extern "C" {

// texts: concatenated lowered UTF-8; offsets[n+1] delimit each text.
// ids/mask: int32 [n, seq_len], caller-allocated.
void bcfl_hash_tokenize(const uint8_t* texts, const int64_t* offsets,
                        int64_t n, int64_t seq_len, int64_t vocab_size,
                        int32_t* ids, int32_t* mask) {
  const uint32_t mod = static_cast<uint32_t>(vocab_size - N_SPECIAL);
  const int64_t cap = seq_len - 2 > 0 ? seq_len - 2 : 0;  // words kept
  for (int64_t t = 0; t < n; ++t) {
    int32_t* row = ids + t * seq_len;
    int32_t* mrow = mask + t * seq_len;
    const uint8_t* p = texts + offsets[t];
    const uint8_t* end = texts + offsets[t + 1];
    int64_t nw = 0;  // words emitted
    int64_t k = 0;   // ids emitted
    if (seq_len > 0) row[k++] = CLS_ID;
    while (p < end && nw < cap) {
      uint8_t b = *p;
      if (is_word_byte(b)) {  // ASCII word run [a-z0-9']+
        const uint8_t* s = p;
        do { ++p; } while (p < end && is_word_byte(*p));
        row[k++] = static_cast<int32_t>(
            crc32_bytes(s, static_cast<size_t>(p - s)) % mod + N_SPECIAL);
        ++nw;
      } else {
        int len = 1;
        uint32_t cp = b < 0x80 ? b : decode_cp(p, &len);
        if (!is_space_cp(cp)) {  // single-codepoint token [^\sa-z0-9']
          row[k++] = static_cast<int32_t>(
              crc32_bytes(p, static_cast<size_t>(len)) % mod + N_SPECIAL);
          ++nw;
        }
        p += len;
      }
    }
    if (k < seq_len) row[k++] = SEP_ID;
    // Python builds [CLS]+words+[SEP] then truncates to seq_len: SEP only
    // survives when it fits, which the k < seq_len guard reproduces (and
    // seq_len==1 keeps only CLS, seq_len==2 -> CLS,SEP — cap==0 paths)
    for (int64_t i = 0; i < k; ++i) mrow[i] = 1;
    for (int64_t i = k; i < seq_len; ++i) { row[i] = PAD_ID; mrow[i] = 0; }
  }
}

}  // extern "C"
