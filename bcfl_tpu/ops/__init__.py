from bcfl_tpu.ops.attention import dot_product_attention  # noqa: F401
