"""Attention ops.

The reference never touches attention directly — it calls prebuilt torch
kernels inside HF models (SURVEY.md §2.3). Here attention is ours, built for
the TPU compilation model:

- :func:`dot_product_attention` — einsum formulation XLA fuses onto the MXU;
  the default for the reference-scale seq lengths (<=512).
- :mod:`bcfl_tpu.ops.flash` — a Pallas blockwise (flash) kernel for long
  sequences, selected via ``use_flash`` in the model config.

Shapes follow the TPU-friendly convention [batch, heads, seq, head_dim] with
an additive mask/bias (0 for keep, large-negative for drop) so padding masks,
causal masks, and ALiBi-style biases all ride the same operand.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

NEG_INF = -1e9  # large-negative instead of -inf: keeps softmax NaN-free for
# fully-masked (all-padding) rows, which static-shape batches produce


def attention_bias_from_mask(mask: jnp.ndarray, dtype=jnp.float32) -> jnp.ndarray:
    """[batch, seq] 0/1 padding mask -> [batch, 1, 1, seq] additive bias."""
    return jnp.where(mask[:, None, None, :] > 0, 0.0, NEG_INF).astype(dtype)


def dot_product_attention(
    q: jnp.ndarray,  # [B, H, S, D]
    k: jnp.ndarray,  # [B, H, S, D]
    v: jnp.ndarray,  # [B, H, S, D]
    bias: Optional[jnp.ndarray] = None,  # broadcastable to [B, H, S, S]
) -> jnp.ndarray:
    """Plain softmax(QK^T/sqrt(d))V. Stable softmax in f32, output in q.dtype."""
    depth = q.shape[-1]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(depth, jnp.float32))
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    scores = scores - scores.max(axis=-1, keepdims=True)
    probs = jnp.exp(scores)
    probs = probs / (probs.sum(axis=-1, keepdims=True) + 1e-9)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)
