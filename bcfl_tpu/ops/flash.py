"""Blockwise (flash) attention for long sequences.

Two implementations behind one signature:

- :func:`flash_attention_xla` — pure-JAX blockwise online-softmax over KV
  blocks via ``lax.scan``. O(S) memory in the sequence instead of the O(S^2)
  score matrix; runs on any backend (and is the CPU-mesh test oracle).
- :func:`flash_attention_pallas` — TPU Pallas kernel (see
  ``/opt/skills/guides/pallas_guide.md``), used automatically on TPU backends
  when shapes allow; falls back to the XLA version elsewhere.

Both support ``causal=True`` (decoder masking) computed from block indices —
no dense ``[S, S]`` bias ever exists, which is what lets the Llama decoder
(:mod:`bcfl_tpu.models.llama`) run at long context.

The reference never needed this (it truncates at 512 tokens — SURVEY.md §5
"long-context: absent"), but long-context is first-class here: this is the
building block that scales fine-tuning past the HF tokenizer cap, and ring
attention in :mod:`bcfl_tpu.parallel` composes it across chips.
"""

from __future__ import annotations

import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bcfl_tpu.ops import registry

DEFAULT_BLOCK = 512


def flash_attention_xla(
    q: jnp.ndarray,  # [B, H, S, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,  # broadcastable to [B, H, S, S]
    block_size: int = DEFAULT_BLOCK,
    causal: bool = False,
) -> jnp.ndarray:
    """Online-softmax blockwise attention (Rabe & Staats / FlashAttention
    recurrence), scanning KV blocks so the full score matrix never exists."""
    B, H, S, D = q.shape
    Sk = k.shape[2]
    scale = 1.0 / jnp.sqrt(jnp.asarray(D, jnp.float32))
    nb = max(Sk // block_size, 1)
    bs = Sk // nb
    if Sk % nb:
        # fall back to one block if the length doesn't tile evenly
        nb, bs = 1, Sk

    kb = k.reshape(B, H, nb, bs, D).transpose(2, 0, 1, 3, 4)  # [nb, B, H, bs, D]
    vb = v.reshape(B, H, nb, bs, D).transpose(2, 0, 1, 3, 4)
    # A key-side bias ([B, Sk], or 4-D with singleton head/query dims — what
    # padding masks produce) stays in [B, Sk] form, blocked [nb, B, bs] and
    # broadcast per KV block inside the scan: no [B, H, S, Sk] buffer ever
    # exists, preserving O(S) memory. Only a genuinely dense per-(head, query)
    # bias falls back to full materialization.
    key_side = bias is not None and (
        bias.ndim == 2
        or (bias.ndim == 4 and bias.shape[1] == 1 and bias.shape[2] == 1))
    if bias is None:
        bb = jnp.zeros((nb, 1, 1, 1, bs), jnp.float32)
    elif key_side:
        kb2 = bias if bias.ndim == 2 else bias[:, 0, 0, :]
        kb2 = jnp.broadcast_to(kb2, (B, Sk)).astype(jnp.float32)
        bb = kb2.reshape(B, nb, bs).transpose(1, 0, 2)  # [nb, B, bs]
    else:
        bias = jnp.broadcast_to(bias, (B, H, S, Sk)).astype(jnp.float32)
        bb = bias.reshape(B, H, S, nb, bs).transpose(3, 0, 1, 2, 4)  # [nb, B, H, S, bs]

    qf = q.astype(jnp.float32) * scale
    # causal alignment for Sq != Sk (suffix-decode pattern): query i sits at
    # global position (Sk - S) + i
    qpos = (Sk - S) + jnp.arange(S)[:, None]  # [S, 1]
    kcol = jnp.arange(bs)[None, :]  # [1, bs]

    NEG = -1e30  # large-negative instead of -inf: exp() underflows to 0
    # without creating (-inf) - (-inf) NaN paths for fully-masked rows

    def step(carry, xs):
        acc, m, l = carry  # acc [B,H,S,D] f32; m,l [B,H,S,1]
        kj, vj, bj, j = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kj.astype(jnp.float32))
        # bj is [B, bs] on the key-side path, [B/1, H/1, S/1, bs] on the dense
        s = s + (bj[:, None, None, :] if bj.ndim == 2 else bj)
        if causal:
            kpos = j * bs + kcol  # [S, bs] via broadcast
            s = jnp.where((kpos > qpos)[None, None], NEG, s)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p, vj.astype(jnp.float32))
        return (acc, m_new, l), None

    init = (
        jnp.zeros((B, H, S, D), jnp.float32),
        jnp.full((B, H, S, 1), NEG, jnp.float32),
        jnp.zeros((B, H, S, 1), jnp.float32),
    )
    (acc, m, l), _ = lax.scan(step, init, (kb, vb, bb, jnp.arange(nb)))
    return (acc / jnp.maximum(l, 1e-9)).astype(q.dtype)


def flash_attention_pallas(q, k, v, bias=None, causal: bool = False,
                           block_q: int = 256, block_k: int = 256):
    """TPU Pallas flash kernel; implemented in :mod:`bcfl_tpu.ops.pallas_flash`."""
    from bcfl_tpu.ops.pallas_flash import flash_attention as _pl

    # positional: custom_vjp functions don't accept keyword arguments
    return _pl(q, k, v, bias, causal, block_q, block_k)


_pallas_fallback_warned = False

# registry entry (PERF.md "Custom kernels"): flash is the harness's
# tolerance-parity client — online-softmax reassociation makes the Pallas
# and XLA paths numerically close, not bit-identical (the pin lives in
# tests/test_pallas_kernels.py). The codec ops are the bit-identical ones.
FLASH_ATTENTION = registry.register_op(registry.KernelOp(
    name="flash_attention",
    xla=flash_attention_xla,
    pallas=flash_attention_pallas,
    parity="allclose:2e-2 (online-softmax reassociation; "
           "pinned in tests/test_pallas_kernels.py)",
    bench_shapes=(
        {"label": "bert-base-B4-S512", "B": 4, "H": 12, "S": 512, "D": 64},
        {"label": "llama-decode-B1-S2048", "B": 1, "H": 8, "S": 2048,
         "D": 64},
    ),
))


def flash_attention(q, k, v, bias=None, causal: bool = False,
                    block_size: int = DEFAULT_BLOCK):
    """Dispatch: Pallas on TPU when available, XLA blockwise elsewhere —
    impl selection through the kernel registry (``resolve("auto")`` =
    pallas iff the backend is a TPU), with the warn-once degradation kept
    here: an unsupported shape/bias falls back to the XLA reference.

    ``bias`` here is key-side only ([B, Sk] or [B, 1, 1, Sk]) so both paths
    stay O(S) in memory; use :func:`flash_attention_xla` directly for an
    arbitrary dense bias.
    """
    global _pallas_fallback_warned
    _, impl = registry.resolve("flash_attention", "auto")
    if impl == "pallas":
        try:
            # the module global (not the registry's captured callable), so
            # tests can monkeypatch the kernel under the dispatcher
            return flash_attention_pallas(q, k, v, bias, causal=causal)
        except (ValueError, NotImplementedError, TypeError,
                jax.errors.JaxRuntimeError) as e:
            # Expected degradations only (unsupported shape/bias, lowering
            # gap); anything else propagates. Warn ONCE so a silently slower
            # fallback never hides a kernel regression.
            if not _pallas_fallback_warned:
                _pallas_fallback_warned = True
                warnings.warn(
                    f"pallas flash kernel unavailable ({e!r}); falling back "
                    "to the XLA blockwise implementation",
                    RuntimeWarning, stacklevel=2)
    return flash_attention_xla(q, k, v, bias, block_size=block_size,
                               causal=causal)
